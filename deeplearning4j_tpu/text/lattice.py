"""Lattice-based morphological analysis — the kuromoji architecture
(ref: deeplearning4j-nlp-japanese vendored analyzer,
com/atilika/kuromoji/** 55 files: TokenizerBase builds a ViterbiLattice
from dictionary + unknown-word candidates, ViterbiSearcher picks the
min-cost path using word costs + a connection-cost matrix).

This is the same three-stage design, self-contained:

1. **Dictionary lookup** (`MorphDictionary`): a character-trie over
   surface forms; every entry carries a part-of-speech class and a word
   cost.  A seed lexicon of common Japanese function words, auxiliaries
   and high-frequency morphemes ships in-module (no IPADIC in this
   image); domain words are added via ``add`` / ``user_entries`` with a
   low cost, mirroring kuromoji's user-dictionary override.

2. **Unknown-word candidates** (ref: kuromoji UnknownDictionary +
   CharacterDefinition): at positions where the dictionary has no (or
   only short) matches, same-script character groups are emitted as
   candidate tokens with script-class-dependent costs (kanji expensive
   per char, katakana runs cheap, latin/digit grouped whole).

3. **Viterbi search** (`viterbi_segment`): min-cost path through the
   lattice, cost = Σ word_cost + connection(left.pos, right.pos) — the
   connection matrix encodes Japanese ordering preferences (noun→particle
   cheap, particle→particle expensive, ...), the role of kuromoji's
   ConnectionCosts binary.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.text.cjk import _script
from deeplearning4j_tpu.text.tokenization import (
    TokenPreProcess, Tokenizer, TokenizerFactory)

# Part-of-speech classes — the connection-cost context ids
# (ref: kuromoji ConnectionCosts left/right ids, collapsed to POS class).
BOS = "BOS"
EOS = "EOS"
NOUN = "noun"
PARTICLE = "particle"
VERB = "verb"
AUX = "aux"
ADJ = "adj"
ADV = "adv"
PREFIX = "prefix"
SUFFIX = "suffix"
SYMBOL = "symbol"
UNK = "unk"

_POS_IDS = {p: i for i, p in enumerate(
    [BOS, EOS, NOUN, PARTICLE, VERB, AUX, ADJ, ADV, PREFIX, SUFFIX,
     SYMBOL, UNK])}

# Connection-cost matrix [left.pos][right.pos] — small integers; the
# DEFAULT is 10, entries below override.  Encodes the ordering
# preferences kuromoji's ConnectionCosts matrix provides: particles
# attach after nouns/verbs, auxiliaries after verbs, two particles in a
# row are dispreferred, sentences end after verb/aux/noun.
_DEFAULT_CONN = 10
_CONN: Dict[Tuple[str, str], int] = {}


def _conn(pairs: Dict[Tuple[str, str], int]) -> None:
    _CONN.update(pairs)


_conn({
    (BOS, NOUN): 2, (BOS, VERB): 5, (BOS, ADV): 4, (BOS, PREFIX): 3,
    (BOS, ADJ): 4, (BOS, PARTICLE): 12, (BOS, AUX): 14, (BOS, UNK): 6,
    (NOUN, PARTICLE): 1, (NOUN, SUFFIX): 2, (NOUN, NOUN): 6,
    (NOUN, VERB): 5, (NOUN, AUX): 7, (NOUN, EOS): 4,
    (PARTICLE, NOUN): 2, (PARTICLE, VERB): 3, (PARTICLE, ADJ): 3,
    (PARTICLE, ADV): 4, (PARTICLE, PARTICLE): 9, (PARTICLE, UNK): 4,
    (PARTICLE, EOS): 8, (PARTICLE, PREFIX): 4,
    (VERB, AUX): 1, (VERB, PARTICLE): 3, (VERB, EOS): 2, (VERB, NOUN): 6,
    (AUX, EOS): 1, (AUX, PARTICLE): 4, (AUX, AUX): 3, (AUX, NOUN): 8,
    (ADJ, NOUN): 3, (ADJ, EOS): 3, (ADJ, PARTICLE): 4, (ADJ, AUX): 4,
    (ADV, VERB): 2, (ADV, ADJ): 3, (ADV, NOUN): 6,
    (PREFIX, NOUN): 1,
    (SUFFIX, PARTICLE): 2, (SUFFIX, EOS): 4, (SUFFIX, NOUN): 7,
    (UNK, PARTICLE): 3, (UNK, SUFFIX): 4, (UNK, EOS): 5, (UNK, NOUN): 7,
    (UNK, VERB): 6, (UNK, AUX): 7,
    (SYMBOL, NOUN): 5, (NOUN, SYMBOL): 5, (SYMBOL, EOS): 3,
})


def connection_cost(left_pos: str, right_pos: str) -> int:
    return _CONN.get((left_pos, right_pos), _DEFAULT_CONN)


@dataclasses.dataclass(frozen=True)
class MorphEntry:
    """One dictionary entry (ref: kuromoji TokenInfoDictionary record:
    surface, left/right id, word cost, POS features)."""

    surface: str
    pos: str = NOUN
    cost: int = 8
    base_form: Optional[str] = None  # dictionary form for inflections

    def __post_init__(self):
        if self.pos not in _POS_IDS:
            raise ValueError(f"unknown POS {self.pos!r}; "
                             f"known: {sorted(_POS_IDS)}")


# ---------------------------------------------------------------------------
# Seed lexicon — common particles, auxiliaries, demonstratives, frequent
# verbs (with common inflected forms), counters.  Costs: particles and
# auxiliaries very cheap (they are near-certain when they match),
# content words moderate.
# ---------------------------------------------------------------------------

def _entries() -> List[MorphEntry]:
    E = MorphEntry
    out: List[MorphEntry] = []
    # case particles / binding particles
    for s in ("は", "が", "を", "に", "へ", "と", "で", "も", "の", "や",
              "か", "ね", "よ", "ぞ", "わ", "さ"):
        out.append(E(s, PARTICLE, 2))
    for s in ("から", "まで", "より", "には", "では", "とは", "への",
              "だけ", "ほど", "くらい", "など", "ばかり", "しか", "こそ",
              "でも", "にも", "かも", "って"):
        out.append(E(s, PARTICLE, 4))
    # auxiliaries / copula and inflections
    for s, c in (("です", 2), ("でした", 3), ("ます", 2), ("ました", 3),
                 ("ません", 3), ("だ", 3), ("だった", 4), ("である", 4),
                 ("ない", 4), ("なかった", 5), ("たい", 4), ("られる", 4),
                 ("れる", 5), ("せる", 5), ("ている", 4), ("ていた", 4),
                 ("でいる", 5), ("ちゃう", 6), ("けど", 5)):
        out.append(E(s, AUX, c))
    # frequent verbs incl. inflected surfaces
    for s, base in (("する", None), ("した", "する"), ("して", "する"),
                    ("います", "いる"), ("いる", None), ("いた", "いる"),
                    ("ある", None), ("あった", "ある"), ("あります", "ある"),
                    ("なる", None), ("なった", "なる"), ("行く", None),
                    ("行った", "行く"), ("来る", None), ("来た", "来る"),
                    ("見る", None), ("見た", "見る"), ("言う", None),
                    ("言った", "言う"), ("思う", None), ("思った", "思う"),
                    ("食べる", None), ("食べた", "食べる"), ("ぬぐ", None),
                    ("書く", None), ("書いた", "書く"), ("読む", None),
                    ("読んだ", "読む"), ("使う", None), ("使った", "使う"),
                    ("できる", None), ("わかる", None), ("はく", None)):
        out.append(E(s, VERB, 6, base))
    # adjectives / adverbs / demonstratives
    for s in ("大きい", "小さい", "新しい", "古い", "良い", "よい", "いい",
              "高い", "安い", "早い", "遅い", "多い", "少ない", "長い", "短い"):
        out.append(E(s, ADJ, 6))
    for s in ("とても", "すこし", "少し", "もっと", "すぐ", "まだ", "もう",
              "いつも", "よく", "そして", "しかし", "また", "でも"):
        out.append(E(s, ADV, 5))
    for s in ("これ", "それ", "あれ", "どれ", "ここ", "そこ", "あそこ",
              "どこ", "この", "その", "あの", "どの", "こう", "そう", "ああ"):
        out.append(E(s, NOUN, 4))
    # common nouns (incl. the classic segmentation-ambiguity test words)
    for s in ("こと", "もの", "とき", "ところ", "ため", "ひと", "人", "日",
              "年", "月", "時間", "今日", "明日", "昨日", "日本", "東京",
              "東京都", "京都", "学校", "会社", "電車", "天気", "雨",
              "すもも", "もも", "うち", "にわ", "にわとり", "きもの",
              "はきもの", "仕事", "言葉", "問題", "結果", "世界", "自分"):
        out.append(E(s, NOUN, 6))
    for s in ("お", "ご", "新", "再"):
        out.append(E(s, PREFIX, 5))
    for s in ("さん", "ちゃん", "くん", "様", "たち", "的", "者", "化"):
        out.append(E(s, SUFFIX, 4))
    return out


class MorphDictionary:
    """Trie-backed surface dictionary with common-prefix lookup
    (ref: kuromoji TokenInfoDictionary + DoubleArrayTrie — a plain char
    trie here; lookups are per-sentence, not a serving hot path)."""

    def __init__(self, entries: Optional[Iterable[MorphEntry]] = None,
                 seed: bool = True):
        self._trie: dict = {}
        self.max_len = 1
        if seed:
            for e in _entries():
                self.add(e)
        for e in entries or ():
            self.add(e)

    def add(self, entry: MorphEntry) -> None:
        node = self._trie
        for ch in entry.surface:
            node = node.setdefault(ch, {})
        node.setdefault(None, []).append(entry)
        self.max_len = max(self.max_len, len(entry.surface))

    def add_word(self, surface: str, pos: str = NOUN, cost: int = 3) -> None:
        """User-dictionary entry — low default cost so it wins over the
        seed lexicon and unknown-word candidates (kuromoji user-dict
        semantics)."""
        self.add(MorphEntry(surface, pos, cost))

    def prefixes(self, text: str, start: int) -> List[MorphEntry]:
        """All dictionary entries whose surface == text[start:start+k]."""
        out: List[MorphEntry] = []
        node = self._trie
        i = start
        n = len(text)
        while i < n:
            node = node.get(text[i])
            if node is None:
                break
            i += 1
            out.extend(node.get(None, ()))
        return out


# ---------------------------------------------------------------------------
# Lattice + Viterbi
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class LatticeNode:
    start: int
    end: int
    surface: str
    pos: str
    cost: int
    base_form: Optional[str] = None
    is_unknown: bool = False


# unknown-word generation per script class
# (ref: kuromoji CharacterDefinition invoke/group/length settings);
# punct groups whole so it carries pos=SYMBOL (and the symbol rows of
# the connection matrix apply)
_UNK_GROUP_WHOLE = {"latin", "digit", "katakana", "hangul", "punct"}
_UNK_CHAR_COST = {"kanji": 9, "hiragana": 8, "katakana": 4, "latin": 3,
                  "digit": 3, "hangul": 4, "punct": 4}
_UNK_MAX_LEN = {"kanji": 3, "hiragana": 4}


def _unknown_candidates(text: str, start: int) -> List[LatticeNode]:
    s = _script(text[start])
    n = len(text)
    end = start + 1
    while end < n and _script(text[end]) == s:
        end += 1
    run_len = end - start
    base = _UNK_CHAR_COST.get(s, 6)
    out: List[LatticeNode] = []
    if s in _UNK_GROUP_WHOLE:
        # whole same-script group as one token (kuromoji GROUP=true)
        out.append(LatticeNode(start, end, text[start:end],
                               SYMBOL if s == "punct" else UNK,
                               base + run_len, is_unknown=True))
    else:
        for L in range(1, min(_UNK_MAX_LEN.get(s, 2), run_len) + 1):
            out.append(LatticeNode(start, start + L, text[start:start + L],
                                   UNK, base * L + 2, is_unknown=True))
    return out


def build_lattice(text: str, dictionary: MorphDictionary
                  ) -> List[List[LatticeNode]]:
    """Nodes grouped by start position; every position is guaranteed at
    least one candidate (single-char unknown fallback) so the lattice is
    always connected."""
    n = len(text)
    by_start: List[List[LatticeNode]] = [[] for _ in range(n)]
    for i in range(n):
        if text[i].isspace():
            continue
        for e in dictionary.prefixes(text, i):
            by_start[i].append(LatticeNode(i, i + len(e.surface), e.surface,
                                           e.pos, e.cost, e.base_form))
        # unknown-word candidates: always invoked (short dictionary hits
        # must still compete with longer unknown spans and vice versa)
        by_start[i].extend(_unknown_candidates(text, i))
    return by_start


def viterbi_segment(text: str, dictionary: MorphDictionary
                    ) -> List[LatticeNode]:
    """Min-cost path (ref: kuromoji ViterbiSearcher.search) — dynamic
    program over positions; whitespace breaks the lattice into segments
    scored independently."""
    out: List[LatticeNode] = []
    start = 0
    n = len(text)
    while start < n:
        if text[start].isspace():
            start += 1
            continue
        end = start
        while end < n and not text[end].isspace():
            end += 1
        out.extend(_viterbi_span(text[start:end], dictionary, offset=start))
        start = end
    return out


def _viterbi_span(span: str, dictionary: MorphDictionary,
                  offset: int = 0) -> List[LatticeNode]:
    """True lattice Viterbi: the DP state is (position, POS class), not
    position alone — connection cost depends on the PREDECESSOR's POS,
    so a slightly more expensive prefix ending in a different class can
    still carry the global optimum (kuromoji's ViterbiSearcher relaxes
    per node the same way)."""
    n = len(span)
    if n == 0:
        return []
    by_start = build_lattice(span, dictionary)
    # best cost arriving at position i with a last-token POS class;
    # back[(i, pos)] = (node, prev_pos) for path reconstruction
    best: List[Dict[str, float]] = [dict() for _ in range(n + 1)]
    back: Dict[Tuple[int, str], Tuple[LatticeNode, str]] = {}
    best[0][BOS] = 0.0
    for i in range(n):
        if not best[i]:
            continue
        for node in by_start[i]:
            step = node.cost
            tgt = best[node.end]
            for left_pos, c0 in best[i].items():
                c = c0 + step + connection_cost(left_pos, node.pos)
                if c < tgt.get(node.pos, float("inf")):
                    tgt[node.pos] = c
                    back[(node.end, node.pos)] = (node, left_pos)
    # EOS connection picks the final class
    toks: List[LatticeNode] = []
    if best[n]:
        pos_cls = min(best[n],
                      key=lambda p: best[n][p] + connection_cost(p, EOS))
        pos = n
        while pos > 0:
            entry = back.get((pos, pos_cls))
            if entry is None:  # disconnected (shouldn't happen) — fall back
                toks.append(LatticeNode(pos - 1, pos, span[pos - 1], UNK, 0,
                                        is_unknown=True))
                pos -= 1
                pos_cls = UNK if (pos, UNK) in back else \
                    next((p for e, p in back if e == pos), BOS)
                continue
            node, prev_pos = entry
            toks.append(node)
            pos = node.start
            pos_cls = prev_pos
    toks.reverse()
    if offset:
        toks = [dataclasses.replace(t, start=t.start + offset,
                                    end=t.end + offset) for t in toks]
    return toks


# ---------------------------------------------------------------------------
# Tokenizer contract
# ---------------------------------------------------------------------------
# IPADIC/kuromoji CSV dictionary loading — a user who has a real
# kuromoji-format dictionary (IPADIC, NAIST-jdic, UniDic export, or a
# kuromoji user dictionary) can load it into MorphDictionary instead of
# the seed lexicon.
# ---------------------------------------------------------------------------

def parse_dictionary_line(line: str) -> List[str]:
    """Quote-aware CSV split with ``""`` unescape — kuromoji's
    DictionaryEntryLineParser.parseLine semantics (surfaces may contain
    commas inside quotes, e.g. ``"1,000",...``)."""
    fields: List[str] = []
    buf: List[str] = []
    inside = False
    quotes = 0
    for ch in line:
        if ch == '"':
            inside = not inside
            quotes += 1
        if ch == "," and not inside:
            fields.append("".join(buf))
            buf = []
            continue
        buf.append(ch)
    if quotes % 2:
        raise ValueError(f"Unmatched quote in entry: {line!r}")
    fields.append("".join(buf))

    def unescape(v: str) -> str:
        if len(v) > 1 and v[0] == '"' and v[-1] == '"':
            v = v[1:-1]
        return v.replace('""', '"')

    return [unescape(f) for f in fields]


# IPADIC part-of-speech level-1 (and the 接尾 level-2 marker) → the
# connection-cost POS classes this lattice uses.  IPADIC names are
# standard across kuromoji-format dictionaries.
_IPADIC_POS = {
    "名詞": NOUN, "助詞": PARTICLE, "動詞": VERB, "助動詞": AUX,
    "形容詞": ADJ, "副詞": ADV, "接頭詞": PREFIX, "連体詞": ADJ,
    "接続詞": ADV, "感動詞": ADV, "記号": SYMBOL, "フィラー": ADV,
    "その他": UNK,
}

_IPADIC_POS_BY_LEN = sorted(_IPADIC_POS, key=len, reverse=True)


def ipadic_entry(fields: Sequence[str],
                 cost_divisor: int = 1500) -> MorphEntry:
    """One IPADIC CSV row → MorphEntry.  Layout (ref: kuromoji
    ipadic/compile/DictionaryEntry.java:24-66): surface, left_id,
    right_id, word_cost, pos1..pos4, conj_type, conj_form, base_form,
    reading, pronunciation.  Short rows (user dictionaries) need only
    surface[,left,right,cost[,pos1]].

    IPADIC word costs are shorts (≈ -20000..20000, frequent words most
    negative); this lattice's costs are small non-negative ints on the
    seed lexicon's scale, so raw costs are affinely squashed:
    ``clip(round(cost/divisor) + 8, 0, 24)`` — order-preserving, and a
    typical frequent word (≈ -6000) lands near the seed lexicon's cheap
    entries."""
    surface = fields[0]
    f3 = fields[3].strip() if len(fields) > 3 else ""
    try:
        raw_cost = int(f3) if f3 else 0
    except ValueError:
        # kuromoji USER-dictionary layout instead: surface, segmentation,
        # readings, pos-name (dict/UserDictionary.java) — field 3 is a
        # POS string like カスタム名詞.  Cheap cost so the user entry
        # wins, mirroring add_word / kuromoji user-dict semantics.
        return MorphEntry(surface, _ja_pos_name(f3), 3)
    pos1 = fields[4] if len(fields) > 4 else ""
    pos2 = fields[5] if len(fields) > 5 else ""
    pos = _IPADIC_POS.get(pos1, NOUN)
    if pos is NOUN and "接尾" in (pos1, pos2):
        pos = SUFFIX
    base = fields[10] if len(fields) > 10 else None
    if base in ("*", "", surface):
        base = None
    cost = int(min(24, max(0, round(raw_cost / cost_divisor) + 8)))
    return MorphEntry(surface, pos, cost, base)


def _ja_pos_name(name: str) -> str:
    """Best-effort POS class from a Japanese POS NAME (user dictionaries
    use free-form names like カスタム名詞): substring match against the
    IPADIC level-1 names, LONGEST first (助動詞 must hit aux, not the
    embedded 動詞), NOUN fallback."""
    for ja in _IPADIC_POS_BY_LEN:
        if ja in name:
            return _IPADIC_POS[ja]
    return NOUN


def load_ipadic_csv(source, dictionary: Optional[MorphDictionary] = None,
                    encoding: str = "utf-8-sig",
                    cost_divisor: int = 1500) -> MorphDictionary:
    """Load a kuromoji/IPADIC-format CSV dictionary (or a kuromoji USER
    dictionary — auto-detected per row) into a MorphDictionary (ref: the
    vendored analyzer's compile step,
    com/atilika/kuromoji/ipadic/compile/DictionaryEntry.java,
    dict/UserDictionary.java).

    ``source`` is a path (original IPADIC ships EUC-JP — pass
    ``encoding='euc-jp'``; the default also absorbs a UTF-8 BOM) or an
    iterable of already-decoded lines.  Kuromoji CSV has no comment
    syntax, so every non-empty line is an entry.  With no ``dictionary``
    argument a fresh one WITHOUT the seed lexicon is returned (a real
    dictionary replaces the seed, which remains the zero-download
    fallback); pass an existing dictionary to merge."""
    if dictionary is None:
        dictionary = MorphDictionary(seed=False)
    opened = None
    if isinstance(source, (str, bytes)) or hasattr(source, "__fspath__"):
        import io as _io
        lines = opened = _io.open(source, "r", encoding=encoding)
    else:
        lines = source  # iterable of lines or an open file object
    try:
        for line in lines:
            line = line.strip("\r\n")
            if not line:
                continue
            dictionary.add(ipadic_entry(parse_dictionary_line(line),
                                        cost_divisor))
    finally:
        if opened is not None:
            opened.close()
    return dictionary


class JapaneseLatticeTokenizer(Tokenizer):
    """Viterbi segmentation with morpheme metadata
    (ref: kuromoji Token — surface/base-form/POS accessors)."""

    def __init__(self, sentence: str, dictionary: MorphDictionary,
                 preprocessor: Optional[TokenPreProcess] = None,
                 keep_punct: bool = False):
        import unicodedata
        self.morphemes = viterbi_segment(
            unicodedata.normalize("NFKC", sentence), dictionary)
        if not keep_punct:
            self.morphemes = [m for m in self.morphemes
                              if m.pos != SYMBOL
                              and _script(m.surface[0]) != "punct"]
        super().__init__([m.surface for m in self.morphemes], preprocessor)


class JapaneseLatticeTokenizerFactory(TokenizerFactory):
    """Drop-in TokenizerFactory for Word2Vec / the text pipeline — the
    dictionary-backed upgrade over cjk.JapaneseTokenizerFactory's
    longest-match heuristic."""

    def __init__(self, user_entries: Optional[Iterable] = None,
                 keep_punct: bool = False,
                 dictionary: Optional[MorphDictionary] = None):
        super().__init__()
        # a user-supplied dictionary (e.g. load_ipadic_csv) replaces the
        # seed lexicon, mirroring kuromoji's dictionary selection
        self.dictionary = dictionary if dictionary is not None \
            else MorphDictionary()
        for e in user_entries or ():
            if isinstance(e, MorphEntry):
                self.dictionary.add(e)
            else:
                self.dictionary.add_word(str(e))
        self.keep_punct = keep_punct

    def create(self, sentence: str) -> Tokenizer:
        return JapaneseLatticeTokenizer(sentence, self.dictionary,
                                        self._preprocessor,
                                        self.keep_punct)
