"""Sentence / document iterators.

Mirrors the reference's sentence-iterator SPI (ref: text/sentenceiterator/
SentenceIterator.java, BasicLineIterator.java, CollectionSentenceIterator.java,
FileSentenceIterator.java, LineSentenceIterator.java,
labelaware/LabelAwareListSentenceIterator.java) plus the ``LabelsSource``
used by ParagraphVectors (ref: text/documentiterator/LabelsSource.java).
"""

from __future__ import annotations

import os
from typing import Callable, Iterable, List, Optional


class SentencePreProcessor:
    def pre_process(self, sentence: str) -> str:
        raise NotImplementedError


class _CallablePreProcessor(SentencePreProcessor):
    def __init__(self, fn: Callable[[str], str]):
        self._fn = fn

    def pre_process(self, sentence: str) -> str:
        return self._fn(sentence)


class SentenceIterator:
    """Stream of sentences, resettable (ref: SentenceIterator.java)."""

    def __init__(self, preprocessor: Optional[SentencePreProcessor] = None):
        if callable(preprocessor) and not isinstance(preprocessor, SentencePreProcessor):
            preprocessor = _CallablePreProcessor(preprocessor)
        self._preprocessor = preprocessor

    # -- SPI --------------------------------------------------------------
    def _raw_sentences(self) -> Iterable[str]:
        raise NotImplementedError

    def reset(self) -> None:
        self._iter = None
        self._peeked = None

    # -- driver -----------------------------------------------------------
    _iter = None

    def has_next(self) -> bool:
        if self._iter is None:
            self._iter = iter(self._raw_sentences())
        if getattr(self, "_peeked", None) is not None:
            return True
        try:
            self._peeked = next(self._iter)
            return True
        except StopIteration:
            return False

    def next_sentence(self) -> str:
        if not self.has_next():
            raise StopIteration
        s, self._peeked = self._peeked, None
        if self._preprocessor is not None:
            s = self._preprocessor.pre_process(s)
        return s

    def set_pre_processor(self, pre: SentencePreProcessor) -> None:
        if callable(pre) and not isinstance(pre, SentencePreProcessor):
            pre = _CallablePreProcessor(pre)
        self._preprocessor = pre

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next_sentence()


class CollectionSentenceIterator(SentenceIterator):
    """Over an in-memory collection (ref: CollectionSentenceIterator.java)."""

    def __init__(self, sentences: List[str],
                 preprocessor: Optional[SentencePreProcessor] = None):
        super().__init__(preprocessor)
        self._sentences = list(sentences)

    def _raw_sentences(self):
        return self._sentences


class BasicLineIterator(SentenceIterator):
    """One sentence per line of a file (ref: BasicLineIterator.java)."""

    def __init__(self, path: str,
                 preprocessor: Optional[SentencePreProcessor] = None):
        super().__init__(preprocessor)
        self._path = path

    def _raw_sentences(self):
        with open(self._path, "r", encoding="utf-8", errors="replace") as f:
            for line in f:
                line = line.strip()
                if line:
                    yield line


LineSentenceIterator = BasicLineIterator


class FileSentenceIterator(SentenceIterator):
    """Every line of every file under a directory (ref: FileSentenceIterator.java)."""

    def __init__(self, directory: str,
                 preprocessor: Optional[SentencePreProcessor] = None):
        super().__init__(preprocessor)
        self._dir = directory

    def _raw_sentences(self):
        for root, _dirs, files in os.walk(self._dir):
            for name in sorted(files):
                with open(os.path.join(root, name), "r", encoding="utf-8",
                          errors="replace") as f:
                    for line in f:
                        line = line.strip()
                        if line:
                            yield line


class LabelAwareSentenceIterator(SentenceIterator):
    """Sentence iterator that also exposes the current label
    (ref: labelaware/LabelAwareSentenceIterator.java)."""

    def current_label(self) -> str:
        raise NotImplementedError

    def current_labels(self) -> List[str]:
        return [self.current_label()]


class LabelAwareListSentenceIterator(LabelAwareSentenceIterator):
    """Parallel lists of sentences and labels
    (ref: labelaware/LabelAwareListSentenceIterator.java)."""

    def __init__(self, sentences: List[str], labels: List[str],
                 preprocessor: Optional[SentencePreProcessor] = None):
        assert len(sentences) == len(labels)
        super().__init__(preprocessor)
        self._sentences = list(sentences)
        self._labels = list(labels)
        self._idx = -1

    def _raw_sentences(self):
        for i, s in enumerate(self._sentences):
            self._idx = i
            yield s

    def current_label(self) -> str:
        return self._labels[self._idx]

    def reset(self):
        super().reset()
        self._idx = -1


class LabelsSource:
    """Generates/records document labels (ref: documentiterator/LabelsSource.java)."""

    def __init__(self, template: str = "DOC_%d",
                 labels: Optional[List[str]] = None):
        self._template = template
        self._labels: List[str] = list(labels or [])
        self._counter = len(self._labels)
        self._fixed = labels is not None

    def next_label(self) -> str:
        if self._fixed:
            label = self._labels[self._counter % len(self._labels)]
        else:
            label = self._template % self._counter
            self._labels.append(label)
        self._counter += 1
        return label

    def get_labels(self) -> List[str]:
        return list(self._labels)

    def store_label(self, label: str) -> None:
        if label not in self._labels:
            self._labels.append(label)

    def reset(self) -> None:
        self._counter = 0
        if not self._fixed:
            self._labels = []
