"""Inverted index over tokenized documents
(ref: deeplearning4j-nlp/.../text/invertedindex/InvertedIndex.java:35 —
addWordsToDoc/document/documents/docs/batchIter/sample surface; the
reference's LuceneInvertedIndex role, stdlib edition)."""

from __future__ import annotations

import random
import threading
from typing import Dict, Iterable, Iterator, List, Optional


class InMemoryInvertedIndex:
    """word-index → doc-ids; doc-id → token list.  Thread-safe adds
    (the reference indexes from multiple vectorizer threads)."""

    def __init__(self, vocab=None, sample: float = 0.0, seed: int = 0):
        self.vocab = vocab
        self._sample = sample
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._docs: Dict[int, List[str]] = {}
        self._postings: Dict[str, List[int]] = {}
        self._posting_sets: Dict[str, set] = {}
        self._next_doc = 0

    # -- write side (ref: addWordsToDoc / addWordToDoc) ---------------------
    def add_words_to_doc(self, doc_id: Optional[int],
                         words: Iterable[str]) -> int:
        words = list(words)
        with self._lock:
            if doc_id is None:
                doc_id = self._next_doc
            self._next_doc = max(self._next_doc, doc_id + 1)
            self._docs.setdefault(doc_id, []).extend(words)
            for w in words:
                seen = self._posting_sets.setdefault(w, set())
                if doc_id not in seen:  # dedup even under interleaved adds
                    seen.add(doc_id)
                    self._postings.setdefault(w, []).append(doc_id)
        return doc_id

    # -- read side ----------------------------------------------------------
    def document(self, doc_id: int) -> List[str]:
        return list(self._docs.get(doc_id, []))

    def documents(self, word: str) -> List[int]:
        """Doc ids containing the word (ref: documents(T))."""
        return list(self._postings.get(word, []))

    def num_documents(self) -> int:
        return len(self._docs)

    def total_words(self) -> int:
        return sum(len(d) for d in self._docs.values())

    def docs(self) -> Iterator[List[str]]:
        """(ref: docs() — iterate documents)"""
        for i in sorted(self._docs):
            yield list(self._docs[i])

    def batch_iter(self, batch_size: int) -> Iterator[List[List[str]]]:
        """(ref: batchIter(int))"""
        batch: List[List[str]] = []
        for doc in self.docs():
            batch.append(doc)
            if len(batch) == batch_size:
                yield batch
                batch = []
        if batch:
            yield batch

    def sample(self) -> float:
        return self._sample

    def eachDocWithLabels(self):  # pragma: no cover - compat shim
        raise NotImplementedError("label-aware indexing via documents()")
