"""Sequence elements — the generic unit the embedding engine trains over.

Mirrors the reference's ``SequenceElement`` / ``VocabWord`` /
``Sequence<T>`` contract (ref: models/sequencevectors/sequence/
SequenceElement.java, Sequence.java; models/word2vec/VocabWord.java):
an element has a label, an element-frequency, a vocab index, and — once
the Huffman tree is built — binary ``codes`` and inner-node ``points``
used by hierarchical softmax.
"""

from __future__ import annotations

from typing import List, Optional, Sequence as TypingSequence


class SequenceElement:
    """A vocabulary element (word, node, document label...)."""

    __slots__ = ("label", "element_frequency", "index", "codes", "points",
                 "special", "is_label")

    def __init__(self, label: str, frequency: float = 1.0,
                 special: bool = False, is_label: bool = False):
        self.label = label
        self.element_frequency = float(frequency)
        self.index = -1
        self.codes: List[int] = []
        self.points: List[int] = []
        self.special = special
        # PV labels (document ids) are excluded from context windows.
        self.is_label = is_label

    def increment_frequency(self, by: float = 1.0) -> None:
        self.element_frequency += by

    @property
    def code_length(self) -> int:
        return len(self.codes)

    def __repr__(self):
        return (f"{type(self).__name__}({self.label!r}, "
                f"freq={self.element_frequency}, idx={self.index})")

    def __eq__(self, other):
        return isinstance(other, SequenceElement) and other.label == self.label

    def __hash__(self):
        return hash(self.label)


class VocabWord(SequenceElement):
    """A word element (ref: models/word2vec/VocabWord.java)."""


class Sequence:
    """An ordered run of elements, optionally tagged with labels.

    Ref: models/sequencevectors/sequence/Sequence.java — labels are how
    ParagraphVectors attaches document ids to word runs.
    """

    __slots__ = ("elements", "labels")

    def __init__(self, elements: Optional[TypingSequence[SequenceElement]] = None):
        self.elements: List[SequenceElement] = list(elements or [])
        self.labels: List[SequenceElement] = []

    def add_element(self, element: SequenceElement) -> None:
        self.elements.append(element)

    def add_sequence_label(self, label: SequenceElement) -> None:
        label.is_label = True
        self.labels.append(label)

    def set_sequence_label(self, label: SequenceElement) -> None:
        label.is_label = True
        self.labels = [label]

    @property
    def sequence_label(self) -> Optional[SequenceElement]:
        return self.labels[0] if self.labels else None

    def size(self) -> int:
        return len(self.elements)

    def __len__(self):
        return len(self.elements)

    def __iter__(self):
        return iter(self.elements)
