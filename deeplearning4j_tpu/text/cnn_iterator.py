"""CNN-for-NLP sentence iterator.

Mirrors the reference's ``CnnSentenceDataSetIterator`` (ref:
deeplearning4j-nlp/.../iterator/CnnSentenceDataSetIterator.java +
LabeledSentenceProvider.java) — sentences become padded word-vector
tensors of shape (batch, 1, max_len, vector_size) with one-hot labels,
ready for text-CNN training.  Fixed max length keeps shapes static for
XLA.
"""

from __future__ import annotations

import random
from typing import List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator
from deeplearning4j_tpu.text.tokenization import (
    DefaultTokenizerFactory, TokenizerFactory)


class CollectionLabeledSentenceProvider:
    """In-memory (sentence, label) source (ref: iterator/provider/
    CollectionLabeledSentenceProvider.java)."""

    def __init__(self, sentences: List[str], labels: List[str],
                 seed: Optional[int] = None):
        assert len(sentences) == len(labels)
        self._data = list(zip(sentences, labels))
        self._labels = sorted(set(labels))
        self._rng = random.Random(seed)
        if seed is not None:
            self._rng.shuffle(self._data)
        self._pos = 0

    def has_next(self) -> bool:
        return self._pos < len(self._data)

    def next_sentence(self) -> Tuple[str, str]:
        item = self._data[self._pos]
        self._pos += 1
        return item

    def reset(self):
        self._pos = 0

    def all_labels(self) -> List[str]:
        return list(self._labels)

    def total_num_sentences(self) -> int:
        return len(self._data)


class CnnSentenceDataSetIterator(DataSetIterator):

    def __init__(self, sentence_provider, word_vectors, batch_size: int = 32,
                 max_sentence_length: int = 64,
                 tokenizer_factory: Optional[TokenizerFactory] = None,
                 sentences_along_height: bool = True):
        self.provider = sentence_provider
        self.word_vectors = word_vectors
        self.batch_size = batch_size
        self.max_len = max_sentence_length
        self.tf = tokenizer_factory or DefaultTokenizerFactory()
        self.sentences_along_height = sentences_along_height
        self.labels = sentence_provider.all_labels()
        self.vector_size = word_vectors.lookup_table.vector_length

    def has_next(self) -> bool:
        return self.provider.has_next()

    def next(self, num: Optional[int] = None) -> DataSet:
        num = num or self.batch_size
        sents, labels = [], []
        while self.provider.has_next() and len(sents) < num:
            s, l = self.provider.next_sentence()
            sents.append(s)
            labels.append(l)
        B, D, L = len(sents), self.vector_size, self.max_len
        feats = np.zeros((B, 1, L, D), np.float32)
        fmask = np.zeros((B, L), np.float32)
        ys = np.zeros((B, len(self.labels)), np.float32)
        for b, (s, l) in enumerate(zip(sents, labels)):
            toks = [t for t in self.tf.create(s).get_tokens()
                    if self.word_vectors.has_word(t)][:L]
            for i, tok in enumerate(toks):
                feats[b, 0, i] = self.word_vectors.word_vector(tok)
                fmask[b, i] = 1.0
            ys[b, self.labels.index(l)] = 1.0
        if not self.sentences_along_height:
            feats = feats.transpose(0, 1, 3, 2)
        return DataSet(feats, ys, features_mask=fmask)

    def reset(self):
        self.provider.reset()

    def total_examples(self) -> int:
        return self.provider.total_num_sentences()

    def get_labels(self) -> List[str]:
        return list(self.labels)
