"""Bag-of-words / TF-IDF vectorizers and inverted index.

Mirrors the reference (ref: bagofwords/vectorizer/
BagOfWordsVectorizer.java, TfidfVectorizer.java — RecordReader/iterator →
fixed-width count or tf-idf vectors over a built vocab;
text/invertedindex/InvertedIndex.java).
"""

from __future__ import annotations

import math
from collections import Counter, defaultdict
from typing import Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.text.sentence_iterators import (
    LabelAwareSentenceIterator, SentenceIterator)
from deeplearning4j_tpu.text.tokenization import (
    DefaultTokenizerFactory, TokenizerFactory)
from deeplearning4j_tpu.text.vocab import AbstractCache
from deeplearning4j_tpu.text.sequence import VocabWord


class InvertedIndex:
    """token → list of (doc id, positions) (ref: text/invertedindex/)."""

    def __init__(self):
        self._postings: Dict[str, List[Tuple[int, int]]] = defaultdict(list)
        self._num_docs = 0

    def add_doc(self, tokens: List[str]) -> int:
        doc_id = self._num_docs
        self._num_docs += 1
        for pos, tok in enumerate(tokens):
            self._postings[tok].append((doc_id, pos))
        return doc_id

    def documents(self, token: str) -> List[int]:
        return sorted({d for d, _ in self._postings.get(token, [])})

    def doc_frequency(self, token: str) -> int:
        return len(self.documents(token))

    @property
    def num_documents(self) -> int:
        return self._num_docs


class BaseTextVectorizer:

    def __init__(self, iterator: SentenceIterator,
                 tokenizer_factory: Optional[TokenizerFactory] = None,
                 min_word_frequency: int = 1,
                 stop_words: Optional[set] = None,
                 labels: Optional[List[str]] = None):
        self.iterator = iterator
        self.tf = tokenizer_factory or DefaultTokenizerFactory()
        self.min_word_frequency = min_word_frequency
        self.stop_words = stop_words or set()
        self.vocab = AbstractCache()
        self.index = InvertedIndex()
        self.labels = labels or []
        self._doc_tokens: List[List[str]] = []
        self._doc_labels: List[str] = []

    def _tokens(self, sentence: str) -> List[str]:
        return [t for t in self.tf.create(sentence).get_tokens()
                if t and t not in self.stop_words]

    def fit(self) -> None:
        label_aware = isinstance(self.iterator, LabelAwareSentenceIterator)
        self.iterator.reset()
        while self.iterator.has_next():
            sentence = self.iterator.next_sentence()
            toks = self._tokens(sentence)
            self._doc_tokens.append(toks)
            if label_aware:
                lbl = self.iterator.current_label()
                self._doc_labels.append(lbl)
                if lbl not in self.labels:
                    self.labels.append(lbl)
            self.index.add_doc(toks)
            for t in toks:
                if self.vocab.contains_word(t):
                    self.vocab.increment_word_count(t)
                else:
                    self.vocab.add_token(VocabWord(t))
        if self.min_word_frequency > 1:
            for label in list(self.vocab._map):
                if (self.vocab._map[label].element_frequency
                        < self.min_word_frequency):
                    self.vocab.remove_element(label)
        self.vocab.build_index()

    # -- SPI ---------------------------------------------------------------
    def _weight(self, token: str, doc_counts: Counter, doc_len: int) -> float:
        raise NotImplementedError

    def transform(self, text_or_tokens) -> np.ndarray:
        if isinstance(text_or_tokens, str):
            toks = self._tokens(text_or_tokens)
        else:
            toks = list(text_or_tokens)
        counts = Counter(toks)
        vec = np.zeros(self.vocab.num_words(), np.float32)
        for tok, _n in counts.items():
            idx = self.vocab.index_of(tok)
            if idx >= 0:
                vec[idx] = self._weight(tok, counts, len(toks))
        return vec

    def vectorize(self, text, label: str) -> DataSet:
        features = self.transform(text)[None, :]
        n_labels = max(len(self.labels), 1)
        y = np.zeros((1, n_labels), np.float32)
        if label in self.labels:
            y[0, self.labels.index(label)] = 1.0
        return DataSet(features, y)

    def fit_transform_all(self) -> DataSet:
        xs = np.stack([self.transform(toks) for toks in self._doc_tokens])
        n_labels = max(len(self.labels), 1)
        ys = np.zeros((len(self._doc_tokens), n_labels), np.float32)
        for i, lbl in enumerate(self._doc_labels):
            ys[i, self.labels.index(lbl)] = 1.0
        return DataSet(xs, ys)


class BagOfWordsVectorizer(BaseTextVectorizer):
    """Raw term counts (ref: bagofwords/vectorizer/BagOfWordsVectorizer.java)."""

    def _weight(self, token, doc_counts, doc_len):
        return float(doc_counts[token])


class TfidfVectorizer(BaseTextVectorizer):
    """tf·idf weights (ref: bagofwords/vectorizer/TfidfVectorizer.java)."""

    def _weight(self, token, doc_counts, doc_len):
        tf = doc_counts[token] / max(doc_len, 1)
        df = max(self.index.doc_frequency(token), 1)
        idf = math.log((1 + self.index.num_documents) / (1 + df)) + 1.0
        return float(tf * idf)
