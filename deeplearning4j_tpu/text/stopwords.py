"""English stop words (ref: text/stopwords/StopWords.java, which loads a
bundled stopwords resource file)."""

_ENGLISH = """a an and are as at be but by for from had has have he her his
i in is it its of on or she that the their them they this to was were what
which who will with you your we our us me my mine him himself herself
itself themselves do does did doing would should could ought not no nor so
than too very can just don t s about above after again against all am any
because been before being below between both down during each few further
here how into more most off once only other out over own same some such
then there these those through under until up when where why if while""".split()


class StopWords:
    _words = set(_ENGLISH)

    @classmethod
    def get_stop_words(cls):
        return sorted(cls._words)

    @classmethod
    def is_stop_word(cls, token: str) -> bool:
        return token.lower() in cls._words
