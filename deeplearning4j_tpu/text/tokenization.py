"""Tokenizers and token preprocessors.

Mirrors the reference's tokenization SPI (ref: text/tokenization/
tokenizerfactory/DefaultTokenizerFactory.java, tokenizer/
DefaultTokenizer.java, preprocessor/CommonPreprocessor.java,
EndingPreProcessor.java, LowCasePreProcessor.java,
NGramTokenizerFactory.java).  Pure host-side string work.
"""

from __future__ import annotations

import re
from typing import Callable, Iterator, List, Optional


class TokenPreProcess:
    """Per-token normalization hook (ref: tokenization/tokenizer/TokenPreProcess.java)."""

    def pre_process(self, token: str) -> str:
        raise NotImplementedError


class CommonPreprocessor(TokenPreProcess):
    """Lowercase + strip punctuation/digits (ref: preprocessor/CommonPreprocessor.java)."""

    _PUNCT = re.compile(r"[\d\.:,\"'\(\)\[\]|/?!;]+")

    def pre_process(self, token: str) -> str:
        return self._PUNCT.sub("", token).lower()


class LowCasePreProcessor(TokenPreProcess):
    def pre_process(self, token: str) -> str:
        return token.lower()


class EndingPreProcessor(TokenPreProcess):
    """Crude English stemmer (ref: preprocessor/EndingPreProcessor.java)."""

    def pre_process(self, token: str) -> str:
        if token.endswith("s") and not token.endswith("ss"):
            token = token[:-1]
        if token.endswith("."):
            token = token[:-1]
        if token.endswith("ly"):
            token = token[:-2]
        if token.endswith("ing"):
            token = token[:-3]
        return token


class StemmingPreprocessor(CommonPreprocessor):
    """CommonPreprocessor + ending stemmer."""

    def pre_process(self, token: str) -> str:
        return EndingPreProcessor().pre_process(super().pre_process(token))


class Tokenizer:
    """Iterator of tokens over one sentence (ref: tokenization/tokenizer/Tokenizer.java)."""

    def __init__(self, tokens: List[str],
                 preprocessor: Optional[TokenPreProcess] = None):
        if preprocessor is not None:
            tokens = [preprocessor.pre_process(t) for t in tokens]
        self._tokens = [t for t in tokens if t]
        self._pos = 0

    def has_more_tokens(self) -> bool:
        return self._pos < len(self._tokens)

    def count_tokens(self) -> int:
        return len(self._tokens)

    def next_token(self) -> str:
        tok = self._tokens[self._pos]
        self._pos += 1
        return tok

    def get_tokens(self) -> List[str]:
        return list(self._tokens)

    def __iter__(self) -> Iterator[str]:
        return iter(self._tokens)


class DefaultTokenizer(Tokenizer):
    """Whitespace tokenizer (ref: tokenizer/DefaultTokenizer.java wraps
    java.util.StringTokenizer — whitespace splitting)."""

    def __init__(self, sentence: str,
                 preprocessor: Optional[TokenPreProcess] = None):
        super().__init__(sentence.split(), preprocessor)


class TokenizerFactory:
    """Creates tokenizers; carries the shared preprocessor
    (ref: tokenizerfactory/TokenizerFactory.java)."""

    def __init__(self):
        self._preprocessor: Optional[TokenPreProcess] = None

    def set_token_pre_processor(self, pre: TokenPreProcess) -> "TokenizerFactory":
        self._preprocessor = pre
        return self

    def get_token_pre_processor(self) -> Optional[TokenPreProcess]:
        return self._preprocessor

    def create(self, sentence: str) -> Tokenizer:
        raise NotImplementedError


class DefaultTokenizerFactory(TokenizerFactory):
    def create(self, sentence: str) -> Tokenizer:
        return DefaultTokenizer(sentence, self._preprocessor)


class RegexTokenizerFactory(TokenizerFactory):
    """Split on a regex (generalization of the reference's PosUima-free options)."""

    def __init__(self, pattern: str = r"\W+"):
        super().__init__()
        self._pattern = re.compile(pattern)

    def create(self, sentence: str) -> Tokenizer:
        return Tokenizer(self._pattern.split(sentence), self._preprocessor)


class NGramTokenizerFactory(TokenizerFactory):
    """Emit n-grams of an underlying tokenizer's tokens
    (ref: tokenizerfactory/NGramTokenizerFactory.java)."""

    def __init__(self, base: TokenizerFactory, min_n: int, max_n: int):
        super().__init__()
        self._base = base
        self._min_n = min_n
        self._max_n = max_n

    def create(self, sentence: str) -> Tokenizer:
        toks = self._base.create(sentence).get_tokens()
        out: List[str] = []
        for n in range(self._min_n, self._max_n + 1):
            for i in range(len(toks) - n + 1):
                out.append(" ".join(toks[i:i + n]))
        return Tokenizer(out, self._preprocessor)
