"""Annotation pipeline — the UIMA-analysis-engine role
(ref: deeplearning4j-nlp-uima/.../text/annotator/{SentenceAnnotator,
TokenizerAnnotator,PoStagger,StemmerAnnotator}.java — ClearTK/OpenNLP
engines behind a pipeline of annotators over a CAS).

The capability is the composable annotate() chain producing sentence,
token, POS, and stem annotations; the heavyweight UIMA CAS is replaced
by a plain Annotation list on an AnalysisContext."""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional


@dataclasses.dataclass
class Annotation:
    kind: str      # sentence | token | pos | stem
    begin: int
    end: int
    value: str


class AnalysisContext:
    """The CAS analog: raw text + annotation layers."""

    def __init__(self, text: str):
        self.text = text
        self.annotations: List[Annotation] = []

    def select(self, kind: str) -> List[Annotation]:
        return [a for a in self.annotations if a.kind == kind]

    def covered(self, kind: str, span: Annotation) -> List[Annotation]:
        return [a for a in self.annotations
                if a.kind == kind and a.begin >= span.begin
                and a.end <= span.end]


class Annotator:
    def process(self, ctx: AnalysisContext) -> None:
        raise NotImplementedError


class SentenceAnnotator(Annotator):
    """(ref: text/annotator/SentenceAnnotator.java)"""

    _BOUNDARY = re.compile(r"[.!?]+[\s\"')\]]*")

    def process(self, ctx: AnalysisContext) -> None:
        start = 0
        for m in self._BOUNDARY.finditer(ctx.text):
            end = m.end()
            seg = ctx.text[start:end].strip()
            if seg:
                b = ctx.text.index(seg, start)
                ctx.annotations.append(
                    Annotation("sentence", b, b + len(seg), seg))
            start = end
        tail = ctx.text[start:].strip()
        if tail:
            b = ctx.text.index(tail, start)
            ctx.annotations.append(
                Annotation("sentence", b, b + len(tail), tail))


class TokenizerAnnotator(Annotator):
    """(ref: text/annotator/TokenizerAnnotator.java)"""

    _TOKEN = re.compile(r"\w+(?:'\w+)?|[^\w\s]")

    def process(self, ctx: AnalysisContext) -> None:
        for sent in ctx.select("sentence"):
            for m in self._TOKEN.finditer(sent.value):
                ctx.annotations.append(Annotation(
                    "token", sent.begin + m.start(),
                    sent.begin + m.end(), m.group()))


class PoSTagger(Annotator):
    """Lightweight rule/lexicon POS tagger filling the PoStagger slot
    (ref: text/annotator/PoStagger.java — OpenNLP maxent model behind
    the same annotate-tokens-with-POS contract)."""

    _LEX: Dict[str, str] = {
        "the": "DT", "a": "DT", "an": "DT", "of": "IN", "in": "IN",
        "on": "IN", "at": "IN", "to": "TO", "and": "CC", "or": "CC",
        "is": "VBZ", "are": "VBP", "was": "VBD", "were": "VBD",
        "be": "VB", "been": "VBN", "he": "PRP", "she": "PRP", "it": "PRP",
        "they": "PRP", "i": "PRP", "you": "PRP", "we": "PRP",
        "not": "RB", "very": "RB", "quickly": "RB",
    }

    def _tag(self, word: str) -> str:
        lw = word.lower()
        if lw in self._LEX:
            return self._LEX[lw]
        if not word[0].isalnum():
            return "."
        if word[0].isdigit():
            return "CD"
        if word.endswith("ing"):
            return "VBG"
        if word.endswith("ed"):
            return "VBD"
        if word.endswith("ly"):
            return "RB"
        if word.endswith("s") and len(word) > 3:
            return "NNS"
        if word[0].isupper():
            return "NNP"
        return "NN"

    def process(self, ctx: AnalysisContext) -> None:
        for tok in ctx.select("token"):
            ctx.annotations.append(Annotation(
                "pos", tok.begin, tok.end, self._tag(tok.value)))


class StemmerAnnotator(Annotator):
    """Porter stemmer (ref: text/annotator/StemmerAnnotator.java —
    Snowball stemmer behind the stem-each-token contract)."""

    def process(self, ctx: AnalysisContext) -> None:
        for tok in ctx.select("token"):
            ctx.annotations.append(Annotation(
                "stem", tok.begin, tok.end, porter_stem(tok.value)))


class AnnotationPipeline:
    """Compose annotators (the AnalysisEngine chain)."""

    def __init__(self, *annotators: Annotator):
        self.annotators = list(annotators) or [
            SentenceAnnotator(), TokenizerAnnotator(), PoSTagger(),
            StemmerAnnotator()]

    def annotate(self, text: str) -> AnalysisContext:
        ctx = AnalysisContext(text)
        for a in self.annotators:
            a.process(ctx)
        return ctx


# ---------------------------------------------------------------------------
# Porter stemming algorithm (Porter 1980) — public-domain algorithm,
# implemented from the paper's rule tables.

_VOWELS = "aeiou"


def _is_cons(word: str, i: int) -> bool:
    c = word[i]
    if c in _VOWELS:
        return False
    if c == "y":
        return i == 0 or not _is_cons(word, i - 1)
    return True


def _measure(stem: str) -> int:
    m = 0
    prev_vowel = False
    for i in range(len(stem)):
        cons = _is_cons(stem, i)
        if prev_vowel and cons:
            m += 1
        prev_vowel = not cons
    return m


def _has_vowel(stem: str) -> bool:
    return any(not _is_cons(stem, i) for i in range(len(stem)))


def _ends_double_cons(word: str) -> bool:
    return (len(word) >= 2 and word[-1] == word[-2]
            and _is_cons(word, len(word) - 1))


def _cvc(word: str) -> bool:
    if len(word) < 3:
        return False
    return (_is_cons(word, len(word) - 3)
            and not _is_cons(word, len(word) - 2)
            and _is_cons(word, len(word) - 1)
            and word[-1] not in "wxy")


def porter_stem(word: str) -> str:
    w = word.lower()
    if len(w) <= 2 or not w.isalpha():
        return w
    # step 1a
    if w.endswith("sses"):
        w = w[:-2]
    elif w.endswith("ies"):
        w = w[:-2]
    elif not w.endswith("ss") and w.endswith("s"):
        w = w[:-1]
    # step 1b
    if w.endswith("eed"):
        if _measure(w[:-3]) > 0:
            w = w[:-1]
    else:
        flag = False
        if w.endswith("ed") and _has_vowel(w[:-2]):
            w = w[:-2]
            flag = True
        elif w.endswith("ing") and _has_vowel(w[:-3]):
            w = w[:-3]
            flag = True
        if flag:
            if w.endswith(("at", "bl", "iz")):
                w += "e"
            elif _ends_double_cons(w) and not w.endswith(("l", "s", "z")):
                w = w[:-1]
            elif _measure(w) == 1 and _cvc(w):
                w += "e"
    # step 1c
    if w.endswith("y") and _has_vowel(w[:-1]):
        w = w[:-1] + "i"
    # step 2
    for suf, rep in (("ational", "ate"), ("tional", "tion"), ("enci", "ence"),
                     ("anci", "ance"), ("izer", "ize"), ("abli", "able"),
                     ("alli", "al"), ("entli", "ent"), ("eli", "e"),
                     ("ousli", "ous"), ("ization", "ize"), ("ation", "ate"),
                     ("ator", "ate"), ("alism", "al"), ("iveness", "ive"),
                     ("fulness", "ful"), ("ousness", "ous"), ("aliti", "al"),
                     ("iviti", "ive"), ("biliti", "ble")):
        if w.endswith(suf):
            if _measure(w[:-len(suf)]) > 0:
                w = w[:-len(suf)] + rep
            break
    # step 3
    for suf, rep in (("icate", "ic"), ("ative", ""), ("alize", "al"),
                     ("iciti", "ic"), ("ical", "ic"), ("ful", ""),
                     ("ness", "")):
        if w.endswith(suf):
            if _measure(w[:-len(suf)]) > 0:
                w = w[:-len(suf)] + rep
            break
    # step 4
    for suf in ("al", "ance", "ence", "er", "ic", "able", "ible", "ant",
                "ement", "ment", "ent", "ou", "ism", "ate", "iti", "ous",
                "ive", "ize"):
        if w.endswith(suf):
            if _measure(w[:-len(suf)]) > 1:
                w = w[:-len(suf)]
            break
    else:
        if w.endswith("ion") and len(w) > 3 and w[-4] in "st":
            if _measure(w[:-3]) > 1:
                w = w[:-3]
    # step 5a
    if w.endswith("e"):
        stem = w[:-1]
        if _measure(stem) > 1 or (_measure(stem) == 1 and not _cvc(stem)):
            w = stem
    # step 5b
    if _measure(w) > 1 and _ends_double_cons(w) and w.endswith("l"):
        w = w[:-1]
    return w
