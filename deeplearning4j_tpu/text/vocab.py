"""Vocabulary cache, constructor, and Huffman coding.

Mirrors the reference's word store (ref: models/word2vec/wordstore/
inmemory/AbstractCache.java — label→element map + index table;
VocabConstructor.java — min-frequency filtering + special tokens;
models/sequencevectors/serialization/ + models/word2vec/Huffman.java —
binary Huffman tree whose codes/points drive hierarchical softmax).
"""

from __future__ import annotations

import heapq
import itertools
from typing import Dict, Iterable, List, Optional

from deeplearning4j_tpu.text.sequence import Sequence, SequenceElement, VocabWord


class AbstractCache:
    """In-memory vocab cache (ref: wordstore/inmemory/AbstractCache.java)."""

    def __init__(self):
        self._map: Dict[str, SequenceElement] = {}
        self._index: List[SequenceElement] = []
        self.total_word_count = 0.0

    # -- membership -------------------------------------------------------
    def contains_word(self, label: str) -> bool:
        return label in self._map

    def word_for(self, label: str) -> Optional[SequenceElement]:
        return self._map.get(label)

    has_token = contains_word
    token_for = word_for

    def add_token(self, element: SequenceElement) -> None:
        existing = self._map.get(element.label)
        if existing is not None:
            existing.increment_frequency(element.element_frequency)
            return
        self._map[element.label] = element

    def increment_word_count(self, label: str, by: float = 1.0) -> None:
        el = self._map.get(label)
        if el is not None:
            el.increment_frequency(by)
            self.total_word_count += by

    # -- indexing ---------------------------------------------------------
    def update_words_occurrences(self) -> None:
        self.total_word_count = sum(e.element_frequency for e in self._index)

    def build_index(self) -> None:
        """Assign indices by descending frequency (word2vec convention)."""
        self._index = sorted(self._map.values(),
                             key=lambda e: (-e.element_frequency, e.label))
        for i, el in enumerate(self._index):
            el.index = i
        self.update_words_occurrences()

    def word_at_index(self, index: int) -> Optional[SequenceElement]:
        if 0 <= index < len(self._index):
            return self._index[index]
        return None

    def index_of(self, label: str) -> int:
        el = self._map.get(label)
        return -1 if el is None else el.index

    def word_frequency(self, label: str) -> float:
        el = self._map.get(label)
        return 0.0 if el is None else el.element_frequency

    def num_words(self) -> int:
        return len(self._index) if self._index else len(self._map)

    def words(self) -> List[str]:
        return [e.label for e in (self._index or self._map.values())]

    def vocab_words(self) -> List[SequenceElement]:
        return list(self._index or self._map.values())

    def remove_element(self, label: str) -> None:
        self._map.pop(label, None)

    def __len__(self):
        return self.num_words()


class Huffman:
    """Binary Huffman tree over element frequencies.

    Produces per-element ``codes`` (bits, root→leaf) and ``points``
    (inner-node syn1 rows along the path) — the hierarchical-softmax
    addressing scheme (ref: models/word2vec/Huffman.java, applied by
    VocabConstructor; consumed by SkipGram.iterateSample's
    idxSyn1/codes arrays).
    """

    MAX_CODE_LENGTH = 40

    def __init__(self, elements: Iterable[SequenceElement]):
        self._elements = sorted(elements,
                                key=lambda e: (-e.element_frequency, e.label))

    def build(self) -> None:
        els = self._elements
        n = len(els)
        if n == 0:
            return
        if n == 1:
            els[0].codes = [0]
            els[0].points = [0]
            return
        counter = itertools.count()
        # heap of (freq, tiebreak, node); node = (element | [left, right])
        heap = [(e.element_frequency, next(counter), e) for e in els]
        heapq.heapify(heap)
        inner_id = itertools.count()
        parents: Dict[int, tuple] = {}  # id(node) -> (parent_inner_idx, bit)
        nodes = []
        while len(heap) > 1:
            f1, _, n1 = heapq.heappop(heap)
            f2, _, n2 = heapq.heappop(heap)
            idx = next(inner_id)
            parents[id(n1)] = (idx, 0)
            parents[id(n2)] = (idx, 1)
            merged = [n1, n2]
            nodes.append(merged)
            heapq.heappush(heap, (f1 + f2, next(counter), merged))
        n_inner = len(nodes)
        for el in els:
            codes: List[int] = []
            points: List[int] = []
            node: object = el
            while id(node) in parents:
                inner, bit = parents[id(node)]
                codes.append(bit)
                # syn1 row index: reference numbers inner nodes so the root
                # ends up addressable; we use inner index directly, root =
                # n_inner-1.  Path is stored root→leaf.
                points.append(inner)
                # climb: find the merged list containing node
                node = nodes[inner]
            codes.reverse()
            points.reverse()
            if len(codes) > self.MAX_CODE_LENGTH:
                codes = codes[:self.MAX_CODE_LENGTH]
                points = points[:self.MAX_CODE_LENGTH]
            el.codes = codes
            el.points = points


class VocabConstructor:
    """Builds a vocab cache from sequence sources with min-frequency
    filtering (ref: wordstore/VocabConstructor.java).
    """

    def __init__(self, min_element_frequency: int = 0,
                 build_huffman: bool = True,
                 cache: Optional[AbstractCache] = None):
        self.min_element_frequency = min_element_frequency
        self.build_huffman = build_huffman
        self.cache = cache or AbstractCache()
        self._sources: List[Iterable[Sequence]] = []

    def add_source(self, sequences: Iterable[Sequence]) -> "VocabConstructor":
        self._sources.append(sequences)
        return self

    def build_joint_vocabulary(self) -> AbstractCache:
        cache = self.cache
        for source in self._sources:
            for seq in source:
                for el in seq.elements:
                    if cache.contains_word(el.label):
                        cache.increment_word_count(el.label)
                    else:
                        fresh = type(el)(el.label, el.element_frequency)
                        fresh.special = el.special
                        fresh.is_label = el.is_label
                        cache.add_token(fresh)
                for lbl in seq.labels:
                    if not cache.contains_word(lbl.label):
                        mirror = type(lbl)(lbl.label, 1.0)
                        mirror.special = True
                        mirror.is_label = True
                        cache.add_token(mirror)
        if self.min_element_frequency > 1:
            for label in list(cache._map):
                el = cache._map[label]
                if (el.element_frequency < self.min_element_frequency
                        and not el.special and not el.is_label):
                    cache.remove_element(label)
        cache.build_index()
        if self.build_huffman:
            Huffman(cache.vocab_words()).build()
        return cache
