"""Japanese and Korean tokenizers
(ref: deeplearning4j-nlp-japanese — vendored kuromoji morphological
analyzer, com/atilika/kuromoji/** 55 files;
deeplearning4j-nlp-korean/.../KoreanTokenizer.java + twitter-text).

No dictionary ships in this image, so segmentation is script-class
driven with longest-match user/function-word dictionaries — the same
TokenizerFactory contract as the reference (plug into Word2Vec &
the text pipeline), with the dictionary as an extension point
(``user_dict``)."""

from __future__ import annotations

import re
import unicodedata
from typing import Iterable, List, Optional, Set

from deeplearning4j_tpu.text.tokenization import (
    TokenPreProcess, Tokenizer, TokenizerFactory)

# -- script classification ---------------------------------------------------

_HIRAGANA = ("぀", "ゟ")
_KATAKANA = ("゠", "ヿ")
_KANJI = ("一", "鿿")
_HANGUL = ("가", "힣")
_HANGUL_JAMO = ("ᄀ", "ᇿ")


def _script(ch: str) -> str:
    if _HIRAGANA[0] <= ch <= _HIRAGANA[1]:
        return "hiragana"
    if _KATAKANA[0] <= ch <= _KATAKANA[1] or ch == "ー":  # chōonpu
        return "katakana"
    if _KANJI[0] <= ch <= _KANJI[1] or ch in "々〇":  # 々〇
        return "kanji"
    if (_HANGUL[0] <= ch <= _HANGUL[1]
            or _HANGUL_JAMO[0] <= ch <= _HANGUL_JAMO[1]):
        return "hangul"
    if ch.isalpha():
        return "latin"
    if ch.isdigit():
        return "digit"
    if ch.isspace():
        return "space"
    return "punct"


def _runs(text: str) -> List[str]:
    """Split into maximal same-script runs, dropping whitespace."""
    out: List[str] = []
    cur = ""
    cur_s = None
    for ch in text:
        s = _script(ch)
        if s == cur_s and s != "punct":
            cur += ch
        else:
            if cur and cur_s != "space":
                out.append(cur)
            cur = ch
            cur_s = s
    if cur and cur_s != "space":
        out.append(cur)
    return out


def _longest_match_split(run: str, dictionary: Set[str],
                         max_len: int) -> List[str]:
    """Greedy longest-match over a dictionary; unmatched prefixes emit
    single characters (kuromoji's unknown-word fallback for kanji)."""
    out: List[str] = []
    i = 0
    n = len(run)
    while i < n:
        matched = None
        for L in range(min(max_len, n - i), 0, -1):
            if run[i:i + L] in dictionary:
                matched = run[i:i + L]
                break
        if matched:
            out.append(matched)
            i += len(matched)
        else:
            out.append(run[i])
            i += 1
    return out


# -- Japanese ----------------------------------------------------------------

# Common particles/auxiliaries (hiragana function words) — the role of
# kuromoji's IPADIC entries for segmentation of hiragana runs.
_JA_FUNCTION = {
    "これ", "それ", "あれ", "ここ", "そこ", "の", "は", "が", "を", "に", "へ", "と",
    "で", "から", "まで", "より", "も", "か", "な", "ね", "よ", "です", "ます",
    "でした", "ました", "する", "した", "して", "いる", "ある", "ない", "だ",
    "という", "こと", "もの", "ため", "そして", "しかし", "また",
}


class JapaneseTokenizer(Tokenizer):
    """(ref: deeplearning4j-nlp-japanese JapaneseTokenizer over kuromoji)

    Segmentation: script-run boundaries are always token boundaries
    (kanji↔kana↔latin↔digit); hiragana runs are further split by
    longest-match over the function-word dictionary; kanji runs by
    longest-match over the user dictionary (else single chars —
    kuromoji's unknown-word heuristic)."""

    def __init__(self, sentence: str,
                 preprocessor: Optional[TokenPreProcess] = None,
                 user_dict: Optional[Set[str]] = None):
        user_dict = user_dict or set()
        max_u = max((len(w) for w in user_dict), default=1)
        toks: List[str] = []
        for run in _runs(unicodedata.normalize("NFKC", sentence)):
            s = _script(run[0])
            if s == "hiragana":
                toks.extend(_longest_match_split(
                    run, _JA_FUNCTION | user_dict,
                    max(max_u, 3)))
            elif s == "kanji":
                if user_dict:
                    toks.extend(_longest_match_split(run, user_dict, max_u))
                else:
                    toks.append(run)
            elif s == "punct":
                continue
            else:
                toks.append(run)
        super().__init__(toks, preprocessor)


class JapaneseTokenizerFactory(TokenizerFactory):
    """(ref: JapaneseTokenizerFactory.java)"""

    def __init__(self, user_dict: Optional[Iterable[str]] = None):
        super().__init__()
        self.user_dict = set(user_dict or [])

    def create(self, sentence: str) -> Tokenizer:
        return JapaneseTokenizer(sentence, self._preprocessor,
                                 self.user_dict)


# -- Korean ------------------------------------------------------------------

# Common postpositions (josa) stripped from the end of eojeol —
# the role of twitter-text's Korean stemmer in the reference.
_KO_JOSA = (
    "은", "는", "이", "가", "을", "를", "과", "와", "의", "에", "에서", "에게",
    "으로", "로", "도", "만", "까지", "부터", "보다", "처럼", "하고", "이나",
)


class KoreanTokenizer(Tokenizer):
    """(ref: deeplearning4j-nlp-korean/.../KoreanTokenizer.java)

    Eojeol (space-delimited) tokens; hangul↔latin↔digit boundaries
    split; trailing single-syllable josa separated (``strip_josa``)."""

    def __init__(self, sentence: str,
                 preprocessor: Optional[TokenPreProcess] = None,
                 strip_josa: bool = True):
        toks: List[str] = []
        for run in _runs(unicodedata.normalize("NFKC", sentence)):
            if _script(run[0]) == "punct":
                continue
            if strip_josa and _script(run[0]) == "hangul" and len(run) > 1:
                stripped = False
                for josa in sorted(_KO_JOSA, key=len, reverse=True):
                    if run.endswith(josa) and len(run) > len(josa):
                        toks.append(run[:-len(josa)])
                        toks.append(josa)
                        stripped = True
                        break
                if not stripped:
                    toks.append(run)
            else:
                toks.append(run)
        super().__init__(toks, preprocessor)


class KoreanTokenizerFactory(TokenizerFactory):
    """(ref: KoreanTokenizerFactory.java)"""

    def __init__(self, strip_josa: bool = True):
        super().__init__()
        self.strip_josa = strip_josa

    def create(self, sentence: str) -> Tokenizer:
        return KoreanTokenizer(sentence, self._preprocessor,
                               self.strip_josa)
