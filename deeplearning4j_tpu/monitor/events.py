"""Structured event journal + request-scoped trace context.

Metrics (monitor/registry.py) answer "how much / how fast"; the journal
answers "what happened, in what order, to WHICH request".  Three pieces:

* **Trace context** — a contextvars-carried dict of correlation fields
  (``request_id``, ``tenant``, ``session_id``, ``fit_id``, ...).  The
  gateway mints a request ID per RPC (:func:`new_request_id`, carried by
  :func:`scope`/:func:`request_scope`); worker threads that process
  requests on behalf of other threads (the micro-batcher, the decode
  batcher) capture :func:`current_context` at enqueue time and re-attach
  it to the events they emit, so one request ID joins gateway admission
  → batcher queue → coalesced compute → response.

* **Event journal** — a lock-cheap bounded ring of typed events
  (:class:`EventJournal`).  :func:`emit` appends one dict (type,
  severity, wall timestamp, thread, the current trace context, plus the
  caller's fields) under a single uncontended lock; the ring drops the
  oldest event past ``capacity`` so a journal can run forever.  Event
  type names are the taxonomy in :data:`EVENT_TYPES`, linted against
  the docs/OBSERVABILITY.md catalog in both directions (DL4J303/304).

* **Chrome trace export** — :func:`chrome_trace` renders journal events
  as Chrome trace-event JSON (Perfetto-loadable: open
  https://ui.perfetto.dev and drop the file): ``span.close`` events
  become complete ("X") slices with real durations, everything else
  becomes instant ("i") marks, correlation fields ride in ``args``.

``DL4J_JOURNAL=0`` is the kill switch: :func:`emit` returns immediately
— events become no-ops, not queued.  ``DL4J_JOURNAL_CAPACITY`` sizes
the ring (default 2048).  The overhead A/B lever for benchmarks is
:func:`set_enabled` (``bench_serving`` reports ``journal_overhead_pct``,
required ≤ 5%).
"""

from __future__ import annotations

import contextvars
import itertools
import os
import threading
import time
import uuid
from collections import deque
from typing import Dict, List, Optional

#: The event taxonomy — every ``emit()`` call site in the framework uses
#: one of these names, and docs/OBSERVABILITY.md catalogs each of them
#: (dl4j-lint DL4J303/304 fail on drift in either direction).
EVENT_TYPES = (
    "span.open",
    "span.close",
    "rpc.request",
    "rpc.response",
    "request.admitted",
    "request.enqueued",
    "request.done",
    "request.shed",
    "batch.dispatch",
    "batcher.died",
    "batcher.restarted",
    "decode.step",
    "decode.spec_verified",
    "decode.arena_alloc_failed",
    "decode.session_opened",
    "decode.session_closed",
    "decode.session_exported",
    "decode.session_imported",
    "decode.session_reinstated",
    "decode.drain",
    "decode.resumed",
    "decode.died",
    "decode.restarted",
    "fleet.replica_added",
    "fleet.replica_removed",
    "fleet.replica_health",
    "fleet.migrated",
    "fleet.migrate_failed",
    "fleet.rollout",
    "slo.state_changed",
    "slo.replica_parked",
    "slo.alert_delivered",
    "dist.worker_joined",
    "dist.worker_active",
    "dist.worker_suspect",
    "dist.worker_dead",
    "dist.generation_rolled",
    "dist.step_fenced",
    "dist.snapshot_transferred",
    "dist.snapshot_restored",
    "dist.heartbeat_lost",
    "cache.load",
    "cache.evicted",
    "rollout.flip",
    "rollout.failed",
    "fault.injected",
    "breaker.transition",
    "checkpoint.write",
    "checkpoint.fallback",
    "checkpoint.restored",
    "fit.start",
    "fit.end",
    "compile.retrace",
    "sanitizer.violation",
    "readyz.flip",
    "flight.dump",
    "ui.stats_posted",
)

SEVERITIES = ("info", "warn", "error")

DEFAULT_CAPACITY = 2048

_flags = {"enabled": None}

#: per-task/thread correlation fields; never mutated in place — scopes
#: push merged copies so concurrent readers see a consistent dict
_ctx: contextvars.ContextVar[Optional[dict]] = contextvars.ContextVar(
    "dl4j_trace_ctx", default=None)


# ----------------------------------------------------------------------
# Kill switch
# ----------------------------------------------------------------------
# parsed-env cache: os.environ.get is an encode/decode MutableMapping
# hop (~µs), and enabled() runs on every emit and every span — the env
# is read once and re-read only after set_enabled() resets the cache
_env_cache: Dict[str, Optional[bool]] = {"enabled": None, "verbose": None}


def set_enabled(on: Optional[bool]) -> None:
    """Force the journal on/off; ``None`` restores the env default
    (``DL4J_JOURNAL``, re-read from the environment) — the bench A/B
    lever, mirroring ``tracing.set_enabled``."""
    _flags["enabled"] = None if on is None else bool(on)
    _env_cache["enabled"] = None
    _env_cache["verbose"] = None


def enabled() -> bool:
    on = _flags["enabled"]
    if on is not None:
        return on
    on = _env_cache["enabled"]
    if on is None:
        on = _env_cache["enabled"] = \
            os.environ.get("DL4J_JOURNAL", "1") != "0"
    return on


def verbose() -> bool:
    """``DL4J_JOURNAL_VERBOSE=1`` adds the high-volume event forms
    (``span.open``, per-request ``request.enqueued``/``request.done``)
    for fine-grained debugging; off by default to hold the always-on
    journal under the serving overhead budget."""
    if not enabled():
        return False
    v = _env_cache["verbose"]
    if v is None:
        v = _env_cache["verbose"] = \
            os.environ.get("DL4J_JOURNAL_VERBOSE") == "1"
    return v


# ----------------------------------------------------------------------
# Trace context
# ----------------------------------------------------------------------
# one random process prefix + a GIL-atomic counter: minting an ID is
# ~20x cheaper than uuid4() (an os.urandom syscall per request would be
# a measurable slice of a sub-millisecond predict), still unique across
# processes and unguessable enough for correlation purposes
_RID_PREFIX = uuid.uuid4().hex[:8]
_RID_SEQ = itertools.count(1)


def new_request_id() -> str:
    """Mint a correlation ID (gateway RPCs, fit runs)."""
    return f"{_RID_PREFIX}{next(_RID_SEQ):08x}"


def current_context() -> dict:
    """The correlation fields in scope on this thread/task (a copy)."""
    cur = _ctx.get()
    return dict(cur) if cur else {}


class _Scope:
    """Hand-rolled context manager (not ``@contextmanager``): scopes sit
    on the per-request hot path, and a slotted object with plain
    ``__enter__``/``__exit__`` skips the generator machinery."""

    __slots__ = ("_fields", "_result", "_token")

    def __init__(self, fields: dict, result=None):
        self._fields = fields
        self._result = result

    def __enter__(self):
        cur = _ctx.get()
        merged = dict(cur) if cur else {}
        for k, v in self._fields.items():
            if v is not None:
                merged[k] = v
        self._token = _ctx.set(merged)
        return self._result if self._result is not None else merged

    def __exit__(self, *exc):
        _ctx.reset(self._token)
        return False


def scope(**fields) -> _Scope:
    """Push correlation fields for the duration of the block; ``None``
    values are dropped, nested scopes merge (inner wins).  Every
    :func:`emit` inside the block carries the merged fields."""
    return _Scope(fields)


def request_scope(tenant: Optional[str] = None, **fields) -> _Scope:
    """Enter (or continue) a request scope: reuses the request ID the
    HTTP server already minted for this RPC, mints one for direct
    (in-process) entry-point calls, and yields it — so bench harnesses
    and tests calling ``DeepLearning4jEntryPoint`` without a ``Server``
    still get correlated events."""
    cur = _ctx.get()
    rid = (cur.get("request_id") if cur else None) or new_request_id()
    fields["request_id"] = rid
    fields["tenant"] = tenant
    return _Scope(fields, result=rid)


# ----------------------------------------------------------------------
# The journal
# ----------------------------------------------------------------------
class Event(tuple):
    """One journal record: a 7-tuple of ``(type, severity, ts, tid,
    seq, ctx, fields)``.  The emit path stores REFERENCES — the scope's
    context dict (scopes build fresh merged dicts and never mutate them
    in place, so a captured reference is stable) and the caller's
    kwargs dict — and the flat dict form is materialized only when
    something reads the journal (tail/export/dump).  A tuple subclass
    keeps emitting a single ``BUILD_TUPLE`` + C allocation instead of
    an object construction plus seven attribute stores: this is the
    hottest line in the serving path's instrumentation."""

    __slots__ = ()

    type = property(lambda self: self[0])
    severity = property(lambda self: self[1])
    ts = property(lambda self: self[2])
    tid = property(lambda self: self[3])
    seq = property(lambda self: self[4])
    ctx = property(lambda self: self[5])
    fields = property(lambda self: self[6])

    def to_dict(self) -> dict:
        ev = {"type": self[0], "severity": self[1],
              "ts": self[2], "tid": self[3]}
        if self[5]:
            ev.update(self[5])
        for k, v in self[6].items():
            if v is not None:
                ev[k] = v
        ev["seq"] = self[4]
        return ev

    def get(self, key, default=None):
        ev = self.to_dict()
        v = ev.get(key, default)
        return v if v is not None else default


_EVENT = Event   # local alias: one global load on the emit hot path


class EventJournal:
    """Bounded lock-free ring of event dicts.  ``deque(maxlen=).append``
    and ``list(deque)`` are single C calls — atomic under the GIL — so
    the emit path takes NO lock of its own: one dict build, one atomic
    sequence bump, one atomic append, one cached per-type counter inc.
    Concurrent emitters never contend on a journal lock (the serving
    path has 8+ threads emitting against one batcher), and a snapshot
    can never observe a torn ring."""

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            try:
                capacity = int(os.environ.get("DL4J_JOURNAL_CAPACITY",
                                              str(DEFAULT_CAPACITY)))
            except ValueError:
                capacity = DEFAULT_CAPACITY
        self.capacity = max(16, int(capacity))
        self._ring: deque = deque(maxlen=self.capacity)
        self._seq = itertools.count(1)   # next() is GIL-atomic
        self._last_seq = 0
        # per-type counts are plain dict bumps published to the registry
        # at SCRAPE time by the collector below: a per-emit labels()+inc
        # would pay two lock rounds on every event (a rare lost bump in
        # a diagnostic counter is an acceptable trade for a lock-free
        # hot path)
        self._type_counts: Dict[str, int] = {}
        self._published: Dict[str, int] = {}
        try:
            from deeplearning4j_tpu.monitor.registry import get_registry
            get_registry().register_collector(self._publish_counts)
        except Exception:
            pass  # a journal without exposition still journals

    def _publish_counts(self, reg) -> None:
        """Scrape-time collector: advance the registry counter by what
        accumulated since the last snapshot."""
        fam = reg.counter("dl4j_journal_events_total",
                          "structured journal events emitted, by type",
                          labels=("type",))
        for etype, n in list(self._type_counts.items()):
            last = self._published.get(etype, 0)
            if n > last:
                fam.labels(type=etype).inc(n - last)
                self._published[etype] = n

    def emit(self, etype: str, severity: str = "info",
             **fields) -> Optional[Event]:
        """Append one event (no-op returning None when the journal is
        disabled).  The current trace context merges in under the
        caller's explicit fields (explicit wins) when the event is
        read back."""
        # enabled() inlined: this is THE hot path, every call counts
        on = _flags["enabled"]
        if on is None:
            on = _env_cache["enabled"]
            if on is None:
                on = _env_cache["enabled"] = \
                    os.environ.get("DL4J_JOURNAL", "1") != "0"
        if not on:
            return None
        seq = self._last_seq = next(self._seq)
        e = _EVENT((etype, severity, time.time(), threading.get_ident(),
                    seq, _ctx.get(), fields))
        self._ring.append(e)
        tc = self._type_counts
        tc[etype] = tc.get(etype, 0) + 1
        return e

    def tail(self, n: Optional[int] = None, etype: Optional[str] = None,
             request_id: Optional[str] = None,
             severity: Optional[str] = None) -> List[dict]:
        """The newest events as flat dicts, oldest-first — optionally
        filtered by type, correlation ID, or minimum severity."""
        raw = list(self._ring)   # one C call: atomic vs appends
        if etype is not None:
            raw = [e for e in raw if e.type == etype]
        if severity is not None:
            floor = SEVERITIES.index(severity)
            raw = [e for e in raw
                   if SEVERITIES.index(e.severity) >= floor]
        out = [e.to_dict() for e in raw]
        if request_id is not None:
            out = [e for e in out
                   if e.get("request_id") == request_id
                   or request_id in (e.get("request_ids") or ())]
        if n is not None:
            out = out[-int(n):]
        return out

    @property
    def total_emitted(self) -> int:
        return self._last_seq

    @property
    def dropped(self) -> int:
        """Events that have already rotated out of the ring."""
        return max(0, self._last_seq - len(self._ring))

    def clear(self) -> None:
        self._ring.clear()


_JOURNAL = EventJournal()


def get_journal() -> EventJournal:
    """THE process-wide journal — serving, decode, fit, resilience and
    the flight recorder all read/write this one instance."""
    return _JOURNAL


# the module-level form every instrumented call site uses: a direct
# bound-method reference, so the hot path pays no wrapper frame and no
# kwargs re-packing
emit = _JOURNAL.emit


# ----------------------------------------------------------------------
# Chrome trace-event export (Perfetto-loadable)
# ----------------------------------------------------------------------
_META_KEYS = ("type", "severity", "ts", "tid", "seq")


def _chrome_entries(events: List[dict], pid: int) -> tuple:
    """(trace entries, tids seen) for one process lane — the shared
    conversion: ``span.close`` → complete ("X") slices placed at their
    start time, everything else → instant ("i") marks, correlation
    fields in ``args``."""
    out: List[dict] = []
    tids: dict = {}
    for e in events:
        tid = e.get("tid", 0)
        tids.setdefault(tid, None)
        args = {k: v for k, v in e.items() if k not in _META_KEYS}
        ts_us = float(e.get("ts", 0.0)) * 1e6
        if e.get("type") == "span.close" and "duration_s" in e:
            dur_us = max(0.0, float(e["duration_s"]) * 1e6)
            name = e.get("span", "span")
            if e.get("phase"):
                name = f"{name}/{e['phase']}"
            out.append({"name": name, "cat": "span", "ph": "X",
                        "ts": ts_us - dur_us, "dur": dur_us,
                        "pid": pid, "tid": tid, "args": args})
        else:
            out.append({"name": e.get("type", "event"),
                        "cat": str(e.get("type", "event")).split(".")[0],
                        "ph": "i", "s": "t", "ts": ts_us,
                        "pid": pid, "tid": tid, "args": args})
    return out, tids


def chrome_trace(events: Optional[List[dict]] = None) -> dict:
    """Render journal events as a Chrome trace-event JSON object
    (https://ui.perfetto.dev loads it directly; ``chrome://tracing``
    too).  ``span.close`` events become complete ("X") slices placed at
    their start time with their measured duration; every other event is
    an instant ("i") mark.  Correlation fields (request_id, session_id,
    tenant, ...) ride in ``args`` so a slice can be found by searching
    for its request ID."""
    if events is None:
        events = get_journal().tail()
    pid = os.getpid()
    out, tids = _chrome_entries(events, pid)
    meta = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": "deeplearning4j_tpu"}}]
    for tid in tids:
        meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": tid, "args": {"name": f"thread-{tid}"}})
    return {"traceEvents": meta + out, "displayTimeUnit": "ms"}


def _match_request_id(e: dict, request_id: str) -> bool:
    return (e.get("request_id") == request_id
            or request_id in (e.get("request_ids") or ()))


def chrome_trace_fleet(events_by_process: Dict[str, List[dict]],
                       request_id: Optional[str] = None) -> dict:
    """ONE Perfetto-loadable Chrome trace over several processes'
    journal events — the fleet-trace assembly (docs/OBSERVABILITY.md
    "Fleet federation & SLOs").  Each source (the router, each replica)
    becomes its own process lane (``pid`` 1..N, named by its key), so a
    migrated decode stream reads as one timeline: its `decode.step`
    events appear in the source replica's lane, the `fleet.migrated`
    hop in the router's, and the continuation in the target's — all
    correlated by the session/request IDs in ``args``.  Wall-clock
    timestamps are emitted as-is; replicas on one host share a clock,
    cross-host skew shows as lane offset (documented caveat)."""
    meta: List[dict] = []
    out: List[dict] = []
    for pid, pname in enumerate(sorted(events_by_process), 1):
        evts = events_by_process[pname]
        if request_id is not None:
            evts = [e for e in evts if _match_request_id(e, request_id)]
        entries, tids = _chrome_entries(evts, pid)
        meta.append({"name": "process_name", "ph": "M", "pid": pid,
                     "tid": 0, "args": {"name": pname}})
        for tid in tids:
            meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                         "tid": tid, "args": {"name": f"thread-{tid}"}})
        out.extend(entries)
    return {"traceEvents": meta + out, "displayTimeUnit": "ms"}
