"""Host/device memory telemetry — the one implementation both the UI's
StatsListener and the ``/metrics`` scrape read, so their numbers agree
(the reference reports JVM+off-heap memory per iteration,
ref: ui/stats/BaseStatsListener.java memory section; here it's host RSS
plus per-device bytes-in-use from ``jax.local_devices()``
``memory_stats()`` where the backend exposes them — TPU/GPU do, CPU
usually doesn't)."""

from __future__ import annotations

from typing import Dict, Optional

from deeplearning4j_tpu.monitor.registry import MetricsRegistry, get_registry


def memory_snapshot(registry: Optional[MetricsRegistry] = None
                    ) -> Dict[str, float]:
    """``{"host_rss_mb": ..., "device<N>_mb": ...}`` — also mirrored
    into the registry gauges ``dl4j_host_rss_mb`` and
    ``dl4j_device_memory_mb{device=...}``.  Every source is best-effort:
    a backend without memory_stats just contributes nothing."""
    reg = registry if registry is not None else get_registry()
    out: Dict[str, float] = {}
    try:
        import resource
        rss_mb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0
        out["host_rss_mb"] = rss_mb
        reg.gauge("dl4j_host_rss_mb", "host max RSS (MB)").set(rss_mb)
    except Exception:
        pass
    try:
        import jax
        g = reg.gauge("dl4j_device_memory_mb",
                      "per-device bytes in use (MB)", labels=("device",))
        for d in jax.local_devices():
            try:
                ms = d.memory_stats()
            except Exception:
                ms = None
            if ms and "bytes_in_use" in ms:
                mb = ms["bytes_in_use"] / (1024.0 * 1024.0)
                out[f"device{d.id}_mb"] = mb
                g.labels(device=str(d.id)).set(mb)
    except Exception:
        pass
    return out


def memory_collector(registry: MetricsRegistry) -> None:
    """Scrape-time collector form (``registry.register_collector``):
    refreshes the memory gauges right before every snapshot."""
    memory_snapshot(registry)
