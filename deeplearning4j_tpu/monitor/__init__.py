"""Unified observability backbone: metrics registry + step-phase
tracing + Prometheus/JSON exposition.

The reproduction's telemetry surfaces — ``CompileTelemetry`` retrace
counts (ops/bucketing.py), serving latency reservoirs
(server/batcher.py), model-cache counters (server/model_cache.py), the
UI's per-iteration stats (ui/stats_listener.py) — all meter into ONE
process-wide :class:`~deeplearning4j_tpu.monitor.registry.MetricsRegistry`,
and the training/serving hot paths are phase-annotated with
:func:`~deeplearning4j_tpu.monitor.tracing.span`, so a single scrape
(the gateway's ``metrics`` RPC / ``GET /metrics``) answers both "what is
the system doing" and "where does a step spend its time".

    from deeplearning4j_tpu import monitor

    with monitor.span("fit/step", phase="h2d"):
        x = jax.device_put(x)

    text = monitor.render_prometheus(monitor.get_registry().snapshot())

A third surface rides the same package: the **structured event
journal** (``monitor/events.py`` — a bounded ring of typed events with
request/session correlation IDs carried by contextvars) and the
**flight recorder** (``monitor/flight.py`` — crash handlers dump the
journal tail plus a registry snapshot to a timestamped JSON file;
``GET /trace`` / the ``trace_dump`` RPC serve the live journal and its
Chrome trace-event export).

Env knobs: ``DL4J_PROFILE=<dir>`` wraps every fit in
``jax.profiler.start_trace``; ``DL4J_TRACE_ANNOTATIONS=1`` mirrors
spans into XLA profiler dumps; ``DL4J_SPANS=0`` disables span timing;
``DL4J_JOURNAL=0`` disables the event journal; ``DL4J_FLIGHT_DIR``
places flight-recorder dumps.  Full catalog: docs/OBSERVABILITY.md.
"""

from deeplearning4j_tpu.monitor import events, flight  # noqa: F401
from deeplearning4j_tpu.monitor.events import (  # noqa: F401
    EventJournal, chrome_trace, chrome_trace_fleet, get_journal,
    new_request_id, request_scope)
from deeplearning4j_tpu.monitor.registry import (  # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry, get_registry)
from deeplearning4j_tpu.monitor.tracing import (  # noqa: F401
    Span, current, enable_jax_annotations, profile_if_configured, span)
from deeplearning4j_tpu.monitor.exposition import (  # noqa: F401
    CONTENT_TYPE, merge_snapshots, parse_prometheus, render_json,
    render_prometheus, snapshot_from_parsed, summarize)
from deeplearning4j_tpu.monitor.system import (  # noqa: F401
    memory_collector, memory_snapshot)

# Device/host memory is only knowable at scrape time — refresh it on
# every snapshot of the process registry.
get_registry().register_collector(memory_collector)


def record_fit_step(batch_size: int, seconds: float,
                    score=None, registry=None) -> None:
    """Per-step training gauges shared by MultiLayerNetwork and
    ComputationGraph (and read back by ui/stats_listener.py, so the UI
    and ``/metrics`` report the same numbers)."""
    reg = registry if registry is not None else get_registry()
    reg.counter("dl4j_fit_iterations_total",
                "training iterations completed").inc()
    reg.histogram("dl4j_fit_step_seconds",
                  "full train-step wall time (seconds)").observe(seconds)
    if seconds > 0:
        reg.gauge("dl4j_fit_examples_per_sec",
                  "training throughput, last step").set(batch_size / seconds)
    reg.gauge("dl4j_fit_last_step_ms",
              "last train-step wall time (ms)").set(seconds * 1e3)
    if score is not None:
        try:
            reg.gauge("dl4j_fit_score", "last training score").set(
                float(score))
        except (TypeError, ValueError):
            pass
