"""Black-box flight recorder — post-mortem state for crashes that
aggregate metrics can't explain.

When something dies (a killed micro-batcher or decode thread, a
:class:`SanitizerError`, a resume-from-checkpoint after a crash, the
gateway's ``/readyz`` flipping to not-ready), :func:`dump` writes a
timestamped JSON file capturing the last-N structured journal events
(``monitor/events.py`` — what happened in the seconds before, with
request/session correlation IDs), a full metrics-registry snapshot
(what the counters said at that instant), and the caller's extra
context.  The file is the serving analog of a core dump: small, always
writable, and readable without the process that produced it.

Files land under ``DL4J_FLIGHT_DIR`` (default ``dl4j_flight/`` in the
working directory) as ``flight_<reason>_<UTC timestamp>_<pid>_<n>.json``
written atomically (tmp + rename).  Dumps are rate-limited per reason
(``DL4J_FLIGHT_MIN_INTERVAL_S``, default 5s) so a crash loop cannot
fill the disk; ``force=True`` bypasses the limit.  ``DL4J_FLIGHT=0``
disables dumping entirely.  Every dump is itself journaled
(``flight.dump``) and counted (``dl4j_flight_dumps_total{reason=}``).

Live access without a crash: the gateway's ``GET /trace`` endpoint and
``trace_dump`` RPC serve the same journal tail (and its Chrome
trace-event export) over HTTP — docs/OBSERVABILITY.md "Tracing &
flight recorder".
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import List, Optional

from deeplearning4j_tpu.monitor import events

DEFAULT_DIR = "dl4j_flight"
DEFAULT_MIN_INTERVAL_S = 5.0
DEFAULT_LAST_N = 512

_lock = threading.Lock()
_last_dump = {}   # reason -> monotonic time of last dump
_dump_count = 0


def enabled() -> bool:
    return os.environ.get("DL4J_FLIGHT", "1") != "0"


def flight_dir() -> str:
    return os.environ.get("DL4J_FLIGHT_DIR", DEFAULT_DIR)


def _min_interval_s() -> float:
    try:
        return float(os.environ.get("DL4J_FLIGHT_MIN_INTERVAL_S",
                                    str(DEFAULT_MIN_INTERVAL_S)))
    except ValueError:
        return DEFAULT_MIN_INTERVAL_S


def _count_dump(reason: str) -> None:
    try:
        from deeplearning4j_tpu.monitor.registry import get_registry
        get_registry().counter(
            "dl4j_flight_dumps_total",
            "flight-recorder dump files written, by trigger",
            labels=("reason",)).labels(reason=reason).inc()
    except Exception:
        pass


def dump(reason: str, extra: Optional[dict] = None,
         last_n: int = DEFAULT_LAST_N, force: bool = False,
         directory: Optional[str] = None) -> Optional[str]:
    """Write one flight-recorder file and return its path (None when
    disabled, rate-limited, or the write itself failed — a recorder
    must never take the crashing process further down).

    The payload schema (versioned, docs/OBSERVABILITY.md):

    * ``reason`` / ``time`` / ``unix_ts`` / ``pid`` — what and when;
    * ``context`` — the trace context of the dumping thread (request
      ID, session ID, tenant when the crash happened on a request);
    * ``events`` — the newest ``last_n`` journal events, oldest-first;
    * ``registry`` — the full metrics-registry snapshot;
    * ``extra`` — caller-provided detail (stranded request IDs, the
      failing check set, ...).
    """
    if not enabled():
        return None
    global _dump_count
    now = time.monotonic()
    with _lock:
        if not force:
            last = _last_dump.get(reason)
            if last is not None and now - last < _min_interval_s():
                return None
        _last_dump[reason] = now
        _dump_count += 1
        n = _dump_count
    try:
        evts = events.get_journal().tail(last_n)
        try:
            from deeplearning4j_tpu.monitor.registry import get_registry
            registry = get_registry().snapshot()
        except Exception:
            registry = {}
        payload = {
            "schema": 1,
            "reason": reason,
            "time": time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime()),
            "unix_ts": time.time(),
            "pid": os.getpid(),
            "context": events.current_context(),
            "n_events": len(evts),
            "events": evts,
            "registry": registry,
            "extra": extra or {},
        }
        d = directory or flight_dir()
        os.makedirs(d, exist_ok=True)
        stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
        safe = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in reason)
        path = os.path.join(
            d, f"flight_{safe}_{stamp}_{os.getpid()}_{n}.json")
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=1, default=str)
        os.replace(tmp, path)
    except Exception:
        return None
    _count_dump(reason)
    events.emit("flight.dump", severity="warn", reason=reason, path=path)
    return path


def list_dumps(directory: Optional[str] = None) -> List[str]:
    """Existing dump files, oldest-first (by mtime — filenames sort by
    reason, not by time)."""
    d = directory or flight_dir()
    if not os.path.isdir(d):
        return []
    paths = [os.path.join(d, f) for f in os.listdir(d)
             if f.startswith("flight_") and f.endswith(".json")]
    return sorted(paths, key=lambda p: (os.path.getmtime(p), p))
