"""Metrics federation — one pane of glass over an N-replica fleet
(docs/OBSERVABILITY.md "Fleet federation & SLOs").

Each gateway replica owns a process-wide registry and serves it at
``GET /metrics``; before this module an operator of an N-replica fleet
scraped (and eyeballed) N endpoints.  :class:`MetricsFederation` pulls
each replica's text-format scrape through the existing
``exposition.parse_prometheus`` parser, keeps the parsed families
per replica, and merges them into ONE snapshot-shaped dict
(``exposition.merge_snapshots``): counters and histogram buckets sum
into fleet totals, gauges keep one sample per replica under a
``replica`` label.  The fleet router serves the merge at
``GET /metrics?scope=fleet`` (and the ``metrics`` RPC with
``scope="fleet"``) next to its own ``dl4j_router_*``/``dl4j_fleet_*``
families.

**Staleness is explicit**: a dead replica's LAST successful scrape
stays in the merge (its series would otherwise silently vanish from
dashboards), and ``dl4j_federation_scrape_age_seconds{replica=}``
says exactly how old each replica's contribution is — a frozen counter
with a growing age is a dead replica, not a quiet one.  Scrape
attempts are counted per outcome in
``dl4j_federation_scrapes_total{replica,outcome}``.

The module is transport-agnostic: ``scrape()`` takes
``{name: fetch_fn}`` where each ``fetch_fn() -> str`` returns one
Prometheus text body.  The fleet tier supplies fetchers built on
``ReplicaClient.get_text`` (fleet/router.py); tests feed canned text.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from deeplearning4j_tpu.monitor.exposition import (
    merge_snapshots, parse_prometheus, snapshot_from_parsed)
from deeplearning4j_tpu.monitor.registry import get_registry


class MetricsFederation:
    """Scrape-state store + merger for one fleet's replicas."""

    def __init__(self, registry=None):
        self._registry = registry if registry is not None else get_registry()
        self._lock = threading.Lock()
        #: name -> {"snapshot", "ts" (last OK wall time), "ok", "error"}
        self._scrapes: Dict[str, dict] = {}
        self._last_attempt: Optional[float] = None
        self._g_age = self._registry.gauge(
            "dl4j_federation_scrape_age_seconds",
            "age of each replica's last successful /metrics scrape — a "
            "growing age means that replica's federated series are "
            "stale, not current", ("replica",))
        self._c_scrapes = self._registry.counter(
            "dl4j_federation_scrapes_total",
            "federation scrape attempts per replica, by outcome",
            ("replica", "outcome"))

    # ------------------------------------------------------------------
    # Scraping
    # ------------------------------------------------------------------
    def scrape(self, sources: Dict[str, Callable[[], str]]) -> Dict[str, bool]:
        """Fetch + parse every source's Prometheus text.  A fetch or
        parse failure KEEPS the replica's previous snapshot (visibly
        stale via the age gauge) and records the error; a replica no
        longer in ``sources`` is dropped from the merge entirely.
        Returns ``{name: ok}``."""
        results: Dict[str, bool] = {}
        now = time.time()
        for name, fetch in sources.items():
            try:
                snap = snapshot_from_parsed(parse_prometheus(fetch()))
                ok, err = True, None
            except Exception as e:
                snap, ok = None, False
                err = f"{type(e).__name__}: {e}"
            with self._lock:
                self._last_attempt = now
                cur = self._scrapes.get(name)
                if ok:
                    self._scrapes[name] = {"snapshot": snap, "ts": now,
                                           "ok": True, "error": None}
                elif cur is not None:
                    cur["ok"] = False
                    cur["error"] = err
                else:
                    self._scrapes[name] = {"snapshot": None, "ts": None,
                                           "ok": False, "error": err}
            self._c_scrapes.labels(
                replica=name, outcome="ok" if ok else "error").inc()
            results[name] = ok
        with self._lock:
            for name in list(self._scrapes):
                if name not in sources:
                    del self._scrapes[name]
        self._refresh_ages()
        return results

    def last_scrape_age(self) -> Optional[float]:
        """Seconds since the last scrape ATTEMPT (None = never) — the
        on-demand-refresh freshness check for ``?scope=fleet``."""
        with self._lock:
            t = self._last_attempt
        return None if t is None else max(0.0, time.time() - t)

    def _refresh_ages(self) -> None:
        now = time.time()
        with self._lock:
            items = [(n, s["ts"]) for n, s in self._scrapes.items()]
        for name, ts in items:
            if ts is not None:
                self._g_age.labels(replica=name).set(round(now - ts, 3))

    # ------------------------------------------------------------------
    # Reading
    # ------------------------------------------------------------------
    def replica_snapshots(self) -> Dict[str, dict]:
        """Each replica's last successfully parsed snapshot (the
        per-replica SLO evaluation input)."""
        with self._lock:
            return {n: s["snapshot"] for n, s in self._scrapes.items()
                    if s["snapshot"] is not None}

    def status(self) -> Dict[str, dict]:
        now = time.time()
        with self._lock:
            return {n: {"ok": s["ok"], "error": s["error"],
                        "age_s": (None if s["ts"] is None
                                  else round(now - s["ts"], 3))}
                    for n, s in self._scrapes.items()}

    def merged(self, local_name: Optional[str] = "router") -> Dict[str, dict]:
        """The federated snapshot: every replica's last parse plus (by
        default) the local process registry under ``local_name`` — so a
        fleet scrape carries the router's own ``dl4j_router_*`` /
        ``dl4j_fleet_*`` / federation-staleness families alongside the
        replicas'.  Ages are refreshed first, so the rendered
        ``dl4j_federation_scrape_age_seconds`` is current as of THIS
        merge."""
        self._refresh_ages()
        sources = self.replica_snapshots()
        if local_name is not None:
            sources[local_name] = self._registry.snapshot()
        return merge_snapshots(sources)
