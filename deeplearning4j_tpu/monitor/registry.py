"""Process-wide, thread-safe metrics registry.

The framework's telemetry used to be fragmented — ``CompileTelemetry``
(ops/bucketing.py), ``LatencyHistogram`` (nn/listeners.py) and
``ui/stats_listener.py`` each kept private counters with no shared
surface and no exposition endpoint.  This registry is the one place all
of them land (the observability analog of the reference's StatsStorage
feeding the UI, ref: ui/stats/BaseStatsListener.java): ``Counter``,
``Gauge`` and ``Histogram`` families with labels, a ``snapshot()`` dict
any renderer can walk (``monitor/exposition.py`` turns it into
Prometheus text-format v0.0.4 or JSON), and scrape-time collectors for
values that are only known at read time (device memory).

Histograms are fixed log-bucket counts PLUS reservoir percentiles:
the bucket counts make the metric a real Prometheus histogram
(aggregatable across processes), while the embedded
``nn/listeners.LatencyHistogram`` reservoir gives exact-ish p50/p95/p99
without a scrape-side quantile engine — the same estimator the serving
stats RPC always reported.
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

# Log-ish ladder from 0.5 ms to 30 s — the latency range a training step
# or serving request plausibly spans (Prometheus-default style).
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

_NO_LABELS: Tuple[str, ...] = ()


def _label_values(label_names: Sequence[str], kv: Dict[str, str]) -> Tuple:
    if set(kv) != set(label_names):
        raise ValueError(f"labels {sorted(kv)} != declared "
                         f"{sorted(label_names)}")
    return tuple(str(kv[k]) for k in label_names)


class _CounterChild:
    __slots__ = ("_lock", "value")

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self.value += n

    def sample(self) -> dict:
        return {"value": self.value}


class _GaugeChild:
    __slots__ = ("_lock", "value")

    def __init__(self):
        self._lock = threading.Lock()
        self.value: Optional[float] = None

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value = (self.value or 0.0) + n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    def sample(self) -> dict:
        return {"value": self.value if self.value is not None else 0.0}


class _HistogramChild:
    """Fixed-bucket counts + a LatencyHistogram reservoir for
    percentiles.  ``observe``/``record`` are synonyms so the serving
    stack's existing ``LatencyHistogram.record`` call sites drop in."""

    __slots__ = ("_lock", "buckets", "_counts", "reservoir")

    def __init__(self, buckets: Sequence[float]):
        # lazy import: monitor must stay importable mid-way through the
        # package __init__ chain (ops/bucketing imports monitor while
        # deeplearning4j_tpu/__init__ is still importing nn.multilayer)
        from deeplearning4j_tpu.nn.listeners import LatencyHistogram
        self._lock = threading.Lock()
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # last = +Inf overflow
        self.reservoir = LatencyHistogram()

    def observe(self, v: float) -> None:
        v = float(v)
        self.reservoir.record(v)
        with self._lock:
            self._counts[bisect_left(self.buckets, v)] += 1

    record = observe  # LatencyHistogram call-site compatibility

    def sample(self) -> dict:
        res = self.reservoir
        with self._lock:
            counts = list(self._counts)
        with res._lock:
            count, total, mx = res.count, res.total, res.max
        cum, buckets = 0, {}
        for b, c in zip(self.buckets, counts):
            cum += c
            buckets[repr(b)] = cum
        buckets["+Inf"] = count
        return {
            "count": count,
            "sum": total,
            "max": mx if count else None,
            "buckets": buckets,
            "p50": res.percentile(0.50),
            "p95": res.percentile(0.95),
            "p99": res.percentile(0.99),
        }

    def latency_snapshot(self) -> dict:
        """The serving stats RPC's legacy ``*_ms`` dict shape."""
        return self.reservoir.snapshot()


class _Family:
    """One metric family: name + help + label names + children keyed by
    label values.  ``labels(**kv)`` get-or-creates a child; the no-label
    convenience methods (inc/set/observe) proxy to the unlabeled child."""

    kind = "untyped"
    _child_cls: Any = None

    def __init__(self, name: str, help: str,
                 label_names: Sequence[str] = _NO_LABELS, **opts):
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._opts = opts
        self._lock = threading.Lock()
        self._children: Dict[Tuple, Any] = {}

    def _make_child(self):
        return self._child_cls()

    def labels(self, **kv):
        key = _label_values(self.label_names, kv)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._make_child()
                self._children[key] = child
            return child

    def _default_child(self):
        if self.label_names:
            raise ValueError(f"{self.name} has labels "
                             f"{self.label_names}; use .labels(...)")
        return self.labels()

    def samples(self) -> List[dict]:
        with self._lock:
            items = list(self._children.items())
        return [{"labels": dict(zip(self.label_names, key)),
                 **child.sample()} for key, child in items]

    def describe(self) -> dict:
        return {"type": self.kind, "help": self.help,
                "label_names": list(self.label_names),
                "samples": self.samples()}


class Counter(_Family):
    kind = "counter"
    _child_cls = _CounterChild

    def inc(self, n: float = 1.0) -> None:
        self._default_child().inc(n)

    @property
    def value(self) -> float:
        return self._default_child().value


class Gauge(_Family):
    kind = "gauge"
    _child_cls = _GaugeChild

    def set(self, v: float) -> None:
        self._default_child().set(v)

    def inc(self, n: float = 1.0) -> None:
        self._default_child().inc(n)

    @property
    def value(self) -> Optional[float]:
        return self._default_child().value


class Histogram(_Family):
    kind = "histogram"

    def _make_child(self):
        return _HistogramChild(self._opts.get("buckets") or DEFAULT_BUCKETS)

    def observe(self, v: float) -> None:
        self._default_child().observe(v)


class MetricsRegistry:
    """Thread-safe family store.  ``counter``/``gauge``/``histogram``
    get-or-create (re-declaring with a different type raises — the usual
    copy-paste bug); collectors run at ``snapshot()`` time for values
    only known at scrape (device memory, cache residency)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}
        self._collectors: List[Callable[["MetricsRegistry"], None]] = []

    def _get_or_create(self, cls, name: str, help: str,
                       labels: Sequence[str], **opts):
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = cls(name, help, labels, **opts)
                self._families[name] = fam
            elif not isinstance(fam, cls):
                raise ValueError(f"{name} already registered as {fam.kind}")
            return fam

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = _NO_LABELS) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = _NO_LABELS) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = _NO_LABELS,
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get_or_create(Histogram, name, help, labels,
                                   buckets=buckets)

    def get(self, name: str) -> Optional[_Family]:
        with self._lock:
            return self._families.get(name)

    def register_collector(
            self, fn: Callable[["MetricsRegistry"], None]) -> None:
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def snapshot(self) -> Dict[str, dict]:
        """{family_name: {type, help, label_names, samples: [...]}} —
        the contract every renderer (exposition.py), the gateway stats
        RPC and bench.py's summary walk.  Collector failures are
        swallowed: a scrape must never take the server down."""
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn(self)
            except Exception:
                pass
        with self._lock:
            families = sorted(self._families.items())
        return {name: fam.describe() for name, fam in families}


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """THE process-wide registry — train, serving, UI and bench all
    meter into this one instance so a single scrape sees everything."""
    return _REGISTRY
