"""Exposition: render a registry snapshot as Prometheus text-format
v0.0.4 or JSON, and parse the text format back (the round-trip check
``tests/test_monitor.py`` pins, and a debugging convenience).

Histogram families render as real Prometheus histograms
(``_bucket``/``_sum``/``_count``) plus a sibling gauge family
``<name>_quantile{quantile="0.5|0.95|0.99"}`` carrying the reservoir
percentiles — scrape-side systems get aggregatable buckets AND the
exact-ish percentiles the serving stats RPC always reported, without
bending the text format (a histogram family may not carry quantile
lines itself).
"""

from __future__ import annotations

import json
import math
import re
from typing import Dict, List, Optional, Tuple

_QUANTILES = ("0.5", "0.95", "0.99")
_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)\s*$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _unescape(v: str) -> str:
    return (v.replace("\\n", "\n").replace('\\"', '"')
            .replace("\\\\", "\\"))


def _fmt(v) -> str:
    if v is None:
        return "NaN"
    v = float(v)
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _label_str(labels: Dict[str, str], extra: Optional[Tuple[str, str]] = None
               ) -> str:
    items = list(labels.items())
    if extra is not None:
        items.append(extra)
    if not items:
        return ""
    return "{" + ",".join(f'{k}="{_escape(v)}"' for k, v in items) + "}"


def render_prometheus(snapshot: Dict[str, dict]) -> str:
    """Prometheus text-format v0.0.4 over a
    ``MetricsRegistry.snapshot()`` dict."""
    lines: List[str] = []
    for name, fam in sorted(snapshot.items()):
        if not _NAME_RE.match(name):
            continue
        kind = fam.get("type", "untyped")
        if fam.get("help"):
            lines.append(f"# HELP {name} {_escape(fam['help'])}")
        lines.append(f"# TYPE {name} {kind}")
        quantile_lines: List[str] = []
        for s in fam.get("samples", []):
            labels = s.get("labels", {})
            if kind == "histogram":
                for le, c in s.get("buckets", {}).items():
                    lines.append(
                        f"{name}_bucket{_label_str(labels, ('le', le))} "
                        f"{_fmt(c)}")
                lines.append(f"{name}_sum{_label_str(labels)} "
                             f"{_fmt(s.get('sum', 0.0))}")
                lines.append(f"{name}_count{_label_str(labels)} "
                             f"{_fmt(s.get('count', 0))}")
                for q, key in zip(_QUANTILES, ("p50", "p95", "p99")):
                    if s.get(key) is not None:
                        quantile_lines.append(
                            f"{name}_quantile"
                            f"{_label_str(labels, ('quantile', q))} "
                            f"{_fmt(s[key])}")
            else:
                lines.append(f"{name}{_label_str(labels)} "
                             f"{_fmt(s.get('value', 0.0))}")
        if quantile_lines:
            lines.append(f"# TYPE {name}_quantile gauge")
            lines.extend(quantile_lines)
    return "\n".join(lines) + "\n"


def render_json(snapshot: Dict[str, dict], indent: Optional[int] = None
                ) -> str:
    return json.dumps(snapshot, indent=indent, sort_keys=True)


def parse_prometheus(text: str) -> Dict[str, dict]:
    """Parse Prometheus text format back into
    ``{family: {"type": ..., "samples": [(name, labels, value), ...]}}``.
    Raises ValueError on malformed lines or samples outside any declared
    family — the validity check the test suite round-trips through."""
    families: Dict[str, dict] = {}
    current: Optional[str] = None
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4 or parts[3] not in (
                    "counter", "gauge", "histogram", "summary", "untyped"):
                raise ValueError(f"line {lineno}: bad TYPE line {raw!r}")
            current = parts[2]
            families[current] = {"type": parts[3], "samples": []}
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"line {lineno}: unparseable sample {raw!r}")
        name, label_blob, value = m.group(1), m.group(2), m.group(3)
        fam = None
        for suffix in ("", "_bucket", "_sum", "_count"):
            base = name[:-len(suffix)] if suffix and name.endswith(suffix) \
                else (name if not suffix else None)
            if base and base in families:
                fam = base
                break
        if fam is None:
            raise ValueError(f"line {lineno}: sample {name!r} outside any "
                             "declared family")
        labels: Dict[str, str] = {}
        if label_blob:
            matched = _LABEL_RE.findall(label_blob)
            rebuilt = ",".join(f'{k}="{v}"' for k, v in matched)
            if rebuilt != label_blob:
                raise ValueError(f"line {lineno}: bad labels {label_blob!r}")
            labels = {k: _unescape(v) for k, v in matched}
        try:
            val = float(value)
        except ValueError:
            raise ValueError(f"line {lineno}: bad value {value!r}")
        families[fam]["samples"].append((name, labels, val))
    return families


# ---------------------------------------------------------------------------
# Federation merge helpers (monitor/federation.py builds on these): turn a
# parsed text scrape back into the snapshot shape every renderer walks, and
# merge N snapshot-shaped sources into ONE federated snapshot.
# ---------------------------------------------------------------------------
def _le_key(le: str) -> float:
    return math.inf if le == "+Inf" else float(le)


def snapshot_from_parsed(parsed: Dict[str, dict]) -> Dict[str, dict]:
    """Reconstruct a ``MetricsRegistry.snapshot()``-shaped dict from
    :func:`parse_prometheus` output, so a scraped replica's families can
    be merged and re-rendered with the same code that serves the local
    registry.  Histogram ``_bucket``/``_sum``/``_count`` samples regroup
    by their non-``le`` label set; reservoir percentiles are not carried
    by the text format, so rebuilt histogram samples omit them (the
    renderer skips absent quantiles)."""
    out: Dict[str, dict] = {}
    for fam, doc in parsed.items():
        kind = doc.get("type", "untyped")
        if kind != "histogram":
            samples = [{"labels": dict(labels), "value": value}
                       for _name, labels, value in doc.get("samples", ())]
            out[fam] = {
                "type": kind, "help": "",
                "label_names": sorted({k for s in samples
                                       for k in s["labels"]}),
                "samples": samples}
            continue
        groups: Dict[Tuple, dict] = {}
        for name, labels, value in doc.get("samples", ()):
            key_labels = {k: v for k, v in labels.items() if k != "le"}
            key = tuple(sorted(key_labels.items()))
            g = groups.setdefault(key, {"labels": key_labels, "buckets": {},
                                        "sum": 0.0, "count": 0.0})
            if name.endswith("_bucket"):
                g["buckets"][labels.get("le", "+Inf")] = value
            elif name.endswith("_sum"):
                g["sum"] = value
            elif name.endswith("_count"):
                g["count"] = value
        samples = []
        for key in sorted(groups):
            g = groups[key]
            g["buckets"] = dict(sorted(g["buckets"].items(),
                                       key=lambda kv: _le_key(kv[0])))
            samples.append(g)
        out[fam] = {
            "type": kind, "help": "",
            "label_names": sorted({k for s in samples
                                   for k in s["labels"]}),
            "samples": samples}
    return out


def _merged_buckets(srcs: List[dict]) -> Dict[str, float]:
    """Sum cumulative bucket counts over the union of each source's
    ``le`` ladder: a source missing an ``le`` contributes its count at
    its greatest bucket at-or-below it (buckets are cumulative, so that
    carry-forward is exact for its own ladder)."""
    les: set = set()
    per_src: List[List[Tuple[float, float]]] = []
    for s in srcs:
        b = s.get("buckets") or {}
        les.update(b)
        per_src.append(sorted(((_le_key(le), v) for le, v in b.items())))
    out: Dict[str, float] = {}
    for le in sorted(les, key=_le_key):
        lv, total = _le_key(le), 0.0
        for pairs in per_src:
            cum = 0.0
            for sle, v in pairs:
                if sle <= lv:
                    cum = v
                else:
                    break
            total += cum
        out[le] = total
    return out


def merge_snapshots(sources: Dict[str, Dict[str, dict]]) -> Dict[str, dict]:
    """Merge snapshot-shaped sources (replica name → snapshot) into one
    federated snapshot (docs/OBSERVABILITY.md "Fleet federation & SLOs"):

    * **counters** sum across sources per label set — fleet totals;
    * **histograms** sum bucket counts (cumulative, union ladder),
      ``sum`` and ``count`` per label set — fleet-aggregatable;
    * **gauges** (and untyped/summary samples) keep one sample per
      source under an added ``replica`` label — a gauge is a per-process
      reading, summing it would fabricate a meaningless number.  A
      sample that ALREADY carries a ``replica`` label keeps it (the
      federation's own per-replica staleness gauges).

    A family whose type disagrees across sources keeps the first type
    seen and drops conflicting sources' samples (re-declaration bug,
    surfaced by the missing series rather than a crash)."""
    merged: Dict[str, dict] = {}
    for src in sorted(sources):
        snap = sources[src]
        for fam, doc in snap.items():
            kind = doc.get("type", "untyped")
            m = merged.setdefault(fam, {"type": kind,
                                        "help": doc.get("help", ""),
                                        "_names": set(), "_acc": {}})
            if m["type"] != kind:
                continue
            if not m["help"] and doc.get("help"):
                m["help"] = doc["help"]
            for s in doc.get("samples", ()):
                labels = dict(s.get("labels") or {})
                if kind not in ("counter", "histogram"):
                    labels.setdefault("replica", src)
                key = tuple(sorted(labels.items()))
                m["_names"].update(labels)
                acc = m["_acc"].get(key)
                if kind == "histogram":
                    if acc is None:
                        acc = m["_acc"][key] = {
                            "labels": labels, "sum": 0.0, "count": 0.0,
                            "_srcs": []}
                    acc["sum"] += float(s.get("sum") or 0.0)
                    acc["count"] += float(s.get("count") or 0.0)
                    acc["_srcs"].append(s)
                else:
                    if acc is None:
                        acc = m["_acc"][key] = {"labels": labels,
                                                "value": 0.0}
                    if kind == "counter":
                        acc["value"] += float(s.get("value") or 0.0)
                    else:
                        acc["value"] = float(s.get("value") or 0.0)
    out: Dict[str, dict] = {}
    for fam, m in merged.items():
        samples = []
        for key in sorted(m["_acc"]):
            acc = m["_acc"][key]
            srcs = acc.pop("_srcs", None)
            if srcs is not None:
                acc["buckets"] = _merged_buckets(srcs)
            samples.append(acc)
        out[fam] = {"type": m["type"], "help": m["help"],
                    "label_names": sorted(m["_names"]),
                    "samples": samples}
    return out


# ---------------------------------------------------------------------------
# Compact summary (bench.py embeds this in every BENCH_*.json record)
# ---------------------------------------------------------------------------
def summarize(snapshot: Dict[str, dict]) -> dict:
    """Perf-trajectory digest of a snapshot: retrace counts by jit entry
    point, per-(span, phase) time breakdown, throughput/score gauges and
    serving latency percentiles — enough to attribute a bench regression
    to a phase without shipping the full registry."""
    out: dict = {}

    fam = snapshot.get("dl4j_compile_retraces_total")
    if fam:
        by_kind = {s["labels"].get("kind", ""): s["value"]
                   for s in fam["samples"]}
        out["retraces"] = by_kind
        out["retraces_total"] = sum(by_kind.values())

    fam = snapshot.get("dl4j_phase_seconds")
    if fam:
        phases = {}
        for s in fam["samples"]:
            key = "/".join(p for p in (s["labels"].get("span", ""),
                                       s["labels"].get("phase", "")) if p)
            phases[key] = {"count": s["count"],
                           "sum_sec": round(s["sum"], 4),
                           "p50_ms": None if s["p50"] is None
                           else round(s["p50"] * 1e3, 3)}
        out["phase_seconds"] = phases

    for gname, key in (("dl4j_fit_examples_per_sec", "examples_per_sec"),
                       ("dl4j_fit_score", "score"),
                       ("dl4j_fit_last_step_ms", "last_step_ms")):
        fam = snapshot.get(gname)
        if fam and fam["samples"]:
            out[key] = fam["samples"][0]["value"]

    fam = snapshot.get("dl4j_serving_total_seconds")
    if fam:
        out["serving_total_ms"] = {
            (s["labels"].get("model") or "default"): {
                "count": s["count"],
                "p50": None if s["p50"] is None else round(s["p50"] * 1e3, 3),
                "p95": None if s["p95"] is None else round(s["p95"] * 1e3, 3),
            } for s in fam["samples"]}

    cache = {}
    for cname in ("hits", "misses", "stale_reloads", "evictions"):
        fam = snapshot.get(f"dl4j_model_cache_{cname}_total")
        if fam and fam["samples"]:
            cache[cname] = fam["samples"][0]["value"]
    if cache:
        out["model_cache"] = cache
    return out
