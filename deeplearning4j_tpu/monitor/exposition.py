"""Exposition: render a registry snapshot as Prometheus text-format
v0.0.4 or JSON, and parse the text format back (the round-trip check
``tests/test_monitor.py`` pins, and a debugging convenience).

Histogram families render as real Prometheus histograms
(``_bucket``/``_sum``/``_count``) plus a sibling gauge family
``<name>_quantile{quantile="0.5|0.95|0.99"}`` carrying the reservoir
percentiles — scrape-side systems get aggregatable buckets AND the
exact-ish percentiles the serving stats RPC always reported, without
bending the text format (a histogram family may not carry quantile
lines itself).
"""

from __future__ import annotations

import json
import math
import re
from typing import Dict, List, Optional, Tuple

_QUANTILES = ("0.5", "0.95", "0.99")
_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)\s*$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _unescape(v: str) -> str:
    return (v.replace("\\n", "\n").replace('\\"', '"')
            .replace("\\\\", "\\"))


def _fmt(v) -> str:
    if v is None:
        return "NaN"
    v = float(v)
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _label_str(labels: Dict[str, str], extra: Optional[Tuple[str, str]] = None
               ) -> str:
    items = list(labels.items())
    if extra is not None:
        items.append(extra)
    if not items:
        return ""
    return "{" + ",".join(f'{k}="{_escape(v)}"' for k, v in items) + "}"


def render_prometheus(snapshot: Dict[str, dict]) -> str:
    """Prometheus text-format v0.0.4 over a
    ``MetricsRegistry.snapshot()`` dict."""
    lines: List[str] = []
    for name, fam in sorted(snapshot.items()):
        if not _NAME_RE.match(name):
            continue
        kind = fam.get("type", "untyped")
        if fam.get("help"):
            lines.append(f"# HELP {name} {_escape(fam['help'])}")
        lines.append(f"# TYPE {name} {kind}")
        quantile_lines: List[str] = []
        for s in fam.get("samples", []):
            labels = s.get("labels", {})
            if kind == "histogram":
                for le, c in s.get("buckets", {}).items():
                    lines.append(
                        f"{name}_bucket{_label_str(labels, ('le', le))} "
                        f"{_fmt(c)}")
                lines.append(f"{name}_sum{_label_str(labels)} "
                             f"{_fmt(s.get('sum', 0.0))}")
                lines.append(f"{name}_count{_label_str(labels)} "
                             f"{_fmt(s.get('count', 0))}")
                for q, key in zip(_QUANTILES, ("p50", "p95", "p99")):
                    if s.get(key) is not None:
                        quantile_lines.append(
                            f"{name}_quantile"
                            f"{_label_str(labels, ('quantile', q))} "
                            f"{_fmt(s[key])}")
            else:
                lines.append(f"{name}{_label_str(labels)} "
                             f"{_fmt(s.get('value', 0.0))}")
        if quantile_lines:
            lines.append(f"# TYPE {name}_quantile gauge")
            lines.extend(quantile_lines)
    return "\n".join(lines) + "\n"


def render_json(snapshot: Dict[str, dict], indent: Optional[int] = None
                ) -> str:
    return json.dumps(snapshot, indent=indent, sort_keys=True)


def parse_prometheus(text: str) -> Dict[str, dict]:
    """Parse Prometheus text format back into
    ``{family: {"type": ..., "samples": [(name, labels, value), ...]}}``.
    Raises ValueError on malformed lines or samples outside any declared
    family — the validity check the test suite round-trips through."""
    families: Dict[str, dict] = {}
    current: Optional[str] = None
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4 or parts[3] not in (
                    "counter", "gauge", "histogram", "summary", "untyped"):
                raise ValueError(f"line {lineno}: bad TYPE line {raw!r}")
            current = parts[2]
            families[current] = {"type": parts[3], "samples": []}
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"line {lineno}: unparseable sample {raw!r}")
        name, label_blob, value = m.group(1), m.group(2), m.group(3)
        fam = None
        for suffix in ("", "_bucket", "_sum", "_count"):
            base = name[:-len(suffix)] if suffix and name.endswith(suffix) \
                else (name if not suffix else None)
            if base and base in families:
                fam = base
                break
        if fam is None:
            raise ValueError(f"line {lineno}: sample {name!r} outside any "
                             "declared family")
        labels: Dict[str, str] = {}
        if label_blob:
            matched = _LABEL_RE.findall(label_blob)
            rebuilt = ",".join(f'{k}="{v}"' for k, v in matched)
            if rebuilt != label_blob:
                raise ValueError(f"line {lineno}: bad labels {label_blob!r}")
            labels = {k: _unescape(v) for k, v in matched}
        try:
            val = float(value)
        except ValueError:
            raise ValueError(f"line {lineno}: bad value {value!r}")
        families[fam]["samples"].append((name, labels, val))
    return families


# ---------------------------------------------------------------------------
# Compact summary (bench.py embeds this in every BENCH_*.json record)
# ---------------------------------------------------------------------------
def summarize(snapshot: Dict[str, dict]) -> dict:
    """Perf-trajectory digest of a snapshot: retrace counts by jit entry
    point, per-(span, phase) time breakdown, throughput/score gauges and
    serving latency percentiles — enough to attribute a bench regression
    to a phase without shipping the full registry."""
    out: dict = {}

    fam = snapshot.get("dl4j_compile_retraces_total")
    if fam:
        by_kind = {s["labels"].get("kind", ""): s["value"]
                   for s in fam["samples"]}
        out["retraces"] = by_kind
        out["retraces_total"] = sum(by_kind.values())

    fam = snapshot.get("dl4j_phase_seconds")
    if fam:
        phases = {}
        for s in fam["samples"]:
            key = "/".join(p for p in (s["labels"].get("span", ""),
                                       s["labels"].get("phase", "")) if p)
            phases[key] = {"count": s["count"],
                           "sum_sec": round(s["sum"], 4),
                           "p50_ms": None if s["p50"] is None
                           else round(s["p50"] * 1e3, 3)}
        out["phase_seconds"] = phases

    for gname, key in (("dl4j_fit_examples_per_sec", "examples_per_sec"),
                       ("dl4j_fit_score", "score"),
                       ("dl4j_fit_last_step_ms", "last_step_ms")):
        fam = snapshot.get(gname)
        if fam and fam["samples"]:
            out[key] = fam["samples"][0]["value"]

    fam = snapshot.get("dl4j_serving_total_seconds")
    if fam:
        out["serving_total_ms"] = {
            (s["labels"].get("model") or "default"): {
                "count": s["count"],
                "p50": None if s["p50"] is None else round(s["p50"] * 1e3, 3),
                "p95": None if s["p95"] is None else round(s["p95"] * 1e3, 3),
            } for s in fam["samples"]}

    cache = {}
    for cname in ("hits", "misses", "stale_reloads", "evictions"):
        fam = snapshot.get(f"dl4j_model_cache_{cname}_total")
        if fam and fam["samples"]:
            cache[cname] = fam["samples"][0]["value"]
    if cache:
        out["model_cache"] = cache
    return out
