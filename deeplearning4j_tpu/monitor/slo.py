"""SLO monitoring — declarative objectives, multi-window burn rates,
and an ``ok → warning → burning`` alert state machine
(docs/OBSERVABILITY.md "Fleet federation & SLOs").

Metrics say what the system is doing; an **objective** says what it is
SUPPOSED to be doing: "99% of predicts under 250 ms", "99.9% of
requests served, not shed".  This module evaluates objectives from
registry snapshots — the local process registry, or a federated fleet
snapshot (``monitor/federation.py``) — so the same tracker watches one
gateway or a whole fleet.

**Burn rate** is the SRE-workbook quantity: over a rolling window,
``(bad / total) / error_budget`` where ``error_budget = 1 - target``.
Burn 1.0 consumes exactly the budget the objective allots; burn 14.4
over a 5-minute window is the classic "page now" fast-burn signal.
Each objective evaluates TWO windows — fast (default 5 m) and slow
(default 1 h) — and the state machine is:

* ``burning``  — fast-window burn ≥ ``burning_burn`` (default 14.4)
  AND the slow window confirms budget is actually being consumed
  (slow burn ≥ 1.0) — a blip after an idle hour does not page;
* ``warning``  — either window's burn ≥ ``warn_burn`` (default 2.0);
* ``ok``       — otherwise.

Every state change journals ``slo.state_changed``; a flip INTO
``burning`` also writes a flight-recorder dump (``slo_fast_burn``) so
the journal tail around the regression is preserved before it rotates
out.  States/burns/budgets are metered as
``dl4j_slo_{burn_rate,budget_remaining,state}`` with ``objective`` and
``series`` labels (``series`` is the label-set key, e.g.
``model=lstm.zip|tenant=acme``; the fleet tier prefixes it with the
scope, e.g. ``replica=r0|``).

``DL4J_SLO=0`` (or :func:`set_enabled`) is the kill switch — the
bench A/B lever (``bench_serving`` reports ``slo_overhead_pct``,
required ≤ 5%).

**Alert delivery**: burn states that only live in ``/metrics`` page
nobody.  ``SloTracker(alert_sink=...)`` delivers every
``slo.state_changed`` flip to a sink — a callable (the in-process
pager hook), an ``http(s)://`` webhook URL (JSON POST), or a
``cmd:<shell command>`` (payload JSON on stdin).  With no explicit
sink, the ``DL4J_SLO_WEBHOOK`` env var supplies one.  Delivery runs
through a :class:`~deeplearning4j_tpu.resilience.policy.RetryPolicy`
(transient webhook failures retry with backoff inside a small
deadline) and is metered ``dl4j_slo_alerts_total{outcome=}``
(``delivered`` / ``failed``).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from deeplearning4j_tpu.monitor import events, flight
from deeplearning4j_tpu.monitor.registry import get_registry

OK, WARNING, BURNING = "ok", "warning", "burning"
STATE_VALUES = {OK: 0, WARNING: 1, BURNING: 2}

_flags = {"enabled": None}


def set_enabled(on: Optional[bool]) -> None:
    """Force SLO evaluation on/off; ``None`` restores the env default
    (``DL4J_SLO``) — the bench A/B lever, mirroring
    ``events.set_enabled``."""
    _flags["enabled"] = None if on is None else bool(on)


def enabled() -> bool:
    on = _flags["enabled"]
    if on is not None:
        return on
    return os.environ.get("DL4J_SLO", "1") != "0"


ENV_WEBHOOK = "DL4J_SLO_WEBHOOK"


def _webhook_sink(url: str):
    """JSON-POST alert sink.  Non-2xx and transport failures raise a
    retryable error so the tracker's RetryPolicy engages."""
    import urllib.error
    import urllib.request

    from deeplearning4j_tpu.resilience.errors import TransientError

    def deliver(payload: dict) -> None:
        body = json.dumps(payload).encode()
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=5.0) as r:
                r.read()
        except (urllib.error.URLError, ConnectionError, TimeoutError,
                OSError) as e:
            raise TransientError(
                f"slo webhook {url} failed: "
                f"{getattr(e, 'reason', e)}") from None
    return deliver


def _command_sink(command: str):
    """Shell-command alert sink (``cmd:<command>``): the payload JSON
    arrives on stdin — the pager/runbook integration hook."""
    import subprocess

    from deeplearning4j_tpu.resilience.errors import TransientError

    def deliver(payload: dict) -> None:
        proc = subprocess.run(command, shell=True,
                              input=json.dumps(payload).encode(),
                              capture_output=True, timeout=10.0)
        if proc.returncode != 0:
            raise TransientError(
                f"slo alert command exited {proc.returncode}: "
                f"{proc.stderr[-200:]!r}")
    return deliver


def resolve_alert_sink(sink):
    """callable → itself; ``http(s)://`` → webhook; ``cmd:`` → command;
    None → the ``DL4J_SLO_WEBHOOK`` env var (or no sink)."""
    if sink is None:
        sink = os.environ.get(ENV_WEBHOOK) or None
    if sink is None or callable(sink):
        return sink
    s = str(sink)
    if s.startswith("cmd:"):
        return _command_sink(s[4:].strip())
    return _webhook_sink(s)


def _le_value(le: str) -> float:
    return float("inf") if le == "+Inf" else float(le)


def _series_key(labels: Dict[str, str]) -> str:
    if not labels:
        return "-"
    return "|".join(f"{k}={v}" for k, v in sorted(labels.items()))


class Objective:
    """One declarative objective.  Two kinds:

    * ``kind="latency"`` — ``family`` names a histogram;
      ``threshold_s`` is the latency bound (align it to a bucket
      boundary of the family's ladder — good counts come from the
      cumulative bucket at the smallest ``le ≥ threshold``); ``target``
      is the fraction that must land under it (0.99 = p99).  One series
      per label set of the family (e.g. per ``model``).

    * ``kind="availability"`` — ``good_family`` / ``bad_family`` name
      counters; ``target`` is the good fraction (0.999 = three nines).
      When the two families share label keys, series group on the
      shared keys (per model/tenant attribution); with disjoint label
      sets both sides aggregate into one ``-`` series.
    """

    def __init__(self, name: str, kind: str, target: float,
                 family: Optional[str] = None,
                 threshold_s: Optional[float] = None,
                 good_family: Optional[str] = None,
                 bad_family: Optional[str] = None,
                 fast_window_s: float = 300.0,
                 slow_window_s: float = 3600.0,
                 warn_burn: float = 2.0, burning_burn: float = 14.4):
        if kind not in ("latency", "availability"):
            raise ValueError(f"objective kind must be latency or "
                             f"availability, got {kind!r}")
        if not 0.0 < float(target) < 1.0:
            raise ValueError("target must be a fraction in (0, 1)")
        if kind == "latency" and (family is None or threshold_s is None):
            raise ValueError("latency objectives need family= and "
                             "threshold_s=")
        if kind == "availability" and (good_family is None
                                       or bad_family is None):
            raise ValueError("availability objectives need good_family= "
                             "and bad_family=")
        self.name = str(name)
        self.kind = kind
        self.target = float(target)
        self.error_budget = 1.0 - self.target
        self.family = family
        self.threshold_s = None if threshold_s is None else float(threshold_s)
        self.good_family = good_family
        self.bad_family = bad_family
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = max(float(slow_window_s), self.fast_window_s)
        self.warn_burn = float(warn_burn)
        self.burning_burn = float(burning_burn)

    def to_dict(self) -> dict:
        return {k: v for k, v in {
            "name": self.name, "kind": self.kind, "target": self.target,
            "family": self.family, "threshold_s": self.threshold_s,
            "good_family": self.good_family, "bad_family": self.bad_family,
            "fast_window_s": self.fast_window_s,
            "slow_window_s": self.slow_window_s,
            "warn_burn": self.warn_burn,
            "burning_burn": self.burning_burn}.items() if v is not None}

    # -- cumulative (bad, total) extraction ----------------------------
    def series(self, snapshot: Dict[str, dict]
               ) -> Dict[str, Tuple[float, float]]:
        """``{series_key: (bad, total)}`` — CUMULATIVE counts from one
        registry/federated snapshot; the tracker turns consecutive
        extractions into windowed rates."""
        if self.kind == "latency":
            return self._latency_series(snapshot)
        return self._availability_series(snapshot)

    def _latency_series(self, snapshot) -> Dict[str, Tuple[float, float]]:
        fam = snapshot.get(self.family)
        out: Dict[str, Tuple[float, float]] = {}
        if not fam or fam.get("type") != "histogram":
            return out
        for s in fam.get("samples", ()):
            labels = {k: v for k, v in (s.get("labels") or {}).items()
                      if k != "replica"}
            total = float(s.get("count") or 0.0)
            good = 0.0
            buckets = s.get("buckets") or {}
            eligible = [(_le_value(le), c) for le, c in buckets.items()
                        if _le_value(le) >= self.threshold_s]
            if eligible:
                good = float(min(eligible)[1])
            key = _series_key(labels)
            prev = out.get(key, (0.0, 0.0))
            out[key] = (prev[0] + max(0.0, total - good), prev[1] + total)
        return out

    def _availability_series(self, snapshot
                             ) -> Dict[str, Tuple[float, float]]:
        good_fam = snapshot.get(self.good_family) or {}
        bad_fam = snapshot.get(self.bad_family) or {}
        good_keys = {k for s in good_fam.get("samples", ())
                     for k in (s.get("labels") or {})} - {"replica"}
        bad_keys = {k for s in bad_fam.get("samples", ())
                    for k in (s.get("labels") or {})} - {"replica"}
        shared = sorted(good_keys & bad_keys)

        def project(s) -> str:
            labels = s.get("labels") or {}
            return _series_key({k: labels[k] for k in shared
                                if k in labels})

        goods: Dict[str, float] = {}
        bads: Dict[str, float] = {}
        for s in good_fam.get("samples", ()):
            k = project(s)
            goods[k] = goods.get(k, 0.0) + float(s.get("value") or 0.0)
        for s in bad_fam.get("samples", ()):
            k = project(s)
            bads[k] = bads.get(k, 0.0) + float(s.get("value") or 0.0)
        out: Dict[str, Tuple[float, float]] = {}
        for k in set(goods) | set(bads):
            g, b = goods.get(k, 0.0), bads.get(k, 0.0)
            out[k] = (b, g + b)
        return out


def default_objectives() -> List[Objective]:
    """The stock serving objectives (docs/OBSERVABILITY.md): predict
    p99 latency, decode-dispatch p99 latency, and availability =
    1 − shed rate."""
    return [
        Objective("predict_p99", "latency", 0.99,
                  family="dl4j_serving_total_seconds", threshold_s=0.25),
        Objective("decode_step_p99", "latency", 0.99,
                  family="dl4j_decode_step_seconds", threshold_s=0.1),
        Objective("availability", "availability", 0.999,
                  good_family="dl4j_serving_requests_total",
                  bad_family="dl4j_resilience_shed_total"),
    ]


class SloTracker:
    """Rolling evaluator for a set of objectives against registry (or
    federated) snapshots.  Stateless objectives + per-series history in
    the tracker, so one objective list can drive the process tracker,
    per-replica trackers AND the fleet-wide tracker without shared
    state (``series_prefix`` keeps their metric series apart)."""

    def __init__(self, objectives: Optional[List[Objective]] = None,
                 registry=None, series_prefix: str = "",
                 on_state_change: Optional[Callable] = None,
                 flight_dump: bool = True, alert_sink=None,
                 alert_retry=None):
        self.objectives = (list(objectives) if objectives is not None
                           else default_objectives())
        self._reg = registry if registry is not None else get_registry()
        self.series_prefix = str(series_prefix)
        self.on_state_change = on_state_change
        self.flight_dump = bool(flight_dump)
        self.alert_sink = resolve_alert_sink(alert_sink)
        if alert_retry is None and self.alert_sink is not None:
            from deeplearning4j_tpu.resilience.policy import RetryPolicy
            alert_retry = RetryPolicy(max_attempts=3, base_delay_ms=100,
                                      max_delay_ms=1000, deadline_s=10.0,
                                      name="slo-alert")
        self.alert_retry = alert_retry
        self._c_alerts = self._reg.counter(
            "dl4j_slo_alerts_total",
            "SLO state-change alerts by delivery outcome "
            "(delivered / failed)", ("outcome",))
        self._lock = threading.Lock()
        self._hist: Dict[Tuple[str, str], deque] = {}
        self._state: Dict[Tuple[str, str], str] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._g_burn = self._reg.gauge(
            "dl4j_slo_burn_rate",
            "error-budget burn rate per objective/series/window (1.0 = "
            "consuming exactly the allotted budget)",
            ("objective", "series", "window"))
        self._g_budget = self._reg.gauge(
            "dl4j_slo_budget_remaining",
            "fraction of the slow-window error budget still unspent "
            "(1.0 = untouched, ≤ 0 = blown)", ("objective", "series"))
        self._g_state = self._reg.gauge(
            "dl4j_slo_state",
            "SLO alert state per objective/series: 0 ok, 1 warning, "
            "2 burning", ("objective", "series"))

    # ------------------------------------------------------------------
    # Evaluation
    # ------------------------------------------------------------------
    def evaluate(self, snapshot: Optional[Dict[str, dict]] = None,
                 now: Optional[float] = None) -> Dict[str, dict]:
        """One evaluation pass: extract cumulative counts, append to
        each series' history, compute fast/slow burns, run the state
        machine.  ``snapshot``/``now`` are injectable for determinism
        (tests, federated evaluation); defaults read the process
        registry and the wall clock.  No-op when disabled."""
        if not enabled():
            return {}
        now = time.time() if now is None else float(now)
        snap = (snapshot if snapshot is not None
                else self._partial_snapshot())
        out: Dict[str, dict] = {}
        for obj in self.objectives:
            for key, (bad, total) in sorted(obj.series(snap).items()):
                series = self.series_prefix + key
                skey = (obj.name, series)
                with self._lock:
                    hist = self._hist.setdefault(skey, deque())
                    hist.append((now, bad, total))
                    horizon = now - obj.slow_window_s
                    # keep exactly one sample at/before the slow-window
                    # start so the slow delta spans the full window
                    while len(hist) > 2 and hist[1][0] <= horizon:
                        hist.popleft()
                    samples = tuple(hist)
                    old = self._state.get(skey, OK)
                burn_fast = self._burn(samples, now, obj.fast_window_s,
                                       obj.error_budget)
                burn_slow = self._burn(samples, now, obj.slow_window_s,
                                       obj.error_budget)
                budget = self._budget_remaining(samples, now, obj)
                if burn_fast >= obj.burning_burn and burn_slow >= 1.0:
                    state = BURNING
                elif max(burn_fast, burn_slow) >= obj.warn_burn:
                    state = WARNING
                else:
                    state = OK
                self._g_burn.labels(objective=obj.name, series=series,
                                    window="fast").set(round(burn_fast, 4))
                self._g_burn.labels(objective=obj.name, series=series,
                                    window="slow").set(round(burn_slow, 4))
                self._g_budget.labels(objective=obj.name,
                                      series=series).set(round(budget, 4))
                self._g_state.labels(objective=obj.name,
                                     series=series).set(STATE_VALUES[state])
                if state != old:
                    with self._lock:
                        self._state[skey] = state
                    self._on_flip(obj, series, old, state,
                                  burn_fast, burn_slow)
                elif skey not in self._state:
                    with self._lock:
                        self._state.setdefault(skey, state)
                out.setdefault(obj.name, {})[series] = {
                    "state": state, "burn_fast": round(burn_fast, 4),
                    "burn_slow": round(burn_slow, 4),
                    "budget_remaining": round(budget, 4),
                    "bad": bad, "total": total}
        return out

    def _partial_snapshot(self) -> Dict[str, dict]:
        """Snapshot ONLY the families the objectives read — a full
        ``registry.snapshot()`` runs every scrape-time collector (host
        RSS, device memory) and walks every family, which at a tight
        evaluation cadence measurably taxes a busy serving box (the
        bench A/B caught ~15% at 20 Hz; this holds it under the 5%
        budget)."""
        needed = set()
        for obj in self.objectives:
            for fam in (obj.family, obj.good_family, obj.bad_family):
                if fam:
                    needed.add(fam)
        out: Dict[str, dict] = {}
        for name in needed:
            fam = self._reg.get(name)
            if fam is not None:
                out[name] = fam.describe()
        return out

    @staticmethod
    def _window_delta(samples, now: float, window_s: float
                      ) -> Tuple[float, float]:
        """(d_bad, d_total) between now's sample and the last sample
        at-or-before the window start (falling back to the oldest)."""
        if len(samples) < 2:
            return 0.0, 0.0
        start = now - window_s
        base = samples[0]
        for s in samples:
            if s[0] <= start:
                base = s
            else:
                break
        latest = samples[-1]
        return (max(0.0, latest[1] - base[1]),
                max(0.0, latest[2] - base[2]))

    @classmethod
    def _burn(cls, samples, now: float, window_s: float,
              error_budget: float) -> float:
        d_bad, d_total = cls._window_delta(samples, now, window_s)
        if d_total <= 0 or error_budget <= 0:
            return 0.0
        return (d_bad / d_total) / error_budget

    @classmethod
    def _budget_remaining(cls, samples, now: float,
                          obj: Objective) -> float:
        d_bad, d_total = cls._window_delta(samples, now,
                                           obj.slow_window_s)
        allowed = obj.error_budget * d_total
        if allowed <= 0:
            return 1.0
        return max(-10.0, 1.0 - d_bad / allowed)

    def _on_flip(self, obj: Objective, series: str, old: str, new: str,
                 burn_fast: float, burn_slow: float) -> None:
        sev = ("error" if new == BURNING
               else "warn" if new == WARNING else "info")
        events.emit("slo.state_changed", severity=sev,
                    objective=obj.name, series=series, old=old, new=new,
                    burn_fast=round(burn_fast, 3),
                    burn_slow=round(burn_slow, 3))
        if new == BURNING and self.flight_dump:
            # the fast-burn flip is the crash-adjacent moment: preserve
            # the journal around the regression before it rotates out
            flight.dump("slo_fast_burn", extra={
                "objective": obj.to_dict(), "series": series,
                "burn_fast": round(burn_fast, 3),
                "burn_slow": round(burn_slow, 3)})
        cb = self.on_state_change
        if cb is not None:
            try:
                cb(obj, series, old, new)
            except Exception:
                pass   # a hook failure must not break evaluation
        self._deliver_alert(obj, series, old, new, burn_fast, burn_slow)

    def _deliver_alert(self, obj: Objective, series: str, old: str,
                       new: str, burn_fast: float,
                       burn_slow: float) -> None:
        """Push the flip to the configured sink through the retry
        policy; outcomes land in ``dl4j_slo_alerts_total``.  A sink
        that stays broken past the retries is counted and dropped — the
        evaluator never wedges on a dead pager."""
        sink = self.alert_sink
        if sink is None:
            return
        payload = {"kind": "slo.state_changed", "objective": obj.name,
                   "series": series, "old": old, "new": new,
                   "burn_fast": round(burn_fast, 3),
                   "burn_slow": round(burn_slow, 3),
                   "target": obj.target, "ts": time.time()}
        try:
            if self.alert_retry is not None:
                self.alert_retry.call(sink, payload)
            else:
                sink(payload)
        except Exception as e:
            self._c_alerts.labels(outcome="failed").inc()
            events.emit("slo.alert_delivered", severity="error",
                        objective=obj.name, series=series, new=new,
                        outcome="failed",
                        error=f"{type(e).__name__}: {e}")
            return
        self._c_alerts.labels(outcome="delivered").inc()
        events.emit("slo.alert_delivered", objective=obj.name,
                    series=series, new=new, outcome="delivered")

    # ------------------------------------------------------------------
    # State surface
    # ------------------------------------------------------------------
    def states(self) -> Dict[str, Dict[str, str]]:
        with self._lock:
            out: Dict[str, Dict[str, str]] = {}
            for (obj, series), state in self._state.items():
                out.setdefault(obj, {})[series] = state
            return out

    def burning_objectives(self) -> set:
        """Objective names with ANY series currently burning."""
        with self._lock:
            return {obj for (obj, _), s in self._state.items()
                    if s == BURNING}

    def healthy(self, objective: str) -> bool:
        """True when NO series of ``objective`` is burning."""
        with self._lock:
            return not any(s == BURNING
                           for (obj, _), s in self._state.items()
                           if obj == objective)

    # ------------------------------------------------------------------
    # Background evaluation
    # ------------------------------------------------------------------
    def start(self, interval_s: float = 5.0) -> "SloTracker":
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, args=(max(0.01, float(interval_s)),),
                daemon=True, name="slo-eval")
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)

    def _loop(self, interval_s: float) -> None:
        while not self._stop.is_set():
            try:
                self.evaluate()
            except Exception:
                pass   # the evaluator must outlive any scrape surprise
            self._stop.wait(interval_s)
