"""Step-phase tracing — lightweight spans over the hot paths.

``with span("fit/step", phase="h2d"):`` times a phase of work against a
per-thread span stack and lands the duration in the registry histogram
``dl4j_phase_seconds{span=...,phase=...}`` — so "where does a training
step spend its time" (data wait vs bucketing vs host-to-device vs the
jitted call vs blocking on the device) is a scrape away instead of a
profiler session ("Array Languages Make Neural Networks Fast":
whole-framework speedups start from knowing which phase dominates).

Two optional bridges into JAX's own profiler:

* ``DL4J_TRACE_ANNOTATIONS=1`` (or :func:`enable_jax_annotations`)
  wraps every span in ``jax.profiler.TraceAnnotation`` so spans appear
  as named regions inside XLA profiler dumps;
* ``DL4J_PROFILE=<dir>`` makes :func:`profile_if_configured` (which
  ``MultiLayerNetwork.fit``/``ComputationGraph.fit`` enter) wrap the
  whole fit call in ``jax.profiler.start_trace(<dir>/fitN)`` — a full
  XPlane/TensorBoard trace per fit with zero code changes.

``DL4J_SPANS=0`` turns span timing into a no-op (the A/B lever for
measuring span overhead; see bench.py's serving workload).
"""

from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager
from typing import Iterator, List, Optional

from deeplearning4j_tpu.monitor import events
from deeplearning4j_tpu.monitor.registry import (
    MetricsRegistry, get_registry)

PHASE_METRIC = "dl4j_phase_seconds"

_local = threading.local()
_flags = {"jax_annotations": None, "enabled": None}
_profile = {"active": False, "count": 0, "lock": threading.Lock()}


class Span:
    __slots__ = ("name", "phase", "parent", "wall_start", "duration")

    def __init__(self, name: str, phase: Optional[str],
                 parent: Optional["Span"]):
        self.name = name
        self.phase = phase
        self.parent = parent
        self.wall_start = time.time()
        self.duration: Optional[float] = None

    def __repr__(self):
        return (f"Span({self.name!r}, phase={self.phase!r}, "
                f"duration={self.duration})")


def _stack() -> List[Span]:
    st = getattr(_local, "stack", None)
    if st is None:
        st = _local.stack = []
    return st


def current() -> Optional[Span]:
    """The innermost open span on this thread, or None."""
    st = _stack()
    return st[-1] if st else None


def set_enabled(on: Optional[bool]) -> None:
    """Force span timing on/off; ``None`` restores the env default
    (``DL4J_SPANS``)."""
    _flags["enabled"] = None if on is None else bool(on)


def enabled() -> bool:
    if _flags["enabled"] is not None:
        return _flags["enabled"]
    return os.environ.get("DL4J_SPANS", "1") != "0"


def enable_jax_annotations(on: bool = True) -> None:
    _flags["jax_annotations"] = bool(on)


def _annotations_enabled() -> bool:
    if _flags["jax_annotations"] is not None:
        return _flags["jax_annotations"]
    return os.environ.get("DL4J_TRACE_ANNOTATIONS") == "1"


@contextmanager
def span(name: str, phase: Optional[str] = None,
         registry: Optional[MetricsRegistry] = None) -> Iterator[Span]:
    """Time a phase of work.  Nested spans stack per-thread (``current()``
    sees the innermost); the duration lands in
    ``dl4j_phase_seconds{span=name, phase=phase}`` on exit — exceptions
    included, a failing step still accounts for its time."""
    if not enabled():
        yield Span(name, phase, None)
        return
    st = _stack()
    s = Span(name, phase, st[-1] if st else None)
    st.append(s)
    # the journal sees every span close with its trace context
    # (request_id / session_id / fit_id ride on the contextvars scope) —
    # this is what lets "why was THIS predict slow" be answered from the
    # event log.  Open events are verbose-only: close carries the
    # duration, and doubling hot-path emits breaks the ≤5% budget.
    if events.verbose():
        events.emit("span.open", span=name, phase=phase or "")
    ann = None
    if _annotations_enabled():
        try:
            import jax
            ann = jax.profiler.TraceAnnotation(
                f"{name}/{phase}" if phase else name)
            ann.__enter__()
        except Exception:
            ann = None
    t0 = time.perf_counter()
    try:
        yield s
    finally:
        s.duration = time.perf_counter() - t0
        if ann is not None:
            try:
                ann.__exit__(None, None, None)
            except Exception:
                pass
        if st and st[-1] is s:
            st.pop()
        reg = registry if registry is not None else get_registry()
        reg.histogram(
            PHASE_METRIC, "span phase wall time (seconds)",
            labels=("span", "phase"),
        ).labels(span=name, phase=phase or "").observe(s.duration)
        events.emit("span.close", span=name, phase=phase or "",
                    duration_s=s.duration)


@contextmanager
def profile_if_configured(tag: str = "fit") -> Iterator[None]:
    """No-op unless ``DL4J_PROFILE=<dir>`` is set; then the body runs
    under ``jax.profiler.start_trace(<dir>/<tag><N>)``.  Re-entrant
    calls (fit inside fit, concurrent fits) skip — JAX allows one live
    trace per process."""
    d = os.environ.get("DL4J_PROFILE")
    if not d:
        yield
        return
    with _profile["lock"]:
        if _profile["active"]:
            started = False
        else:
            _profile["active"] = True
            path = os.path.join(d, f"{tag}{_profile['count']}")
            _profile["count"] += 1
            started = True
    if not started:
        yield
        return
    try:
        import jax
        os.makedirs(path, exist_ok=True)
        jax.profiler.start_trace(path)
    except Exception:
        with _profile["lock"]:
            _profile["active"] = False
        yield
        return
    try:
        yield
    finally:
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        with _profile["lock"]:
            _profile["active"] = False
