"""Data pipeline — the reference's DataSet/DataSetIterator + fetchers
(ref: deeplearning4j-core datasets/, external nd4j DataSet)."""

from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet  # noqa: F401
from deeplearning4j_tpu.datasets.iterators import (  # noqa: F401
    DataSetIterator,
    ListDataSetIterator,
    AsyncDataSetIterator,
    AsyncMultiDataSetIterator,
    ExistingDataSetIterator,
    ListMultiDataSetIterator,
    MultiDataSetIterator,
    MultipleEpochsIterator,
    SamplingDataSetIterator,
)
