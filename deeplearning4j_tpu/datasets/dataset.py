"""DataSet / MultiDataSet containers (the consumed nd4j surface,
SURVEY.md §2.10).

Arrays are host numpy until they cross into the jitted step — the
engine moves them to device; no user-visible workspace management is
needed (XLA buffer donation replaces the reference's MemoryWorkspace
arenas, ref: nn/conf/WorkspaceMode.java).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np


@dataclasses.dataclass
class DataSet:
    features: np.ndarray
    labels: np.ndarray
    features_mask: Optional[np.ndarray] = None
    labels_mask: Optional[np.ndarray] = None

    def num_examples(self) -> int:
        return int(self.features.shape[0])

    def split_test_and_train(self, n_train: int):
        return (self.get_range(0, n_train),
                self.get_range(n_train, self.num_examples()))

    def shuffle(self, seed: Optional[int] = None) -> "DataSet":
        rng = np.random.default_rng(seed)
        idx = rng.permutation(self.num_examples())
        return DataSet(self.features[idx], self.labels[idx],
                       None if self.features_mask is None else self.features_mask[idx],
                       None if self.labels_mask is None else self.labels_mask[idx])

    def get_range(self, start: int, end: int) -> "DataSet":
        sl = slice(start, end)
        return DataSet(
            self.features[sl], self.labels[sl],
            None if self.features_mask is None else self.features_mask[sl],
            None if self.labels_mask is None else self.labels_mask[sl])

    def batch_by(self, batch_size: int) -> List["DataSet"]:
        n = self.num_examples()
        return [self.get_range(i, min(i + batch_size, n))
                for i in range(0, n, batch_size)]

    @staticmethod
    def merge(datasets: Sequence["DataSet"]) -> "DataSet":
        return DataSet(
            np.concatenate([d.features for d in datasets]),
            np.concatenate([d.labels for d in datasets]),
            None if datasets[0].features_mask is None
            else np.concatenate([d.features_mask for d in datasets]),
            None if datasets[0].labels_mask is None
            else np.concatenate([d.labels_mask for d in datasets]))


@dataclasses.dataclass
class MultiDataSet:
    """Multi-input/multi-output container (ref: nd4j MultiDataSet, used by
    ComputationGraph.fit)."""

    features: List[np.ndarray]
    labels: List[np.ndarray]
    features_masks: Optional[List[Optional[np.ndarray]]] = None
    labels_masks: Optional[List[Optional[np.ndarray]]] = None

    def num_examples(self) -> int:
        return int(self.features[0].shape[0])
