"""Built-in dataset fetchers: MNIST / Iris / CIFAR-10, downloaded & cached
(ref: datasets/fetchers/MnistDataFetcher.java, datasets/mnist/MnistManager.java
IDX parsing, base/MnistFetcher.java, iterator/impl/{Mnist,Cifar,Iris}DataSetIterator.java).

In an air-gapped environment the fetchers fall back to a DETERMINISTIC
procedurally-generated stand-in with the same shapes/label structure, so
every pipeline and benchmark runs without network.  Real data is used
automatically when the cache dir (~/.deeplearning4j_tpu/) holds the
standard files.
"""

from __future__ import annotations

import gzip
import os
import struct
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import ListDataSetIterator

CACHE_DIR = Path(os.environ.get("DL4J_TPU_CACHE", str(Path.home() / ".deeplearning4j_tpu")))

MNIST_FILES = {
    "train_images": "train-images-idx3-ubyte.gz",
    "train_labels": "train-labels-idx1-ubyte.gz",
    "test_images": "t10k-images-idx3-ubyte.gz",
    "test_labels": "t10k-labels-idx1-ubyte.gz",
}


def _read_idx(path: Path) -> np.ndarray:
    """Parse an IDX (ubyte) file, gzip or raw (ref: MnistManager.java).
    Raw files go straight through native.read_idx (which carries its own
    numpy fallback); .gz decompresses first then parses the same way."""
    from deeplearning4j_tpu.native import read_idx
    if path.suffix != ".gz":
        return read_idx(path).astype(np.uint8)
    with gzip.open(path, "rb") as f:
        raw = f.read()
    magic = struct.unpack(">I", raw[:4])[0]
    ndim = magic & 0xFF
    dims = [struct.unpack(">I", raw[4 + 4 * i:8 + 4 * i])[0]
            for i in range(ndim)]
    data = np.frombuffer(raw, dtype=np.uint8, offset=4 + 4 * ndim)
    return data.reshape(dims)


def _synthetic_images(n: int, n_classes: int, hw: Tuple[int, int], channels: int,
                      seed: int) -> Tuple[np.ndarray, np.ndarray]:
    """Deterministic class-separable images: each class is a distinct
    frequency/orientation pattern plus noise — learnable by conv nets,
    making loss-decrease and accuracy tests meaningful offline."""
    rng = np.random.default_rng(seed)
    h, w = hw
    ys = rng.integers(0, n_classes, n)
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
    imgs = np.empty((n, channels, h, w), np.float32)
    for c in range(n_classes):
        theta = np.pi * c / n_classes
        freq = 2.0 + (c % 5)
        base = np.sin(freq * (np.cos(theta) * xx + np.sin(theta) * yy) / w * 2 * np.pi)
        sel = ys == c
        k = int(sel.sum())
        if k == 0:
            continue
        noise = rng.normal(0, 0.35, (k, channels, h, w)).astype(np.float32)
        imgs[sel] = base[None, None] + noise
    imgs = (imgs - imgs.min()) / (imgs.max() - imgs.min() + 1e-9)
    labels = np.eye(n_classes, dtype=np.float32)[ys]
    return imgs.astype(np.float32), labels


def load_mnist(train: bool = True, flatten: bool = False,
               num_examples: Optional[int] = None) -> DataSet:
    """MNIST as a DataSet: features [N,1,28,28] (or [N,784]), one-hot labels."""
    sub = "train" if train else "test"
    img_path = CACHE_DIR / "mnist" / MNIST_FILES[f"{sub}_images"]
    lab_path = CACHE_DIR / "mnist" / MNIST_FILES[f"{sub}_labels"]
    if img_path.exists() and lab_path.exists():
        images = _read_idx(img_path).astype(np.float32) / 255.0
        labels_idx = _read_idx(lab_path)
        images = images[:, None, :, :]
        labels = np.eye(10, dtype=np.float32)[labels_idx]
    else:
        n = num_examples or (60000 if train else 10000)
        n = min(n, 8192)  # synthetic fallback kept small
        images, labels = _synthetic_images(n, 10, (28, 28), 1,
                                           seed=1 if train else 2)
    if num_examples:
        images, labels = images[:num_examples], labels[:num_examples]
    if flatten:
        images = images.reshape(images.shape[0], -1)
    return DataSet(images, labels)


def load_cifar10(train: bool = True, num_examples: Optional[int] = None) -> DataSet:
    """CIFAR-10: features [N,3,32,32], one-hot labels (ref: CifarDataSetIterator)."""
    base = CACHE_DIR / "cifar-10-batches-bin"
    files = ([f"data_batch_{i}.bin" for i in range(1, 6)] if train
             else ["test_batch.bin"])
    paths = [base / f for f in files]
    if all(p.exists() for p in paths):
        xs, ys = [], []
        for p in paths:
            raw = np.frombuffer(p.read_bytes(), dtype=np.uint8).reshape(-1, 3073)
            ys.append(raw[:, 0])
            xs.append(raw[:, 1:].reshape(-1, 3, 32, 32))
        images = np.concatenate(xs).astype(np.float32) / 255.0
        labels = np.eye(10, dtype=np.float32)[np.concatenate(ys)]
    else:
        n = num_examples or (50000 if train else 10000)
        n = min(n, 8192)
        images, labels = _synthetic_images(n, 10, (32, 32), 3,
                                           seed=3 if train else 4)
    if num_examples:
        images, labels = images[:num_examples], labels[:num_examples]
    return DataSet(images, labels)


def load_lfw(num_examples: int = 1000, n_labels: int = 40,
             image_hw: Tuple[int, int] = (64, 64), train: bool = True
             ) -> DataSet:
    """LFW faces: features [N,3,H,W], one-hot person labels
    (ref: datasets/fetchers/LFWDataFetcher + LFWDataSetIterator).  With
    no cached copy (zero egress), deterministic class-separable
    synthetic faces stand in, like the MNIST/CIFAR fallbacks."""
    base = CACHE_DIR / "lfw"
    if base.exists():
        from deeplearning4j_tpu.records.readers import ImageRecordReader
        rr = ImageRecordReader(image_hw[0], image_hw[1], 3).initialize(base)
        records = list(zip(rr._files, range(len(rr._files))))
        if records:  # empty/garbage cache dir → synthetic fallback below
            # deterministic 80/20 train/test split by position
            split = max(1, int(0.8 * len(records)))
            chosen = records[:split] if train else records[split:]
            xs, ys = [], []
            for path, _ in chosen[:num_examples]:
                xs.append(rr._load_image(path))
                ys.append(rr.labels.index(path.parent.name))
            labels = np.eye(max(rr.num_labels(), 1),
                            dtype=np.float32)[np.asarray(ys)]
            return DataSet(np.stack(xs) / 255.0, labels)
    n = min(num_examples, 4096)
    images, labels = _synthetic_images(n, n_labels, image_hw, 3,
                                       seed=5 if train else 6)
    return DataSet(images, labels)


def load_curves(num_examples: int = 10000) -> DataSet:
    """The "curves" dataset (28×28 grayscale parametric curves used by
    the original deep-autoencoder work; ref:
    datasets/fetchers/CurvesDataFetcher.java:37-51 — S3 download there,
    deterministic synthesis here: features double as labels, it is an
    autoencoder dataset)."""
    n = min(num_examples, 8192)
    rng = np.random.default_rng(12)
    t = np.linspace(0.0, 1.0, 28, dtype=np.float32)
    images = np.zeros((n, 1, 28, 28), np.float32)
    for i in range(n):
        # random cubic Bézier curve rasterized onto the 28x28 grid
        pts = rng.uniform(2, 26, size=(4, 2)).astype(np.float32)
        b = ((1 - t)[:, None] ** 3 * pts[0]
             + 3 * ((1 - t) ** 2 * t)[:, None] * pts[1]
             + 3 * ((1 - t) * t ** 2)[:, None] * pts[2]
             + (t ** 3)[:, None] * pts[3])
        xi = np.clip(b[:, 0].astype(int), 0, 27)
        yi = np.clip(b[:, 1].astype(int), 0, 27)
        images[i, 0, yi, xi] = 1.0
    flat = images.reshape(n, -1)
    return DataSet(flat, flat)  # autoencoder: labels == features


class LFWDataSetIterator(ListDataSetIterator):
    """(ref: datasets/iterator/impl/LFWDataSetIterator.java)"""

    def __init__(self, batch_size: int, num_examples: int = 1000,
                 n_labels: int = 40, image_hw: Tuple[int, int] = (64, 64),
                 train: bool = True):
        ds = load_lfw(num_examples, n_labels, image_hw, train)
        super().__init__(ds.batch_by(batch_size))


class CurvesDataSetIterator(ListDataSetIterator):
    """(ref: CurvesDataFetcher consumed via BaseDatasetIterator)"""

    def __init__(self, batch_size: int, num_examples: int = 10000):
        ds = load_curves(num_examples)
        super().__init__(ds.batch_by(batch_size))


def load_iris() -> DataSet:
    """The Iris dataset, bundled inline (150 examples — the reference bundles
    it as a resource; ref: IrisDataSetIterator)."""
    data = _IRIS.reshape(150, 5)
    features = data[:, :4].astype(np.float32)
    labels = np.eye(3, dtype=np.float32)[data[:, 4].astype(int)]
    return DataSet(features, labels)


class MnistDataSetIterator(ListDataSetIterator):
    """(ref: datasets/iterator/impl/MnistDataSetIterator.java)"""

    def __init__(self, batch: int, num_examples: Optional[int] = None,
                 train: bool = True, shuffle: bool = True, seed: int = 123,
                 flatten: bool = False):
        ds = load_mnist(train=train, flatten=flatten, num_examples=num_examples)
        if shuffle:
            ds = ds.shuffle(seed)
        super().__init__(ds, batch)


class IrisDataSetIterator(ListDataSetIterator):
    """(ref: datasets/iterator/impl/IrisDataSetIterator.java)"""

    def __init__(self, batch: int = 150, num_examples: int = 150):
        ds = load_iris()
        super().__init__(DataSet(ds.features[:num_examples],
                                 ds.labels[:num_examples]), batch)


class CifarDataSetIterator(ListDataSetIterator):
    """(ref: datasets/iterator/impl/CifarDataSetIterator.java)"""

    def __init__(self, batch: int, num_examples: Optional[int] = None,
                 train: bool = True, shuffle: bool = True, seed: int = 123):
        ds = load_cifar10(train=train, num_examples=num_examples)
        if shuffle:
            ds = ds.shuffle(seed)
        super().__init__(ds, batch)


# Fisher's Iris data: 4 features + class index, 150 rows (public domain).
_IRIS = np.array([
    5.1,3.5,1.4,0.2,0, 4.9,3.0,1.4,0.2,0, 4.7,3.2,1.3,0.2,0, 4.6,3.1,1.5,0.2,0,
    5.0,3.6,1.4,0.2,0, 5.4,3.9,1.7,0.4,0, 4.6,3.4,1.4,0.3,0, 5.0,3.4,1.5,0.2,0,
    4.4,2.9,1.4,0.2,0, 4.9,3.1,1.5,0.1,0, 5.4,3.7,1.5,0.2,0, 4.8,3.4,1.6,0.2,0,
    4.8,3.0,1.4,0.1,0, 4.3,3.0,1.1,0.1,0, 5.8,4.0,1.2,0.2,0, 5.7,4.4,1.5,0.4,0,
    5.4,3.9,1.3,0.4,0, 5.1,3.5,1.4,0.3,0, 5.7,3.8,1.7,0.3,0, 5.1,3.8,1.5,0.3,0,
    5.4,3.4,1.7,0.2,0, 5.1,3.7,1.5,0.4,0, 4.6,3.6,1.0,0.2,0, 5.1,3.3,1.7,0.5,0,
    4.8,3.4,1.9,0.2,0, 5.0,3.0,1.6,0.2,0, 5.0,3.4,1.6,0.4,0, 5.2,3.5,1.5,0.2,0,
    5.2,3.4,1.4,0.2,0, 4.7,3.2,1.6,0.2,0, 4.8,3.1,1.6,0.2,0, 5.4,3.4,1.5,0.4,0,
    5.2,4.1,1.5,0.1,0, 5.5,4.2,1.4,0.2,0, 4.9,3.1,1.5,0.1,0, 5.0,3.2,1.2,0.2,0,
    5.5,3.5,1.3,0.2,0, 4.9,3.1,1.5,0.1,0, 4.4,3.0,1.3,0.2,0, 5.1,3.4,1.5,0.2,0,
    5.0,3.5,1.3,0.3,0, 4.5,2.3,1.3,0.3,0, 4.4,3.2,1.3,0.2,0, 5.0,3.5,1.6,0.6,0,
    5.1,3.8,1.9,0.4,0, 4.8,3.0,1.4,0.3,0, 5.1,3.8,1.6,0.2,0, 4.6,3.2,1.4,0.2,0,
    5.3,3.7,1.5,0.2,0, 5.0,3.3,1.4,0.2,0, 7.0,3.2,4.7,1.4,1, 6.4,3.2,4.5,1.5,1,
    6.9,3.1,4.9,1.5,1, 5.5,2.3,4.0,1.3,1, 6.5,2.8,4.6,1.5,1, 5.7,2.8,4.5,1.3,1,
    6.3,3.3,4.7,1.6,1, 4.9,2.4,3.3,1.0,1, 6.6,2.9,4.6,1.3,1, 5.2,2.7,3.9,1.4,1,
    5.0,2.0,3.5,1.0,1, 5.9,3.0,4.2,1.5,1, 6.0,2.2,4.0,1.0,1, 6.1,2.9,4.7,1.4,1,
    5.6,2.9,3.6,1.3,1, 6.7,3.1,4.4,1.4,1, 5.6,3.0,4.5,1.5,1, 5.8,2.7,4.1,1.0,1,
    6.2,2.2,4.5,1.5,1, 5.6,2.5,3.9,1.1,1, 5.9,3.2,4.8,1.8,1, 6.1,2.8,4.0,1.3,1,
    6.3,2.5,4.9,1.5,1, 6.1,2.8,4.7,1.2,1, 6.4,2.9,4.3,1.3,1, 6.6,3.0,4.4,1.4,1,
    6.8,2.8,4.8,1.4,1, 6.7,3.0,5.0,1.7,1, 6.0,2.9,4.5,1.5,1, 5.7,2.6,3.5,1.0,1,
    5.5,2.4,3.8,1.1,1, 5.5,2.4,3.7,1.0,1, 5.8,2.7,3.9,1.2,1, 6.0,2.7,5.1,1.6,1,
    5.4,3.0,4.5,1.5,1, 6.0,3.4,4.5,1.6,1, 6.7,3.1,4.7,1.5,1, 6.3,2.3,4.4,1.3,1,
    5.6,3.0,4.1,1.3,1, 5.5,2.5,4.0,1.3,1, 5.5,2.6,4.4,1.2,1, 6.1,3.0,4.6,1.4,1,
    5.8,2.6,4.0,1.2,1, 5.0,2.3,3.3,1.0,1, 5.6,2.7,4.2,1.3,1, 5.7,3.0,4.2,1.2,1,
    5.7,2.9,4.2,1.3,1, 6.2,2.9,4.3,1.3,1, 5.1,2.5,3.0,1.1,1, 5.7,2.8,4.1,1.3,1,
    6.3,3.3,6.0,2.5,2, 5.8,2.7,5.1,1.9,2, 7.1,3.0,5.9,2.1,2, 6.3,2.9,5.6,1.8,2,
    6.5,3.0,5.8,2.2,2, 7.6,3.0,6.6,2.1,2, 4.9,2.5,4.5,1.7,2, 7.3,2.9,6.3,1.8,2,
    6.7,2.5,5.8,1.8,2, 7.2,3.6,6.1,2.5,2, 6.5,3.2,5.1,2.0,2, 6.4,2.7,5.3,1.9,2,
    6.8,3.0,5.5,2.1,2, 5.7,2.5,5.0,2.0,2, 5.8,2.8,5.1,2.4,2, 6.4,3.2,5.3,2.3,2,
    6.5,3.0,5.5,1.8,2, 7.7,3.8,6.7,2.2,2, 7.7,2.6,6.9,2.3,2, 6.0,2.2,5.0,1.5,2,
    6.9,3.2,5.7,2.3,2, 5.6,2.8,4.9,2.0,2, 7.7,2.8,6.7,2.0,2, 6.3,2.7,4.9,1.8,2,
    6.7,3.3,5.7,2.1,2, 7.2,3.2,6.0,1.8,2, 6.2,2.8,4.8,1.8,2, 6.1,3.0,4.9,1.8,2,
    6.4,2.8,5.6,2.1,2, 7.2,3.0,5.8,1.6,2, 7.4,2.8,6.1,1.9,2, 7.9,3.8,6.4,2.0,2,
    6.4,2.8,5.6,2.2,2, 6.3,2.8,5.1,1.5,2, 6.1,2.6,5.6,1.4,2, 7.7,3.0,6.1,2.3,2,
    6.3,3.4,5.6,2.4,2, 6.4,3.1,5.5,1.8,2, 6.0,3.0,4.8,1.8,2, 6.9,3.1,5.4,2.1,2,
    6.7,3.1,5.6,2.4,2, 6.9,3.1,5.1,2.3,2, 5.8,2.7,5.1,1.9,2, 6.8,3.2,5.9,2.3,2,
    6.7,3.3,5.7,2.5,2, 6.7,3.0,5.2,2.3,2, 6.3,2.5,5.0,1.9,2, 6.5,3.0,5.2,2.0,2,
    6.2,3.4,5.4,2.3,2, 5.9,3.0,5.1,1.8,2,
], dtype=np.float32)
