"""Data normalizers (the consumed nd4j preprocessing surface:
NormalizerStandardize / NormalizerMinMaxScaler / ImagePreProcessingScaler,
persisted as normalizer.bin inside model checkpoints,
ref: util/ModelSerializer.java:39-41)."""

from __future__ import annotations

from typing import Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet

NORMALIZER_REGISTRY: dict[str, type] = {}


def register_normalizer(cls):
    NORMALIZER_REGISTRY[cls.__name__] = cls
    return cls


class Normalizer:
    def fit(self, dataset: DataSet) -> "Normalizer":
        raise NotImplementedError

    def transform(self, dataset: DataSet) -> DataSet:
        raise NotImplementedError

    def transform_features(self, x: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def to_dict(self) -> dict:
        raise NotImplementedError

    @staticmethod
    def from_dict(d: dict) -> "Normalizer":
        d = dict(d)
        cls = NORMALIZER_REGISTRY[d.pop("@class")]
        return cls._from_dict(d)


@register_normalizer
class NormalizerStandardize(Normalizer):
    """Zero-mean unit-variance per feature column."""

    def __init__(self):
        self.mean: Optional[np.ndarray] = None
        self.std: Optional[np.ndarray] = None

    def fit(self, dataset):
        f = dataset.features.reshape(dataset.features.shape[0], -1)
        self.mean = f.mean(axis=0)
        self.std = f.std(axis=0) + 1e-8
        return self

    def transform_features(self, x):
        shape = x.shape
        f = x.reshape(shape[0], -1)
        return ((f - self.mean) / self.std).reshape(shape).astype(np.float32)

    def transform(self, dataset):
        return DataSet(self.transform_features(dataset.features), dataset.labels,
                       dataset.features_mask, dataset.labels_mask)

    def to_dict(self):
        return {"@class": "NormalizerStandardize",
                "mean": self.mean.tolist(), "std": self.std.tolist()}

    @classmethod
    def _from_dict(cls, d):
        n = cls()
        n.mean = np.asarray(d["mean"], np.float32)
        n.std = np.asarray(d["std"], np.float32)
        return n


@register_normalizer
class NormalizerMinMaxScaler(Normalizer):
    """Scale features into [lo, hi] per column."""

    def __init__(self, lo: float = 0.0, hi: float = 1.0):
        self.lo = lo
        self.hi = hi
        self.min: Optional[np.ndarray] = None
        self.max: Optional[np.ndarray] = None

    def fit(self, dataset):
        f = dataset.features.reshape(dataset.features.shape[0], -1)
        self.min = f.min(axis=0)
        self.max = f.max(axis=0)
        return self

    def transform_features(self, x):
        shape = x.shape
        f = x.reshape(shape[0], -1)
        rng = np.maximum(self.max - self.min, 1e-8)
        scaled = (f - self.min) / rng * (self.hi - self.lo) + self.lo
        return scaled.reshape(shape).astype(np.float32)

    def transform(self, dataset):
        return DataSet(self.transform_features(dataset.features), dataset.labels,
                       dataset.features_mask, dataset.labels_mask)

    def to_dict(self):
        return {"@class": "NormalizerMinMaxScaler", "lo": self.lo, "hi": self.hi,
                "min": self.min.tolist(), "max": self.max.tolist()}

    @classmethod
    def _from_dict(cls, d):
        n = cls(d["lo"], d["hi"])
        n.min = np.asarray(d["min"], np.float32)
        n.max = np.asarray(d["max"], np.float32)
        return n


@register_normalizer
class ImagePreProcessingScaler(Normalizer):
    """Scale raw pixel values [0,maxval] → [lo,hi] (ref: nd4j
    ImagePreProcessingScaler, used for MNIST/CIFAR pipelines)."""

    def __init__(self, lo: float = 0.0, hi: float = 1.0, max_value: float = 255.0):
        self.lo = lo
        self.hi = hi
        self.max_value = max_value

    def fit(self, dataset):
        return self

    def transform_features(self, x):
        return (x / self.max_value * (self.hi - self.lo) + self.lo).astype(np.float32)

    def transform(self, dataset):
        return DataSet(self.transform_features(dataset.features), dataset.labels,
                       dataset.features_mask, dataset.labels_mask)

    def to_dict(self):
        return {"@class": "ImagePreProcessingScaler", "lo": self.lo,
                "hi": self.hi, "max_value": self.max_value}

    @classmethod
    def _from_dict(cls, d):
        return cls(d["lo"], d["hi"], d["max_value"])
