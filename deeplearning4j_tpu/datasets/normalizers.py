"""Data normalizers (the consumed nd4j preprocessing surface:
NormalizerStandardize / NormalizerMinMaxScaler / ImagePreProcessingScaler,
persisted as normalizer.bin inside model checkpoints,
ref: util/ModelSerializer.java:39-41)."""

from __future__ import annotations

from typing import Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet

NORMALIZER_REGISTRY: dict[str, type] = {}


def register_normalizer(cls):
    NORMALIZER_REGISTRY[cls.__name__] = cls
    return cls


def _iter_batches(data):
    """Yield flattened-2D float feature matrices from a DataSet or any
    DataSetIterator-shaped object, without materializing the epoch."""
    if isinstance(data, DataSet):
        yield data.features.reshape(data.features.shape[0], -1)
        return
    data.reset()
    while data.has_next():
        d = data.next()
        yield np.asarray(d.features).reshape(d.features.shape[0], -1)
    # leave the iterator rewound: fit(iterator) then fit-the-model on
    # the same iterator must not silently see an exhausted epoch
    data.reset()


class Normalizer:
    def fit(self, dataset) -> "Normalizer":
        """Accepts a DataSet or a DataSetIterator; iterator fitting is
        single-pass whole-batch accumulation (no per-row work)."""
        raise NotImplementedError

    def transform(self, dataset: DataSet) -> DataSet:
        raise NotImplementedError

    def transform_features(self, x: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def to_dict(self) -> dict:
        raise NotImplementedError

    @staticmethod
    def from_dict(d: dict) -> "Normalizer":
        d = dict(d)
        cls = NORMALIZER_REGISTRY[d.pop("@class")]
        return cls._from_dict(d)


@register_normalizer
class NormalizerStandardize(Normalizer):
    """Zero-mean unit-variance per feature column."""

    def __init__(self):
        self.mean: Optional[np.ndarray] = None
        self.std: Optional[np.ndarray] = None

    def fit(self, dataset):
        if isinstance(dataset, DataSet):
            f = dataset.features.reshape(dataset.features.shape[0], -1)
            self.mean = f.mean(axis=0)
            self.std = f.std(axis=0) + 1e-8
            return self
        # Iterator: single-pass parallel-variance merge (Chan et al.) —
        # per batch one vectorized mean/M2, merged into running stats;
        # same population mean/std as concatenating the whole epoch.
        n = 0
        mean = m2 = None
        for f in _iter_batches(dataset):
            f = f.astype(np.float64, copy=False)
            bn = f.shape[0]
            if bn == 0:
                continue
            bmean = f.mean(axis=0)
            bm2 = ((f - bmean) ** 2).sum(axis=0)
            if mean is None:
                n, mean, m2 = bn, bmean, bm2
            else:
                delta = bmean - mean
                tot = n + bn
                mean = mean + delta * (bn / tot)
                m2 = m2 + bm2 + delta * delta * (n * bn / tot)
                n = tot
        if mean is None:
            raise ValueError("fit on an empty iterator")
        self.mean = mean.astype(np.float32)
        self.std = (np.sqrt(m2 / n) + 1e-8).astype(np.float32)
        return self

    def transform_features(self, x):
        shape = x.shape
        f = x.reshape(shape[0], -1)
        return ((f - self.mean) / self.std).reshape(shape).astype(np.float32)

    def transform(self, dataset):
        return DataSet(self.transform_features(dataset.features), dataset.labels,
                       dataset.features_mask, dataset.labels_mask)

    def to_dict(self):
        return {"@class": "NormalizerStandardize",
                "mean": self.mean.tolist(), "std": self.std.tolist()}

    @classmethod
    def _from_dict(cls, d):
        n = cls()
        n.mean = np.asarray(d["mean"], np.float32)
        n.std = np.asarray(d["std"], np.float32)
        return n


@register_normalizer
class NormalizerMinMaxScaler(Normalizer):
    """Scale features into [lo, hi] per column."""

    def __init__(self, lo: float = 0.0, hi: float = 1.0):
        self.lo = lo
        self.hi = hi
        self.min: Optional[np.ndarray] = None
        self.max: Optional[np.ndarray] = None

    def fit(self, dataset):
        if isinstance(dataset, DataSet):
            f = dataset.features.reshape(dataset.features.shape[0], -1)
            self.min = f.min(axis=0)
            self.max = f.max(axis=0)
            return self
        lo = hi = None  # iterator: running elementwise min/max per batch
        for f in _iter_batches(dataset):
            if f.shape[0] == 0:
                continue
            bmin, bmax = f.min(axis=0), f.max(axis=0)
            lo = bmin if lo is None else np.minimum(lo, bmin)
            hi = bmax if hi is None else np.maximum(hi, bmax)
        if lo is None:
            raise ValueError("fit on an empty iterator")
        self.min, self.max = lo, hi
        return self

    def transform_features(self, x):
        shape = x.shape
        f = x.reshape(shape[0], -1)
        rng = np.maximum(self.max - self.min, 1e-8)
        scaled = (f - self.min) / rng * (self.hi - self.lo) + self.lo
        return scaled.reshape(shape).astype(np.float32)

    def transform(self, dataset):
        return DataSet(self.transform_features(dataset.features), dataset.labels,
                       dataset.features_mask, dataset.labels_mask)

    def to_dict(self):
        return {"@class": "NormalizerMinMaxScaler", "lo": self.lo, "hi": self.hi,
                "min": self.min.tolist(), "max": self.max.tolist()}

    @classmethod
    def _from_dict(cls, d):
        n = cls(d["lo"], d["hi"])
        n.min = np.asarray(d["min"], np.float32)
        n.max = np.asarray(d["max"], np.float32)
        return n


@register_normalizer
class ImagePreProcessingScaler(Normalizer):
    """Scale raw pixel values [0,maxval] → [lo,hi] (ref: nd4j
    ImagePreProcessingScaler, used for MNIST/CIFAR pipelines)."""

    def __init__(self, lo: float = 0.0, hi: float = 1.0, max_value: float = 255.0):
        self.lo = lo
        self.hi = hi
        self.max_value = max_value

    def fit(self, dataset):
        return self

    def transform_features(self, x):
        return (x / self.max_value * (self.hi - self.lo) + self.lo).astype(np.float32)

    def transform(self, dataset):
        return DataSet(self.transform_features(dataset.features), dataset.labels,
                       dataset.features_mask, dataset.labels_mask)

    def to_dict(self):
        return {"@class": "ImagePreProcessingScaler", "lo": self.lo,
                "hi": self.hi, "max_value": self.max_value}

    @classmethod
    def _from_dict(cls, d):
        return cls(d["lo"], d["hi"], d["max_value"])
