"""DataSet iterators, including async device prefetch.

The reference wraps every training iterator in an
``AsyncDataSetIterator`` — a background thread filling a BlockingQueue
(ref: datasets/iterator/AsyncDataSetIterator.java:39-127).  Here the
async iterator additionally stages host→device transfer so the TPU never
waits on ETL (the reference's device-affinity prefetch, :108-109).
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, List, Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet


class DataSetIterator:
    """Iterator contract (ref: nd4j DataSetIterator consumed throughout)."""

    def __iter__(self) -> Iterator[DataSet]:
        self.reset()
        return self

    def __next__(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        return self.next()

    def next(self) -> DataSet:
        raise NotImplementedError

    def has_next(self) -> bool:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def batch_size(self) -> int:
        raise NotImplementedError

    def async_supported(self) -> bool:
        return True


class ListDataSetIterator(DataSetIterator):
    """Iterate a list of pre-built minibatches (ref: ListDataSetIterator)."""

    def __init__(self, data, batch: Optional[int] = None):
        if isinstance(data, DataSet):
            data = data.batch_by(batch) if batch else [data]
        self._data: List[DataSet] = list(data)
        self._i = 0

    def next(self):
        d = self._data[self._i]
        self._i += 1
        return d

    def has_next(self):
        return self._i < len(self._data)

    def reset(self):
        self._i = 0

    def batch_size(self):
        return self._data[0].num_examples() if self._data else 0


class ExistingDataSetIterator(DataSetIterator):
    """Wrap any python iterable of DataSets (ref: ExistingDataSetIterator)."""

    def __init__(self, iterable_factory):
        self._factory = iterable_factory
        self._it = iter(iterable_factory())
        self._peek = None
        self._advance()

    def _advance(self):
        try:
            self._peek = next(self._it)
        except StopIteration:
            self._peek = None

    def next(self):
        d = self._peek
        self._advance()
        return d

    def has_next(self):
        return self._peek is not None

    def reset(self):
        self._it = iter(self._factory())
        self._advance()

    def batch_size(self):
        return self._peek.num_examples() if self._peek else 0


class MultipleEpochsIterator(DataSetIterator):
    """Repeat an underlying iterator N epochs (ref: MultipleEpochsIterator)."""

    def __init__(self, epochs: int, underlying: DataSetIterator):
        self.epochs = epochs
        self.underlying = underlying
        self._epoch = 0

    def next(self):
        if not self.underlying.has_next():
            self.underlying.reset()
            self._epoch += 1
        return self.underlying.next()

    def has_next(self):
        return self.underlying.has_next() or self._epoch < self.epochs - 1

    def reset(self):
        self.underlying.reset()
        self._epoch = 0

    def batch_size(self):
        return self.underlying.batch_size()


class SamplingDataSetIterator(DataSetIterator):
    """Sample minibatches with replacement from one DataSet
    (ref: SamplingDataSetIterator)."""

    def __init__(self, dataset: DataSet, batch: int, total_batches: int, seed: int = 0):
        self.dataset = dataset
        self.batch = batch
        self.total = total_batches
        self._rng = np.random.default_rng(seed)
        self._count = 0

    def next(self):
        idx = self._rng.integers(0, self.dataset.num_examples(), self.batch)
        self._count += 1
        d = self.dataset
        return DataSet(d.features[idx], d.labels[idx],
                       None if d.features_mask is None else d.features_mask[idx],
                       None if d.labels_mask is None else d.labels_mask[idx])

    def has_next(self):
        return self._count < self.total

    def reset(self):
        self._count = 0

    def batch_size(self):
        return self.batch


class AsyncDataSetIterator(DataSetIterator):
    """Background-thread prefetch with a bounded queue
    (ref: AsyncDataSetIterator.java:39-127 — thread + BlockingQueue + poison
    sentinel).  `device_put` stages arrays onto the accelerator so the
    training loop overlaps ETL with compute."""

    _SENTINEL = object()

    def __init__(self, underlying: DataSetIterator, queue_size: int = 4,
                 device_put: bool = False, transform=None):
        """``transform`` runs on the prefetch thread BEFORE device_put —
        the shape-bucketing hook (ops/bucketing.py): batches are padded
        up to their bucket off the critical path, so the H2D transfer
        is already bucket-shaped."""
        self.underlying = underlying
        self.queue_size = queue_size
        self.device_put = device_put
        self.transform_fn = transform
        self._queue: queue.Queue = queue.Queue(maxsize=queue_size)
        self._thread: Optional[threading.Thread] = None
        self._peek = None
        self._exhausted = False
        self._started = False  # worker starts lazily on first use, so a
        # reset() right after construction doesn't drain a prefetch pass

    def _transform(self, d):
        if self.transform_fn is not None:
            d = self.transform_fn(d)
        if self.device_put:
            import jax
            d = DataSet(jax.device_put(d.features), jax.device_put(d.labels),
                        None if d.features_mask is None else jax.device_put(d.features_mask),
                        None if d.labels_mask is None else jax.device_put(d.labels_mask))
        return d

    def _worker(self):
        try:
            while self.underlying.has_next():
                self._queue.put(self._transform(self.underlying.next()))
        except BaseException as e:  # re-raised on the consumer thread —
            self._worker_exc = e    # a dead worker must not look like EOF
        finally:
            self._queue.put(self._SENTINEL)

    def _start(self):
        self._exhausted = False
        self._peek = None
        self._started = True
        self._queue = queue.Queue(maxsize=self.queue_size)
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()
        self._advance()

    def _ensure_started(self):
        if not self._started:
            self._start()

    def _advance(self):
        if self._exhausted:
            self._peek = None
            return
        item = self._queue.get()
        if item is self._SENTINEL:
            self._exhausted = True
            self._peek = None
            exc = getattr(self, "_worker_exc", None)
            if exc is not None:
                self._worker_exc = None
                raise exc
        else:
            self._peek = item

    def next(self):
        self._ensure_started()
        d = self._peek
        self._advance()
        return d

    def has_next(self):
        self._ensure_started()
        return self._peek is not None

    def reset(self):
        if not self._started:
            return
        if self._thread is not None and self._thread.is_alive():
            # Drain so the worker can exit.
            while not self._exhausted:
                self._advance()
            self._thread.join(timeout=5)
        self.underlying.reset()
        self._started = False

    def batch_size(self):
        return self.underlying.batch_size()


class MultiDataSetIterator:
    """Iterator contract for multi-input/output batches
    (ref: nd4j MultiDataSetIterator consumed by ComputationGraph.fit)."""

    def has_next(self) -> bool:
        raise NotImplementedError

    def next(self):
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next()


class ListMultiDataSetIterator(MultiDataSetIterator):
    """Pre-built MultiDataSet minibatches."""

    def __init__(self, batches):
        self._data = list(batches)
        self._i = 0

    def has_next(self):
        return self._i < len(self._data)

    def next(self):
        d = self._data[self._i]
        self._i += 1
        return d

    def reset(self):
        self._i = 0


class AsyncMultiDataSetIterator(AsyncDataSetIterator):
    """Background-prefetch wrapper for MultiDataSet iterators
    (ref: datasets/iterator/AsyncMultiDataSetIterator.java).  Shares the
    whole thread/queue/sentinel machinery with AsyncDataSetIterator —
    only the item transform differs (MultiDataSets pass through)."""

    def __init__(self, underlying: MultiDataSetIterator,
                 queue_size: int = 4, transform=None):
        super().__init__(underlying, queue_size=queue_size,
                         device_put=False, transform=transform)

    def _transform(self, d):
        return d if self.transform_fn is None else self.transform_fn(d)

    def batch_size(self):  # MultiDataSet iterators need not expose this
        fn = getattr(self.underlying, "batch_size", None)
        return fn() if fn else 0
