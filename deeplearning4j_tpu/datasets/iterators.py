"""DataSet iterators, including the parallel async input pipeline.

The reference wraps every training iterator in an
``AsyncDataSetIterator`` — a background thread filling a BlockingQueue
(ref: datasets/iterator/AsyncDataSetIterator.java:39-127).  Here that
design is generalized into a multi-worker ETL pipeline:

    feeder ──▶ task queue ──▶ N workers ──▶ reorder buffer ──▶ consumer
    (serial raw pull,          (collate → normalize →          (ordered,
     order = sync iterator)     transform → device_put)         bounded)

The feeder pulls *raw* batches serially (readers are stateful, so this
is what keeps batch order deterministic and identical to the sync
iterator); workers run the ETL chain in parallel and stage finished,
already-``device_put`` batches into an order-preserving reorder buffer
bounded by ``staging_depth``, so H2D transfer overlaps the jitted step
and the device never waits on ETL.  Iterators that can split "pull raw
records" from "assemble arrays" expose ``next_raw()``/``collate()``
(records/iterators.py does) so the expensive vectorized assembly also
runs on the workers.

Everything meters into the ``dl4j_pipeline_*`` registry families
(docs/OBSERVABILITY.md); the consumer-side wait is the fit loops'
``data_wait`` phase.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
import weakref
from typing import Iterator, List, Optional

import numpy as np

log = logging.getLogger(__name__)

from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet


class DataSetIterator:
    """Iterator contract (ref: nd4j DataSetIterator consumed throughout)."""

    def __iter__(self) -> Iterator[DataSet]:
        self.reset()
        return self

    def __next__(self) -> DataSet:
        if not self.has_next():
            raise StopIteration
        return self.next()

    def next(self) -> DataSet:
        raise NotImplementedError

    def has_next(self) -> bool:
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def batch_size(self) -> int:
        raise NotImplementedError

    def async_supported(self) -> bool:
        return True


class ListDataSetIterator(DataSetIterator):
    """Iterate a list of pre-built minibatches (ref: ListDataSetIterator)."""

    def __init__(self, data, batch: Optional[int] = None):
        if isinstance(data, DataSet):
            data = data.batch_by(batch) if batch else [data]
        self._data: List[DataSet] = list(data)
        self._i = 0

    def next(self):
        d = self._data[self._i]
        self._i += 1
        return d

    def has_next(self):
        return self._i < len(self._data)

    def reset(self):
        self._i = 0

    def batch_size(self):
        return self._data[0].num_examples() if self._data else 0


class ExistingDataSetIterator(DataSetIterator):
    """Wrap any python iterable of DataSets (ref: ExistingDataSetIterator)."""

    def __init__(self, iterable_factory):
        self._factory = iterable_factory
        self._it = iter(iterable_factory())
        self._peek = None
        self._advance()

    def _advance(self):
        try:
            self._peek = next(self._it)
        except StopIteration:
            self._peek = None

    def next(self):
        d = self._peek
        self._advance()
        return d

    def has_next(self):
        return self._peek is not None

    def reset(self):
        self._it = iter(self._factory())
        self._advance()

    def batch_size(self):
        return self._peek.num_examples() if self._peek else 0


class MultipleEpochsIterator(DataSetIterator):
    """Repeat an underlying iterator N epochs (ref: MultipleEpochsIterator)."""

    def __init__(self, epochs: int, underlying: DataSetIterator):
        self.epochs = epochs
        self.underlying = underlying
        self._epoch = 0

    def next(self):
        if not self.underlying.has_next():
            self.underlying.reset()
            self._epoch += 1
        return self.underlying.next()

    def has_next(self):
        return self.underlying.has_next() or self._epoch < self.epochs - 1

    def reset(self):
        self.underlying.reset()
        self._epoch = 0

    def batch_size(self):
        return self.underlying.batch_size()


class SamplingDataSetIterator(DataSetIterator):
    """Sample minibatches with replacement from one DataSet
    (ref: SamplingDataSetIterator)."""

    def __init__(self, dataset: DataSet, batch: int, total_batches: int, seed: int = 0):
        self.dataset = dataset
        self.batch = batch
        self.total = total_batches
        self._rng = np.random.default_rng(seed)
        self._count = 0

    def next(self):
        idx = self._rng.integers(0, self.dataset.num_examples(), self.batch)
        self._count += 1
        d = self.dataset
        return DataSet(d.features[idx], d.labels[idx],
                       None if d.features_mask is None else d.features_mask[idx],
                       None if d.labels_mask is None else d.labels_mask[idx])

    def has_next(self):
        return self._count < self.total

    def reset(self):
        self._count = 0

    def batch_size(self):
        return self.batch


def _pipeline_metrics():
    """dl4j_pipeline_* instruments (lazy import: datasets must stay
    importable before the monitor package finishes initializing)."""
    global _METRICS
    if _METRICS is None:
        from deeplearning4j_tpu import monitor
        reg = monitor.get_registry()
        _METRICS = {
            "batches": reg.counter(
                "dl4j_pipeline_batches_total",
                "input-pipeline batches by stage "
                "(produced=raw pull, transformed=ETL done, consumed=handed"
                " to the training loop)", labels=("stage",)),
            "queue_depth": reg.gauge(
                "dl4j_pipeline_queue_depth",
                "current depth of the pipeline queues "
                "(task=raw batches awaiting ETL, ready=staged batches "
                "awaiting the consumer)", labels=("queue",)),
            "busy": reg.counter(
                "dl4j_pipeline_worker_busy_seconds_total",
                "cumulative wall time ETL workers spent transforming"),
            "staged_bytes": reg.counter(
                "dl4j_pipeline_staged_bytes_total",
                "bytes of batches staged through the reorder buffer"),
            "workers": reg.gauge(
                "dl4j_pipeline_workers",
                "worker threads of the most recently started pipeline"),
        }
    return _METRICS


_METRICS = None


def _batch_nbytes(d) -> int:
    if isinstance(d, MultiDataSet):
        arrs = list(d.features) + list(d.labels)
        for ms in (d.features_masks, d.labels_masks):
            if ms is not None:
                arrs.extend(ms)
    elif isinstance(d, DataSet):
        arrs = [d.features, d.labels, d.features_mask, d.labels_mask]
    else:
        arrs = [d]
    return sum(int(getattr(a, "nbytes", 0) or 0) for a in arrs
               if a is not None)


def _device_put_batch(d):
    """Stage a DataSet or MultiDataSet onto the default device."""
    import jax
    if isinstance(d, MultiDataSet):
        def put_list(arrs):
            if arrs is None:
                return None
            return [None if a is None else jax.device_put(a) for a in arrs]
        return MultiDataSet(put_list(d.features), put_list(d.labels),
                            put_list(d.features_masks),
                            put_list(d.labels_masks))
    if isinstance(d, DataSet):
        return DataSet(jax.device_put(d.features), jax.device_put(d.labels),
                       None if d.features_mask is None
                       else jax.device_put(d.features_mask),
                       None if d.labels_mask is None
                       else jax.device_put(d.labels_mask))
    return jax.device_put(d)


def _make_etl(collate, normalizer, transform, device_put):
    """The worker-side ETL chain as a closure over plain values — it
    must NOT capture the iterator (running threads would pin it and the
    GC-finalizer shutdown path could never fire)."""
    def etl(raw):
        d = collate(raw) if collate is not None else raw
        if normalizer is not None:
            d = normalizer.transform(d)
        if transform is not None:
            d = transform(d)
        if device_put:
            d = _device_put_batch(d)
        return d
    return etl


class _PipelineRun:
    """One started generation of the pipeline: feeder + worker threads,
    the bounded task queue and the order-preserving reorder buffer.

    Holds no reference to the owning iterator: thread targets are bound
    methods of THIS object, so when the iterator is dropped without
    close(), its ``weakref.finalize`` can still fire and ``request_stop``
    unwinds the threads (a producer blocked on a full queue checks the
    stop event instead of leaking)."""

    def __init__(self, underlying, etl, workers: int, queue_size: int,
                 staging_depth: int, reader_retry=None):
        self.underlying = underlying
        self.next_raw, _ = _etl_split(underlying)
        self.etl = etl
        self.reader_retry = reader_retry
        self.workers = workers
        self.staging_depth = staging_depth
        self.stop = threading.Event()
        self.task_q: queue.Queue = queue.Queue(maxsize=queue_size)
        self.cond = threading.Condition()
        self.ready: dict = {}
        self.ready_high_water = 0
        self.next_seq = 0
        self.total: Optional[int] = None
        self.errors: List[tuple] = []
        self.live_workers = workers
        self.threads = [threading.Thread(target=self._feed, daemon=True,
                                         name="dl4j-pipe-feeder")]
        self.threads += [
            threading.Thread(target=self._work, daemon=True,
                             name=f"dl4j-pipe-worker-{i}")
            for i in range(workers)]

    def start(self):
        _pipeline_metrics()["workers"].set(self.workers)
        for t in self.threads:
            t.start()

    # -- bounded-queue helpers that never block past a stop ------------
    def _q_put(self, item) -> bool:
        while not self.stop.is_set():
            try:
                self.task_q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _q_get(self):
        while not self.stop.is_set():
            try:
                return self.task_q.get(timeout=0.05)
            except queue.Empty:
                continue
        return None

    def _pull_raw(self):
        """One raw pull through the resilience stack: the
        ``reader.next_raw`` fault site, then the optional retry policy
        — a transient reader flake (or injected chaos) is retried with
        backoff on THIS thread instead of surfacing on the consumer.
        The fault check fires before the stateful reader advances, so a
        retried pull re-reads nothing and batch order is unchanged."""
        from deeplearning4j_tpu.resilience import faults

        def pull():
            faults.check("reader.next_raw")
            return self.next_raw()
        if self.reader_retry is None:
            return pull()
        return self.reader_retry.call(pull)

    def _feed(self):
        m = _pipeline_metrics()
        seq = 0
        try:
            while not self.stop.is_set() and self.underlying.has_next():
                raw = self._pull_raw()
                if not self._q_put((seq, raw)):
                    return
                seq += 1
                m["batches"].labels(stage="produced").inc()
                m["queue_depth"].labels(queue="task").set(
                    self.task_q.qsize())
        except BaseException as e:  # surfaced on the consumer thread at
            with self.cond:         # this batch position — a dead feeder
                self.errors.append((seq, e))  # must not look like EOF
                self.cond.notify_all()
        finally:
            with self.cond:
                self.total = seq
                self.cond.notify_all()
            for _ in range(self.workers):
                self._q_put(AsyncDataSetIterator._SENTINEL)

    def _work(self):
        m = _pipeline_metrics()
        try:
            while not self.stop.is_set():
                task = self._q_get()
                if task is None or task is AsyncDataSetIterator._SENTINEL:
                    return
                seq, raw = task
                m["queue_depth"].labels(queue="task").set(
                    self.task_q.qsize())
                t0 = time.perf_counter()
                try:
                    item = self.etl(raw)
                except BaseException as e:
                    with self.cond:
                        self.errors.append((seq, e))
                        self.cond.notify_all()
                    continue
                m["busy"].inc(time.perf_counter() - t0)
                m["batches"].labels(stage="transformed").inc()
                m["staged_bytes"].inc(_batch_nbytes(item))
                with self.cond:
                    # staging bound: at most staging_depth finished
                    # batches resident ahead of the consumer
                    while (not self.stop.is_set()
                           and seq >= self.next_seq + self.staging_depth):
                        self.cond.wait(0.05)
                    if self.stop.is_set():
                        return
                    self.ready[seq] = item
                    self.ready_high_water = max(self.ready_high_water,
                                                len(self.ready))
                    m["queue_depth"].labels(queue="ready").set(
                        len(self.ready))
                    self.cond.notify_all()
        finally:
            with self.cond:
                self.live_workers -= 1
                self.cond.notify_all()

    def get_next(self):
        """Block until the next in-order batch is staged.  Returns
        ``(item, True)`` or ``(None, False)`` at EOF; re-raises a
        feeder/worker exception at the failed batch's position."""
        m = _pipeline_metrics()
        with self.cond:
            while True:
                if self.next_seq in self.ready:
                    item = self.ready.pop(self.next_seq)
                    self.next_seq += 1
                    m["queue_depth"].labels(queue="ready").set(
                        len(self.ready))
                    m["batches"].labels(stage="consumed").inc()
                    self.cond.notify_all()
                    return item, True
                if self.errors:
                    err_seq = min(s for s, _ in self.errors)
                    if err_seq <= self.next_seq:
                        exc = next(e for s, e in self.errors
                                   if s == err_seq)
                        self.stop.set()
                        self.cond.notify_all()
                        raise exc
                if (self.total is not None
                        and self.next_seq >= self.total
                        and self.live_workers == 0):
                    return None, False
                if self.stop.is_set():  # close() raced us
                    return None, False
                self.cond.wait(0.05)

    def request_stop(self):
        """Signal-only shutdown — safe from a GC finalizer."""
        self.stop.set()
        with self.cond:
            self.cond.notify_all()

    def shutdown(self):
        self.request_stop()
        for t in self.threads:
            t.join(timeout=5)
        # A thread still alive here is mid-flight in user ETL or
        # next_raw (every queue wait checks `stop`).  Block until it
        # drains: callers touch the shared stateful reader right after
        # shutdown(), and a feeder still inside next_raw would mutate
        # it concurrently.
        stuck = [t for t in self.threads if t.is_alive()]
        if stuck:
            log.warning(
                "pipeline shutdown: %d thread(s) still in ETL after 5s; "
                "waiting for in-flight work to finish", len(stuck))
            for t in stuck:
                t.join()  # dl4j: noqa[DL4J204] callers touch the shared stateful reader right after shutdown() — in-flight ETL must fully drain
        self.threads = []


def reader_retry_from_conf(g):
    """The feeder-side RetryPolicy for ``conf.fault_tolerance(
    reader_retries=N)``, or None when retries are off.  Seeded from the
    conf seed so the backoff schedule is reproducible run-to-run."""
    if getattr(g, "ft_reader_retries", 0) <= 0:
        return None
    from deeplearning4j_tpu.resilience import RetryPolicy
    return RetryPolicy(max_attempts=int(g.ft_reader_retries) + 1,
                       base_delay_ms=25, max_delay_ms=1000,
                       seed=g.seed, name="reader.next_raw")


def _etl_split(underlying):
    """(next_raw, collate) when the underlying iterator supports the
    raw-pull/assembly split, else (next, None) — the two must pair: raw
    records without the matching collate are not a batch."""
    raw = getattr(underlying, "next_raw", None)
    collate = getattr(underlying, "collate", None)
    if raw is not None and collate is not None:
        return raw, collate
    return underlying.next, None


class AsyncDataSetIterator(DataSetIterator):
    """Multi-worker, order-preserving prefetch pipeline
    (ref: AsyncDataSetIterator.java:39-127 — generalized from one
    thread + BlockingQueue to a feeder + N ETL workers + a bounded
    reorder buffer).

    The feeder pulls raw batches from ``underlying`` serially — batch
    order out of this iterator is therefore deterministic and exactly
    matches the sync iterator.  Workers run collate → normalize →
    transform → ``device_put`` concurrently; finished batches wait in a
    reorder buffer holding at most ``staging_depth`` device-resident
    batches ahead of the consumer.  A worker exception surfaces on the
    consumer thread at the failed batch's position (batches before it
    are still delivered, in order)."""

    _SENTINEL = object()

    def __init__(self, underlying: DataSetIterator, queue_size: int = 4,
                 device_put: bool = False, transform=None,
                 workers: int = 1, staging_depth: Optional[int] = None,
                 normalizer=None, reader_retry=None):
        """``transform`` runs on a worker thread BEFORE device_put —
        the shape-bucketing hook (ops/bucketing.py): batches are padded
        up to their bucket off the critical path, so the H2D transfer
        is already bucket-shaped.  ``normalizer`` (datasets/normalizers)
        is applied before ``transform``.  ``staging_depth`` bounds how
        many finished (device-resident) batches may sit ahead of the
        consumer; default = ``queue_size``.  ``reader_retry`` (a
        ``resilience.RetryPolicy``) retries transient raw-pull failures
        on the feeder thread — ``conf.fault_tolerance(reader_retries=N)``
        plumbs it in."""
        self.underlying = underlying
        self.reader_retry = reader_retry
        self.queue_size = max(1, int(queue_size))
        self.device_put = device_put
        self.transform_fn = transform
        self.normalizer = normalizer
        self.workers = max(1, int(workers))
        self.staging_depth = (self.queue_size if staging_depth is None
                              else max(1, int(staging_depth)))
        self._peek = None
        self._exhausted = False
        self._pending_exc: Optional[BaseException] = None
        self._run: Optional[_PipelineRun] = None
        self._finalizer = None
        self._started = False  # threads start lazily on first use, so a
        # reset() right after construction doesn't drain a prefetch pass

    # -- consumer side ---------------------------------------------------
    def _start(self):
        self._exhausted = False
        self._peek = None
        self._pending_exc = None
        self._started = True
        etl = _make_etl(_etl_split(self.underlying)[1],
                        self.normalizer, self.transform_fn,
                        self.device_put)
        self._run = _PipelineRun(self.underlying, etl, self.workers,
                                 self.queue_size, self.staging_depth,
                                 reader_retry=self.reader_retry)
        # GC safety net: a dropped-without-close() iterator must not
        # leak its threads.  The run holds no reference back to self,
        # so collection of self is possible while threads still spin —
        # the finalizer stops them.
        self._finalizer = weakref.finalize(self, _PipelineRun.request_stop,
                                           self._run)
        self._run.start()
        self._advance()

    def _ensure_started(self):
        if not self._started:
            self._start()

    def _advance(self):
        if self._exhausted:
            self._peek = None
            return
        try:
            self._peek, ok = self._run.get_next()
        except BaseException as e:
            # deferred: every batch staged BEFORE the failure is still
            # delivered in order; the exception surfaces on the consumer
            # right after the last good batch
            self._exhausted = True
            self._peek = None
            self._pending_exc = e
            return
        if not ok:
            self._exhausted = True

    def _raise_pending(self):
        e = self._pending_exc
        if e is not None:
            self._pending_exc = None
            raise e

    def next(self):
        self._ensure_started()
        if self._peek is None:
            self._raise_pending()
        d = self._peek
        self._advance()
        return d

    def has_next(self):
        self._ensure_started()
        if self._peek is not None:
            return True
        self._raise_pending()
        return False

    @property
    def staging_high_water(self) -> int:
        """Max finished batches ever resident in the reorder buffer
        (bounded by ``staging_depth``); survives close()."""
        if self._run is not None:
            return self._run.ready_high_water
        return getattr(self, "_last_high_water", 0)

    def close(self):
        """Stop feeder + workers and release the queues.  Idempotent;
        safe to call mid-stream (a producer blocked on a full queue sees
        the stop event instead of leaking).  The iterator restarts
        lazily on next use from wherever ``underlying`` stands."""
        if not self._started:
            return
        if self._finalizer is not None:
            self._finalizer.detach()
            self._finalizer = None
        if self._run is not None:
            self._last_high_water = self._run.ready_high_water
            self._run.shutdown()
            self._run = None
        self._started = False
        self._peek = None
        self._exhausted = False
        self._pending_exc = None

    def reset(self):
        # Rewind the underlying iterator even when the pipeline never
        # started: threads haven't spun up, but the caller may hand us a
        # partially-consumed iterator (e.g. one a Normalizer.fit just
        # drained) and expects reset() to mean "epoch starts from 0".
        if self._started:
            self.close()
        self.underlying.reset()

    def batch_size(self):
        return self.underlying.batch_size()


class MultiDataSetIterator:
    """Iterator contract for multi-input/output batches
    (ref: nd4j MultiDataSetIterator consumed by ComputationGraph.fit)."""

    def has_next(self) -> bool:
        raise NotImplementedError

    def next(self):
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next()


class ListMultiDataSetIterator(MultiDataSetIterator):
    """Pre-built MultiDataSet minibatches."""

    def __init__(self, batches):
        self._data = list(batches)
        self._i = 0

    def has_next(self):
        return self._i < len(self._data)

    def next(self):
        d = self._data[self._i]
        self._i += 1
        return d

    def reset(self):
        self._i = 0


class AsyncMultiDataSetIterator(AsyncDataSetIterator):
    """Multi-worker prefetch wrapper for MultiDataSet iterators
    (ref: datasets/iterator/AsyncMultiDataSetIterator.java).  Shares the
    whole feeder/worker/reorder machinery with AsyncDataSetIterator —
    only the device staging differs (every array in the features/labels
    lists moves, None masks pass through)."""

    def __init__(self, underlying: MultiDataSetIterator,
                 queue_size: int = 4, transform=None,
                 device_put: bool = False, workers: int = 1,
                 staging_depth: Optional[int] = None, reader_retry=None):
        super().__init__(underlying, queue_size=queue_size,
                         device_put=device_put, transform=transform,
                         workers=workers, staging_depth=staging_depth,
                         reader_retry=reader_retry)

    def batch_size(self):  # MultiDataSet iterators need not expose this
        fn = getattr(self.underlying, "batch_size", None)
        return fn() if fn else 0
