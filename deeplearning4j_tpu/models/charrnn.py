"""GravesLSTM character RNN — the north-star char-RNN config
(dl4j-examples GravesLSTMCharModellingExample: 2xLSTM(200) + RnnOutput,
TBPTT 50)."""

from __future__ import annotations

from typing import Optional

import numpy as np

from deeplearning4j_tpu.datasets.iterators import DataSetIterator
from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def char_rnn(vocab_size: int, hidden: int = 200, layers: int = 2,
             learning_rate: float = 0.1, tbptt_length: int = 50,
             seed: int = 12345) -> MultiLayerNetwork:
    b = (NeuralNetConfiguration.builder()
         .seed(seed)
         .learning_rate(learning_rate)
         .updater("rmsprop")
         .weight_init("xavier")
         .list())
    n_in = vocab_size
    for _ in range(layers):
        b.layer(GravesLSTM(n_in=n_in, n_out=hidden, activation="tanh"))
        n_in = hidden
    b.layer(RnnOutputLayer(n_in=hidden, n_out=vocab_size,
                           activation="softmax", loss="mcxent"))
    conf = (b.backprop_type("truncatedbptt")
            .t_bptt_forward_length(tbptt_length)
            .t_bptt_backward_length(tbptt_length)
            .build())
    return MultiLayerNetwork(conf)


class CharacterIterator(DataSetIterator):
    """Text → one-hot char sequences for char-RNN training
    (ref: dl4j-examples CharacterIterator) — a real DataSetIterator so
    ``net.fit(iterator, epochs=N)`` accepts it directly."""

    def __init__(self, text: str, seq_length: int = 100, batch: int = 32,
                 seed: int = 0):
        chars = sorted(set(text))
        self.char_to_idx = {c: i for i, c in enumerate(chars)}
        self.idx_to_char = {i: c for i, c in enumerate(chars)}
        self.vocab_size = len(chars)
        self.seq_length = seq_length
        self.batch = batch
        self.data = np.asarray([self.char_to_idx[c] for c in text], np.int32)
        self._rng = np.random.default_rng(seed)
        self.n_batches_per_epoch = max(
            1, (len(self.data) - seq_length - 1) // (batch * seq_length))
        self._count = 0

    def next(self):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        starts = self._rng.integers(0, len(self.data) - self.seq_length - 1,
                                    self.batch)
        xs = np.stack([self.data[s:s + self.seq_length] for s in starts])
        ys = np.stack([self.data[s + 1:s + self.seq_length + 1] for s in starts])
        eye = np.eye(self.vocab_size, dtype=np.float32)
        self._count += 1
        return DataSet(eye[xs], eye[ys])

    def has_next(self):
        return self._count < self.n_batches_per_epoch

    def reset(self):
        self._count = 0

    def batch_size(self):
        return self.batch


def sample_text(net: MultiLayerNetwork, iterator: CharacterIterator,
                seed_text: str, length: int = 200,
                temperature: float = 1.0, rng_seed: int = 0) -> str:
    """Autoregressive sampling via rnn_time_step stateful inference
    (ref: dl4j-examples sampleCharactersFromNetwork)."""
    rng = np.random.default_rng(rng_seed)
    eye = np.eye(iterator.vocab_size, dtype=np.float32)
    net.rnn_clear_previous_state()
    idxs = [iterator.char_to_idx[c] for c in seed_text]
    x = eye[np.asarray(idxs)][None]  # [1, T, V]
    out = np.asarray(net.rnn_time_step(x))[0, -1]
    result = list(seed_text)
    for _ in range(length):
        logits = np.log(np.maximum(out, 1e-9)) / temperature
        p = np.exp(logits - logits.max())
        p /= p.sum()
        nxt = int(rng.choice(iterator.vocab_size, p=p))
        result.append(iterator.idx_to_char[nxt])
        out = np.asarray(net.rnn_time_step(eye[np.asarray([nxt])][None]))[0, -1]
    return "".join(result)
