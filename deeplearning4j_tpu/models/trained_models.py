"""Pretrained-model helper
(ref: deeplearning4j-modelimport keras/trainedmodels/TrainedModels.java
(VGG16 enum + ImageNet preprocessing/decoding) and
TrainedModelHelper.java (download + import)).

Zero-egress environment: weights are loaded from a LOCAL Keras .h5 file
(the same artifact the reference downloads) or from a cache directory;
the download step itself is gated with a clear error naming the cache
path.  Preprocessing/decoding match the reference (Caffe-style BGR mean
subtraction for VGG16)."""

from __future__ import annotations

from pathlib import Path
from typing import List, Optional, Tuple

import numpy as np

CACHE_DIR = Path.home() / ".deeplearning4j_tpu" / "trainedmodels"

# Mean pixel (BGR) used by VGG16's Caffe preprocessing
# (ref: TrainedModels.VGG16.getPreProcessor → VGG16ImagePreProcessor).
VGG16_BGR_MEAN = np.array([103.939, 116.779, 123.68], np.float32)


class TrainedModels:
    """Enum-style registry (ref: keras/trainedmodels/TrainedModels.java)."""

    VGG16 = "vgg16"
    ALL = (VGG16,)

    _FILES = {VGG16: "vgg16_weights.h5"}

    @classmethod
    def weights_file(cls, model: str) -> Path:
        return CACHE_DIR / cls._FILES[model]


def vgg16_preprocess(images: np.ndarray) -> np.ndarray:
    """RGB [N,3,H,W] in [0,255] → BGR mean-subtracted
    (ref: VGG16ImagePreProcessor.preProcess)."""
    x = np.asarray(images, np.float32)
    bgr = x[:, ::-1, :, :].copy()               # RGB→BGR on channel axis
    for c in range(3):
        bgr[:, c] -= VGG16_BGR_MEAN[c]
    return bgr


def decode_predictions(probs: np.ndarray, top: int = 5,
                       labels: Optional[List[str]] = None
                       ) -> List[List[Tuple[str, float]]]:
    """Top-k (label, probability) per row (ref: TrainedModels
    decodePredictions).  Default labels are positional placeholders;
    pass the ImageNet class list to get named classes."""
    probs = np.asarray(probs)
    out = []
    for row in probs:
        idx = np.argsort(-row)[:top]
        out.append([(labels[i] if labels else f"class_{i}", float(row[i]))
                    for i in idx])
    return out


class TrainedModelHelper:
    """(ref: keras/trainedmodels/TrainedModelHelper.java)"""

    def __init__(self, model: str = TrainedModels.VGG16):
        if model not in TrainedModels.ALL:
            raise ValueError(f"unknown pretrained model {model!r}")
        self.model = model

    def load_model(self, weights_path: Optional[str] = None):
        """Import the pretrained network.  ``weights_path`` overrides the
        cache location; with neither present the error names the cache
        path to drop the file into (this environment cannot download)."""
        path = Path(weights_path) if weights_path else (
            TrainedModels.weights_file(self.model))
        if not path.exists():
            raise FileNotFoundError(
                f"pretrained weights for {self.model} not found at {path}; "
                "this environment has no network egress — place the Keras "
                f".h5 weights file there (the artifact the reference "
                "downloads from its model zoo) and retry")
        from deeplearning4j_tpu.keras_import import KerasModelImport
        return KerasModelImport.import_keras_model_and_weights(str(path))
