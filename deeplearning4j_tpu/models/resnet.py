"""ResNet-50 — the north-star data-parallel config (He et al. 2015),
built on ComputationGraph residual blocks (ElementWiseVertex add, the
reference's residual idiom for its graph API)."""

from __future__ import annotations

from deeplearning4j_tpu.nn.conf.graph_conf import (
    ElementWiseVertex, GraphBuilder)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ActivationLayer, BatchNormalization, ConvolutionLayer, DenseLayer,
    GlobalPoolingLayer, OutputLayer, SubsamplingLayer)
from deeplearning4j_tpu.nn.conf.network import GlobalConf
from deeplearning4j_tpu.nn.graph import ComputationGraph

# (n_blocks, bottleneck_channels) per stage; out channels = 4x bottleneck
_STAGES = [(3, 64), (4, 128), (6, 256), (3, 512)]


def _conv_bn(b: GraphBuilder, name: str, inp: str, n_out: int, kernel, stride,
             padding=(0, 0), act: str = "relu") -> str:
    b.add_layer(f"{name}_conv", ConvolutionLayer(
        n_out=n_out, kernel=kernel, stride=stride, padding=padding,
        activation="identity"), inp)
    b.add_layer(f"{name}_bn", BatchNormalization(), f"{name}_conv")
    if act != "identity":
        b.add_layer(f"{name}_act", ActivationLayer(activation=act), f"{name}_bn")
        return f"{name}_act"
    return f"{name}_bn"


def _bottleneck(b: GraphBuilder, name: str, inp: str, ch: int,
                stride, project: bool) -> str:
    x = _conv_bn(b, f"{name}_a", inp, ch, (1, 1), stride)
    x = _conv_bn(b, f"{name}_b", x, ch, (3, 3), (1, 1), padding=(1, 1))
    x = _conv_bn(b, f"{name}_c", x, 4 * ch, (1, 1), (1, 1), act="identity")
    if project:
        shortcut = _conv_bn(b, f"{name}_proj", inp, 4 * ch, (1, 1), stride,
                            act="identity")
    else:
        shortcut = inp
    b.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), x, shortcut)
    b.add_layer(f"{name}_out", ActivationLayer(activation="relu"), f"{name}_add")
    return f"{name}_out"


def resnet50(height: int = 224, width: int = 224, channels: int = 3,
             n_classes: int = 1000, learning_rate: float = 0.1,
             updater: str = "nesterovs", seed: int = 12345) -> ComputationGraph:
    g = GlobalConf(seed=seed, learning_rate=learning_rate, updater=updater,
                   weight_init="relu")
    b = GraphBuilder(g).add_inputs("in")
    x = _conv_bn(b, "stem", "in", 64, (7, 7), (2, 2), padding=(3, 3))
    b.add_layer("stem_pool", SubsamplingLayer(pooling_type="max", kernel=(3, 3),
                                              stride=(2, 2), padding=(1, 1)), x)
    x = "stem_pool"
    for si, (n_blocks, ch) in enumerate(_STAGES):
        for bi in range(n_blocks):
            stride = (2, 2) if (bi == 0 and si > 0) else (1, 1)
            x = _bottleneck(b, f"s{si}b{bi}", x, ch, stride, project=(bi == 0))
    b.add_layer("gap", GlobalPoolingLayer(pooling_type="avg"), x)
    b.add_layer("fc", OutputLayer(n_out=n_classes, activation="softmax",
                                  loss="mcxent"), "gap")
    conf = (b.set_outputs("fc")
            .set_input_types(InputType.convolutional(height, width, channels))
            .build())
    return ComputationGraph(conf)


def resnet18(height: int = 32, width: int = 32, channels: int = 3,
             n_classes: int = 10, learning_rate: float = 0.1,
             seed: int = 12345) -> ComputationGraph:
    """Small basic-block variant for CIFAR-scale smoke tests."""
    g = GlobalConf(seed=seed, learning_rate=learning_rate, updater="nesterovs",
                   weight_init="relu")
    b = GraphBuilder(g).add_inputs("in")
    x = _conv_bn(b, "stem", "in", 64, (3, 3), (1, 1), padding=(1, 1))
    for si, ch in enumerate([64, 128, 256, 512]):
        for bi in range(2):
            name = f"s{si}b{bi}"
            stride = (2, 2) if (bi == 0 and si > 0) else (1, 1)
            project = (bi == 0 and si > 0)
            y = _conv_bn(b, f"{name}_a", x, ch, (3, 3), stride, padding=(1, 1))
            y = _conv_bn(b, f"{name}_b", y, ch, (3, 3), (1, 1), padding=(1, 1),
                         act="identity")
            shortcut = x
            if project:
                shortcut = _conv_bn(b, f"{name}_proj", x, ch, (1, 1), stride,
                                    act="identity")
            b.add_vertex(f"{name}_add", ElementWiseVertex(op="add"), y, shortcut)
            b.add_layer(f"{name}_out", ActivationLayer(activation="relu"),
                        f"{name}_add")
            x = f"{name}_out"
    b.add_layer("gap", GlobalPoolingLayer(pooling_type="avg"), x)
    b.add_layer("fc", OutputLayer(n_out=n_classes, activation="softmax",
                                  loss="mcxent"), "gap")
    conf = (b.set_outputs("fc")
            .set_input_types(InputType.convolutional(height, width, channels))
            .build())
    return ComputationGraph(conf)
