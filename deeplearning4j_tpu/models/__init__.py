"""Model zoo — the north-star benchmark configs (BASELINE.md):
LeNet-MNIST, VGG16, ResNet-50, GravesLSTM char-RNN.

The reference ships these as dl4j-examples recipes / keras-imported
models; here they are first-class builders over the same config DSL.
"""

from deeplearning4j_tpu.models.lenet import lenet  # noqa: F401
from deeplearning4j_tpu.models.vgg import vgg16  # noqa: F401
from deeplearning4j_tpu.models.resnet import resnet50  # noqa: F401
from deeplearning4j_tpu.models.charrnn import char_rnn  # noqa: F401
