"""LeNet-5 for MNIST — the dl4j-examples LenetMnistExample recipe
(conv5x5x20 → maxpool → conv5x5x50 → maxpool → dense500 → softmax10)."""

from __future__ import annotations

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ConvolutionLayer, DenseLayer, OutputLayer, SubsamplingLayer)
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


def lenet(height: int = 28, width: int = 28, channels: int = 1,
          n_classes: int = 10, learning_rate: float = 0.01,
          updater: str = "adam", seed: int = 12345) -> MultiLayerNetwork:
    conf = (NeuralNetConfiguration.builder()
            .seed(seed)
            .learning_rate(learning_rate)
            .updater(updater)
            .weight_init("xavier")
            .list()
            .layer(ConvolutionLayer(n_out=20, kernel=(5, 5), stride=(1, 1),
                                    activation="identity"))
            .layer(SubsamplingLayer(pooling_type="max", kernel=(2, 2), stride=(2, 2)))
            .layer(ConvolutionLayer(n_out=50, kernel=(5, 5), stride=(1, 1),
                                    activation="identity"))
            .layer(SubsamplingLayer(pooling_type="max", kernel=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(n_out=500, activation="relu"))
            .layer(OutputLayer(n_out=n_classes, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional(height, width, channels))
            .build())
    return MultiLayerNetwork(conf)
