"""VGG16 — the north-star VGG16-CIFAR10 / ImageNet config
(ref: modelimport keras/trainedmodels/TrainedModels.java VGG16; the
standard 13-conv + 3-dense topology, Simonyan & Zisserman 2014)."""

from __future__ import annotations

from typing import Optional

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    ConvolutionLayer, DenseLayer, OutputLayer, SubsamplingLayer)
from deeplearning4j_tpu.nn.conf.network import NeuralNetConfiguration
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

# (n_convs, channels) per VGG16 block
_BLOCKS = [(2, 64), (2, 128), (3, 256), (3, 512), (3, 512)]


def vgg16(height: int = 224, width: int = 224, channels: int = 3,
          n_classes: int = 1000, learning_rate: float = 0.01,
          updater: str = "nesterovs", seed: int = 12345,
          fc_size: int = 4096, dropout: Optional[float] = None) -> MultiLayerNetwork:
    b = (NeuralNetConfiguration.builder()
         .seed(seed)
         .learning_rate(learning_rate)
         .updater(updater)
         .weight_init("relu")
         .list())
    for n_convs, ch in _BLOCKS:
        for _ in range(n_convs):
            b.layer(ConvolutionLayer(n_out=ch, kernel=(3, 3), stride=(1, 1),
                                     padding=(1, 1), activation="relu"))
        b.layer(SubsamplingLayer(pooling_type="max", kernel=(2, 2), stride=(2, 2)))
    b.layer(DenseLayer(n_out=fc_size, activation="relu", dropout=dropout))
    b.layer(DenseLayer(n_out=fc_size, activation="relu", dropout=dropout))
    b.layer(OutputLayer(n_out=n_classes, activation="softmax", loss="mcxent"))
    conf = (b.set_input_type(InputType.convolutional(height, width, channels))
            .build())
    return MultiLayerNetwork(conf)


def vgg16_cifar10(learning_rate: float = 0.01, seed: int = 12345) -> MultiLayerNetwork:
    """The VGG16-CIFAR10 north-star recipe (32x32x3, 10 classes, smaller FC)."""
    return vgg16(32, 32, 3, 10, learning_rate=learning_rate, seed=seed,
                 fc_size=512)
