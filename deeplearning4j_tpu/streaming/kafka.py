"""Kafka streaming source (ref: dl4j-streaming/.../streaming/kafka/
NDArrayKafkaClient.java, NDArrayPublisher/Consumer — Kafka topics
carrying serialized arrays).

kafka-python is NOT baked into this image, so the consumer is gated:
``kafka_available()`` reports the capability, construction raises a
clear error when absent, and the wire format (npz bytes per message)
matches scaleout.data's export so producers are trivial."""

from __future__ import annotations

import dataclasses
import io
from typing import Optional

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator


def kafka_available() -> bool:
    try:
        import kafka  # noqa: F401
        return True
    except ImportError:
        return False


@dataclasses.dataclass
class KafkaConnectionInformation:
    """(ref: streaming/kafka/KafkaConnectionInformation.java)"""

    zookeeper_host: str = "localhost"
    zookeeper_port: int = 2181
    kafka_broker_list: str = "localhost:9092"
    topic_name: str = "dl4j"
    group_id: str = "dl4j-tpu"


def decode_dataset_message(payload: bytes) -> DataSet:
    """npz bytes → DataSet (the NDArray serde role)."""
    with np.load(io.BytesIO(payload)) as z:
        return DataSet(z["features"], z["labels"],
                       z["features_mask"] if "features_mask" in z else None,
                       z["labels_mask"] if "labels_mask" in z else None)


class KafkaDataSetIterator(DataSetIterator):
    """(ref: streaming/kafka/NDArrayConsumer.java — consume → convert →
    feed training)"""

    def __init__(self, connection: KafkaConnectionInformation,
                 poll_timeout_ms: int = 1000,
                 max_messages: Optional[int] = None):
        if not kafka_available():
            raise ImportError(
                "kafka-python is not installed in this environment; use "
                "streaming.DirectoryWatchDataSetIterator, or install "
                "kafka-python to enable the Kafka source")
        from kafka import KafkaConsumer
        self.connection = connection
        self.poll_timeout_ms = poll_timeout_ms
        self.max_messages = max_messages
        self._consumed = 0
        self._consumer = KafkaConsumer(
            connection.topic_name,
            bootstrap_servers=connection.kafka_broker_list.split(","),
            group_id=connection.group_id)
        self._pending: list = []

    def has_next(self) -> bool:
        if self.max_messages is not None and self._consumed >= self.max_messages:
            return False
        if self._pending:
            return True
        polled = self._consumer.poll(timeout_ms=self.poll_timeout_ms)
        for records in polled.values():
            self._pending.extend(r.value for r in records)
        return bool(self._pending)

    def next(self) -> DataSet:
        payload = self._pending.pop(0)
        self._consumed += 1
        return decode_dataset_message(payload)

    def reset(self) -> None:
        self._consumed = 0
