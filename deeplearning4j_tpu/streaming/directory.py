"""Directory-watch streaming source: new .npz DataSet files appearing in
a directory are consumed in arrival order — the Camel-route role
(ref: dl4j-streaming/.../streaming/routes/DL4jServeRouteBuilder.java:
camel endpoint → DataSet conversion → training consumer) with the
filesystem as the transport."""

from __future__ import annotations

import time
from pathlib import Path
from typing import Optional, Set, Union

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator
from deeplearning4j_tpu.scaleout.data import load_dataset


class DirectoryWatchDataSetIterator(DataSetIterator):
    """Blocking iterator over a growing directory of exported DataSets.

    ``has_next`` polls until a new file arrives, the idle timeout
    expires, or a sentinel file named ``_DONE`` appears (the producer's
    end-of-stream marker)."""

    def __init__(self, directory: Union[str, Path], pattern: str = "*.npz",
                 poll_interval: float = 0.05,
                 idle_timeout: Optional[float] = 10.0):
        self.directory = Path(directory)
        self.pattern = pattern
        self.poll_interval = poll_interval
        self.idle_timeout = idle_timeout
        self._seen: Set[str] = set()
        self._queue: list = []

    def _scan(self) -> None:
        for p in sorted(self.directory.glob(self.pattern)):
            key = p.name
            if key not in self._seen:
                self._seen.add(key)
                self._queue.append(p)

    def _done(self) -> bool:
        return (self.directory / "_DONE").exists()

    def has_next(self) -> bool:
        deadline = (time.monotonic() + self.idle_timeout
                    if self.idle_timeout is not None else None)
        while True:
            self._scan()
            if self._queue:
                return True
            if self._done():
                return False
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(self.poll_interval)

    def next(self) -> DataSet:
        if not self._queue:
            if not self.has_next():
                raise StopIteration
        return load_dataset(self._queue.pop(0))

    def reset(self) -> None:
        # streaming source: reset replays everything seen so far
        self._seen.clear()
        self._queue.clear()
