"""Streaming ingestion (ref: dl4j-streaming — Kafka+Camel routes,
streaming/{kafka,routes,conversion}; SURVEY.md §2.6).

The durable capability is "training consumes records as they arrive".
Two sources: a directory watcher (filesystem as the queue — works
everywhere, zero deps) and a Kafka consumer (gated on kafka-python
being installed; it is not baked into this image)."""

from deeplearning4j_tpu.streaming.directory import (
    DirectoryWatchDataSetIterator)
from deeplearning4j_tpu.streaming.kafka import (
    KafkaConnectionInformation, KafkaDataSetIterator, kafka_available)

__all__ = ["DirectoryWatchDataSetIterator", "KafkaConnectionInformation",
           "KafkaDataSetIterator", "kafka_available"]
