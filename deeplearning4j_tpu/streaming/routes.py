"""Streaming serve/publish routes
(ref: dl4j-streaming/.../streaming/routes/DL4jServeRouteBuilder.java:27-95
— consume messages from a topic, decode each payload to an array,
run the model, hand predictions to the output; CamelKafkaRouteBuilder —
records → conversion → serialized bytes → topic).

Camel's route DSL collapses to plain composition: a route is a message
SOURCE (any iterable — a Kafka consumer when kafka-python is present,
a directory watcher, an in-process queue), per-message processors, and
a SINK callable.  The payload decode accepts the reference's own wire
shapes: a base64-encoded legacy ``Nd4j.write`` buffer (the
DL4jServeRouteBuilder byte path), npz bytes (this framework's export
format), or a ready array."""

from __future__ import annotations

import base64
import binascii
import io
import struct
from typing import Callable, Iterable, List, Optional

import numpy as np

from deeplearning4j_tpu.streaming.conversion import RecordToNDArray


def decode_payload(payload) -> np.ndarray:
    """One message → ndarray.  Accepts ndarrays/sequences, npz bytes
    (``features`` or the first entry), or base64-encoded legacy
    Nd4j.write bytes (ref: DL4jServeRouteBuilder.java:68-74 decodes
    Base64 then Nd4j.read)."""
    if isinstance(payload, np.ndarray):
        return payload
    if isinstance(payload, (bytes, bytearray)):
        raw = bytes(payload)
        if raw[:2] == b"PK":  # npz (zip magic)
            with np.load(io.BytesIO(raw)) as z:
                key = "features" if "features" in z.files else z.files[0]
                return np.asarray(z[key])
        try:
            from deeplearning4j_tpu.nn.dl4j_migration import read_nd4j_array
            return np.asarray(read_nd4j_array(
                io.BytesIO(base64.b64decode(raw, validate=True))))
        except (binascii.Error, ValueError, KeyError, EOFError,
                struct.error) as e:   # short/garbage buffers included
            raise ValueError(
                f"payload bytes are neither npz nor base64 Nd4j.write: {e}")
    return np.asarray(payload, np.float32)


class DL4jServeRoute:
    """Model-serving route (ref: DL4jServeRouteBuilder.java:27-95).

    ``before`` / ``final`` processors mirror the builder's
    beforeProcessor/finalProcessor hooks; ``converter`` turns non-array
    records (e.g. CSV lines) into the model input."""

    def __init__(self, model_path: str, computation_graph: bool = False,
                 before: Optional[Callable] = None,
                 final: Optional[Callable] = None,
                 converter: Optional[RecordToNDArray] = None):
        from deeplearning4j_tpu.nn.serialization import (
            restore_computation_graph, restore_multi_layer_network)
        if computation_graph:
            self.model = restore_computation_graph(model_path)
        else:
            self.model = restore_multi_layer_network(model_path)
        self.computation_graph = computation_graph
        self.before = before
        self.final = final
        self.converter = converter

    def process(self, payload) -> np.ndarray:
        """One message → model prediction."""
        if self.before is not None:
            payload = self.before(payload)
        if self.converter is not None and not isinstance(
                payload, (np.ndarray, bytes, bytearray)):
            x = self.converter.convert(
                payload if isinstance(payload, list) else [payload])
        else:
            x = decode_payload(payload)
        out = self.model.output(x)
        out = (np.asarray(out[0]) if isinstance(out, (list, tuple))
               else np.asarray(out))
        if self.final is not None:
            out = self.final(out)
        return out

    def serve(self, source: Iterable, sink: Callable[[np.ndarray], None],
              max_messages: Optional[int] = None) -> int:
        """Drain ``source`` through the model into ``sink``; returns the
        number of messages served (the from(kafka).process(...).to(out)
        pipeline of the reference, transport supplied by the caller)."""
        n = 0
        for msg in source:
            sink(self.process(msg))
            n += 1
            if max_messages is not None and n >= max_messages:
                break
        return n


class RecordPublishRoute:
    """Records → conversion → serialized bytes → sink
    (ref: routes/CamelKafkaRouteBuilder.java — the producing half).
    The sink is any callable (a Kafka producer's send when available)."""

    def __init__(self, converter: RecordToNDArray,
                 sink: Callable[[bytes], None]):
        self.converter = converter
        self.sink = sink

    @staticmethod
    def serialize(arr: np.ndarray,
                  labels: Optional[np.ndarray] = None) -> bytes:
        """npz bytes in the wire format streaming/kafka.py's
        ``decode_dataset_message`` consumes: BOTH ``features`` and
        ``labels`` entries (labels default to an empty [N, 0] block for
        unlabeled serving traffic)."""
        feats = np.asarray(arr, np.float32)
        if labels is None:
            labels = np.zeros((feats.shape[0] if feats.ndim else 0, 0),
                              np.float32)
        buf = io.BytesIO()
        np.savez(buf, features=feats, labels=np.asarray(labels, np.float32))
        return buf.getvalue()

    def publish(self, records: List,
                labels: Optional[np.ndarray] = None) -> bytes:
        payload = self.serialize(self.converter.convert(records), labels)
        self.sink(payload)
        return payload
