"""Record → array/DataSet conversion strategies for streaming routes
(ref: dl4j-streaming/.../streaming/conversion/ndarray/RecordToNDArray.java:13
interface + CSVRecordToINDArray / NDArrayRecordToNDArray impls;
conversion/dataset/RecordToDataSet.java + CSVRecordToDataSet).

A "record" is one message's worth of values: a CSV line/string, a
sequence of numbers, or an ndarray.  Converters collapse a batch of
records into one array (rows) or a DataSet (features + one-hot labels
from the trailing column)."""

from __future__ import annotations

from typing import Iterable, Sequence, Union

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet

Record = Union[str, Sequence[float], np.ndarray]


class RecordToNDArray:
    """(ref: conversion/ndarray/RecordToNDArray.java:13)"""

    def convert(self, records: Iterable[Record]) -> np.ndarray:
        raise NotImplementedError


class CSVRecordToNDArray(RecordToNDArray):
    """CSV lines (or value sequences) → [N, F] float32 rows
    (ref: conversion/ndarray/CSVRecordToINDArray.java)."""

    def __init__(self, delimiter: str = ","):
        self.delimiter = delimiter

    def _row(self, rec: Record) -> np.ndarray:
        if isinstance(rec, str):
            vals = [v for v in rec.strip().split(self.delimiter) if v != ""]
            return np.asarray([float(v) for v in vals], np.float32)
        return np.asarray(rec, np.float32).ravel()

    def convert(self, records: Iterable[Record]) -> np.ndarray:
        rows = [self._row(r) for r in records]
        if not rows:
            return np.zeros((0, 0), np.float32)
        return np.stack(rows)


class NDArrayRecordToNDArray(RecordToNDArray):
    """Pre-built arrays → one stacked batch
    (ref: conversion/ndarray/NDArrayRecordToNDArray.java — concats the
    record arrays along the batch axis)."""

    def convert(self, records: Iterable[Record]) -> np.ndarray:
        arrs = [np.asarray(r, np.float32) for r in records]
        if not arrs:
            return np.zeros((0, 0), np.float32)
        arrs = [a[None] if a.ndim == 1 else a for a in arrs]
        return np.concatenate(arrs, axis=0)


class RecordToDataSet:
    """(ref: conversion/dataset/RecordToDataSet.java — records +
    numLabels → DataSet)"""

    def convert(self, records: Iterable[Record],
                num_labels: int) -> DataSet:
        raise NotImplementedError


class CSVRecordToDataSet(RecordToDataSet):
    """CSV rows whose LAST column is the class index → features +
    one-hot labels (ref: conversion/dataset/CSVRecordToDataSet.java)."""

    def __init__(self, delimiter: str = ","):
        self._nd = CSVRecordToNDArray(delimiter)

    def convert(self, records: Iterable[Record],
                num_labels: int) -> DataSet:
        m = self._nd.convert(records)
        if m.size == 0:
            return DataSet(np.zeros((0, 0), np.float32),
                           np.zeros((0, num_labels), np.float32))
        feats = m[:, :-1]
        idx = m[:, -1].astype(np.int64)
        labels = np.eye(num_labels, dtype=np.float32)[idx]
        return DataSet(feats, labels)
