"""Early stopping — config-driven training with termination conditions.

(ref: earlystopping/EarlyStoppingConfiguration.java,
trainer/BaseEarlyStoppingTrainer.java:76, saver/LocalFileModelSaver.java,
scorecalc/DataSetLossCalculator.java, termination/* — MaxEpochs, MaxTime,
ScoreImprovement, MaxScore, InvalidScore, BestScore)
"""

from __future__ import annotations

import dataclasses
import math
import time
from pathlib import Path
from typing import Callable, List, Optional


# ---------------------------------------------------------------- terminators
class EpochTerminationCondition:
    def terminate(self, epoch: int, score: float) -> bool:
        raise NotImplementedError


class IterationTerminationCondition:
    def terminate(self, iteration: int, score: float) -> bool:
        raise NotImplementedError


@dataclasses.dataclass
class MaxEpochsTerminationCondition(EpochTerminationCondition):
    max_epochs: int

    def terminate(self, epoch, score):
        return epoch >= self.max_epochs - 1


@dataclasses.dataclass
class ScoreImprovementEpochTerminationCondition(EpochTerminationCondition):
    """Stop after N epochs without score improvement."""

    max_epochs_without_improvement: int
    min_improvement: float = 0.0
    _best: float = dataclasses.field(default=math.inf, repr=False)
    _stale: int = dataclasses.field(default=0, repr=False)

    def terminate(self, epoch, score):
        if score < self._best - self.min_improvement:
            self._best = score
            self._stale = 0
        else:
            self._stale += 1
        return self._stale > self.max_epochs_without_improvement


@dataclasses.dataclass
class BestScoreEpochTerminationCondition(EpochTerminationCondition):
    best_expected_score: float

    def terminate(self, epoch, score):
        return score <= self.best_expected_score


@dataclasses.dataclass
class MaxTimeIterationTerminationCondition(IterationTerminationCondition):
    max_seconds: float
    _start: Optional[float] = dataclasses.field(default=None, repr=False)

    def terminate(self, iteration, score):
        if self._start is None:
            self._start = time.monotonic()
        return time.monotonic() - self._start > self.max_seconds


@dataclasses.dataclass
class MaxScoreIterationTerminationCondition(IterationTerminationCondition):
    max_score: float

    def terminate(self, iteration, score):
        return score > self.max_score


class InvalidScoreIterationTerminationCondition(IterationTerminationCondition):
    def terminate(self, iteration, score):
        return math.isnan(score) or math.isinf(score)


# ---------------------------------------------------------------- savers
class InMemoryModelSaver:
    """(ref: saver/InMemoryModelSaver.java)"""

    def __init__(self):
        self.best = None
        self.latest = None

    def save_best(self, model):
        self.best = model.clone()

    def save_latest(self, model):
        self.latest = model.clone()

    def get_best(self):
        return self.best

    def get_latest(self):
        return self.latest


class LocalFileModelSaver:
    """(ref: saver/LocalFileModelSaver.java)"""

    def __init__(self, directory):
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)

    def save_best(self, model):
        from deeplearning4j_tpu.nn import serialization
        serialization.write_model(model, self.dir / "bestModel.zip")

    def save_latest(self, model):
        from deeplearning4j_tpu.nn import serialization
        serialization.write_model(model, self.dir / "latestModel.zip")

    def get_best(self):
        from deeplearning4j_tpu.nn import serialization
        return serialization.load_model(self.dir / "bestModel.zip")

    def get_latest(self):
        from deeplearning4j_tpu.nn import serialization
        return serialization.load_model(self.dir / "latestModel.zip")


# ---------------------------------------------------------------- score calc
class DataSetLossCalculator:
    """(ref: scorecalc/DataSetLossCalculator.java)"""

    def __init__(self, iterator_or_dataset, average: bool = True):
        self.data = iterator_or_dataset
        self.average = average

    def calculate_score(self, model) -> float:
        from deeplearning4j_tpu.datasets.dataset import DataSet
        if isinstance(self.data, DataSet):
            return model.score(self.data)
        self.data.reset()
        total, n = 0.0, 0
        for ds in self.data:
            total += model.score(ds) * ds.num_examples()
            n += ds.num_examples()
        return total / n if self.average and n else total


# ---------------------------------------------------------------- config+trainer
@dataclasses.dataclass
class EarlyStoppingConfiguration:
    """(ref: earlystopping/EarlyStoppingConfiguration.java)"""

    score_calculator: DataSetLossCalculator
    model_saver: object = dataclasses.field(default_factory=InMemoryModelSaver)
    epoch_termination_conditions: List[EpochTerminationCondition] = dataclasses.field(default_factory=list)
    iteration_termination_conditions: List[IterationTerminationCondition] = dataclasses.field(default_factory=list)
    evaluate_every_n_epochs: int = 1
    save_last_model: bool = False


@dataclasses.dataclass
class EarlyStoppingResult:
    """(ref: earlystopping/EarlyStoppingResult.java)"""

    termination_reason: str
    termination_details: str
    total_epochs: int
    best_model_epoch: int
    best_model_score: float
    score_vs_epoch: dict
    best_model: object


def validate_termination_conditions(cfg: EarlyStoppingConfiguration) -> None:
    """A configuration with no termination condition at all would train
    forever — reject it up front (advisor round-1 finding; the reference's
    builder documents that at least one condition is required)."""
    if (not cfg.epoch_termination_conditions
            and not cfg.iteration_termination_conditions):
        raise ValueError(
            "EarlyStoppingConfiguration requires at least one epoch or "
            "iteration termination condition — otherwise fit() never stops")


def check_score_free_epoch_conditions(cfg: EarlyStoppingConfiguration,
                                      epoch: int):
    """Score-independent epoch conditions (MaxEpochs) must fire on EVERY
    epoch, not only on evaluate_every_n_epochs boundaries — otherwise
    MaxEpochs(3) with evaluate_every_n_epochs=5 overshoots (or loops
    forever).  Returns the fired condition or None."""
    for cond in cfg.epoch_termination_conditions:
        if isinstance(cond, MaxEpochsTerminationCondition) \
                and cond.terminate(epoch, math.nan):
            return cond
    return None


class EarlyStoppingTrainer:
    """(ref: trainer/EarlyStoppingTrainer.java / BaseEarlyStoppingTrainer.fit :76)"""

    def __init__(self, config: EarlyStoppingConfiguration, network, train_data):
        self.config = config
        self.net = network
        self.train_data = train_data

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        validate_termination_conditions(cfg)
        best_score = math.inf
        best_epoch = -1
        score_vs_epoch = {}
        epoch = 0
        reason, details = "MaxEpochs", ""
        while True:
            # --- one epoch with iteration-level termination checks ---
            self.train_data.reset()
            terminated_iter = False
            for ds in self.train_data:
                self.net.fit(ds)
                s = self.net.score()
                for cond in cfg.iteration_termination_conditions:
                    if cond.terminate(self.net.iteration, s):
                        reason = "IterationTerminationCondition"
                        details = repr(cond)
                        terminated_iter = True
                        break
                if terminated_iter:
                    break
            if terminated_iter:
                break

            if epoch % cfg.evaluate_every_n_epochs == 0:
                score = cfg.score_calculator.calculate_score(self.net)
                score_vs_epoch[epoch] = score
                if score < best_score:
                    best_score = score
                    best_epoch = epoch
                    cfg.model_saver.save_best(self.net)
                if cfg.save_last_model:
                    cfg.model_saver.save_latest(self.net)
                stop = False
                for cond in cfg.epoch_termination_conditions:
                    if cond.terminate(epoch, score):
                        reason = "EpochTerminationCondition"
                        details = repr(cond)
                        stop = True
                        break
                if stop:
                    break
            else:
                fired = check_score_free_epoch_conditions(cfg, epoch)
                if fired is not None:
                    reason, details = "EpochTerminationCondition", repr(fired)
                    break
            epoch += 1
        best = cfg.model_saver.get_best()
        return EarlyStoppingResult(
            termination_reason=reason, termination_details=details,
            total_epochs=epoch + 1, best_model_epoch=best_epoch,
            best_model_score=best_score, score_vs_epoch=score_vs_epoch,
            best_model=best if best is not None else self.net)


class EarlyStoppingGraphTrainer(EarlyStoppingTrainer):
    """ComputationGraph early stopping (ref: trainer/EarlyStoppingGraphTrainer.java)
    — the loop is model-agnostic here (fit/score/iteration are the same
    surface on both engines); the class exists for reference API parity."""
