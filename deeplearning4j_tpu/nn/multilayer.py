"""MultiLayerNetwork — the sequential-network engine.

The reference's MultiLayerNetwork (ref: nn/multilayer/MultiLayerNetwork.java,
2747 LoC) runs an eager per-op training loop: feedForwardToLayer →
backprop → updater → params-=gradient, dispatching every op through nd4j
(call stack SURVEY.md §3.1).  Here the ENTIRE update step — forward, loss,
backward (jax.grad), gradient normalization, learning rule, param update —
is traced once and compiled into a single XLA program with donated
buffers, which is precisely the north star's "trace a full update step
into one cached XLA computation".

Public surface parity: init(), fit(iterator|DataSet|(x,y)),
output(), predict(), score(), params()/set_params() (flat row-vector
view parity), rnn_time_step(), tbptt via conf.backprop_type, listeners.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.analysis import sanitizer
from deeplearning4j_tpu.monitor import events
from deeplearning4j_tpu.nn import params as param_util
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import BaseOutputLayer, Layer, LossLayer
from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration
from deeplearning4j_tpu.nn.listeners import IterationListener, TrainingListener
from deeplearning4j_tpu.ops import bucketing
from deeplearning4j_tpu.ops import dtypes as dtype_ops
from deeplearning4j_tpu.ops import updaters as upd_ops

WEIGHT_KEYS = {"W", "RW", "f_W", "f_RW", "b_W", "b_RW"}
BIAS_KEYS = {"b", "f_b", "b_b"}


def render_table(rows: Sequence[Tuple[str, ...]], footer: Sequence[str] = ()):
    """Fixed-width text table: rows[0] is the header; footer lines follow
    a rule.  Shared by MultiLayerNetwork.summary and
    ComputationGraph.summary."""
    ncols = len(rows[0])
    widths = [max(len(r[c]) for r in rows) for c in range(ncols)]
    lines = ["  ".join(r[c].ljust(widths[c]) for c in range(ncols))
             for r in rows]
    sep = "-" * len(lines[0])
    lines.insert(1, sep)
    lines.append(sep)
    lines.extend(footer)
    return "\n".join(lines)


def _updater_for(layer: Layer) -> upd_ops.Updater:
    name = (layer.updater or "sgd").lower()
    hyper = {}
    if name == "nesterovs":
        hyper["momentum"] = layer.momentum if layer.momentum is not None else 0.9
    elif name == "adadelta":
        hyper["rho"] = layer.rho if layer.rho is not None else 0.95
        if layer.epsilon is not None:
            hyper["epsilon"] = layer.epsilon
    elif name == "rmsprop":
        hyper["rmsdecay"] = layer.rms_decay if layer.rms_decay is not None else 0.95
        if layer.epsilon is not None:
            hyper["epsilon"] = layer.epsilon
    elif name in ("adam", "adamax"):
        hyper["beta1"] = layer.adam_mean_decay if layer.adam_mean_decay is not None else 0.9
        hyper["beta2"] = layer.adam_var_decay if layer.adam_var_decay is not None else 0.999
        if layer.epsilon is not None:
            hyper["epsilon"] = layer.epsilon
    elif name == "adagrad" and layer.epsilon is not None:
        hyper["epsilon"] = layer.epsilon
    return upd_ops.make(name, **hyper)


class MultiLayerNetwork:
    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.layers: List[Layer] = conf.layers
        self.net_params: Optional[List[dict]] = None
        self.net_state: Optional[List[dict]] = None
        self.opt_states: Optional[List[Any]] = None
        self.updaters = [_updater_for(l) for l in self.layers]
        self.iteration = 0
        self.epoch = 0
        self.listeners: List[IterationListener] = []
        self._score: float = float("nan")
        self._key = jax.random.PRNGKey(conf.global_conf.seed)
        self._step_fn = None
        self._score_fn = None
        self._output_fn = None
        self._ext_grad_fn = None
        self._apply_fn = None
        self.last_batch_size = 0
        self.last_etl_time_ms = 0.0
        self.compile_telemetry = bucketing.CompileTelemetry()
        self._bucket_train_ok: Optional[bool] = None
        self.frozen: List[bool] = [type(l).__name__ == "FrozenLayerConf"
                                   for l in self.layers]

    # ------------------------------------------------------------------
    # Initialization
    # ------------------------------------------------------------------
    def init(self, params: Optional[List[dict]] = None) -> "MultiLayerNetwork":
        """Build param/state pytrees (ref: MultiLayerNetwork.init :411)."""
        cur = self._input_type_chain_start()
        key = jax.random.PRNGKey(self.conf.global_conf.seed)
        ps, ss = [], []
        for i, layer in enumerate(self.layers):
            if i in self.conf.preprocessors:
                cur = self.conf.preprocessors[i].output_type(cur)
            key, sub = jax.random.split(key)
            p, s, cur = layer.initialize(sub, cur)
            ps.append(p)
            ss.append(s)
        self.net_params = params if params is not None else ps
        self.net_state = ss
        self.opt_states = [self.updaters[i].init(self.net_params[i])
                           for i in range(len(self.layers))]
        return self

    def _input_type_chain_start(self) -> InputType:
        if self.conf.input_type is not None:
            return self.conf.input_type
        from deeplearning4j_tpu.nn.conf import layers as L
        first = self.layers[0]
        if isinstance(first, L.FrozenLayerConf):
            first = first._inner()
        n_in = getattr(first, "n_in", None)
        if n_in:
            if isinstance(first, (L.GravesLSTM, L.GravesBidirectionalLSTM)):
                return InputType.recurrent(n_in)
            return InputType.feed_forward(n_in)
        raise ValueError("Network needs conf.input_type or an explicit n_in on layer 0")

    # ------------------------------------------------------------------
    # Forward (pure, traceable)
    # ------------------------------------------------------------------
    def _layer_step(self, layer, i, train: bool, rng):
        """One layer's forward as a pure fn of (params, state, x, mask),
        wrapped in jax.checkpoint when gradient_checkpointing is on and
        we're training — activations are then recomputed during the
        backward pass instead of living in HBM (the TPU remat recipe)."""
        def fwd(p, s, x, mask):
            return layer.forward(p, s, x, train=train,
                                 rng=jax.random.fold_in(rng, i), mask=mask)
        if train and self.conf.global_conf.gradient_checkpointing:
            return jax.checkpoint(fwd)
        return fwd

    def _forward_core(self, params, state, x, mask, train: bool, rng,
                      stateful_rnn: bool, collect_acts: bool = False,
                      stop: Optional[int] = None):
        """THE per-layer forward loop (preprocessor hook, rnn-state
        gating, per-layer rng fold) — single source for _forward,
        _forward_to_preout, feed_forward and
        rnn_activate_using_stored_state so the loop contract cannot
        drift between them.  ``stop`` runs only layers[:stop]."""
        acts = []
        new_states = []
        layers = self.layers if stop is None else self.layers[:stop]
        for i, layer in enumerate(layers):
            if i in self.conf.preprocessors:
                x, mask = self.conf.preprocessors[i](x, mask)
            s = state[i]
            if not stateful_rnn and "rnn_state" in s:
                s = {k: v for k, v in s.items() if k != "rnn_state"}
            x, ns, mask = self._layer_step(layer, i, train, rng)(
                params[i], s, x, mask)
            new_states.append(ns)
            if collect_acts:
                acts.append(x)
        return x, new_states, mask, acts

    def _forward(self, params, state, x, mask, train: bool, rng,
                 stateful_rnn: bool = False):
        """Full-stack activations.  Returns (out, new_states, out_mask)."""
        out, new_states, mask, _ = self._forward_core(
            params, state, x, mask, train, rng, stateful_rnn)
        return out, new_states, mask

    def _forward_to_preout(self, params, state, x, mask, train: bool, rng,
                           stateful_rnn: bool = False):
        """Forward to the output layer's PRE-activation (stable fused loss)."""
        n = len(self.layers)
        x, new_states, mask, _ = self._forward_core(
            params, state, x, mask, train, rng, stateful_rnn, stop=n - 1)
        last = self.layers[-1]
        if (n - 1) in self.conf.preprocessors:
            x, mask = self.conf.preprocessors[n - 1](x, mask)
        if train:
            x = last._maybe_dropout(x, True, jax.random.fold_in(rng, n - 1))
        preout = last.preoutput(
            last._maybe_drop_connect(params[-1], train,
                                     jax.random.fold_in(rng, n - 1)), x)
        new_states.append(state[-1])
        return preout, new_states, mask, x

    def _reg_penalty(self, params):
        total = 0.0
        for layer, lp in zip(self.layers, params):
            total = total + self._layer_reg_penalty(layer, lp)
        return total

    @staticmethod
    def _layer_reg_penalty(layer, lp):
        total = 0.0
        l1 = layer.l1 or 0.0
        l2 = layer.l2 or 0.0
        l1b = layer.l1_bias or 0.0
        l2b = layer.l2_bias or 0.0
        for k, v in lp.items():
            if k in BIAS_KEYS:
                if l1b:
                    total = total + l1b * jnp.sum(jnp.abs(v))
                if l2b:
                    total = total + 0.5 * l2b * jnp.sum(v * v)
            elif k in WEIGHT_KEYS:
                if l1:
                    total = total + l1 * jnp.sum(jnp.abs(v))
                if l2:
                    total = total + 0.5 * l2 * jnp.sum(v * v)
        return total

    def _check_trace_token(self):
        """Invalidate cached jitted functions when ambient trace-relevant
        state changed: the sequence-parallel regime
        (parallel/sequence.sequence_mesh — shard_map collectives are baked
        into the traced program) or the mixed-precision policy
        (ops/dtypes.set_default_policy — compute dtypes are baked in too)."""
        from deeplearning4j_tpu.parallel import fsdp
        from deeplearning4j_tpu.parallel import sequence as seq_ops
        tok = (seq_ops.cache_token(),
               dtype_ops.resolve(self.conf.global_conf.precision),
               self.conf.global_conf.gradient_checkpointing,
               fsdp.conf_key(self.conf.global_conf),
               getattr(self, "_infer_quant", None))
        if tok != getattr(self, "_trace_token", None):
            self._trace_token = tok
            self._step_fn = self._score_fn = self._output_fn = None
            self._ext_grad_fn = self._apply_fn = None
            self._score_ex_fn = None
            self._fused_fns = None
            self._rnn_step_fn = None
            self._dist_cache = None
            self.compile_telemetry.invalidate()

    def _ensure_sharding(self):
        """Activate (or deactivate) the conf-declared sharding plan
        (conf.sharding(...), parallel/fsdp.py): resolve the mesh, place
        params/updater state with their NamedShardings and invalidate
        the cached step so it re-jits with in/out_shardings.  A no-op —
        replica-style training, byte-identical numerics — when sharding
        is off, only one device is visible, or the net trains TBPTT."""
        from deeplearning4j_tpu.parallel import fsdp
        plan = (None if self.conf.backprop_type == "truncatedbptt"
                else fsdp.plan_from_conf(self.conf.global_conf))
        if fsdp.plan_key(plan) == fsdp.plan_key(
                getattr(self, "_sharding_plan", None)):
            return
        self._sharding_plan = plan
        self._step_fn = None
        self._fused_fns = None
        # inference entry points re-jit too: the output path carries the
        # plan's in/out_shardings (sharded serving, ROADMAP 3a)
        self._output_fn = None
        self._rnn_step_fn = None
        if plan is not None and self.net_params is not None:
            fsdp.place_model(plan, self)

    def _replace_on_mesh(self):
        """Re-commit params/updater/state to the active plan's layout
        after a host-side overwrite (set_params / checkpoint restore) —
        the host-side reshard that makes checkpoints mesh-tolerant."""
        plan = getattr(self, "_sharding_plan", None)
        if plan is not None:
            from deeplearning4j_tpu.parallel import fsdp
            fsdp.place_model(plan, self)

    # ------------------------------------------------------------------
    # Shape bucketing (ops/bucketing.py)
    # ------------------------------------------------------------------
    def _bucket_train_enabled(self) -> bool:
        """Bucketing for loss-bearing paths (fit/score): needs the conf
        knob AND the exact pad-and-mask preconditions (mask-linear
        losses, mean reduction, no batch-coupled aux losses).  TBPTT
        segments its own time axis — excluded."""
        g = self.conf.global_conf
        if not g.shape_bucketing or self.conf.backprop_type == "truncatedbptt":
            return False
        if self._bucket_train_ok is None:
            self._bucket_train_ok = bucketing.pad_supported(self)
        return self._bucket_train_ok

    def _maybe_bucket_train(self, ds):
        """(ds, bucket) — ds padded up to its bucket when enabled."""
        if self._bucket_train_enabled():
            return bucketing.bucket_train_dataset(ds, self.conf.global_conf)
        return ds, None

    # ------------------------------------------------------------------
    # The jitted train step — ONE XLA computation per step
    # ------------------------------------------------------------------
    def _build_step(self):
        plan = getattr(self, "_sharding_plan", None)
        if plan is not None:
            from deeplearning4j_tpu.parallel import fsdp
            return fsdp.jit_sharded_step(self._build_step_raw(), plan,
                                         self.net_params, self.opt_states)
        return jax.jit(self._build_step_raw(), donate_argnums=(0, 1, 2))

    def _build_grad_raw(self):
        """The loss-and-gradient HALF of the train step — ``(params,
        state, x, y, fmask, lmask, rng) → (score, new_states, grads)``.
        The fused step composes it with ``_apply_updates`` in one trace
        (identical jaxpr to the pre-split single-closure step); the
        distributed runtime jits it alone so the cluster all-reduce sits
        between gradient and update (distributed/worker.fit_batch).

        Mixed precision (the reference trains f32; the TPU-native fast path
        is bf16 on the MXU): the policy from conf.precision / ops.dtypes
        casts params+inputs to the compute dtype INSIDE the loss closure, so
        jax.grad differentiates through the cast and yields float32 master
        gradients; updater state and the loss/softmax accumulation stay
        float32, and carried state (BN stats, RNN carries) is upcast back."""
        g = self.conf.global_conf
        policy = dtype_ops.resolve(g.precision)
        out_layer = self.layers[-1]
        if not isinstance(out_layer, (BaseOutputLayer, LossLayer)):
            raise ValueError("Last layer must be an output/loss layer to fit()")

        def grad_step(params, state, x, y, fmask, lmask, rng):
            xc, fmc = policy.cast_to_compute((x, fmask))

            def loss_fn(p):
                pc = policy.cast_to_compute(p)
                preout, new_states, m, feats = self._forward_to_preout(
                    pc, state, xc, fmc, True, rng,
                    stateful_rnn=(self.conf.backprop_type == "truncatedbptt"))
                preout = policy.cast_to_accum(preout)
                new_states = policy.cast_to_param(new_states)
                lm = lmask if lmask is not None else (
                    m if (m is not None and m.ndim == preout.ndim - 1) else None)
                if getattr(out_layer, "requires_features_for_score", False):
                    per_ex = out_layer.compute_score_with_features(
                        y, preout, policy.cast_to_accum(feats), p[-1], lm)
                else:
                    per_ex = out_layer.compute_score(y, preout, lm)
                score = jnp.mean(per_ex) if g.mini_batch else jnp.sum(per_ex)
                score = score + self._reg_penalty(p)
                # auxiliary losses surfaced by layers through their state
                # (e.g. MoE load-balancing, nn/conf/layers.py MoE layer)
                for s in new_states:
                    if isinstance(s, dict) and "moe_aux_loss" in s:
                        score = score + s["moe_aux_loss"]
                if not g.minimize:
                    score = -score
                return score, new_states

            (score, new_states), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            return score, new_states, grads

        return grad_step

    def _build_step_raw(self):
        """The pure (un-jitted) train step — ParallelWrapper re-jits it
        with mesh shardings or vmaps it for parameter-averaging compat.
        Tracing inlines :meth:`_build_grad_raw`, so the compiled step is
        byte-identical to the pre-split single-closure form."""
        grad_step = self._build_grad_raw()

        def step(params, state, opts, x, y, fmask, lmask, it, rng):
            score, new_states, grads = grad_step(params, state, x, y,
                                                 fmask, lmask, rng)
            new_params, new_opts = self._apply_updates(params, opts,
                                                       grads, it)
            return new_params, new_states, new_opts, score

        return step

    def _apply_updates(self, params, opts, grads, it):
        """Traceable gradient→param update: per-layer gradient
        normalization, LR schedule, learning rule, bias-LR override and
        frozen-layer gating.  Shared by the fused train step and the
        external-gradients path (apply_gradients)."""
        g = self.conf.global_conf
        plan = getattr(self, "_sharding_plan", None)
        new_params, new_opts = [], []
        for i, layer in enumerate(self.layers):
            gi = grads[i]
            if not gi:
                new_params.append(params[i])
                new_opts.append(opts[i])
                continue
            if self.frozen[i]:
                new_params.append(params[i])
                new_opts.append(opts[i])
                continue
            if plan is not None:
                # ZeRO weight-update sharding (arXiv 2004.13336): pin
                # each gradient to its param's fsdp layout so XLA lowers
                # the data-parallel reduction as reduce-scatter into
                # shards; the updater below then runs per-shard and the
                # next forward all-gathers the updated params.
                gi = plan.constrain_grads(gi)
            gi = upd_ops.normalize_gradient(
                gi, layer.gradient_normalization,
                layer.gradient_normalization_threshold or 1.0)
            lr = upd_ops.schedule_lr(
                layer.learning_rate if layer.learning_rate is not None else g.learning_rate,
                g.lr_policy, it,
                decay_rate=g.lr_policy_decay_rate, steps=g.lr_policy_steps,
                power=g.lr_policy_power, schedule_map=g.learning_rate_schedule)
            blr = layer.bias_learning_rate
            upd, new_opt = self.updaters[i].apply(gi, opts[i], lr, it)
            if blr is not None and blr != (layer.learning_rate or g.learning_rate):
                # bias LR override: rescale bias update (exact for linear-in-lr rules)
                base = layer.learning_rate if layer.learning_rate is not None else g.learning_rate
                scale = blr / base if base else 1.0
                upd = {k: (v * scale if k in BIAS_KEYS else v)
                       for k, v in upd.items()}
            new_params.append({k: params[i][k] - upd[k] for k in params[i]})
            new_opts.append(new_opt)
        return new_params, new_opts

    def _build_score_fn(self):
        out_layer = self.layers[-1]
        g = self.conf.global_conf
        policy = dtype_ops.resolve(g.precision)

        def score_fn(params, state, x, y, fmask, lmask):
            pc, xc, fmc = policy.cast_to_compute((params, x, fmask))
            preout, _, m, feats = self._forward_to_preout(
                pc, state, xc, fmc, False, jax.random.PRNGKey(0))
            preout = policy.cast_to_accum(preout)
            lm = lmask if lmask is not None else (
                m if (m is not None and m.ndim == preout.ndim - 1) else None)
            if getattr(out_layer, "requires_features_for_score", False):
                per_ex = out_layer.compute_score_with_features(
                    y, preout, policy.cast_to_accum(feats), params[-1], lm)
            else:
                per_ex = out_layer.compute_score(y, preout, lm)
            score = jnp.mean(per_ex) if g.mini_batch else jnp.sum(per_ex)
            return score + self._reg_penalty(params)

        return jax.jit(score_fn)

    def _build_output_fn(self):
        policy = dtype_ops.resolve(self.conf.global_conf.precision)
        quant = getattr(self, "_infer_quant", None)

        def output_fn(params, state, x, fmask):
            if quant is not None:
                # weight-only quantized serving: params arrive as int8/
                # fp8 codes + per-channel scales; the expand fuses into
                # the first consumer matmul (ops/quantize.py)
                from deeplearning4j_tpu.ops import quantize as qz
                params = qz.dequantize_params(params)
            pc, xc, fmc = policy.cast_to_compute((params, x, fmask))
            out, _, _ = self._forward(pc, state, xc, fmc, False,
                                      jax.random.PRNGKey(0))
            return policy.cast_to_param(out)
        plan = getattr(self, "_sharding_plan", None)
        if plan is not None:
            # sharded serving (ROADMAP 3a): a model that only fits
            # sharded serves through the same plan the fit path uses —
            # params stay in their fsdp layout, the batch shards over
            # data(+fsdp), the output all-gathers on device
            from deeplearning4j_tpu.parallel import fsdp
            return fsdp.jit_sharded_output(output_fn, plan, self.net_params)
        return jax.jit(output_fn)

    # ------------------------------------------------------------------
    # Training API
    # ------------------------------------------------------------------
    def set_listeners(self, *listeners: IterationListener):
        self.listeners = list(listeners)
        return self

    def add_listener(self, listener: IterationListener):
        self.listeners.append(listener)
        return self

    def fit(self, data, labels=None, epochs: int = 1,
            fused_steps: int = 1):
        """fit(DataSetIterator) | fit(DataSet) | fit(x, y)
        (ref: MultiLayerNetwork.fit :996).

        ``fused_steps=K>1`` fuses K consecutive same-shape batches into
        ONE compiled launch (`lax.scan` over the train step) — the
        per-step host dispatch that bounds small-model TPU throughput
        disappears; the reference has no analog (its fit loop is
        inherently per-batch, MultiLayerNetwork.fit :996).  Semantics
        divergence, documented: listeners fire once per LAUNCH (seeing
        the last score of the group), not once per batch; groups need
        identical shapes/mask-presence (ragged tails fall back to
        per-step); TBPTT ignores the flag."""
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.datasets.iterators import (
            AsyncDataSetIterator, DataSetIterator, ListDataSetIterator,
            reader_retry_from_conf)

        if labels is not None:
            data = DataSet(np.asarray(data), np.asarray(labels))
        if isinstance(data, DataSet):
            data = ListDataSetIterator([data])
        assert isinstance(data, DataSetIterator)
        if self.net_params is None:
            self.init()
        bucketing.maybe_enable_persistent_cache()
        # warm-validate the fused-kernel helper tier (ops/helpers.py)
        # BEFORE the first step traces: a Mosaic rejection flips that
        # tier's kill switch here instead of killing the training run
        from deeplearning4j_tpu.ops import helpers as pallas_helpers
        pallas_helpers.ensure_validated()
        self._check_trace_token()
        self._ensure_sharding()
        if self._step_fn is None:
            self._step_fn = self._build_step()

        it = data
        g = self.conf.global_conf
        # elastic cluster training (conf.distributed(...)): attach the
        # process's DistSession so every batch routes through the
        # coordinator barrier step (distributed/worker.fit_batch);
        # without a coordinator the conf is inert (replica semantics)
        if getattr(self, "_dist_session", None) is None \
                and getattr(g, "dist_enabled", False):
            from deeplearning4j_tpu import distributed as dist_mod
            self._dist_session = dist_mod.maybe_session(g)
        dist_sess = getattr(self, "_dist_session", None)
        if dist_sess is not None:
            dist_sess.attach(self)
        # crash-safe resume (conf.fault_tolerance(resume=True)): restore
        # the newest valid checkpoint into this model and skip the
        # already-trained epochs/batches so the resumed trajectory
        # matches an uninterrupted run (nn/checkpoint.py)
        from deeplearning4j_tpu.nn import checkpoint as ckpt_mod
        skip_epochs, skip_batches = ckpt_mod.maybe_auto_resume(self)
        if dist_sess is not None:
            # a worker absorbed into a running cluster restores the
            # survivors' in-memory snapshot and replay-skips the
            # already-trained prefix, exactly like a checkpoint resume
            skip_epochs, skip_batches = dist_sess.resume_position(
                self, skip_epochs, skip_batches)
        if (g.pipeline_workers > 0 and it.async_supported()
                and not isinstance(it, AsyncDataSetIterator)):
            plan = getattr(self, "_sharding_plan", None)
            transform = None
            if self._bucket_train_enabled():
                gg = self.conf.global_conf
                # bucket on a worker thread, BEFORE device_put: the
                # H2D transfer is then already bucket-shaped and the
                # engine's own bucketing hits its no-op fast path.
                # Under a sharding plan the bucket is lifted to a
                # data-degree multiple so the sharded normalize is a
                # no-op too.
                min_mult = plan.n_data if plan is not None else 1
                transform = lambda d: bucketing.bucket_train_dataset(  # noqa: E731
                    d, gg, min_multiple=min_mult)[0]
            it = AsyncDataSetIterator(
                it, queue_size=g.pipeline_prefetch,
                workers=g.pipeline_workers,
                staging_depth=g.pipeline_staging_depth,
                # sharded fit scatters each batch across the mesh itself
                # (fsdp.shard_put); staging to one device first would
                # just bounce the rows device→host→mesh
                device_put=(plan is None), transform=transform,
                reader_retry=reader_retry_from_conf(g))

        # fused path steps the updater once per batch; a conf with
        # iterations>1 (multiple updates per batch) keeps exact
        # semantics on the per-step path instead; the distributed step
        # barriers per batch, so scan fusion cannot apply
        fuse = (max(1, int(fused_steps))
                if (self.conf.backprop_type != "truncatedbptt"
                    and self.conf.global_conf.iterations <= 1
                    and dist_sess is None) else 1)
        try:
            # DL4J_SANITIZE: debug-nans/rank checks for the duration,
            # retrace-budget assertion on clean exit (analysis/sanitizer).
            # The events.scope gives this fit a correlation ID so every
            # fit/step span and checkpoint event journals under it.
            with sanitizer.armed_fit(self), \
                    monitor.profile_if_configured("fit"), \
                    events.scope(fit_id=events.new_request_id(),
                                 model=type(self).__name__):
                events.emit("fit.start", epochs=epochs,
                            iteration=self.iteration)
                for ep_i in range(epochs):
                    if ep_i < skip_epochs:
                        continue  # resumed past this epoch entirely
                    to_skip = skip_batches if ep_i == skip_epochs else 0
                    # the epoch's notional starting iteration — what
                    # CheckpointListener subtracts to record how many
                    # batches into the epoch a save landed
                    self._epoch_start_iter = self.iteration - to_skip
                    for lst in self.listeners:
                        if isinstance(lst, TrainingListener):
                            lst.on_epoch_start(self)
                    it.reset()
                    t_etl = time.perf_counter()
                    pending = []
                    while it.has_next():
                        with monitor.span("fit/step", phase="data_wait"):
                            ds = it.next()
                        if to_skip > 0:
                            # replay-skip: consume (keeps the stream
                            # position identical to the crashed run)
                            # without training or advancing iteration
                            to_skip -= 1
                            t_etl = time.perf_counter()
                            continue
                        self.last_etl_time_ms = \
                            (time.perf_counter() - t_etl) * 1e3
                        if fuse > 1:
                            pending.append(ds)
                            if len(pending) == fuse:
                                self._fit_fused_group(pending)
                                pending = []
                        else:
                            self._fit_batch(ds)
                        t_etl = time.perf_counter()
                    for ds in pending:  # ragged tail: per-step path
                        self._fit_batch(ds)
                    for lst in self.listeners:
                        if isinstance(lst, TrainingListener):
                            lst.on_epoch_end(self)
                    self.epoch += 1
                events.emit("fit.end", iteration=self.iteration,
                            epoch=self.epoch)
        finally:
            # release pipeline threads — a producer blocked on a full
            # queue mid-exception would otherwise leak (close() is
            # idempotent and the iterator restarts lazily if reused)
            if isinstance(it, AsyncDataSetIterator):
                it.close()
        return self

    def _build_fused_step(self, k: int):
        """K train steps as one compiled program: lax.scan over the raw
        step with the batch axis stacked in front.  Dispatch once, step
        K times — the bench's scan-fused ceiling as an engine feature."""
        raw = self._build_step_raw()

        def strip_rnn(state):
            # in-trace equivalent of _strip_rnn_state: RNN layers emit a
            # carried 'rnn_state' each step; dropping it inside the body
            # keeps the scan carry structure closed AND stops hidden
            # state leaking across unrelated minibatches in a group
            return [{kk: v for kk, v in s.items() if kk != "rnn_state"}
                    for s in state]

        def k_steps(params, state, opts, xs, ys, fms, lms, it0, key):
            def body(carry, inp):
                p, s, o = carry
                i, x, y, fm, lm = inp
                p, s, o, score = raw(p, s, o, x, y, fm, lm, it0 + i,
                                     jax.random.fold_in(key, i))
                return (p, strip_rnn(s), o), score
            (params, state, opts), scores = jax.lax.scan(
                body, (params, strip_rnn(state), opts),
                (jnp.arange(k), xs, ys, fms, lms))
            return params, state, opts, scores[-1]

        return jax.jit(k_steps, donate_argnums=(0, 1, 2))  # dl4j: noqa[DL4J104] one jitted fn per k, cached in _fused_fns[k]

    def _fit_fused_group(self, group):
        if getattr(self, "_sharding_plan", None) is not None:
            self._fit_fused_group_sharded(group)
            return
        sizes = [d.num_examples() for d in group]
        # bucketing makes ragged groups (mixed batch sizes / RNN time
        # lengths, the tail of any real stream) bucket-uniform so they
        # STAY on the fused scan path instead of degrading to per-step
        group = [self._maybe_bucket_train(d)[0] for d in group]
        k = len(group)
        shapes = {(d.features.shape, d.labels.shape,
                   d.features.dtype, d.labels.dtype,
                   d.features_mask is None, d.labels_mask is None)
                  for d in group}
        if len(shapes) != 1:
            for d in group:   # mixed shapes can't stack — per-step
                self._fit_batch(d)
            return
        # first-ever launch runs ONE batch per-step so carried state
        # (e.g. a layer adding aux-state keys) reaches its steady
        # structure before it becomes a scan carry
        if getattr(self, "_fused_fns", None) is None:
            self._fused_fns = {}
            self._fit_batch(group[0])
            group, sizes = group[1:], sizes[1:]
            k = len(group)
            if not k:
                return
        if k not in self._fused_fns:
            self._fused_fns[k] = self._build_fused_step(k)
        t_step = time.perf_counter()
        with monitor.span("fit/step", phase="h2d"):
            xs = jnp.stack([jnp.asarray(d.features) for d in group])
            ys = jnp.stack([jnp.asarray(d.labels) for d in group])
            fms = (jnp.stack([jnp.asarray(d.features_mask) for d in group])
                   if group[0].features_mask is not None else None)
            lms = (jnp.stack([jnp.asarray(d.labels_mask) for d in group])
                   if group[0].labels_mask is not None else None)
        fresh = self.compile_telemetry.record(f"fused_step_k{k}",
                                              (xs, ys, fms, lms))
        self._key, sub = jax.random.split(self._key)
        it_arr = jnp.asarray(self.iteration, jnp.int32)
        with monitor.span("fit/step", phase="jit_call"), \
                sanitizer.guard_step(compiling=fresh):
            (self.net_params, self.net_state, self.opt_states,
             score) = self._fused_fns[k](
                self.net_params, self.net_state, self.opt_states,
                xs, ys, fms, lms, it_arr, sub)
        with monitor.span("fit/step", phase="block_until_ready"):
            jax.block_until_ready(score)
        self._strip_rnn_state()
        self._score = score
        self.iteration += k
        self.last_batch_size = sum(sizes)
        monitor.record_fit_step(self.last_batch_size,
                                time.perf_counter() - t_step, score)
        with monitor.span("fit/step", phase="listeners"):
            for lst in self.listeners:
                lst.iteration_done(self, self.iteration)

    def _fit_fused_group_sharded(self, group):
        """fused_steps=K under a sharding plan: each batch is padded to
        the data degree, the group stacks along a leading scan axis with
        the scan-aware sharding P(None, ('data','fsdp')), and the
        engine's own fused builder runs — params/updater are committed
        with their mesh shardings so jit composes the per-step
        reduce-scatter/all-gather with the scan without a wrapper-side
        re-implementation."""
        from deeplearning4j_tpu.parallel import fsdp
        plan = self._sharding_plan
        norms = [fsdp.normalize_batch(self, d, plan.n_data, is_graph=False)
                 for d in group]
        if any(n is None for n in norms):
            for d in group:
                self._fit_batch(d)
            return

        def sig(batch):
            leaves, treedef = jax.tree_util.tree_flatten(batch)
            return (treedef, tuple((a.shape, a.dtype) for a in leaves))
        if len({sig(b) for b, _, _ in norms}) != 1:
            for d in group:   # mixed shapes can't stack — per-step
                self._fit_batch(d)
            return
        # first-ever launch runs ONE batch per-step so carried state
        # reaches its steady structure before it becomes a scan carry
        if getattr(self, "_fused_fns", None) is None:
            self._fused_fns = {}
            self._fit_batch(group[0])
            group, norms = group[1:], norms[1:]
            if not norms:
                return
        k = len(norms)
        if k not in self._fused_fns:
            self._fused_fns[k] = self._build_fused_step(k)
        t_step = time.perf_counter()
        with monitor.span("fit/step", phase="shard_h2d"):
            xs, ys, fms, lms = fsdp.stack_for_scan(
                plan, [b for b, _, _ in norms])
        fresh = self.compile_telemetry.record(f"fused_step_k{k}",
                                              (xs, ys, fms, lms))
        self._key, sub = jax.random.split(self._key)
        it_arr = jnp.asarray(self.iteration, jnp.int32)
        with monitor.span("fit/step", phase="jit_call"), \
                sanitizer.guard_step(compiling=fresh):
            (self.net_params, self.net_state, self.opt_states,
             score) = self._fused_fns[k](
                self.net_params, self.net_state, self.opt_states,
                xs, ys, fms, lms, it_arr, sub)
        with monitor.span("fit/step", phase="block_until_ready"):
            jax.block_until_ready(score)
        self._strip_rnn_state()
        self._score = score
        self.iteration += k
        self.last_batch_size = sum(n for _, n, _ in norms)
        monitor.record_fit_step(self.last_batch_size,
                                time.perf_counter() - t_step, score)
        with monitor.span("fit/step", phase="listeners"):
            for lst in self.listeners:
                lst.iteration_done(self, self.iteration)

    def _fit_batch(self, ds):
        g = self.conf.global_conf
        self.last_batch_size = ds.num_examples()
        if self.conf.backprop_type == "truncatedbptt" and ds.features.ndim == 3:
            self._fit_tbptt(ds)
            return
        dist_sess = getattr(self, "_dist_session", None)
        if dist_sess is not None:
            # cluster step: shard-local grads → coordinator all-reduce →
            # updater apply (docs/DISTRIBUTED.md); TBPTT stays local
            from deeplearning4j_tpu.distributed import worker as dist_worker
            dist_worker.fit_batch(self, ds, dist_sess, is_graph=False)
            return
        t_step = time.perf_counter()
        plan = getattr(self, "_sharding_plan", None)
        if plan is not None:
            from deeplearning4j_tpu.parallel import fsdp
            with monitor.span("fit/step", phase="bucket"):
                # pad (mask-exact) or trim the batch to the data degree;
                # shape bucketing, when on, subsumes this by lifting the
                # bucket to a data-degree multiple
                norm = fsdp.normalize_batch(self, ds, plan.n_data,
                                            is_graph=False)
            if norm is None:
                return
            batch, n, bucket = norm
            self.last_batch_size = n
            fresh = self.compile_telemetry.record("sharded_step", batch,
                                                  bucket=bucket)
            with monitor.span("fit/step", phase="shard_h2d"):
                # host→mesh scatter: each device receives only its batch
                # shard (the sharded step's in_shardings layout)
                feats, labels, fmask, lmask = fsdp.shard_put(plan, batch)
        else:
            with monitor.span("fit/step", phase="bucket"):
                ds, bucket = self._maybe_bucket_train(ds)
            fresh = self.compile_telemetry.record(
                "train_step", (ds.features, ds.labels, ds.features_mask,
                               ds.labels_mask), bucket=bucket)
            with monitor.span("fit/step", phase="h2d"):
                # no-op when the async iterator already device_put the
                # batch; otherwise this is the host→device transfer,
                # timed apart from the jitted call it used to hide inside
                feats = jnp.asarray(ds.features)
                labels = jnp.asarray(ds.labels)
                fmask = (None if ds.features_mask is None
                         else jnp.asarray(ds.features_mask))
                lmask = (None if ds.labels_mask is None
                         else jnp.asarray(ds.labels_mask))
        for _ in range(max(1, g.iterations)):
            self._key, sub = jax.random.split(self._key)
            # the iteration scalar moves H2D here, OUTSIDE the guarded
            # dispatch — inside it every transfer is a bug
            it_arr = jnp.asarray(self.iteration, jnp.int32)
            with monitor.span("fit/step", phase="jit_call"), \
                    sanitizer.guard_step(compiling=fresh):
                (self.net_params, self.net_state, self.opt_states,
                 score) = self._step_fn(
                    self.net_params, self.net_state, self.opt_states,
                    feats, labels, fmask, lmask, it_arr, sub)
            with monitor.span("fit/step", phase="block_until_ready"):
                jax.block_until_ready(score)
            self._strip_rnn_state()
            self._score = score
            self.iteration += 1
            monitor.record_fit_step(self.last_batch_size,
                                    time.perf_counter() - t_step, score)
            with monitor.span("fit/step", phase="listeners"):
                for lst in self.listeners:
                    lst.iteration_done(self, self.iteration)
            t_step = time.perf_counter()
            fresh = False

    def _fit_tbptt(self, ds):
        """Truncated BPTT over time segments, carrying RNN state
        (ref: MultiLayerNetwork.doTruncatedBPTT :1227)."""
        T = ds.features.shape[1]  # native layout [N, T, C]
        L = self.conf.tbptt_fwd_length
        self.rnn_clear_previous_state()
        for t0 in range(0, T, L):
            seg = slice(t0, min(t0 + L, T))
            f = ds.features[:, seg]
            l = ds.labels[:, seg] if ds.labels.ndim == 3 else ds.labels
            fm = ds.features_mask[:, seg] if ds.features_mask is not None else None
            lm = ds.labels_mask[:, seg] if ds.labels_mask is not None else None
            self._key, sub = jax.random.split(self._key)
            (self.net_params, self.net_state, self.opt_states, score) = self._step_fn(
                self.net_params, self.net_state, self.opt_states,
                f, l, fm, lm, jnp.asarray(self.iteration, jnp.int32), sub)
            self._score = score
            self.iteration += 1
            for lst in self.listeners:
                lst.iteration_done(self, self.iteration)

    # ------------------------------------------------------------------
    # Layerwise unsupervised pretraining (AE / RBM / VAE)
    # ------------------------------------------------------------------
    def pretrain(self, data, epochs: int = 1):
        """Layerwise pretrain every pretrain-capable layer
        (ref: MultiLayerNetwork.pretrain :1010-1024)."""
        for i, layer in enumerate(self.layers):
            if layer.is_pretrain_layer():
                self.pretrain_layer(i, data, epochs=epochs)
        return self

    def pretrain_layer(self, layer_idx: int, data, epochs: int = 1):
        """Unsupervised fit of one layer on activations of the layers below
        (ref: MultiLayerNetwork.pretrainLayer :197).  The per-layer step —
        forward-to-layer, pretrain loss, grad, updater — is one jitted XLA
        program with donated param/opt buffers."""
        from deeplearning4j_tpu.datasets.dataset import DataSet
        from deeplearning4j_tpu.datasets.iterators import (
            DataSetIterator, ListDataSetIterator)

        layer = self.layers[layer_idx]
        if not layer.is_pretrain_layer():
            return self
        if self.net_params is None:
            self.init()
        if isinstance(data, (np.ndarray, jax.Array)):
            data = DataSet(np.asarray(data), np.asarray(data))
        if isinstance(data, DataSet):
            data = ListDataSetIterator([data])
        assert isinstance(data, DataSetIterator)

        g = self.conf.global_conf
        updater = self.updaters[layer_idx]

        def pre_step(lp, opt, prefix_params, state, x, it, rng):
            def to_layer_input(xi):
                m = None
                for j in range(layer_idx):
                    if j in self.conf.preprocessors:
                        xi, m = self.conf.preprocessors[j](xi, m)
                    xi, _, m = self.layers[j].forward(
                        prefix_params[j], state[j], xi, train=False,
                        rng=jax.random.fold_in(rng, j), mask=m)
                if layer_idx in self.conf.preprocessors:
                    xi, m = self.conf.preprocessors[layer_idx](xi, m)
                return xi

            feats = jax.lax.stop_gradient(to_layer_input(x))

            def full_loss(p):
                # pretrain score includes this layer's l1/l2 and honors
                # minimize, matching the supervised step (ref:
                # BasePretrainNetwork score includes regularization)
                loss = layer.pretrain_loss(p, feats, rng) + \
                    self._layer_reg_penalty(layer, p)
                return loss if g.minimize else -loss

            loss, grads = jax.value_and_grad(full_loss)(lp)
            grads = upd_ops.normalize_gradient(
                grads, layer.gradient_normalization,
                layer.gradient_normalization_threshold or 1.0)
            lr = upd_ops.schedule_lr(
                layer.learning_rate if layer.learning_rate is not None
                else g.learning_rate,
                g.lr_policy, it,
                decay_rate=g.lr_policy_decay_rate, steps=g.lr_policy_steps,
                power=g.lr_policy_power, schedule_map=g.learning_rate_schedule)
            upd, new_opt = updater.apply(grads, opt, lr, it)
            new_lp = {k: lp[k] - upd[k] for k in lp}
            return new_lp, new_opt, loss

        step_jit = jax.jit(pre_step, donate_argnums=(0, 1))  # dl4j: noqa[DL4J104] one pretrain jit per layer by design
        for _ in range(epochs):
            data.reset()
            while data.has_next():
                ds = data.next()
                self._key, sub = jax.random.split(self._key)
                lp, opt, loss = step_jit(
                    self.net_params[layer_idx], self.opt_states[layer_idx],
                    self.net_params[:layer_idx], self.net_state, ds.features,
                    jnp.asarray(self.iteration, jnp.int32), sub)
                self.net_params[layer_idx] = lp
                self.opt_states[layer_idx] = opt
                self._score = loss
                self.iteration += 1
                for lst in self.listeners:
                    lst.iteration_done(self, self.iteration)
        return self

    def _strip_rnn_state(self):
        """Drop per-batch RNN carry so standard training doesn't leak state
        across minibatches (and jit sees a stable state structure)."""
        if self.net_state is None:
            return
        self.net_state = [{k: v for k, v in s.items() if k != "rnn_state"}
                          for s in self.net_state]

    # ------------------------------------------------------------------
    # Inference API
    # ------------------------------------------------------------------
    def quantize_inference(self, mode: str = "int8"):
        """Serve from weight-only quantized params (docs/PERFORMANCE.md
        "Precision tiers"): every ndim>=2 float param becomes int8 (or
        fp8) codes + per-channel f32 scales, dequantized IN-TRACE, so
        ``output()``/the micro-batcher/warmup hold ~4x-smaller resident
        weights.  Selection goes through the precision-tier registry
        (ops/helpers.py): the tier's parity self-test runs first, and a
        kill switch (``DL4J_PRECISION_{INT8,FP8}=0``) or failed
        self-test degrades to dense serving.  ``mode=None`` restores
        dense serving.  Inert under a sharding plan (sharded serving
        keeps the fsdp layout).  Training is untouched — fit() keeps
        the fp32 master params, and the codes refresh from them lazily
        after further training."""
        from deeplearning4j_tpu.ops import helpers as pallas_helpers
        if mode is None:
            self._infer_quant = None
            self._q_params = None
            self._check_trace_token()
            return self
        if self.net_params is None:
            self.init()
        self._ensure_sharding()
        mode = str(mode).lower()
        if mode not in ("int8", "fp8"):
            raise ValueError(f"unknown inference quantization '{mode}' "
                             "(known: int8, fp8)")
        if getattr(self, "_sharding_plan", None) is not None:
            return self  # sharded serving keeps the dense fsdp layout
        tier = f"{mode}_infer"
        if not (pallas_helpers.precision_enabled(tier, True)
                and pallas_helpers.ensure_precision_validated(tier)):
            self._infer_quant = None
            self._q_params = None
            self._check_trace_token()
            return self
        self._infer_quant = mode
        self._q_params = None  # re-quantized lazily by _infer_params
        self._check_trace_token()
        return self

    def _infer_params(self):
        """Params for the serving path: the quantized codes when the
        int8/fp8 tier is on (refreshed when training moved the masters
        since the last quantization), else the dense params."""
        quant = getattr(self, "_infer_quant", None)
        if quant is None:
            return self.net_params
        if getattr(self, "_q_params", None) is None \
                or getattr(self, "_q_iteration", -1) != self.iteration:
            from deeplearning4j_tpu.ops import quantize as qz
            self._q_params, self._q_stats = qz.quantize_params(
                self.net_params, quant)
            self._q_iteration = self.iteration
        return self._q_params

    def output(self, x, train: bool = False, mask=None):
        """(ref: MultiLayerNetwork.output :1668)"""
        if self.net_params is None:
            self.init()
        self._check_trace_token()
        self._ensure_sharding()
        if self._output_fn is None:
            self._output_fn = self._build_output_fn()
        plan = getattr(self, "_sharding_plan", None)
        unpad = bucket = None
        if self.conf.global_conf.shape_bucketing:
            x, mask, n, t, bucket = bucketing.bucket_inference_features(
                x, mask, self.conf.global_conf)
            unpad = (n, t, bucket[1])
        if plan is not None:
            # data-sharded layout needs a batch divisible by the mesh's
            # batch degree; zero rows are exact at inference and the
            # unpad slice below removes them
            from deeplearning4j_tpu.parallel import fsdp
            x, mask, n_real = fsdp.pad_inference_rows(x, mask, plan.n_data)
            if n_real is not None and unpad is None:
                unpad = (n_real, None, None)
        self.compile_telemetry.record("output", (x, mask), bucket=bucket)
        out = self._output_fn(self._infer_params(),
                              [{k: v for k, v in s.items() if k != "rnn_state"}
                               for s in self.net_state],
                              jnp.asarray(x),
                              None if mask is None else jnp.asarray(mask))
        if unpad is not None:
            out = bucketing.unpad_outputs(out, *unpad)
        return out

    def predict(self, x) -> np.ndarray:
        """Argmax class predictions (ref: MultiLayerNetwork.predict :1456)."""
        out = self.output(x)
        return jax.device_get(jnp.argmax(out, axis=-1))

    def warmup_inference(self, feature_dims, max_batch: int = 32,
                         batch_sizes=None, dtype=np.float32) -> dict:
        """Pre-compile the jitted inference path for every batch bucket
        a serving frontend can hand it, so first requests never pay a
        cold XLA compile.  ``feature_dims`` is the per-example feature
        shape (``(F,)``, ``(C, H, W)``, ``(T, C)`` …); the ladder is
        ``batch_sizes`` / the configured bucket ladder / powers of two
        up to ``max_batch`` (ops/bucketing.warmup_ladder).  Reuses the
        same jitted ``output`` entry point real requests hit — with
        shape bucketing enabled each warmed bucket is exactly the
        program a padded request executes.  Returns the warmed ladder
        and wall time."""
        if self.net_params is None:
            self.init()
        g = self.conf.global_conf
        ladder = bucketing.warmup_ladder(
            batch_sizes or g.bucket_batch_sizes, max_batch)
        dims = tuple(int(d) for d in feature_dims)
        t0 = time.perf_counter()
        for nb in ladder:
            jax.block_until_ready(self.output(np.zeros((nb,) + dims, dtype)))
        return {"buckets": ladder,
                "warmup_sec": round(time.perf_counter() - t0, 3)}

    def feed_forward(self, x, train: bool = False, mask=None):
        """All layer activations (ref: feedForward :696-788)."""
        if self.net_params is None:
            self.init()
        self._key, sub = jax.random.split(self._key)
        _, _, _, acts = self._forward_core(
            self.net_params, self.net_state, jnp.asarray(x), mask, train,
            sub, stateful_rnn=False, collect_acts=True)
        return acts

    def score(self, dataset=None) -> float:
        """Loss on a DataSet, or last training score
        (ref: MultiLayerNetwork.score)."""
        if dataset is None:
            return float(self._score)
        self._check_trace_token()
        if self._score_fn is None:
            self._score_fn = self._build_score_fn()
        ds, bucket = self._maybe_bucket_train(dataset)
        self.compile_telemetry.record(
            "score", (ds.features, ds.labels, ds.features_mask,
                      ds.labels_mask), bucket=bucket)
        return float(self._score_fn(self.net_params, self.net_state,
                                    ds.features, ds.labels,
                                    ds.features_mask, ds.labels_mask))

    def score_examples(self, data, add_regularization_terms: bool = False):
        """Per-example scores WITHOUT minibatch averaging — the anomaly-
        detection / per-example-attribution API (ref:
        MultiLayerNetwork.scoreExamples :1884 iterator, :1901 DataSet;
        addRegularizationTerms adds the net's l1/l2 penalty to every
        example's score).  Accepts a DataSet or an iterator; returns a 1-D
        np.ndarray of length total-examples."""
        from deeplearning4j_tpu.datasets.dataset import DataSet
        if self.net_params is None:
            self.init()
        self._check_trace_token()
        if getattr(self, "_score_ex_fn", None) is None:
            out_layer = self.layers[-1]
            policy = dtype_ops.resolve(self.conf.global_conf.precision)

            def score_ex(params, state, x, y, fmask, lmask, add_reg):
                pc, xc, fmc = policy.cast_to_compute((params, x, fmask))
                preout, _, m, feats = self._forward_to_preout(
                    pc, state, xc, fmc, False, jax.random.PRNGKey(0))
                preout = policy.cast_to_accum(preout)
                lm = lmask if lmask is not None else (
                    m if (m is not None and m.ndim == preout.ndim - 1)
                    else None)
                if getattr(out_layer, "requires_features_for_score", False):
                    per_ex = out_layer.compute_score_with_features(
                        y, preout, policy.cast_to_accum(feats), params[-1],
                        lm)
                else:
                    per_ex = out_layer.compute_score(y, preout, lm)
                return per_ex + jnp.where(add_reg,
                                          self._reg_penalty(params), 0.0)

            self._score_ex_fn = jax.jit(score_ex)
        batches = [data] if isinstance(data, DataSet) else data
        g = self.conf.global_conf
        # per-example scoring needs no minibatch mean, so the bucket gate
        # drops the mean-reduction requirement; padded rows are sliced
        # back off (masks stay UNSCALED so real rows keep exact values)
        bucket_ok = (g.shape_bucketing
                     and bucketing.pad_supported(self, require_mean=False))
        out = []
        for ds in batches:
            n = ds.num_examples()
            bucket = None
            if bucket_ok:
                ds, bucket = bucketing.bucket_train_dataset(
                    ds, g, scale_loss=False)
            self.compile_telemetry.record(
                "score_examples", (ds.features, ds.labels, ds.features_mask,
                                   ds.labels_mask), bucket=bucket)
            per = np.asarray(self._score_ex_fn(
                self.net_params, self.net_state, ds.features, ds.labels,
                ds.features_mask, ds.labels_mask,
                jnp.asarray(add_regularization_terms)))
            out.append(per[:n] if bucket is not None else per)
        return np.concatenate(out)

    def _merge_rnn_state(self, new_states) -> None:
        """Persist per-layer rnn carries into the live state, leaving
        everything else (BN running stats) untouched."""
        merged = []
        for old, new in zip(self.net_state, new_states):
            s = dict(old)
            if "rnn_state" in new:
                s["rnn_state"] = new["rnn_state"]
            merged.append(s)
        self.net_state = merged

    def _rnn_step_raw(self):
        """The pure carried decode step — the seam shared by
        :meth:`rnn_time_step` and the serving decode pool
        (``server/decode.py``): ``(params, base_state, carries, x,
        fmask) -> (out, new_carries)`` where ``carries`` is a per-layer
        list of recurrent carry pytrees (``None`` for carry-free
        layers).  Keeping the carry EXPLICIT in the signature (instead
        of buried inside ``net_state``) is what makes the structure
        closed under iteration, so ONE jitted trace serves every step
        of an autoregressive stream (arXiv 2603.09555's compiled-carry
        contract — no per-step retrace, no per-step re-dispatch of the
        whole layer stack).  The forward traces under
        ``kv_decode_scope``: attention layers swap their re-run-window
        core for the incremental ring-cached step, so their KV ring is
        just another carry leaf closed under iteration."""
        from deeplearning4j_tpu.parallel import sequence as seq_ops
        policy = dtype_ops.resolve(self.conf.global_conf.precision)

        def rnn_fn(params, state, carries, x, fmask):
            pc, cc, xc, fmc = policy.cast_to_compute(
                (params, carries, x, fmask))
            st = []
            for s, c in zip(state, cc):
                s = {k: v for k, v in s.items() if k != "rnn_state"}
                if c is not None:
                    s["rnn_state"] = c
                st.append(s)
            with seq_ops.kv_decode_scope():
                out, new_states, _ = self._forward(
                    pc, st, xc, fmc, False, jax.random.PRNGKey(0),
                    stateful_rnn=True)
            new_carries = [ns.get("rnn_state")
                           if isinstance(ns, dict) else None
                           for ns in new_states]
            return (policy.cast_to_param(out),
                    policy.cast_to_param(new_carries))

        return rnn_fn

    def rnn_carry_template(self, n: int, feature_tail=None,
                           dtype=jnp.float32):
        """Zero-initialized per-layer carry pytree for ``n`` concurrent
        streams — shapes discovered via ``jax.eval_shape`` over the
        carried step (no compile, no device work), so ANY layer that
        emits an ``rnn_state`` carry participates without a per-type
        registry.  ``feature_tail`` is the per-example input shape tail
        (``(T, C)``); defaults to one timestep of the conf's recurrent
        input type."""
        if self.net_params is None:
            self.init()
        if feature_tail is None:
            it = self._input_type_chain_start()
            if it.kind != "rnn":
                raise ValueError(
                    "rnn_carry_template needs a recurrent input type "
                    "(or an explicit feature_tail=)")
            feature_tail = (1, it.size)
        x_sds = jax.ShapeDtypeStruct(
            (int(n),) + tuple(int(d) for d in feature_tail), dtype)
        base = [{k: v for k, v in s.items() if k != "rnn_state"}
                for s in self.net_state]
        _, spec = jax.eval_shape(
            self._rnn_step_raw(), self.net_params, base,
            [None] * len(self.layers), x_sds, None)
        return jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), spec)

    def rnn_time_step(self, x, mask=None):
        """Stateful single/multi-step inference, carrying RNN state across
        calls (ref: MultiLayerNetwork.rnnTimeStep :2383).  x: [N, T, C].

        Every call is the SAME cached jitted step: the first call
        materializes a zero carry template (so the carry structure is
        identical with and without stored state) and each subsequent
        call re-dispatches the one compiled program — per-token cost is
        O(1) in how much history the stream has consumed."""
        if self.net_params is None:
            self.init()
        self._check_trace_token()
        if getattr(self, "_rnn_step_fn", None) is None:
            self._rnn_step_fn = jax.jit(self._rnn_step_raw())
        x = jnp.asarray(x)
        m = None if mask is None else jnp.asarray(mask)
        carries = [s.get("rnn_state") for s in self.net_state]
        if all(c is None for c in carries):
            carries = self.rnn_carry_template(
                x.shape[0], feature_tail=tuple(x.shape[1:]), dtype=x.dtype)
        self.compile_telemetry.record("rnn_time_step", (x, m, carries))
        out, new_carries = self._rnn_step_fn(
            self.net_params,
            [{k: v for k, v in s.items() if k != "rnn_state"}
             for s in self.net_state],
            carries, x, m)
        merged = []
        for s, c in zip(self.net_state, new_carries):
            s = {k: v for k, v in s.items() if k != "rnn_state"}
            if c is not None:
                s["rnn_state"] = c
            merged.append(s)
        self.net_state = merged
        return out

    def rnn_clear_previous_state(self):
        self._strip_rnn_state()

    def rnn_activate_using_stored_state(self, x, training: bool = False,
                                        store_last_for_tbptt: bool = False):
        """All layer activations computed FROM the stored RNN state,
        optionally persisting the final carry (ref:
        MultiLayerNetwork.rnnActivateUsingStoredState :1955 — the TBPTT
        engine's forward; exposed for parity and inspection)."""
        if self.net_params is None:
            self.init()
        if training:
            # fresh dropout masks per call (feed_forward's convention);
            # a fixed key would train a fixed subnetwork
            self._key, sub = jax.random.split(self._key)
        else:
            sub = jax.random.PRNGKey(0)
        _, new_states, _, acts = self._forward_core(
            self.net_params, self.net_state, jnp.asarray(x), None, training,
            sub, stateful_rnn=True, collect_acts=True)
        if store_last_for_tbptt:
            self._merge_rnn_state(new_states)
        return acts

    # ------------------------------------------------------------------
    # External-errors backprop (the RL pattern: caller owns the loss)
    # ------------------------------------------------------------------
    def backprop_gradient(self, x, epsilon, mask=None, train: bool = False):
        """Param gradients + input epsilon from an EXTERNAL error signal
        dL/d(output) — no labels or loss function involved (ref:
        ComputationGraph.calcBackpropGradients external epsilons,
        nn/graph/ComputationGraph.java:1421; MLN backpropGradient).
        Reinforcement-learning frameworks drive the reference engine this
        way: run output(), compute their own loss outside, hand the error
        back.  Returns ``(grads, input_epsilon)`` where grads matches the
        net_params structure and input_epsilon is dL/dx.

        ``train=False`` (default) makes the internal forward EXACTLY the
        one output() ran — no dropout — so the gradients correspond to the
        activations the caller computed its error from.  ``train=True``
        samples fresh dropout masks (a different stochastic forward than
        the caller's output() call) and also folds the forward's updated
        carried state (BatchNorm running stats) back into the network,
        like a fit() step does."""
        if self.net_params is None:
            self.init()
        self._check_trace_token()
        if self._ext_grad_fn is None:
            self._ext_grad_fn = {}
        if train not in self._ext_grad_fn:
            policy = dtype_ops.resolve(self.conf.global_conf.precision)

            def ext_grad(params, state, xi, eps, m, rng, _train=train):
                def fwd(p, xin):
                    # cast through the precision policy exactly like
                    # _build_output_fn: under bf16 the VJP must
                    # differentiate the same forward output() ran, and
                    # grads come back in the f32 master-param dtype
                    pc, xc, mc = policy.cast_to_compute((p, xin, m))
                    out, ns, _ = self._forward(pc, state, xc, mc, _train,
                                               rng)
                    return out, ns
                out, vjp, ns = jax.vjp(fwd, params, xi, has_aux=True)
                g, dx = vjp(eps.astype(out.dtype))
                return g, dx, policy.cast_to_param(ns)
            self._ext_grad_fn[train] = jax.jit(ext_grad)
        if train:
            self._key, sub = jax.random.split(self._key)
        else:
            sub = jax.random.PRNGKey(0)
        x = jnp.asarray(x)
        grads, dx, new_states = self._ext_grad_fn[train](
            self.net_params, self.net_state, x, jnp.asarray(epsilon), mask,
            sub)
        if train:
            self.net_state = new_states
            self._strip_rnn_state()
        return grads, dx

    def apply_gradients(self, grads):
        """Apply externally computed per-layer gradients through the
        configured updaters (normalization, LR schedule, learning rule,
        frozen gating) — one jitted step.  Completes the external-errors
        training loop started by :meth:`backprop_gradient`.

        The l1/l2 regularization gradient is added here, matching the
        fused fit step's in-loss penalty (reference analog:
        UpdaterBlock.postApply applies l1/l2 updater-side so externally
        driven training still decays weights); ``minimize=False`` negates
        like fit() does, so callers always pass plain dL/dparam."""
        if self.net_params is None:
            self.init()
        self._check_trace_token()
        if self._apply_fn is None:
            g_conf = self.conf.global_conf

            def apply(p, o, gr, it):
                reg = jax.grad(
                    lambda p_: jnp.asarray(self._reg_penalty(p_),
                                           jnp.float32))(p)
                gr = jax.tree_util.tree_map(jnp.add, gr, reg)
                if not g_conf.minimize:
                    gr = jax.tree_util.tree_map(jnp.negative, gr)
                return self._apply_updates(p, o, gr, it)

            self._apply_fn = jax.jit(apply, donate_argnums=(0, 1))
        self.net_params, self.opt_states = self._apply_fn(
            self.net_params, self.opt_states, grads,
            jnp.asarray(self.iteration, jnp.int32))
        self.iteration += 1
        return self

    # ------------------------------------------------------------------
    def summary(self) -> str:
        """Printable layer table: index, type, param shapes, param count
        (ref: MultiLayerNetwork.summary :2689)."""
        if self.net_params is None:
            self.init()
        rows = [("Idx", "LayerType", "ParamShapes", "ParamCount")]
        total = 0
        for i, (layer, lp) in enumerate(zip(self.layers, self.net_params)):
            n = sum(int(np.prod(v.shape)) for v in lp.values())
            total += n
            shapes = ", ".join(f"{k}{tuple(int(d) for d in v.shape)}"
                               for k, v in sorted(lp.items()))
            rows.append((str(i), type(layer).__name__, shapes or "-",
                         f"{n:,}"))
        return render_table(rows, [f"Total parameters: {total:,}"])

    # ------------------------------------------------------------------
    # Param view parity
    # ------------------------------------------------------------------
    def params(self) -> jnp.ndarray:
        """Flat 1-D param vector (ref: Model.params() 1xN row view)."""
        return param_util.flatten(self.net_params)

    def set_params(self, flat) -> None:
        self.net_params = param_util.unflatten(flat, self.net_params)
        self._replace_on_mesh()

    def num_params(self) -> int:
        return param_util.num_params(self.net_params)

    def get_layer_params(self, i: int) -> dict:
        return self.net_params[i]

    def param_table(self) -> Dict[str, jnp.ndarray]:
        """Named param map keyed ``"<layerIdx>_<paramName>"`` — e.g.
        ``"0_W"``, ``"1_b"`` (ref: Model.paramTable / MLN param keys)."""
        if self.net_params is None:
            self.init()
        return {f"{i}_{k}": v for i, lp in enumerate(self.net_params)
                for k, v in lp.items()}

    def get_param(self, key: str) -> jnp.ndarray:
        """(ref: Model.getParam("0_W"))"""
        i, k = key.split("_", 1)
        return self.net_params[int(i)][k]

    def set_param(self, key: str, value) -> None:
        """(ref: Model.setParam) — shape must match the existing param."""
        i, k = key.split("_", 1)
        cur = self.net_params[int(i)][k]
        value = jnp.asarray(value, cur.dtype)
        if value.shape != cur.shape:
            raise ValueError(f"setParam('{key}'): shape {value.shape} != "
                             f"{cur.shape}")
        self.net_params[int(i)] = {**self.net_params[int(i)], k: value}

    def updater_state_flat(self) -> jnp.ndarray:
        leaves = jax.tree_util.tree_leaves(self.opt_states)
        if not leaves:
            return jnp.zeros((0,), jnp.float32)
        # host-side gather for concrete arrays: op-by-op concatenate
        # over the mixed NamedShardings an FSDP model carries
        # miscomputes (see nn/params.flatten)
        if any(isinstance(l, jax.core.Tracer) for l in leaves):
            return jnp.concatenate([jnp.ravel(l) for l in leaves])
        return jnp.asarray(np.concatenate(
            [np.ravel(np.asarray(l)) for l in leaves]))

    def set_updater_state_flat(self, flat) -> None:
        leaves, treedef = jax.tree_util.tree_flatten(self.opt_states)
        out, off = [], 0
        flat = jnp.asarray(flat).reshape(-1)
        for l in leaves:
            n = int(np.prod(l.shape))
            out.append(flat[off:off + n].reshape(l.shape).astype(l.dtype))
            off += n
        self.opt_states = jax.tree_util.tree_unflatten(treedef, out)
        self._replace_on_mesh()

    # ------------------------------------------------------------------
    def evaluate(self, iterator_or_dataset):
        """Classification evaluation (ref: MultiLayerNetwork.evaluate)."""
        from deeplearning4j_tpu.nn.evaluation import Evaluation
        from deeplearning4j_tpu.datasets.dataset import DataSet
        ev = Evaluation()
        if isinstance(iterator_or_dataset, DataSet):
            batches = [iterator_or_dataset]
        else:
            iterator_or_dataset.reset()
            batches = iterator_or_dataset
        for ds in batches:
            out = self.output(ds.features)
            ev.eval(ds.labels, jax.device_get(out), mask=ds.labels_mask)
        return ev

    def clone(self) -> "MultiLayerNetwork":
        # Arrays must be COPIED, not aliased: the jitted step donates its
        # input buffers, so a clone sharing buffers with a live net would be
        # invalidated by the next fit() step on either of them.
        import copy
        net = MultiLayerNetwork(copy.deepcopy(self.conf))
        if self.net_params is not None:
            copy_tree = lambda t: jax.tree_util.tree_map(  # noqa: E731
                lambda a: jnp.array(a, copy=True), t)
            # assign directly — no init(): avoids sampling a fresh random
            # initialization that would be immediately discarded
            net.net_params = copy_tree(self.net_params)
            net.net_state = copy_tree(self.net_state)
            net.opt_states = copy_tree(self.opt_states)
        net.iteration = self.iteration
        return net
