"""Evaluation chart rendering
(ref: deeplearning4j-core/.../evaluation/EvaluationTools.java —
exportRocChartsToHtmlFile: ROC + precision/recall charts via the
ui-components library; here self-contained SVG, zero assets)."""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np


def _svg_line_chart(series: Sequence[Tuple[str, np.ndarray, np.ndarray]],
                    title: str, w: int = 420, h: int = 340) -> str:
    colors = ["#E45756", "#4C78A8", "#54A24B", "#F58518", "#72B7B2",
              "#B279A2"]
    pad = 40
    parts = [f'<svg viewBox="0 0 {w} {h}" width="{w}" height="{h}" '
             'xmlns="http://www.w3.org/2000/svg">',
             f'<text x="{w / 2}" y="16" text-anchor="middle" '
             f'font-size="14">{title}</text>',
             f'<rect x="{pad}" y="{pad}" width="{w - 2 * pad}" '
             f'height="{h - 2 * pad}" fill="none" stroke="#999"/>']
    # unit axes (ROC space is [0,1]²)
    px = lambda x: pad + x * (w - 2 * pad)       # noqa: E731
    py = lambda y: h - pad - y * (h - 2 * pad)   # noqa: E731
    for t in (0.0, 0.25, 0.5, 0.75, 1.0):
        parts.append(f'<text x="{px(t):.0f}" y="{h - pad + 14}" '
                     f'font-size="9" text-anchor="middle">{t:g}</text>')
        parts.append(f'<text x="{pad - 6}" y="{py(t) + 3:.0f}" '
                     f'font-size="9" text-anchor="end">{t:g}</text>')
    parts.append(f'<line x1="{px(0)}" y1="{py(0)}" x2="{px(1)}" y2="{py(1)}" '
                 'stroke="#ccc" stroke-dasharray="4"/>')
    for i, (label, xs, ys) in enumerate(series):
        color = colors[i % len(colors)]
        d = " ".join(f"{'M' if j == 0 else 'L'}{px(float(x)):.1f},"
                     f"{py(float(y)):.1f}" for j, (x, y) in enumerate(zip(xs, ys)))
        parts.append(f'<path d="{d}" fill="none" stroke="{color}" '
                     'stroke-width="1.6"/>')
        parts.append(f'<text x="{w - pad - 4}" y="{pad + 14 + 13 * i}" '
                     f'font-size="10" text-anchor="end" fill="{color}">'
                     f'{label}</text>')
    parts.append("</svg>")
    return "".join(parts)


def roc_chart_html(roc, class_names: Optional[List[str]] = None) -> str:
    """ROC curve(s) → standalone HTML fragment.  Accepts ROC, ROCBinary,
    or ROCMultiClass (ref: EvaluationTools.rocChartToHtml overloads)."""
    series = []
    if hasattr(roc, "per_class"):        # ROCMultiClass
        for c, r in sorted(roc.per_class.items()):
            fpr, tpr, _ = r.roc_curve()
            name = class_names[c] if class_names else f"class {c}"
            series.append((f"{name} (AUC {r.auc():.3f})", fpr, tpr))
    elif hasattr(roc, "per_output"):     # ROCBinary
        for c, r in sorted(roc.per_output.items()):
            fpr, tpr, _ = r.roc_curve()
            name = class_names[c] if class_names else f"output {c}"
            series.append((f"{name} (AUC {r.auc():.3f})", fpr, tpr))
    else:                                # plain binary ROC
        fpr, tpr, _ = roc.roc_curve()
        series.append((f"AUC {roc.auc():.3f}", fpr, tpr))
    return _svg_line_chart(series, "ROC: TPR vs FPR")


def export_roc_charts_to_html_file(roc, path: str,
                                   class_names: Optional[List[str]] = None
                                   ) -> None:
    """(ref: EvaluationTools.exportRocChartsToHtmlFile)"""
    body = roc_chart_html(roc, class_names)
    with open(path, "w") as f:
        f.write("<!DOCTYPE html><html><head><meta charset=\"utf-8\">"
                "<title>ROC</title></head><body>" + body + "</body></html>")
