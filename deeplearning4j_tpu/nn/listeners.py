"""Training listeners — observability hooks.

(ref: optimize/api/IterationListener.java, TrainingListener.java:73;
impls optimize/listeners/{ScoreIterationListener, PerformanceListener,
CollectScoresIterationListener}.java)
"""

from __future__ import annotations

import logging
import time
from typing import List, Optional, Tuple

log = logging.getLogger(__name__)


class IterationListener:
    def iteration_done(self, model, iteration: int) -> None:
        pass


class TrainingListener(IterationListener):
    def on_epoch_start(self, model) -> None:
        pass

    def on_epoch_end(self, model) -> None:
        pass

    def on_forward_pass(self, model, activations) -> None:
        pass

    def on_gradient_calculation(self, model) -> None:
        pass

    def on_backward_pass(self, model) -> None:
        pass


class ScoreIterationListener(IterationListener):
    """Log score every N iterations (ref: ScoreIterationListener)."""

    def __init__(self, print_iterations: int = 10):
        self.print_iterations = max(1, print_iterations)

    def iteration_done(self, model, iteration):
        if iteration % self.print_iterations == 0:
            log.info("Score at iteration %d is %s", iteration, model.score())


class PerformanceListener(IterationListener):
    """samples/sec + batches/sec + ETL time per iteration
    (ref: PerformanceListener.java:119-122)."""

    def __init__(self, frequency: int = 1, report_etl: bool = True):
        self.frequency = max(1, frequency)
        self.report_etl = report_etl
        self._last_time: Optional[float] = None
        self.history: List[dict] = []

    def iteration_done(self, model, iteration):
        now = time.perf_counter()
        if self._last_time is not None and iteration % self.frequency == 0:
            dt = now - self._last_time
            batch = getattr(model, "last_batch_size", 0)
            rec = {
                "iteration": iteration,
                "batches_per_sec": 1.0 / dt if dt > 0 else float("inf"),
                "samples_per_sec": batch / dt if dt > 0 else float("inf"),
                "etl_ms": getattr(model, "last_etl_time_ms", 0.0),
            }
            self.history.append(rec)
            log.info("iteration %d: %.1f samples/sec, %.2f batches/sec, ETL %.1f ms",
                     iteration, rec["samples_per_sec"], rec["batches_per_sec"],
                     rec["etl_ms"])
        self._last_time = now


class CollectScoresIterationListener(IterationListener):
    """Collect (iteration, score) pairs (ref: CollectScoresIterationListener)."""

    def __init__(self, frequency: int = 1):
        self.frequency = max(1, frequency)
        self.scores: List[Tuple[int, float]] = []

    def iteration_done(self, model, iteration):
        if iteration % self.frequency == 0:
            self.scores.append((iteration, float(model.score())))


class ProfilerListener(IterationListener):
    """Capture a ``jax.profiler`` trace directory every N iterations —
    SURVEY §5's prescribed deep-observability analog of the reference's
    PerformanceListener timing logs (ref: PerformanceListener.java:119-122):
    instead of wall-clock numbers, a full XPlane/TensorBoard trace of
    XLA ops, host↔device transfers, and compilation events is written
    under ``log_dir/iter<N>/`` for `trace_iterations` steps.

    View with TensorBoard's profile plugin or xprof (`tensorboard
    --logdir <log_dir>`)."""

    def __init__(self, log_dir, frequency: int = 100,
                 trace_iterations: int = 3):
        self.log_dir = str(log_dir)
        self.frequency = max(1, frequency)
        self.trace_iterations = max(1, trace_iterations)
        self._tracing_until: Optional[int] = None
        self.trace_dirs: List[str] = []

    def _start(self, iteration: int) -> None:
        import os
        import jax
        path = os.path.join(self.log_dir, f"iter{iteration}")
        os.makedirs(path, exist_ok=True)
        jax.profiler.start_trace(path)
        self._tracing_until = iteration + self.trace_iterations
        self.trace_dirs.append(path)

    def _stop(self) -> None:
        import jax
        try:
            jax.profiler.stop_trace()
        finally:
            self._tracing_until = None

    def iteration_done(self, model, iteration):
        if self._tracing_until is not None:
            if iteration >= self._tracing_until:
                self._stop()
            return
        if iteration % self.frequency == 0:
            self._start(iteration)

    def close(self) -> None:
        """Stop a trace left open mid-capture (end of training)."""
        if self._tracing_until is not None:
            self._stop()


class CompileTelemetryListener(IterationListener):
    """Surface the engine's ``CompileTelemetry`` (ops/bucketing.py)
    through the listener interface: logs whenever an iteration caused a
    new XLA trace (a retrace — the compile-cost event shape bucketing
    exists to bound) and keeps periodic snapshots of the retrace counter
    and per-bucket hit counts for dashboards/benches."""

    def __init__(self, frequency: int = 1):
        self.frequency = max(1, frequency)
        self.history: List[dict] = []
        self._last_retraces = 0

    def iteration_done(self, model, iteration):
        tel = getattr(model, "compile_telemetry", None)
        if tel is None:
            return
        if tel.retraces > self._last_retraces:
            log.info("iteration %d: %d new XLA trace(s), %d total "
                     "(ragged shapes? enable conf.shape_bucketing)",
                     iteration, tel.retraces - self._last_retraces,
                     tel.retraces)
            self._last_retraces = tel.retraces
        if iteration % self.frequency == 0:
            self.history.append({"iteration": iteration, **tel.snapshot()})

    def snapshot(self) -> Optional[dict]:
        return self.history[-1] if self.history else None


class LatencyHistogram:
    """Thread-safe latency recorder with percentile snapshots — the
    shared telemetry surface for serving metrics (``server/batcher.py``
    records per-request queue/compute/total latency through it) and for
    any listener that needs p50/p95/p99 instead of raw means.

    A bounded reservoir keeps memory constant under serving traffic
    (millions of requests must not grow an unbounded list): the first
    ``capacity`` samples are kept verbatim, later ones replace a random
    slot with probability ``capacity/count`` (Vitter's Algorithm R), so
    the percentile snapshot stays an unbiased estimate of the full
    stream.  Counters (count/mean/max) are exact."""

    def __init__(self, capacity: int = 4096):
        import threading
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._samples: List[float] = []
        self.count = 0
        self.total = 0.0
        self.max = 0.0

    def record(self, seconds: float) -> None:
        import random
        s = float(seconds)
        with self._lock:
            self.count += 1
            self.total += s
            if s > self.max:
                self.max = s
            if len(self._samples) < self.capacity:
                self._samples.append(s)
            else:
                i = random.randrange(self.count)
                if i < self.capacity:
                    self._samples[i] = s

    def percentile(self, q: float) -> Optional[float]:
        """q in [0, 1]; None when nothing was recorded (a 0.0 here reads
        as a real zero-latency sample downstream — callers must handle
        the empty reservoir explicitly)."""
        with self._lock:
            if not self._samples:
                return None
            xs = sorted(self._samples)
        return xs[min(len(xs) - 1, int(q * len(xs)))]

    def snapshot(self) -> dict:
        with self._lock:
            count, total, mx = self.count, self.total, self.max

        def ms(v):
            return None if v is None else round(v * 1e3, 3)

        return {
            "count": count,
            "mean_ms": round(total / count * 1e3, 3) if count else None,
            "p50_ms": ms(self.percentile(0.50)),
            "p95_ms": ms(self.percentile(0.95)),
            "p99_ms": ms(self.percentile(0.99)),
            "max_ms": ms(mx) if count else None,
        }


class ParamAndGradientIterationListener(IterationListener):
    """Per-iteration parameter/update magnitude stats, optionally written
    as TSV (ref: optimize/listeners/ParamAndGradientIterationListener.java
    — mean magnitudes of params & gradients per iteration to file)."""

    def __init__(self, iterations: int = 1, file_path=None,
                 delimiter: str = "\t"):
        self.iterations = max(1, iterations)
        self.file_path = file_path
        self.delimiter = delimiter
        self.history: List[dict] = []
        self._last = None
        self._wrote_header = False

    def iteration_done(self, model, iteration):
        import numpy as np
        if iteration % self.iterations:
            return
        params = np.asarray(model.params())
        rec = {
            "iteration": iteration,
            "score": float(model.score()),
            "param_mean_magnitude": float(np.abs(params).mean()),
        }
        if self._last is not None and self._last.shape == params.shape:
            rec["update_mean_magnitude"] = float(
                np.abs(params - self._last).mean())
        self._last = params
        self.history.append(rec)
        if self.file_path:
            cols = ["iteration", "score", "param_mean_magnitude",
                    "update_mean_magnitude"]
            with open(self.file_path, "a") as f:
                if not self._wrote_header:
                    f.write(self.delimiter.join(cols) + "\n")
                    self._wrote_header = True
                f.write(self.delimiter.join(
                    str(rec.get(c, "")) for c in cols) + "\n")
