"""Flat parameter view adapter.

The reference stores ALL params as one 1xN row vector with per-layer
views into it (ref: nn/api/Model.java:128 setParamsViewArray,
MultiLayerNetwork.java:102 flattenedParams).  The native representation
here is a pytree (list of per-layer dicts), but checkpoints, parameter
averaging compat, and `params()`/`setParams()` parity need a canonical
flattening order.  Order: layer index ascending, then within a layer the
canonical key order below (W before b, matching
DefaultParamInitializer / GravesLSTMParamInitializer orderings).
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp
import numpy as np

# Canonical within-layer ordering; unknown keys go last, alphabetically.
PARAM_ORDER = ["W", "RW", "b", "pI", "pF", "pO", "gamma", "beta",
               "f_W", "f_RW", "f_b", "f_pI", "f_pF", "f_pO",
               "b_W", "b_RW", "b_b", "b_pI", "b_pF", "b_pO"]


def ordered_keys(layer_params: dict) -> List[str]:
    known = [k for k in PARAM_ORDER if k in layer_params]
    rest = sorted(k for k in layer_params if k not in PARAM_ORDER)
    return known + rest


def num_params(params: List[dict]) -> int:
    return sum(int(np.prod(v.shape)) for lp in params for v in lp.values())


def flatten(params: List[dict]) -> jnp.ndarray:
    """→ 1-D flat vector in canonical order (the reference's params()).

    Concrete arrays are gathered on the HOST: the leaves of an
    FSDP-trained model carry heterogeneous NamedShardings, and op-by-op
    ``jnp.concatenate`` over mixed committed shardings miscomputes on
    multi-axis meshes (observed on jax 0.4.37, CPU 2x4 data×fsdp mesh —
    values silently wrong, not an error).  Per-leaf ``np.asarray`` is
    the always-correct gather, and the flat vector is the portable
    cross-mesh checkpoint format anyway (parallel/fsdp.py).  Under a
    jit trace (the line-search solvers flatten inside their value-and-
    grad closures) leaves are tracers — there the compiled concatenate
    is both required and correct."""
    import jax
    leaves = [lp[k] for lp in params for k in ordered_keys(lp)]
    if not leaves:
        return jnp.zeros((0,), jnp.float32)
    if any(isinstance(l, jax.core.Tracer) for l in leaves):
        return jnp.concatenate([jnp.ravel(l) for l in leaves])
    return jnp.asarray(np.concatenate(
        [np.ravel(np.asarray(l)) for l in leaves]))  # dl4j: noqa[DL4J102] tracer-guarded host gather — the traced branch above uses jnp


def unflatten(flat, template: List[dict]) -> List[dict]:
    """Inverse of flatten, shaped like `template` (the reference's setParams())."""
    out = []
    off = 0
    flat = jnp.asarray(flat).reshape(-1)
    for lp in template:
        new = {}
        for k in ordered_keys(lp):
            n = int(np.prod(lp[k].shape))
            new[k] = flat[off:off + n].reshape(lp[k].shape).astype(lp[k].dtype)
            off += n
        out.append(new)
    if off != flat.shape[0]:
        raise ValueError(f"Param count mismatch: template {off} vs flat {flat.shape[0]}")
    return out
