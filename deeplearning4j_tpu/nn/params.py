"""Flat parameter view adapter.

The reference stores ALL params as one 1xN row vector with per-layer
views into it (ref: nn/api/Model.java:128 setParamsViewArray,
MultiLayerNetwork.java:102 flattenedParams).  The native representation
here is a pytree (list of per-layer dicts), but checkpoints, parameter
averaging compat, and `params()`/`setParams()` parity need a canonical
flattening order.  Order: layer index ascending, then within a layer the
canonical key order below (W before b, matching
DefaultParamInitializer / GravesLSTMParamInitializer orderings).
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp
import numpy as np

# Canonical within-layer ordering; unknown keys go last, alphabetically.
PARAM_ORDER = ["W", "RW", "b", "pI", "pF", "pO", "gamma", "beta",
               "f_W", "f_RW", "f_b", "f_pI", "f_pF", "f_pO",
               "b_W", "b_RW", "b_b", "b_pI", "b_pF", "b_pO"]


def ordered_keys(layer_params: dict) -> List[str]:
    known = [k for k in PARAM_ORDER if k in layer_params]
    rest = sorted(k for k in layer_params if k not in PARAM_ORDER)
    return known + rest


def num_params(params: List[dict]) -> int:
    return sum(int(np.prod(v.shape)) for lp in params for v in lp.values())


def flatten(params: List[dict]) -> jnp.ndarray:
    """→ 1-D flat vector in canonical order (the reference's params())."""
    flats = []
    for lp in params:
        for k in ordered_keys(lp):
            flats.append(jnp.ravel(lp[k]))
    if not flats:
        return jnp.zeros((0,), jnp.float32)
    return jnp.concatenate(flats)


def unflatten(flat, template: List[dict]) -> List[dict]:
    """Inverse of flatten, shaped like `template` (the reference's setParams())."""
    out = []
    off = 0
    flat = jnp.asarray(flat).reshape(-1)
    for lp in template:
        new = {}
        for k in ordered_keys(lp):
            n = int(np.prod(lp[k].shape))
            new[k] = flat[off:off + n].reshape(lp[k].shape).astype(lp[k].dtype)
            off += n
        out.append(new)
    if off != flat.shape[0]:
        raise ValueError(f"Param count mismatch: template {off} vs flat {flat.shape[0]}")
    return out
