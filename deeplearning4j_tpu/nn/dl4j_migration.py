"""Migrate checkpoints written by the ORIGINAL DL4J (0.x Java) into this
framework — the interop half of checkpoint parity: `nn/serialization.py`
round-trips this framework's own zips; this module reads the reference's.

Format (ref: util/ModelSerializer.java:79-120): a zip with
``configuration.json`` (Jackson MultiLayerConfiguration, wrapper-object
typed layers — nn/conf/layers/Layer.java:47 @JsonTypeInfo WRAPPER_OBJECT),
``coefficients.bin`` (legacy ``Nd4j.write``: shapeInfo DataBuffer then data
DataBuffer, each ``writeUTF(allocationMode) writeInt(length)
writeUTF(dtype) big-endian elements``), and optionally
``updaterState.bin``.

Parameter layout (ref: nn/params/DefaultParamInitializer.java:60-99): the
flat params row is the per-layer concatenation, each layer contributing
its views in initializer order — Dense/Output/Embedding: W [nIn,nOut]
then b, **'f' (column-major) flattened** (weights/WeightInitUtil.java:40
DEFAULT_WEIGHT_INIT_ORDER='f'); Convolution: b FIRST then W
[nOut,nIn,kH,kW] reshaped **'c'** — the one row-major exception
(nn/params/ConvolutionParamInitializer.java:76-80); BatchNorm: gamma, beta,
mean, var (nn/params/BatchNormalizationParamInitializer.java:59-80);
GravesLSTM: W [nIn,4H], RW [H,4H+3] (last 3 cols = peepholes wFF, wOO,
wGG), b [4H], gate order IFOG
(nn/params/GravesLSTMParamInitializer.java:60-148,
nn/layers/recurrent/LSTMHelpers.java:62).

LSTM gate-block mapping: DL4J's IFOG column order is [input(candidate,
LAYER activation fn), forget, output, inputMod(SIGMOID multiplier)] —
LSTMHelpers.java:180-226 applies activationFn to block 0 and
gateActivationFn to block 3, and block 3 is the multiplier on the
candidate in the cell update (``c = f*c_prev + inputMod*input``).  This
framework's cell order is [i(sigmoid multiplier), f, o, g(tanh
candidate)] (ops/recurrent.py) — blocks 0 and 3 swap ROLES.  Migration
therefore permutes column blocks 0↔3 of W, RW and b in both directions
(:func:`_swap_ifog_blocks`, an involution).  After the permutation the
peephole mapping is semantically EXACT: wFF→pF (prev cell → forget),
wOO→pO (current cell → output, LSTMHelpers.java:226-228), wGG→pI (prev
cell → sigmoid multiplier, LSTMHelpers.java:202-209) — migrated LSTMs
match DL4J forward activations with NONZERO peepholes
(tests/test_dl4j_migration.py::test_lstm_forward_matches_dl4j_semantics).
"""

from __future__ import annotations

import io
import json
import math
import struct
import zipfile
from typing import BinaryIO, Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf import preprocessors as pp
from deeplearning4j_tpu.nn.conf.network import (GlobalConf,
                                                MultiLayerConfiguration)

# ---------------------------------------------------------------------------
# Legacy Nd4j binary format
# ---------------------------------------------------------------------------

_DTYPES = {"FLOAT": ("f", 4, np.float32), "DOUBLE": ("d", 8, np.float64),
           "INT": ("i", 4, np.int32), "LONG": ("q", 8, np.int64),
           "HALF": ("e", 2, np.float16)}


def _read_utf(stream: BinaryIO) -> str:
    (n,) = struct.unpack(">H", stream.read(2))
    return stream.read(n).decode("utf-8")


def _write_utf(stream: BinaryIO, s: str) -> None:
    b = s.encode("utf-8")
    stream.write(struct.pack(">H", len(b)))
    stream.write(b)


def read_data_buffer(stream: BinaryIO) -> np.ndarray:
    """One legacy DataBuffer: UTF allocation mode, int32 length, UTF
    element type, big-endian elements (BaseDataBuffer.write)."""
    _alloc = _read_utf(stream)  # HEAP/DIRECT/JAVACPP — irrelevant here
    (length,) = struct.unpack(">i", stream.read(4))
    dtype = _read_utf(stream)
    if dtype not in _DTYPES:
        raise ValueError(f"unknown nd4j DataBuffer element type {dtype!r}")
    _, size, np_t = _DTYPES[dtype]
    raw = stream.read(length * size)
    if len(raw) != length * size:
        raise ValueError("truncated nd4j DataBuffer")
    return np.frombuffer(raw, dtype=np.dtype(np_t).newbyteorder(">")).astype(
        np_t)


def write_data_buffer(stream: BinaryIO, arr: np.ndarray,
                      dtype: str = "FLOAT") -> None:
    _write_utf(stream, "HEAP")
    stream.write(struct.pack(">i", arr.size))
    _write_utf(stream, dtype)
    _, _, np_t = _DTYPES[dtype]
    stream.write(np.ascontiguousarray(arr, np_t).astype(
        np.dtype(np_t).newbyteorder(">")).tobytes())


def read_nd4j_array(stream: BinaryIO) -> np.ndarray:
    """Legacy ``Nd4j.write``: shapeInfo buffer then data buffer.
    shapeInfo layout: [rank, shape..., stride..., offset,
    elementWiseStride, order-char]."""
    shape_info = read_data_buffer(stream).astype(np.int64)
    rank = int(shape_info[0])
    shape = tuple(int(d) for d in shape_info[1:1 + rank])
    order = chr(int(shape_info[-1]))
    data = read_data_buffer(stream)
    n = int(np.prod(shape)) if shape else data.size
    return np.reshape(data[:n], shape,
                      order="F" if order == "f" else "C")


def write_nd4j_array(stream: BinaryIO, arr: np.ndarray,
                     order: str = "f") -> None:
    """Inverse of :func:`read_nd4j_array` — used to author DL4J-schema
    fixtures (and to export params a Java DL4J could read back)."""
    arr = np.asarray(arr)
    rank = arr.ndim
    shape = list(arr.shape)
    # strides in elements for the chosen order ('f' matches DL4J params)
    strides = [0] * rank
    acc = 1
    idx = range(rank) if order == "f" else range(rank - 1, -1, -1)
    for i in idx:
        strides[i] = acc
        acc *= shape[i]
    info = [rank] + shape + strides + [0, 1, ord(order)]
    write_data_buffer(stream, np.asarray(info, np.int32), "INT")
    flat = np.ravel(arr, order="F" if order == "f" else "C")
    write_data_buffer(stream, flat,
                      "DOUBLE" if arr.dtype == np.float64 else "FLOAT")


# ---------------------------------------------------------------------------
# configuration.json → builder-DSL confs
# ---------------------------------------------------------------------------

_ACT_NAMES = sorted(
    ["rationaltanh", "rectifiedtanh", "hardsigmoid", "hardtanh",
     "leakyrelu", "softmax", "softplus", "softsign", "sigmoid",
     "identity", "linear", "relu", "tanh", "cube", "elu", "selu",
     "gelu", "swish"],
    key=len, reverse=True)  # longest first: "selu"/"gelu" before "elu"


def _parse_activation(v, default: str = "sigmoid") -> str:
    """activationFn appears as a legacy string ("relu"), a wrapper object
    ({"ReLU": {}} / {".ActivationReLU": {}}), or an @class map."""
    if v is None:
        return default
    if isinstance(v, dict):
        if "@class" in v:
            v = v["@class"]
        else:
            v = next(iter(v), "")
    s = str(v).lower()
    for name in _ACT_NAMES:   # longest/most-specific first in list order
        if name in s:
            return "identity" if name == "linear" else name
    return default


# matched against the name lowercased with "loss"/"_" stripped, longest
# key first, so SQUARED_HINGE beats hinge and KL_DIVERGENCE resolves
_LOSS_MAP = {"negativeloglikelihood": "negativeloglikelihood",
             "squaredhinge": "squared_hinge",
             "cosineproximity": "cosine_proximity",
             "kldivergence": "kl_divergence", "kld": "kl_divergence",
             "poisson": "poisson", "hinge": "hinge",
             "mcxent": "mcxent", "msle": "msle", "mape": "mape",
             "xent": "xent", "mse": "mse", "mae": "mae",
             "l2": "l2", "l1": "l1",
             "squared": "mse", "cosine": "cosine_proximity"}
_LOSS_KEYS_BY_LEN = sorted(_LOSS_MAP, key=len, reverse=True)


def _parse_loss(layer_json: dict, default: str = "mse") -> str:
    v = layer_json.get("lossFn", layer_json.get("lossFunction"))
    if v is None:
        return default
    if isinstance(v, dict):
        v = v.get("@class") or next(iter(v), "")
    s = str(v).lower().replace("loss", "").replace("_", "")
    for k in _LOSS_KEYS_BY_LEN:
        if k in s:
            return _LOSS_MAP[k]
    return default


def _num(v, default=0.0) -> float:
    """Jackson writes unset doubles as NaN (l1/l2 default NaN in
    nn/conf/layers/Layer.java) — treat NaN/None as unset."""
    if v is None:
        return default
    try:
        f = float(v)
    except (TypeError, ValueError):
        return default
    return default if math.isnan(f) else f


def _ints(v, default=(0, 0)) -> Tuple[int, ...]:
    if v is None:
        return tuple(default)
    if isinstance(v, (int, float)):
        return (int(v), int(v))
    return tuple(int(x) for x in v)


def _num_opt(j: dict, key) -> Optional[float]:
    """Present-and-set (non-NaN) numeric field, else None.  Explicit
    zeros are KEPT: a DL4J net saved with momentum=0.0 must not migrate
    to the global default 0.9 (Jackson writes resolved values; only NaN
    means unset)."""
    if key not in j:
        return None
    try:
        f = float(j[key])
    except (TypeError, ValueError):
        return None
    return None if math.isnan(f) else f


def _common_kwargs(j: dict, default_activation: str = "sigmoid") -> dict:
    kw = {}
    if j.get("nIn"):
        kw["n_in"] = int(j["nIn"])
    if j.get("nOut"):
        kw["n_out"] = int(j["nOut"])
    kw["activation"] = _parse_activation(
        j.get("activationFn", j.get("activationFunction")),
        default_activation)
    for src, dst in (("l1", "l1"), ("l2", "l2"), ("l1Bias", "l1_bias"),
                     ("l2Bias", "l2_bias"), ("dropOut", "dropout"),
                     ("learningRate", "learning_rate"),
                     ("biasLearningRate", "bias_learning_rate"),
                     ("momentum", "momentum"), ("rho", "rho"),
                     ("rmsDecay", "rms_decay"),
                     ("adamMeanDecay", "adam_mean_decay"),
                     ("adamVarDecay", "adam_var_decay"),
                     ("epsilon", "epsilon"), ("biasInit", "bias_init")):
        x = _num_opt(j, src)
        if x is not None:
            kw[dst] = x
    wi = j.get("weightInit")
    if wi:
        kw["weight_init"] = str(wi).lower()
    upd = j.get("updater")
    if upd:
        kw["updater"] = _UPDATER_MAP.get(str(upd).lower(), "sgd")
    gn = j.get("gradientNormalization")
    if gn and str(gn) != "None":
        kw["gradient_normalization"] = str(gn).lower()
        t = _num_opt(j, "gradientNormalizationThreshold")
        if t is not None:
            kw["gradient_normalization_threshold"] = t
    return kw


def _build_layer(type_name: str, j: dict) -> L.Layer:
    kw = _common_kwargs(
        j, default_activation="tanh"
        if type_name in ("gravesLSTM", "gravesBidirectionalLSTM")
        else "sigmoid")
    t = type_name
    if t == "dense":
        return L.DenseLayer(**kw)
    if t == "output":
        return L.OutputLayer(loss=_parse_loss(j), **kw)
    if t == "rnnoutput":
        return L.RnnOutputLayer(loss=_parse_loss(j), **kw)
    if t == "loss":
        kw.pop("n_in", None), kw.pop("n_out", None)
        return L.LossLayer(loss=_parse_loss(j), **kw)
    if t == "convolution":
        return L.ConvolutionLayer(
            kernel=_ints(j.get("kernelSize"), (3, 3)),
            stride=_ints(j.get("stride"), (1, 1)),
            padding=_ints(j.get("padding"), (0, 0)),
            dilation=_ints(j.get("dilation"), (1, 1)),
            convolution_mode=str(j.get("convolutionMode",
                                       "truncate")).lower(), **kw)
    if t == "subsampling":
        kw.pop("activation", None)
        kw.pop("n_in", None), kw.pop("n_out", None)
        return L.SubsamplingLayer(
            pooling_type=str(j.get("poolingType", "max")).lower(),
            kernel=_ints(j.get("kernelSize"), (2, 2)),
            stride=_ints(j.get("stride"), (2, 2)),
            padding=_ints(j.get("padding"), (0, 0)),
            pnorm=int(j.get("pnorm", 2) or 2),
            convolution_mode=str(j.get("convolutionMode",
                                       "truncate")).lower(), **kw)
    if t == "batchNormalization":
        kw.pop("n_in", None)
        # DL4J BN applies NO activation regardless of the recorded
        # activationFn (nn/layers/normalization/BatchNormalization.java:228)
        kw.pop("activation", None)
        n_out = kw.pop("n_out", None)
        return L.BatchNormalization(
            activation="identity",
            decay=_num(j.get("decay"), 0.9), eps=_num(j.get("eps"), 1e-5),
            lock_gamma_beta=bool(j.get("lockGammaBeta", False)),
            n_features=n_out, **kw)
    if t == "gravesBidirectionalLSTM":
        return L.GravesBidirectionalLSTM(
            forget_gate_bias_init=_num(j.get("forgetGateBiasInit"), 1.0),
            gate_activation=_parse_activation(j.get("gateActivationFn"),
                                              "sigmoid"), **kw)
    if t == "gravesLSTM":
        return L.GravesLSTM(
            forget_gate_bias_init=_num(j.get("forgetGateBiasInit"), 1.0),
            gate_activation=_parse_activation(j.get("gateActivationFn"),
                                              "sigmoid"), **kw)
    if t == "embedding":
        return L.EmbeddingLayer(**kw)
    if t == "activation":
        kw.pop("n_in", None), kw.pop("n_out", None)
        return L.ActivationLayer(**kw)
    if t == "dropout":
        kw.pop("n_in", None), kw.pop("n_out", None)
        return L.DropoutLayer(**kw)
    if t == "GlobalPooling":
        kw.pop("activation", None)
        kw.pop("n_in", None), kw.pop("n_out", None)
        return L.GlobalPoolingLayer(
            pooling_type=str(j.get("poolingType", "max")).lower(), **kw)
    if t == "zeroPadding":
        kw.pop("activation", None)
        pad = j.get("padding", [0, 0, 0, 0])
        return L.ZeroPaddingLayer(padding=tuple(int(x) for x in pad))
    raise ValueError(f"DL4J layer type {type_name!r} has no migration "
                     f"mapping yet")


_PREPROC_MAP = {
    "cnnToFeedForward": lambda j: pp.CnnToFeedForwardPreProcessor(
        height=int(j.get("inputHeight", 0)), width=int(j.get("inputWidth", 0)),
        channels=int(j.get("numChannels", 0))),
    "feedForwardToCnn": lambda j: pp.FeedForwardToCnnPreProcessor(
        height=int(j.get("inputHeight", 0)), width=int(j.get("inputWidth", 0)),
        channels=int(j.get("numChannels", 0))),
    "rnnToFeedForward": lambda j: pp.RnnToFeedForwardPreProcessor(),
    "feedForwardToRnn": lambda j: pp.FeedForwardToRnnPreProcessor(),
    "rnnToCnn": lambda j: pp.RnnToCnnPreProcessor(
        height=int(j.get("inputHeight", 0)), width=int(j.get("inputWidth", 0)),
        channels=int(j.get("numChannels", 0))),
    # DL4J's CnnToRnn derives T from the runtime minibatch; ours needs
    # it up front.  Import with timesteps=None — the preprocessor itself
    # raises with instructions at first use, so the restore succeeds and
    # the user can attach CnnToRnnPreProcessor(timesteps=T) to
    # conf.preprocessors before running the net
    "cnnToRnn": lambda j: pp.CnnToRnnPreProcessor(),
}


_UPDATER_MAP = {"nesterovs": "nesterovs", "sgd": "sgd", "adam": "adam",
                "adamax": "adamax", "adagrad": "adagrad",
                "adadelta": "adadelta", "rmsprop": "rmsprop",
                "none": "none"}


def config_from_dl4j_json(text: str) -> MultiLayerConfiguration:
    """Jackson MultiLayerConfiguration JSON → our builder-DSL conf
    (schema: nn/conf/MultiLayerConfiguration.java:59-74 — confs[],
    inputPreProcessors, backprop/pretrain, backpropType, tbptt lengths)."""
    top = json.loads(text)
    confs = top.get("confs", [])
    if not confs:
        raise ValueError("configuration.json has no 'confs' — not a "
                         "MultiLayerConfiguration (for a ComputationGraph "
                         "zip use restore_computation_graph / "
                         "config_from_dl4j_graph_json)")

    layers: List[L.Layer] = []
    g = GlobalConf()
    for i, c in enumerate(confs):
        lw = c.get("layer", {})
        if not isinstance(lw, dict) or len(lw) != 1:
            raise ValueError(f"conf {i}: expected wrapper-object layer, "
                             f"got {type(lw).__name__}")
        (tname, lj), = lw.items()
        layers.append(_build_layer(tname, lj))
        if i == 0:
            g.seed = int(c.get("seed", 0) or 0)
            g.minimize = bool(c.get("minimize", True))
            g.mini_batch = bool(c.get("miniBatch", True))
            g.use_regularization = bool(c.get("useRegularization", False))
            lr = _num(lj.get("learningRate"))
            if lr:
                g.learning_rate = lr
            upd = str(lj.get("updater", "sgd")).lower()
            g.updater = _UPDATER_MAP.get(upd, "sgd")
            mom = _num(lj.get("momentum"))
            if mom:
                g.momentum = mom

    # global-then-override merge (nn/conf/network.merge_layer_conf):
    # fills unset updater/momentum/etc from the global conf and zeroes
    # l1/l2 when useRegularization=false — without this, migrated nets
    # would fine-tune with plain SGD regardless of the saved updater
    from deeplearning4j_tpu.nn.conf.network import merge_layer_conf
    layers = [merge_layer_conf(l, g) for l in layers]

    preprocs = {}
    for k, v in (top.get("inputPreProcessors") or {}).items():
        if isinstance(v, dict) and len(v) == 1:
            (pname, pj), = v.items()
            if pname in _PREPROC_MAP:
                preprocs[int(k)] = _PREPROC_MAP[pname](pj)

    return MultiLayerConfiguration(
        layers=layers, global_conf=g, preprocessors=preprocs,
        backprop=bool(top.get("backprop", True)),
        pretrain=bool(top.get("pretrain", False)),
        backprop_type=("truncatedbptt"
                       if str(top.get("backpropType", "")).lower()
                       .startswith("truncated") else "standard"),
        tbptt_fwd_length=int(top.get("tbpttFwdLength", 20)),
        tbptt_back_length=int(top.get("tbpttBackLength", 20)))


# ---------------------------------------------------------------------------
# ComputationGraph configuration.json → graph conf
# ---------------------------------------------------------------------------

def _build_vertex(wrapper: dict):
    """One Jackson GraphVertex (wrapper-object typed,
    nn/conf/graph/GraphVertex.java:38-51) → our GraphVertexConf."""
    from deeplearning4j_tpu.nn.conf import graph_conf as gc
    (vtype, vj), = wrapper.items()
    if vtype == "LayerVertex":
        lconf = vj.get("layerConf") or {}
        lw = lconf.get("layer") or {}
        (tname, lj), = lw.items()
        layer = _build_layer(tname, lj)
        pre = None
        pj = vj.get("preProcessor")
        if isinstance(pj, dict) and len(pj) == 1:
            (pname, pjj), = pj.items()
            if pname in _PREPROC_MAP:
                pre = _PREPROC_MAP[pname](pjj)
        return layer, pre
    if vtype == "MergeVertex":
        return gc.MergeVertex(), None
    if vtype == "ElementWiseVertex":
        return gc.ElementWiseVertex(
            op=str(vj.get("op", "Add")).lower()), None
    if vtype == "SubsetVertex":
        return gc.SubsetVertex(from_idx=int(vj.get("from", 0)),
                               to_idx=int(vj.get("to", 0))), None
    if vtype == "ScaleVertex":
        return gc.ScaleVertex(scale=_num(vj.get("scaleFactor"), 1.0)), None
    if vtype == "ShiftVertex":
        return gc.ShiftVertex(shift=_num(vj.get("shiftFactor"), 0.0)), None
    if vtype == "StackVertex":
        return gc.StackVertex(), None
    if vtype == "UnstackVertex":
        return gc.UnstackVertex(from_idx=int(vj.get("from", 0)),
                                stack_size=int(vj.get("stackSize", 1))), None
    if vtype == "L2Vertex":
        return gc.L2Vertex(), None
    if vtype == "L2NormalizeVertex":
        return gc.L2NormalizeVertex(), None
    if vtype == "LastTimeStepVertex":
        return gc.LastTimeStepVertex(
            mask_input=vj.get("maskArrayInputName")), None
    if vtype == "DuplicateToTimeSeriesVertex":
        return gc.DuplicateToTimeSeriesVertex(
            ts_input=vj.get("inputName")), None
    if vtype == "PreprocessorVertex":
        pj = vj.get("preProcessor") or {}
        if isinstance(pj, dict) and len(pj) == 1:
            (pname, pjj), = pj.items()
            if pname in _PREPROC_MAP:
                return gc.PreprocessorVertex.of(_PREPROC_MAP[pname](pjj)), \
                    None
        raise ValueError(f"unsupported PreprocessorVertex payload: {pj}")
    raise ValueError(f"DL4J graph vertex type {vtype!r} has no migration "
                     f"mapping yet")


def config_from_dl4j_graph_json(text):
    """Jackson ComputationGraphConfiguration JSON (string or parsed
    dict) → our graph conf (schema:
    nn/conf/ComputationGraphConfiguration.java:59-87 —
    networkInputs/networkOutputs, vertices + vertexInputs maps,
    defaultConfiguration)."""
    from deeplearning4j_tpu.nn.conf import graph_conf as gc
    from deeplearning4j_tpu.nn.conf.network import merge_layer_conf
    top = json.loads(text) if isinstance(text, (str, bytes)) else text
    if "vertices" not in top or "networkInputs" not in top:
        raise ValueError("not a DL4J ComputationGraphConfiguration")

    g = GlobalConf()
    default = top.get("defaultConfiguration") or {}
    g.seed = int(default.get("seed", 0) or 0)
    g.minimize = bool(default.get("minimize", True))
    g.mini_batch = bool(default.get("miniBatch", True))
    g.use_regularization = bool(default.get("useRegularization", False))

    vertices = {}
    vertex_inputs = {k: list(v)
                     for k, v in (top.get("vertexInputs") or {}).items()}
    raw_vertices = top.get("vertices") or {}
    # global training hyperparams ride the TOPOLOGICALLY first layer
    # vertex (vertex-map order is builder-insertion order in DL4J and
    # may start with the output layer), matching the MLN path's confs[0]
    first_layer_name = next(
        (n for n in dl4j_graph_topological_order(
            list(top.get("networkInputs") or []), list(raw_vertices),
            vertex_inputs)
         if "LayerVertex" in raw_vertices.get(n, {})), None)
    built_map = {}
    for name, wrapper in raw_vertices.items():
        built_map[name] = _build_vertex(wrapper)
    if first_layer_name is not None:
        first = built_map[first_layer_name][0]
        if first.learning_rate:
            g.learning_rate = first.learning_rate
        if first.updater:
            g.updater = first.updater
        if first.momentum is not None:
            g.momentum = first.momentum
    for name, (built, pre) in built_map.items():
        if isinstance(built, L.Layer):
            layer = merge_layer_conf(built, g)
            vertices[name] = gc.LayerVertex(layer=layer.to_dict())
            if pre is not None:
                # our engine has no per-LayerVertex preprocessor slot;
                # splice a PreprocessorVertex in front (same math)
                pname = f"{name}__pre"
                vertices[pname] = gc.PreprocessorVertex.of(pre)
                vertex_inputs[pname] = vertex_inputs.get(name, [])
                vertex_inputs[name] = [pname]
        else:
            vertices[name] = built

    return gc.ComputationGraphConfiguration(
        network_inputs=list(top.get("networkInputs") or []),
        network_outputs=list(top.get("networkOutputs") or []),
        vertices=vertices, vertex_inputs=vertex_inputs, global_conf=g,
        backprop_type=("truncatedbptt"
                       if str(top.get("backpropType", "")).lower()
                       .startswith("truncated") else "standard"),
        tbptt_fwd_length=int(top.get("tbpttFwdLength", 20)),
        tbptt_back_length=int(top.get("tbpttBackLength", 20)))


def dl4j_graph_topological_order(network_inputs: List[str],
                                 vertex_names: List[str],
                                 vertex_inputs: Dict[str, List[str]]
                                 ) -> List[str]:
    """Replicate ComputationGraph.topologicalSortOrder (:312) exactly:
    indices are assigned inputs-first then vertex-map order; Kahn's with
    a FIFO queue whose initial fill and neighbor expansion iterate in
    ASCENDING index order (Java HashMap<Integer>/HashSet<Integer>
    iterate small non-negative ints in value order).  The flat param row
    is laid out in THIS order, so it must match bit-for-bit."""
    names = list(network_inputs) + list(vertex_names)
    idx = {n: i for i, n in enumerate(names)}
    n = len(names)
    in_edges: Dict[int, set] = {i: set() for i in range(n)}
    out_edges: Dict[int, set] = {i: set() for i in range(n)}
    for name, ins in vertex_inputs.items():
        if name not in idx:
            continue
        for src in ins:
            if src in idx:
                in_edges[idx[name]].add(idx[src])
                out_edges[idx[src]].add(idx[name])
    from collections import deque
    queue = deque(sorted(i for i in range(n) if not in_edges[i]))
    order = []
    while queue:
        nxt = queue.popleft()
        order.append(nxt)
        for v in sorted(out_edges[nxt]):
            in_edges[v].discard(nxt)
            if not in_edges[v]:
                queue.append(v)
    if len(order) != n:
        raise ValueError("cycle in DL4J graph configuration")
    return [names[i] for i in order]


def restore_computation_graph(path, load_params: bool = True,
                              load_updater: bool = True):
    """Load a ComputationGraph zip the ORIGINAL DL4J wrote (ref:
    ModelSerializer.restoreComputationGraph; param layout:
    ComputationGraph.java:336-380 — per-vertex views sliced from the
    flat row in topological order)."""
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    import jax.numpy as jnp

    with zipfile.ZipFile(path, "r") as zf:
        names = set(zf.namelist())
        if "configuration.json" not in names:
            raise ValueError("not a DL4J model zip: no configuration.json")
        raw = json.loads(zf.read("configuration.json").decode("utf-8"))
        conf = config_from_dl4j_graph_json(raw)
        net = ComputationGraph(conf)
        net.init()
        if load_params and "coefficients.bin" in names:
            flat = read_nd4j_array(
                io.BytesIO(zf.read("coefficients.bin"))).ravel(order="C")
            # topo order over the ORIGINAL vertex map (before any
            # PreprocessorVertex splicing, which has no params)
            topo = dl4j_graph_topological_order(
                list(raw.get("networkInputs") or []),
                list((raw.get("vertices") or {}).keys()),
                {k: list(v)
                 for k, v in (raw.get("vertexInputs") or {}).items()})
            off = 0
            for vname in topo:
                if vname not in conf.vertices:
                    continue  # a network input
                v = conf.vertices[vname]
                from deeplearning4j_tpu.nn.conf.graph_conf import LayerVertex
                if not isinstance(v, LayerVertex):
                    continue
                layer = v.layer_conf()
                spec = _layer_param_spec(layer)
                if not spec:
                    continue
                total = sum(s[2] for s in spec)
                params, states = params_from_flat(
                    [layer], flat[off:off + total])
                off += total
                merged = dict(net.net_params[vname])
                for k, val in params[0].items():
                    if k in merged and merged[k].shape != val.shape:
                        raise ValueError(
                            f"vertex {vname} param {k}: DL4J shape "
                            f"{val.shape} != {merged[k].shape}")
                    merged[k] = jnp.asarray(val, jnp.float32)
                net.net_params[vname] = merged
                ms = dict(net.net_state[vname])
                for k, val in states[0].items():
                    ms[k] = jnp.asarray(val, jnp.float32)
                net.net_state[vname] = ms
            if off != flat.size:
                raise ValueError(f"coefficients.bin has {flat.size} "
                                 f"params, vertex specs consume {off}")
            net.opt_states = {n2: net.updaters[n2].init(net.net_params[n2])
                              for n2 in net.order}
        if load_updater and "updaterState.bin" in names:
            # ComputationGraphUpdater flattens in the SAME topological
            # order as the params (BaseMultiLayerUpdater.getOrderedLayers)
            try:
                from deeplearning4j_tpu.nn.conf.graph_conf import LayerVertex
                topo = dl4j_graph_topological_order(
                    list(raw.get("networkInputs") or []),
                    list((raw.get("vertices") or {}).keys()),
                    {k: list(v)
                     for k, v in (raw.get("vertexInputs") or {}).items()})
                indexed = [(vname, conf.vertices[vname].layer_conf())
                           for vname in topo
                           if vname in conf.vertices
                           and isinstance(conf.vertices[vname], LayerVertex)]
                ustate = read_nd4j_array(
                    io.BytesIO(zf.read("updaterState.bin"))).ravel(order="C")
                migrated = updater_state_from_flat(indexed, ustate,
                                                   conf.global_conf)
                for vname in migrated:
                    net.opt_states[vname] = _merge_updater_state(
                        net.opt_states[vname], migrated[vname])
            except Exception as e:
                import warnings
                warnings.warn(
                    f"updaterState.bin could not be migrated ({e}); "
                    "training resumes with fresh updater state",
                    UserWarning, stacklevel=2)
    return net


# ---------------------------------------------------------------------------
# coefficients.bin → per-layer param dicts
# ---------------------------------------------------------------------------

def _layer_param_spec(layer: L.Layer):
    """[(name, shape, n, order)] in DL4J view order, or [] for no-param
    layers.  Shapes are DL4J's; most views reshape 'f' (column-major,
    WeightInitUtil.java:40) — EXCEPT conv kernels, which DL4J reshapes
    'c' and stores AFTER the bias (ConvolutionParamInitializer.java:76-80
    bias at interval(0,nOut), weights reshape('c', nOut,nIn,kH,kW))."""
    if isinstance(layer, L.ConvolutionLayer):
        n_in, n_out = layer.n_in, layer.n_out
        kh, kw = layer.kernel
        return [("b", (n_out,), n_out, "C"),
                ("W", (n_out, n_in, kh, kw), n_out * n_in * kh * kw, "C")]
    if isinstance(layer, L.BatchNormalization):
        n = layer.n_features
        spec = [] if layer.lock_gamma_beta else [("gamma", (n,), n, "F"),
                                                 ("beta", (n,), n, "F")]
        return spec + [("mean", (n,), n, "F"), ("var", (n,), n, "F")]
    if isinstance(layer, L.GravesBidirectionalLSTM):
        # forward block then backward block, each (W, RW+peepholes, b)
        # (nn/params/GravesBidirectionalLSTMParamInitializer.java:92-106)
        n_in, H = layer.n_in, layer.n_out
        out = []
        for pre in ("f_", "b_"):
            out += [(pre + "W", (n_in, 4 * H), n_in * 4 * H, "F"),
                    (pre + "RW+p", (H, 4 * H + 3), H * (4 * H + 3), "F"),
                    (pre + "b", (4 * H,), 4 * H, "F")]
        return out
    if isinstance(layer, L.GravesLSTM):
        n_in, H = layer.n_in, layer.n_out
        return [("W", (n_in, 4 * H), n_in * 4 * H, "F"),
                ("RW+p", (H, 4 * H + 3), H * (4 * H + 3), "F"),
                ("b", (4 * H,), 4 * H, "F")]
    if layer.has_params():   # dense/output/rnnoutput/embedding family
        n_in, n_out = layer.n_in, layer.n_out
        return [("W", (n_in, n_out), n_in * n_out, "F"),
                ("b", (n_out,), n_out, "F")]
    return []


def _is_lstm_gated(layer: L.Layer, name: str) -> bool:
    """True for LSTM param views whose last axis is 4H gate blocks
    (W/b, incl. the f_/b_ bidirectional variants) — these need the
    IFOG block swap.  RW is handled inside the RW+p branch."""
    return (isinstance(layer, (L.GravesLSTM, L.GravesBidirectionalLSTM))
            and (name.endswith("W") or name.endswith("b")))


def _swap_ifog_blocks(a: np.ndarray, H: int) -> np.ndarray:
    """Permute LSTM gate column blocks 0↔3 along the last axis.

    DL4J's IFOG order puts the tanh candidate in block 0 and the sigmoid
    multiplier in block 3 (GravesLSTMParamInitializer.java:108 "Order:
    input, forget, output, input modulation"; LSTMHelpers.java:180-226);
    this framework's cell is [i(sigmoid), f, o, g(tanh)]
    (ops/recurrent.py).  Swapping blocks 0 and 3 converts either layout
    to the other (involution), for W [*,4H], RW [H,4H] and b [4H]."""
    out = np.array(a, copy=True)
    out[..., 0:H] = a[..., 3 * H:4 * H]
    out[..., 3 * H:4 * H] = a[..., 0:H]
    return out


def _decode_view(layer: L.Layer, name: str, shape, order: str,
                 view: np.ndarray) -> Dict[str, np.ndarray]:
    """Decode ONE flat DL4J view into this framework's param keys.
    Shared by params_from_flat and each updater-state plane — updater
    state aligns elementwise with the flat param layout
    (BaseMultiLayerUpdater.java:61-120 slices both from parallel views),
    so the same reshapes/permutations apply."""
    if name.endswith("RW+p"):
        pre = name[:-len("RW+p")]
        m = np.reshape(view, shape, order=order)
        H = shape[0]
        # peephole cols: wFF, wOO, wGG (LSTMHelpers.java:62); after the
        # IFOG block swap the mapping is exact (module docstring):
        # wFF→pF, wOO→pO, wGG→pI
        return {pre + "RW": _swap_ifog_blocks(m[:, :4 * H], H),
                pre + "pF": m[:, 4 * H],
                pre + "pO": m[:, 4 * H + 1],
                pre + "pI": m[:, 4 * H + 2]}
    if _is_lstm_gated(layer, name):
        H = shape[-1] // 4
        return {name: _swap_ifog_blocks(
            np.reshape(view, shape, order=order), H)}
    return {name: np.reshape(view, shape, order=order)}


def _encode_view(layer: L.Layer, name: str, shape, order: str,
                 values: Dict) -> np.ndarray:
    """Inverse of _decode_view: one raveled DL4J view from param keys."""
    if name.endswith("RW+p"):
        pre = name[:-len("RW+p")]
        H = shape[0]
        m = np.zeros(shape, np.float32)
        m[:, :4 * H] = _swap_ifog_blocks(np.asarray(values[pre + "RW"]), H)
        m[:, 4 * H] = np.asarray(values[pre + "pF"])
        m[:, 4 * H + 1] = np.asarray(values[pre + "pO"])
        m[:, 4 * H + 2] = np.asarray(values[pre + "pI"])
        return np.ravel(m, order=order)
    if _is_lstm_gated(layer, name):
        H = shape[-1] // 4
        return np.ravel(_swap_ifog_blocks(np.asarray(values[name]), H),
                        order=order)
    return np.ravel(np.asarray(values[name]), order=order)


def params_from_flat(layers: List[L.Layer],
                     flat: np.ndarray) -> Tuple[List[Dict], List[Dict]]:
    """Replay DefaultParamInitializer's flattening: slice the flat row
    per layer/view, 'f'-order reshape.  Returns (params, states) in this
    framework's conventions (BN mean/var live in state, not params)."""
    params, states = [], []
    off = 0
    for i, layer in enumerate(layers):
        spec = _layer_param_spec(layer)
        lp, ls = {}, {}
        for name, shape, n, order in spec:
            if off + n > flat.size:
                raise ValueError(
                    f"coefficients.bin too short at layer {i} ({name}): "
                    f"need {off + n}, have {flat.size}")
            view = flat[off:off + n]
            off += n
            if name in ("mean", "var"):
                ls[name] = view.copy()
            else:
                lp.update(_decode_view(layer, name, shape, order, view))
        params.append(lp)
        states.append(ls)
    if off != flat.size:
        raise ValueError(f"coefficients.bin has {flat.size} params, "
                         f"layer specs consume {off}")
    return params, states


# ---------------------------------------------------------------------------
# updaterState.bin — the updater's flat state view
# ---------------------------------------------------------------------------

# Plane names map nd4j's legacy per-rule buffers onto this framework's
# ops/updaters.Updater.init keys.  Per-view state sizes:
# UpdaterUtils.stateSizeForLayerVariable:42-61 — SGD/NONE 0×, NESTEROVS
# (momentum v) / ADAGRAD (historical g²) / RMSPROP (moving-avg g²) 1×,
# ADAM (m then v) / ADADELTA (msg then msdx) 2× the param length; the
# 2-plane rules split their block view in half, first plane first
# (nd4j legacy AdamUpdater/AdaDeltaUpdater.setStateViewArray).
_STATE_PLANES = {
    "sgd": (), "none": (),
    "nesterovs": ("v",),
    "adagrad": ("g2",),
    "rmsprop": ("g2",),
    "adam": ("m", "v"),
    "adamax": ("m", "v"),
    "adadelta": ("g2", "dx2"),
}


def _view_updater(layer: L.Layer, name: str, g: GlobalConf) -> str:
    """Effective updater rule for one param view.  BN mean/var are
    Updater.NONE (BatchNormalization.java:151-161)."""
    if name in ("mean", "var"):
        return "none"
    return (layer.updater or g.updater or "sgd").lower()


def _updater_sig(layer: L.Layer, name: str, g: GlobalConf):
    """UpdaterBlock merge key: contiguous param views with equal updater
    configuration share one block (UpdaterUtils
    .updaterConfigurationsEquals:64-120 — same rule, same per-param
    learning rate incl. biasLearningRate, same LR schedule, same
    rule-specific hyperparameters).  Hyperparameters are RESOLVED to
    their effective values (layer → global → rule default, the same
    resolution nn/multilayer._updater_for applies) before comparison —
    DL4J compares resolved configs, so an explicit epsilon=1e-8 on one
    layer and an unset-default 1e-8 on the next must still merge."""
    upd = _view_updater(layer, name, g)
    is_bias = name == "b" or name.endswith("_b")
    lr = layer.learning_rate if layer.learning_rate is not None \
        else g.learning_rate
    if is_bias and layer.bias_learning_rate is not None:
        lr = layer.bias_learning_rate

    def res(field, default):
        v = getattr(layer, field, None)
        if v is None:
            v = getattr(g, field, None)
        return default if v is None else v

    hyper = ()
    if upd == "nesterovs":
        hyper = (res("momentum", 0.9),)
    elif upd in ("adam", "adamax"):
        hyper = (res("adam_mean_decay", 0.9), res("adam_var_decay", 0.999),
                 res("epsilon", 1e-8))
    elif upd == "adadelta":
        hyper = (res("rho", 0.95), res("epsilon", 1e-6))
    elif upd == "rmsprop":
        hyper = (res("rms_decay", 0.95), res("epsilon", 1e-8))
    elif upd == "adagrad":
        hyper = (res("epsilon", 1e-6),)
    sched = (g.lr_policy, g.lr_policy_decay_rate, g.lr_policy_steps,
             g.lr_policy_power,
             tuple(sorted((g.learning_rate_schedule or {}).items())))
    return (upd, lr, hyper, sched)


def _updater_blocks(indexed_layers, g: GlobalConf):
    """Walk (index, layer) pairs in flat-param order and group contiguous
    views with equal updater config into UpdaterBlocks
    (BaseMultiLayerUpdater.java:55-120).  Returns
    [{"updater", "views": [(idx, layer, name, shape, n, order)]}]."""
    blocks = []
    cur_sig = object()
    for idx, layer in indexed_layers:
        for name, shape, n, order in _layer_param_spec(layer):
            sig = _updater_sig(layer, name, g)
            if blocks and sig == cur_sig:
                blocks[-1]["views"].append((idx, layer, name, shape, n,
                                            order))
            else:
                cur_sig = sig
                blocks.append({"updater": sig[0],
                               "views": [(idx, layer, name, shape, n,
                                          order)]})
    return blocks


def updater_state_from_flat(indexed_layers, flat: np.ndarray,
                            g: GlobalConf) -> Dict:
    """Distribute a DL4J ``updaterState.bin`` row onto per-layer updater
    state in this framework's ops/updaters structure.

    Layout (BaseMultiLayerUpdater.java:55-130): layers input→output
    (topological order for a ComputationGraph), param views in
    initializer order, contiguous views with equal updater config merged
    into UpdaterBlocks; each block contributes its planes back-to-back —
    a 2-plane rule stores plane 0 for ALL the block's params, then plane
    1.  State elements align 1:1 with the flat param layout, so each
    plane decodes with the same per-view reshapes (incl. the LSTM IFOG
    swap) as coefficients.bin.

    Returns {layer_index: {plane: {param_key: array}}}."""
    out: Dict = {}
    off = 0
    for block in _updater_blocks(indexed_layers, g):
        planes = _STATE_PLANES.get(block["updater"])
        if planes is None:
            raise ValueError(
                f"unknown updater {block['updater']!r} in updater state")
        block_n = sum(v[4] for v in block["views"])
        for k, plane in enumerate(planes):
            row = flat[off + k * block_n: off + (k + 1) * block_n]
            if row.size != block_n:
                raise ValueError(
                    f"updaterState.bin too short: block needs {block_n} "
                    f"per plane at offset {off}")
            vo = 0
            for idx, layer, name, shape, n, order in block["views"]:
                vals = _decode_view(layer, name, shape, order,
                                    row[vo:vo + n])
                vo += n
                out.setdefault(idx, {}).setdefault(plane, {}).update(vals)
        off += len(planes) * block_n
    if off != flat.size:
        raise ValueError(f"updaterState.bin has {flat.size} entries, "
                         f"updater blocks consume {off}")
    return out


def updater_state_to_flat(indexed_layers, states: Dict,
                          g: GlobalConf) -> np.ndarray:
    """Inverse of :func:`updater_state_from_flat`: emit the flat DL4J
    updater-state row from {layer_index: {plane: {param_key: array}}}."""
    chunks = []
    for block in _updater_blocks(indexed_layers, g):
        planes = _STATE_PLANES.get(block["updater"])
        if planes is None:
            raise ValueError(
                f"updater {block['updater']!r} has no DL4J state layout")
        for plane in planes:
            for idx, layer, name, shape, n, order in block["views"]:
                vals = states.get(idx, {}).get(plane, {})
                try:
                    chunks.append(_encode_view(layer, name, shape, order,
                                               vals))
                except KeyError:
                    # missing state (e.g. frozen layer) → zeros, matching
                    # a freshly initialized Java updater view
                    chunks.append(np.zeros(n, np.float32))
    if not chunks:
        return np.empty(0, np.float32)
    return np.concatenate(chunks).astype(np.float32)


def _merge_updater_state(opt_state, migrated: Dict):
    """Overwrite the engine-initialized opt-state leaves for one layer
    with migrated arrays (structure comes from Updater.init so jitted
    steps see the exact pytree they expect)."""
    import jax.numpy as jnp
    if not migrated or not isinstance(opt_state, dict):
        return opt_state
    new = dict(opt_state)
    for plane, vals in migrated.items():
        if plane not in new or not isinstance(new[plane], dict):
            continue
        np_new = dict(new[plane])
        for k, v in vals.items():
            if k in np_new:
                np_new[k] = jnp.asarray(
                    v, getattr(np_new[k], "dtype", jnp.float32))
        new[plane] = np_new
    return new


# ---------------------------------------------------------------------------
# Export TO the DL4J container format (the reverse direction)
# ---------------------------------------------------------------------------

# selu/gelu/swish post-date DL4J 0.8's IActivation set; exporting their
# names keeps OUR round-trip exact (the importer substring-matches), a
# Java 0.8 reader would reject those three
_ACT_EXPORT = {"relu": "ReLU", "tanh": "TanH", "sigmoid": "Sigmoid",
               "softmax": "Softmax", "identity": "Identity",
               "leakyrelu": "LeakyReLU", "elu": "ELU", "cube": "Cube",
               "softplus": "SoftPlus", "softsign": "SoftSign",
               "hardtanh": "HardTanh", "hardsigmoid": "HardSigmoid",
               "rationaltanh": "RationalTanh",
               "rectifiedtanh": "RectifiedTanh", "selu": "SELU",
               "gelu": "GELU", "swish": "Swish", "linear": "Identity"}

_LOSS_EXPORT = {"mcxent": "LossMCXENT", "mse": "LossMSE", "l1": "LossL1",
                "l2": "LossL2", "mae": "LossMAE", "xent": "LossBinaryXENT",
                "negativeloglikelihood": "LossNegativeLogLikelihood",
                "hinge": "LossHinge", "squared_hinge": "LossSquaredHinge",
                "poisson": "LossPoisson", "kl_divergence": "LossKLD",
                "msle": "LossMSLE", "mape": "LossMAPE",
                "cosine_proximity": "LossCosineProximity",
                "squared_loss": "LossMSE"}


_LOSS_CANON = {"nll": "negativeloglikelihood",
               "mean_absolute_error": "mae",
               "mean_absolute_percentage_error": "mape",
               "mean_squared_logarithmic_error": "msle",
               "reconstruction_crossentropy": "xent",
               "squared_loss": "mse"}


def _loss_export(name: str) -> dict:
    name = _LOSS_CANON.get(name, name)  # registry aliases (ops/losses.py)
    if name not in _LOSS_EXPORT:
        raise ValueError(f"loss {name!r} has no DL4J export name")
    return {_LOSS_EXPORT[name]: {}}


def _export_layer_json(layer: L.Layer, g: GlobalConf):
    """(wrapper_type_name, layer_json) in the Jackson shape — inverse of
    :func:`_build_layer` for the supported families."""
    act = layer.activation or g.activation
    if act not in _ACT_EXPORT:
        raise ValueError(f"activation {act!r} has no DL4J export name")

    def eff(field, gfield=None):
        v = getattr(layer, field)
        return v if v is not None else getattr(g, gfield or field)

    j = {
        "activationFn": {_ACT_EXPORT[act]: {}},
        "weightInit": str(layer.weight_init or g.weight_init).upper(),
        "learningRate": eff("learning_rate"),
        "updater": str(layer.updater or g.updater).upper(),
        "momentum": eff("momentum"),
        "rho": eff("rho"),
        "rmsDecay": eff("rms_decay"),
        "adamMeanDecay": eff("adam_mean_decay"),
        "adamVarDecay": eff("adam_var_decay"),
        "l1": layer.l1 if layer.l1 else float("nan"),
        "l2": layer.l2 if layer.l2 else float("nan"),
        "l1Bias": layer.l1_bias if layer.l1_bias else float("nan"),
        "l2Bias": layer.l2_bias if layer.l2_bias else float("nan"),
        "dropOut": layer.dropout or 0.0,
        "biasInit": layer.bias_init
        if layer.bias_init is not None else g.bias_init,
    }
    eps = layer.epsilon if layer.epsilon is not None else g.epsilon
    if eps is not None:
        j["epsilon"] = eps
    if layer.bias_learning_rate is not None:
        j["biasLearningRate"] = layer.bias_learning_rate
    gn = layer.gradient_normalization or g.gradient_normalization
    if gn:
        j["gradientNormalization"] = str(gn)
        j["gradientNormalizationThreshold"] = (
            layer.gradient_normalization_threshold
            if layer.gradient_normalization_threshold is not None
            else g.gradient_normalization_threshold)
    if getattr(layer, "n_in", None):
        j["nIn"] = int(layer.n_in)
    if getattr(layer, "n_out", None):
        j["nOut"] = int(layer.n_out)
    if isinstance(layer, L.ConvolutionLayer):
        j.update(kernelSize=list(layer.kernel), stride=list(layer.stride),
                 padding=list(layer.padding),
                 dilation=list(layer.dilation),
                 convolutionMode="Same" if layer.convolution_mode == "same"
                 else "Truncate")
        return "convolution", j
    if isinstance(layer, L.SubsamplingLayer):
        j.pop("activationFn", None)
        j.update(poolingType=layer.pooling_type.upper(),
                 kernelSize=list(layer.kernel), stride=list(layer.stride),
                 padding=list(layer.padding), pnorm=layer.pnorm,
                 convolutionMode="Same" if layer.convolution_mode == "same"
                 else "Truncate")
        return "subsampling", j
    if isinstance(layer, L.BatchNormalization):
        j.update(decay=layer.decay, eps=layer.eps,
                 lockGammaBeta=layer.lock_gamma_beta,
                 nOut=int(layer.n_features or 0),
                 nIn=int(layer.n_features or 0))
        return "batchNormalization", j
    if isinstance(layer, (L.GravesLSTM, L.GravesBidirectionalLSTM)):
        if layer.gate_activation not in _ACT_EXPORT:
            raise ValueError(f"gate activation {layer.gate_activation!r} "
                             f"has no DL4J export name")
        j.update(forgetGateBiasInit=layer.forget_gate_bias_init,
                 gateActivationFn={_ACT_EXPORT[layer.gate_activation]: {}})
        return ("gravesBidirectionalLSTM"
                if isinstance(layer, L.GravesBidirectionalLSTM)
                else "gravesLSTM"), j
    if isinstance(layer, L.RnnOutputLayer):
        j["lossFn"] = _loss_export(layer.loss)
        return "rnnoutput", j
    if isinstance(layer, L.OutputLayer):
        j["lossFn"] = _loss_export(layer.loss)
        return "output", j
    if isinstance(layer, L.LossLayer):
        j["lossFn"] = _loss_export(layer.loss)
        return "loss", j
    if isinstance(layer, L.EmbeddingLayer):
        return "embedding", j
    if isinstance(layer, L.DenseLayer):
        return "dense", j
    if isinstance(layer, L.ActivationLayer):
        return "activation", j
    if isinstance(layer, L.DropoutLayer):
        return "dropout", j
    if isinstance(layer, L.GlobalPoolingLayer):
        j.pop("activationFn", None)
        j["poolingType"] = layer.pooling_type.upper()
        return "GlobalPooling", j
    if isinstance(layer, L.ZeroPaddingLayer):
        j.pop("activationFn", None)
        j["padding"] = list(layer.padding)
        return "zeroPadding", j
    raise ValueError(f"layer {type(layer).__name__} has no DL4J export "
                     f"mapping")


def _export_preprocessor(proc) -> dict:
    """Our InputPreProcessor → the Jackson wrapper-object form (inverse
    of _PREPROC_MAP).  Raises for shapes with no DL4J mapping — a
    silently dropped preprocessor would export a zip that reshapes
    wrongly on restore."""
    hwc = lambda p: {"inputHeight": p.height, "inputWidth": p.width,  # noqa: E731
                     "numChannels": p.channels}
    if isinstance(proc, pp.CnnToFeedForwardPreProcessor):
        return {"cnnToFeedForward": hwc(proc)}
    if isinstance(proc, pp.FeedForwardToCnnPreProcessor):
        return {"feedForwardToCnn": hwc(proc)}
    if isinstance(proc, pp.RnnToFeedForwardPreProcessor):
        return {"rnnToFeedForward": {}}
    if isinstance(proc, pp.FeedForwardToRnnPreProcessor):
        return {"feedForwardToRnn": {}}
    if isinstance(proc, pp.CnnToRnnPreProcessor):
        return {"cnnToRnn": {}}
    if isinstance(proc, pp.RnnToCnnPreProcessor):
        return {"rnnToCnn": hwc(proc)}
    raise ValueError(f"preprocessor {type(proc).__name__} has no DL4J "
                     f"export mapping")


def _flatten_layer_params(layer: L.Layer, lp: Dict, ls: Dict) -> np.ndarray:
    """Inverse of the :func:`params_from_flat` slicing for one layer:
    emit views in DL4J order with the per-view ravel order."""
    spec = _layer_param_spec(layer)
    chunks = []
    for name, shape, n, order in spec:
        if name in ("mean", "var"):
            chunks.append(np.ravel(np.asarray(ls[name]), order=order))
        else:
            chunks.append(_encode_view(layer, name, shape, order, lp))
    return np.concatenate(chunks) if chunks else np.empty(0, np.float32)


def export_multi_layer_network(net, path) -> None:
    """Write ``net`` as a zip in the ORIGINAL DL4J's container format
    (configuration.json in the Jackson schema + coefficients.bin in the
    legacy Nd4j.write format, util/ModelSerializer.java:79-120) so the
    params survive a round-trip through :func:`restore_multi_layer_network`
    bit-for-bit — and follow the documented layouts a Java DL4J reader
    replays.  Non-empty updater state is written as ``updaterState.bin``
    in the UpdaterBlock layout (see :func:`updater_state_to_flat`)."""
    import dataclasses as _dc
    conf = net.conf
    g = conf.global_conf
    # merge_layer_conf already zeroed per-layer l1/l2 when the flag was
    # off, so any surviving nonzero value implies regularization is live
    use_reg = bool(g.use_regularization or any(
        (lv.l1 or lv.l2 or lv.l1_bias or lv.l2_bias)
        for lv in conf.layers if not isinstance(lv, L.FrozenLayerConf)))
    confs = []
    inners = []
    for layer, lp, ls in zip(conf.layers, net.net_params, net.net_state):
        inner = layer._inner() if isinstance(layer, L.FrozenLayerConf) \
            else layer  # NOTE: DL4J 0.8 has no FrozenLayer JSON type —
        # frozen status does not survive export
        if isinstance(inner, L.BatchNormalization) and not inner.n_features:
            # conf-level n_features may be inferred at init; the running
            # stats carry the realized width
            inner = _dc.replace(inner,
                                n_features=int(ls["mean"].shape[0]))
        if getattr(inner, "n_in", None) in (None, 0) and "W" in lp:
            # n_in is usually inferred at init; the weights carry it
            W = lp["W"]
            n_in = int(W.shape[1] if isinstance(inner, L.ConvolutionLayer)
                       else W.shape[0])
            inner = _dc.replace(inner, n_in=n_in)
        inners.append(inner)
        tname, lj = _export_layer_json(inner, g)
        confs.append({
            "layer": {tname: lj},
            "miniBatch": g.mini_batch, "seed": g.seed,
            "minimize": g.minimize,
            "useRegularization": use_reg,
            "pretrain": False,
        })
    top = {
        "backprop": conf.backprop, "pretrain": conf.pretrain,
        "backpropType": ("TruncatedBPTT"
                         if conf.backprop_type == "truncatedbptt"
                         else "Standard"),
        "tbpttFwdLength": conf.tbptt_fwd_length,
        "tbpttBackLength": conf.tbptt_back_length,
        "inputPreProcessors": {
            str(i): _export_preprocessor(p)
            for i, p in (conf.preprocessors or {}).items()},
        "confs": confs,
    }
    flats = []
    for inner, lp, ls in zip(inners, net.net_params, net.net_state):
        flats.append(_flatten_layer_params(inner, lp, ls))
    flat = (np.concatenate([f for f in flats if f.size])
            if any(f.size for f in flats) else np.empty(0, np.float32))
    buf = io.BytesIO()
    write_nd4j_array(buf, flat.reshape(1, -1), order="f")
    ustates = {i: s for i, s in enumerate(net.opt_states)
               if isinstance(s, dict) and s}
    uflat = updater_state_to_flat(list(enumerate(inners)), ustates, g) \
        if ustates else np.empty(0, np.float32)
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("configuration.json", json.dumps(top, indent=2))
        zf.writestr("coefficients.bin", buf.getvalue())
        if uflat.size:
            # ModelSerializer.writeModel:106-125 appends the updater
            # state view only when present and non-empty
            ubuf = io.BytesIO()
            write_nd4j_array(ubuf, uflat.reshape(1, -1), order="f")
            zf.writestr("updaterState.bin", ubuf.getvalue())


def _export_vertex(v, g: GlobalConf) -> dict:
    """Our GraphVertexConf → the Jackson wrapper-object form (inverse of
    :func:`_build_vertex`)."""
    from deeplearning4j_tpu.nn.conf import graph_conf as gc
    if isinstance(v, gc.MergeVertex):
        return {"MergeVertex": {}}
    if isinstance(v, gc.ElementWiseVertex):
        return {"ElementWiseVertex": {"op": v.op.capitalize()}}
    if isinstance(v, gc.SubsetVertex):
        return {"SubsetVertex": {"from": v.from_idx, "to": v.to_idx}}
    if isinstance(v, gc.ScaleVertex):
        return {"ScaleVertex": {"scaleFactor": v.scale}}
    if isinstance(v, gc.ShiftVertex):
        return {"ShiftVertex": {"shiftFactor": v.shift}}
    if isinstance(v, gc.StackVertex):
        return {"StackVertex": {}}
    if isinstance(v, gc.UnstackVertex):
        return {"UnstackVertex": {"from": v.from_idx,
                                  "stackSize": v.stack_size}}
    if isinstance(v, gc.L2Vertex):
        return {"L2Vertex": {}}
    if isinstance(v, gc.L2NormalizeVertex):
        return {"L2NormalizeVertex": {}}
    if isinstance(v, gc.LastTimeStepVertex):
        return {"LastTimeStepVertex": {"maskArrayInputName": v.mask_input}}
    if isinstance(v, gc.DuplicateToTimeSeriesVertex):
        return {"DuplicateToTimeSeriesVertex": {"inputName": v.ts_input}}
    if isinstance(v, gc.PreprocessorVertex):
        return {"PreprocessorVertex": {"preProcessor": _export_preprocessor(
            pp.InputPreProcessor.from_dict(v.preprocessor))}}
    raise ValueError(f"vertex {type(v).__name__} has no DL4J export "
                     f"mapping")


def export_computation_graph(net, path) -> None:
    """Write a ComputationGraph as a zip in the ORIGINAL DL4J's container
    format (graph schema: nn/conf/ComputationGraphConfiguration.java:
    59-87; flat params in topologicalSortOrder per
    ComputationGraph.java:336-380).  Params, outputs AND updater state
    round-trip exactly through :func:`restore_computation_graph`
    (non-empty updater state is written as ``updaterState.bin`` in the
    same topological UpdaterBlock layout the restore side decodes);
    frozen-vertex status does NOT survive (DL4J 0.8 has no FrozenLayer
    JSON type — same caveat as export_multi_layer_network)."""
    import dataclasses as _dc
    from deeplearning4j_tpu.nn.conf.graph_conf import LayerVertex
    conf = net.conf
    g = conf.global_conf

    def resolved_inner(name, v):
        """Layer conf with frozen wrapper peeled and inferred n_in / BN
        width recovered from the live params — used by BOTH the JSON
        pass and the param-flatten pass so specs stay in sync."""
        lc = v.layer_conf()
        inner = lc._inner() if isinstance(lc, L.FrozenLayerConf) else lc
        lp = net.net_params.get(name) or {}
        W = lp.get("W")
        if W is None:
            W = lp.get("f_W")   # bidirectional LSTM keys f_W/b_W
        if getattr(inner, "n_in", None) in (None, 0) and W is not None:
            inner = _dc.replace(inner, n_in=int(
                W.shape[1] if isinstance(inner, L.ConvolutionLayer)
                else W.shape[0]))
        if isinstance(inner, L.BatchNormalization) and not inner.n_features:
            inner = _dc.replace(inner, n_features=int(
                net.net_state[name]["mean"].shape[0]))
        return inner

    inners = {name: resolved_inner(name, v)
              for name, v in conf.vertices.items()
              if isinstance(v, LayerVertex)}
    vertices_json = {}
    for name, v in conf.vertices.items():
        if isinstance(v, LayerVertex):
            tname, lj = _export_layer_json(inners[name], g)
            vertices_json[name] = {"LayerVertex": {
                "layerConf": {"layer": {tname: lj}, "seed": g.seed,
                              "miniBatch": g.mini_batch,
                              "minimize": g.minimize, "pretrain": False},
                "preProcessor": None}}
        else:
            vertices_json[name] = _export_vertex(v, g)
    top = {
        "networkInputs": list(conf.network_inputs),
        "networkOutputs": list(conf.network_outputs),
        "vertices": vertices_json,
        "vertexInputs": {k: list(vv)
                         for k, vv in conf.vertex_inputs.items()},
        "defaultConfiguration": {"seed": g.seed, "minimize": g.minimize,
                                 "miniBatch": g.mini_batch,
                                 "useRegularization": bool(
                                     g.use_regularization or any(
                                         (i.l1 or i.l2 or i.l1_bias
                                          or i.l2_bias)
                                         for i in inners.values()))},
        "backprop": True, "pretrain": False,
        "backpropType": ("TruncatedBPTT"
                         if conf.backprop_type == "truncatedbptt"
                         else "Standard"),
        "tbpttFwdLength": conf.tbptt_fwd_length,
        "tbpttBackLength": conf.tbptt_back_length,
    }
    topo = dl4j_graph_topological_order(
        list(conf.network_inputs), list(conf.vertices),
        {k: list(vv) for k, vv in conf.vertex_inputs.items()})
    flats = []
    for name in topo:
        if name not in inners:
            continue
        flats.append(_flatten_layer_params(
            inners[name], net.net_params.get(name) or {},
            net.net_state.get(name) or {}))
    flat = (np.concatenate([f for f in flats if f.size])
            if any(f.size for f in flats) else np.empty(0, np.float32))
    buf = io.BytesIO()
    write_nd4j_array(buf, flat.reshape(1, -1), order="f")
    # updater state in the same topological order the restore side walks
    indexed = [(name, inners[name]) for name in topo if name in inners]
    ustates = {name: s for name, s in (net.opt_states or {}).items()
               if isinstance(s, dict) and s}
    uflat = updater_state_to_flat(indexed, ustates, g) \
        if ustates else np.empty(0, np.float32)
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("configuration.json", json.dumps(top, indent=2))
        zf.writestr("coefficients.bin", buf.getvalue())
        if uflat.size:
            ubuf = io.BytesIO()
            write_nd4j_array(ubuf, uflat.reshape(1, -1), order="f")
            zf.writestr("updaterState.bin", ubuf.getvalue())


# ---------------------------------------------------------------------------
# Entry point
# ---------------------------------------------------------------------------

def restore_multi_layer_network(path, load_params: bool = True,
                                load_updater: bool = True):
    """Load a zip the ORIGINAL DL4J's ModelSerializer wrote and return an
    initialized :class:`MultiLayerNetwork` of this framework (ref:
    ModelSerializer.restoreMultiLayerNetwork, util/ModelSerializer.java;
    regression contract: regressiontest/RegressionTest071.java).

    ``updaterState.bin`` is migrated through the UpdaterBlock layout
    (see :func:`updater_state_from_flat`; docs/MIGRATION.md documents
    the byte-level spec) so fine-tuning resumes with the Java updater's
    momentum/moment buffers instead of a cold restart."""
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    import jax.numpy as jnp

    with zipfile.ZipFile(path, "r") as zf:
        names = set(zf.namelist())
        if "configuration.json" not in names:
            raise ValueError("not a DL4J model zip: no configuration.json")
        conf = config_from_dl4j_json(
            zf.read("configuration.json").decode("utf-8"))
        net = MultiLayerNetwork(conf)
        net.init()
        if load_params and "coefficients.bin" in names:
            flat = read_nd4j_array(
                io.BytesIO(zf.read("coefficients.bin"))).ravel(order="C")
            params, states = params_from_flat(conf.layers, flat)
            new_p, new_s = [], []
            for lp, ls, cur_p, cur_s in zip(params, states, net.net_params,
                                            net.net_state):
                merged_p = dict(cur_p)
                for k, v in lp.items():
                    if k in merged_p and merged_p[k].shape != v.shape:
                        raise ValueError(
                            f"param {k}: DL4J shape {v.shape} != "
                            f"{merged_p[k].shape}")
                    merged_p[k] = jnp.asarray(v, jnp.float32)
                merged_s = dict(cur_s)
                for k, v in ls.items():
                    merged_s[k] = jnp.asarray(v, jnp.float32)
                new_p.append(merged_p)
                new_s.append(merged_s)
            net.net_params = new_p
            net.net_state = new_s
            net.opt_states = [net.updaters[i].init(net.net_params[i])
                              for i in range(len(net.layers))]
        if load_updater and "updaterState.bin" in names:
            try:
                ustate = read_nd4j_array(
                    io.BytesIO(zf.read("updaterState.bin"))).ravel(order="C")
                migrated = updater_state_from_flat(
                    list(enumerate(conf.layers)), ustate, conf.global_conf)
                net.opt_states = [
                    _merge_updater_state(net.opt_states[i],
                                         migrated.get(i, {}))
                    for i in range(len(net.layers))]
            except Exception as e:  # e.g. an updater rule outside the
                # 0.8 set (NADAM/CUSTOM) whose state layout we can't
                # place — params still load, resume with fresh state
                import warnings
                warnings.warn(
                    f"updaterState.bin could not be migrated ({e}); "
                    "training resumes with fresh updater state",
                    UserWarning, stacklevel=2)
    return net
