"""Periodic checkpointing + crash-safe resume — the failure-recovery
mechanism (SURVEY §5: "Recovery story is checkpoint-based: save via
ModelSerializer, resume by reloading"; ref: util/ModelSerializer.java +
the early-stopping savers' persist pattern,
earlystopping/saver/LocalFileModelSaver.java).

``CheckpointListener`` saves the full training state (config, params,
updater state) every N iterations/epochs and prunes old checkpoints.
Writes are atomic AND durable: the zip lands in a temp file that is
fsync'd before an ``os.replace`` publish (plus a directory fsync), so a
crash mid-save — the exact window this module exists for — never leaves
a half-written "latest" checkpoint.  Each save also updates
``checkpoint_manifest.json`` (same atomic protocol) recording, per
checkpoint, the global iteration, completed epochs, and how many
batches into the current epoch the save landed — what
``fit(resume=True)`` needs to skip exactly the already-trained prefix
of the stream and match an uninterrupted run.

``resume_from_checkpoint`` restores the newest VALID checkpoint:
candidates are validated (zip CRC, parsable config, non-empty
coefficients) and a truncated/corrupt file from a crashed writer is
skipped with a warning — falling back to the previous checkpoint —
instead of raising.  ``restore_into`` is the in-place flavor the fit
loops use for ``conf.fault_tolerance(resume=True)``."""

from __future__ import annotations

import json
import logging
import os
import re
import time
import zipfile
from pathlib import Path
from typing import List, Optional, Tuple

from deeplearning4j_tpu.nn.listeners import TrainingListener
from deeplearning4j_tpu.resilience import faults
from deeplearning4j_tpu.resilience.errors import CorruptCheckpointError

log = logging.getLogger(__name__)

_CKPT_RE = re.compile(r"checkpoint_it(\d+)\.zip$")
MANIFEST_NAME = "checkpoint_manifest.json"


def _fsync_dir(directory: Path) -> None:
    try:
        fd = os.open(str(directory), os.O_RDONLY)
    except OSError:
        return  # platform without directory fds — rename is still atomic
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_file(path: Path) -> None:
    with open(path, "rb") as f:
        os.fsync(f.fileno())


def _publish(tmp: Path, final: Path) -> None:
    """fsync(tmp) → rename → fsync(dir): after this returns, the
    checkpoint is on disk under its final name or not at all."""
    _fsync_file(tmp)
    os.replace(tmp, final)
    _fsync_dir(final.parent)


def _atomic_write_text(path: Path, text: str) -> None:
    tmp = path.with_suffix(path.suffix + ".tmp")
    tmp.write_text(text)
    _publish(tmp, path)


def validate_checkpoint(path) -> dict:
    """Cheap integrity check of a checkpoint zip: archive readable, every
    member's CRC intact, ``configuration.json`` parses, coefficients
    present and a whole number of float32s.  Returns the parsed config
    dict; raises :class:`CorruptCheckpointError` on any violation —
    exactly what a crash mid-write (truncation) or torn storage
    produces."""
    from deeplearning4j_tpu.nn.serialization import (
        COEFFICIENTS_NAME, CONFIG_NAME)
    p = Path(path)
    try:
        with zipfile.ZipFile(p, "r") as zf:
            bad = zf.testzip()
            if bad is not None:
                raise CorruptCheckpointError(
                    f"{p.name}: CRC mismatch in member {bad!r}")
            names = zf.namelist()
            if CONFIG_NAME not in names:
                raise CorruptCheckpointError(f"{p.name}: no {CONFIG_NAME}")
            conf = json.loads(zf.read(CONFIG_NAME))
            if COEFFICIENTS_NAME not in names:
                raise CorruptCheckpointError(
                    f"{p.name}: no {COEFFICIENTS_NAME}")
            info = zf.getinfo(COEFFICIENTS_NAME)
            if info.file_size == 0 or info.file_size % 4 != 0:
                raise CorruptCheckpointError(
                    f"{p.name}: coefficients size {info.file_size} is not "
                    f"a non-empty float32 array")
    except CorruptCheckpointError:
        raise
    except Exception as e:
        # BadZipFile, OSError, json/ValueError, zlib.error, ... — any
        # failure READING the archive means the archive is not readable
        raise CorruptCheckpointError(f"{p.name}: {type(e).__name__}: {e}")
    return conf


def _sharding_meta(model):
    """Mesh + per-param sharding description for the manifest (None for
    replica-style models) — parallel/fsdp.sharding_manifest, guarded so
    metadata can never break a save."""
    try:
        from deeplearning4j_tpu.parallel import fsdp
        return fsdp.sharding_manifest(model)
    except Exception:
        return None


def _dist_meta(model):
    """Cluster placement at save time for the manifest (None outside
    distributed training) — which generation/rank/world wrote this
    checkpoint.  The coefficients stay the gathered flat host vector,
    so a checkpoint written by a 4-worker cluster restores into a
    1-worker (or single-host) run unchanged; this records provenance
    for the resume log and the cross-world-restore tests."""
    sess = getattr(model, "_dist_session", None)
    if sess is None:
        return None
    try:
        return {"worker": sess.worker_id,
                "generation": int(sess._generation),
                "rank": int(sess._rank), "world": int(sess._world)}
    except Exception:
        return None


def _precision_meta(model):
    """The precision tiers active at save time (None when everything is
    dense fp32 — which is also what manifests from before this field
    implied).  The coefficients in the zip are ALWAYS the fp32 master
    vector, so a checkpoint restores under any tier; configuration.json
    carries the conf knobs, so a cross-load restore re-activates the
    same tiers.  This records the tier for the manifest reader — which
    precision regime trained the weights."""
    try:
        import numpy as np
        g = model.conf.global_conf
        from deeplearning4j_tpu.ops import dtypes as dtype_ops
        pol = dtype_ops.resolve(getattr(g, "precision", None))
        tiers = {
            "compute": np.dtype(pol.compute_dtype).name,
            "infer_quant": getattr(g, "precision_infer_quant", None),
            "grad_quant": getattr(g, "dist_grad_quant", None),
        }
        if tiers["compute"] == "float32" and not tiers["infer_quant"] \
                and not tiers["grad_quant"]:
            return None
        return tiers
    except Exception:
        return None


def _count_fallback() -> None:
    try:
        from deeplearning4j_tpu import monitor
        monitor.get_registry().counter(
            "dl4j_resilience_checkpoint_fallbacks_total",
            "corrupt/unloadable checkpoints skipped during resume").inc()
        monitor.events.emit("checkpoint.fallback", severity="warn")
    except Exception:
        pass


class CheckpointListener(TrainingListener):
    """Save every ``save_every_n_iterations`` iterations (or every epoch
    when ``save_every_epoch``), keeping only the last ``keep_last``
    checkpoint zips."""

    def __init__(self, directory, save_every_n_iterations: Optional[int] = None,
                 save_every_epoch: bool = False, keep_last: int = 3,
                 save_updater: bool = True):
        if save_every_n_iterations is None and not save_every_epoch:
            raise ValueError("enable at least one of save_every_n_iterations "
                             "/ save_every_epoch")
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.every_n = save_every_n_iterations
        self.every_epoch = save_every_epoch
        self.keep_last = max(1, keep_last)
        self.save_updater = save_updater
        self._epoch_start_iter: Optional[int] = None

    # -- listener hooks ----------------------------------------------------
    def on_epoch_start(self, model):
        # fallback epoch-start marker for models driven without the fit
        # loops' own ``_epoch_start_iter`` bookkeeping
        self._epoch_start_iter = getattr(model, "iteration", 0)

    def iteration_done(self, model, iteration):
        if self.every_n and iteration % self.every_n == 0:
            # mid-epoch save: model.epoch COMPLETED epochs so far
            self._save(model, iteration, getattr(model, "epoch", 0),
                       self._iteration_in_epoch(model, iteration))

    def on_epoch_end(self, model):
        if self.every_epoch:
            # on_epoch_end fires before the engine increments model.epoch,
            # so the just-finished epoch counts as completed here — and
            # the NEXT epoch starts from its first batch
            self._save(model, model.iteration,
                       getattr(model, "epoch", 0) + 1, 0)

    def _iteration_in_epoch(self, model, iteration: int) -> Optional[int]:
        # the fit loops publish the epoch's starting iteration (resume-
        # aware); the on_epoch_start hook is the fallback marker
        start = getattr(model, "_epoch_start_iter", self._epoch_start_iter)
        if start is None:
            return None
        return max(0, int(iteration) - int(start))

    # -- internals ---------------------------------------------------------
    def _save(self, model, iteration: int, epochs_completed: int,
              iteration_in_epoch: Optional[int] = None) -> Path:
        from deeplearning4j_tpu.nn.serialization import write_model
        faults.check("checkpoint.write")
        path = self.dir / f"checkpoint_it{iteration}.zip"
        tmp = path.with_suffix(".tmp")
        write_model(model, tmp, save_updater=self.save_updater)
        _publish(tmp, path)  # fsync + atomic rename: a crash never
        # leaves a half-written "latest" checkpoint
        meta = {"file": path.name, "iteration": iteration,
                "epoch": epochs_completed,
                "iteration_in_epoch": iteration_in_epoch,
                "timestamp": int(time.time() * 1000),
                "model_class": type(model).__name__,
                # mesh/sharding the params were laid out on at save time
                # (None = replicated everywhere, which is also what
                # manifests from before this field implied — readers use
                # .get() so both load identically).  The coefficients in
                # the zip are ALWAYS the gathered flat host vector, so a
                # checkpoint restores onto any mesh; this records where
                # it came from for the reshard log/metrics.
                "sharding": _sharding_meta(model),
                # cluster placement at save time (None outside
                # distributed training) — restores work across process
                # counts; this is provenance, not a constraint
                "dist": _dist_meta(model),
                # precision tiers active at save time (None = dense
                # fp32); the coefficients stay the fp32 masters, so a
                # checkpoint restores under any tier — the conf inside
                # the zip re-activates the same tiers on cross-load
                "precision": _precision_meta(model)}
        self._update_manifest(meta)
        # legacy single-entry index, kept for older readers
        _atomic_write_text(self.dir / "checkpoint_index.json",
                           json.dumps(meta))
        self._prune()
        try:
            from deeplearning4j_tpu import monitor
            monitor.events.emit("checkpoint.write", path=path.name,
                                iteration=iteration,
                                epoch=epochs_completed)
        except Exception:
            pass
        return path

    def _update_manifest(self, meta: dict) -> None:
        entries = read_manifest(self.dir)
        entries = [e for e in entries if e.get("file") != meta["file"]]
        entries.append(meta)
        entries.sort(key=lambda e: e.get("iteration", 0))
        _atomic_write_text(self.dir / MANIFEST_NAME,
                           json.dumps({"version": 1, "checkpoints": entries},
                                      indent=2))

    def _prune(self) -> None:
        ckpts = self.checkpoints(self.dir)
        dropped = {p.name for p in ckpts[:-self.keep_last]}
        for old in ckpts[:-self.keep_last]:
            old.unlink(missing_ok=True)
        if dropped:
            entries = [e for e in read_manifest(self.dir)
                       if e.get("file") not in dropped]
            _atomic_write_text(self.dir / MANIFEST_NAME,
                               json.dumps({"version": 1,
                                           "checkpoints": entries},
                                          indent=2))

    @staticmethod
    def checkpoints(directory) -> List[Path]:
        """All checkpoints oldest→newest."""
        d = Path(directory)
        found = [(int(m.group(1)), p) for p in d.glob("checkpoint_it*.zip")
                 if (m := _CKPT_RE.search(p.name))]
        return [p for _, p in sorted(found)]

    @staticmethod
    def last_checkpoint(directory) -> Optional[Path]:
        ckpts = CheckpointListener.checkpoints(directory)
        return ckpts[-1] if ckpts else None


def read_manifest(directory) -> List[dict]:
    """The manifest's checkpoint entries (oldest→newest), or [] when the
    manifest is missing/corrupt — resume still works from filenames."""
    p = Path(directory) / MANIFEST_NAME
    try:
        data = json.loads(p.read_text())
        entries = data.get("checkpoints", [])
        return entries if isinstance(entries, list) else []
    except (OSError, ValueError):
        return []


def _checkpoint_meta(directory, path: Path) -> dict:
    """Best-available metadata for one checkpoint file: manifest entry
    if it names this file, else the legacy index (only when it describes
    this very iteration — a crash between zip publish and index write
    can leave it stale), else just the filename's iteration."""
    m = _CKPT_RE.search(path.name)
    meta = {"file": path.name,
            "iteration": int(m.group(1)) if m else 0,
            "epoch": None, "iteration_in_epoch": None, "sharding": None,
            "dist": None, "precision": None}
    for e in read_manifest(directory):
        if e.get("file") == path.name:
            meta.update({k: e.get(k, meta.get(k)) for k in
                         ("epoch", "iteration_in_epoch", "model_class",
                          "sharding", "dist", "precision")})
            return meta
    idx = Path(directory) / "checkpoint_index.json"
    if idx.exists():
        try:
            legacy = json.loads(idx.read_text())
            if int(legacy.get("iteration", -1)) == meta["iteration"]:
                meta["epoch"] = legacy.get("epoch")
        except (ValueError, OSError):
            pass
    return meta


def last_valid_checkpoint(directory) -> Optional[Tuple[Path, dict]]:
    """Newest checkpoint that passes :func:`validate_checkpoint`,
    walking backwards past corrupt/truncated ones (each skip logged and
    counted in ``dl4j_resilience_checkpoint_fallbacks_total``)."""
    for path in reversed(CheckpointListener.checkpoints(directory)):
        try:
            validate_checkpoint(path)
        except CorruptCheckpointError as e:
            log.warning("skipping corrupt checkpoint %s (%s); falling back "
                        "to the previous one", path.name, e)
            _count_fallback()
            continue
        return path, _checkpoint_meta(directory, path)
    return None


def _resume(directory, load_updater: bool = True
            ) -> Optional[Tuple[object, dict]]:
    """Walk checkpoints newest→oldest; validate, load, and return the
    first ``(model, meta)`` that survives both — skipping (and counting)
    corrupt or unloadable files."""
    from deeplearning4j_tpu.nn.serialization import load_model
    for path in reversed(CheckpointListener.checkpoints(directory)):
        try:
            validate_checkpoint(path)
            model = load_model(path, load_updater=load_updater)
        except Exception as e:
            # validation is necessary but not sufficient (a config can
            # parse yet fail to load) — either way, fall back to the
            # previous checkpoint instead of dying on the newest file
            log.warning("skipping unloadable checkpoint %s (%s: %s); "
                        "falling back to the previous one",
                        path.name, type(e).__name__, e)
            _count_fallback()
            continue
        meta = _checkpoint_meta(directory, path)
        meta["path"] = str(path)
        model.iteration = meta["iteration"]
        if meta.get("epoch") is not None:
            model.epoch = int(meta["epoch"])
        return model, meta
    return None


def resume_from_checkpoint(directory, load_updater: bool = True):
    """Restore the newest VALID checkpoint in ``directory`` (model type
    sniffed from the zip) with its iteration counter, or None when no
    loadable checkpoint exists — the crash-recovery entry point.

    Corrupt/truncated checkpoints (a crashed writer, torn storage) are
    validated against and skipped in favor of the previous one instead
    of raising.  The zip FILENAME is authoritative for the iteration;
    the manifest/index contributes the epoch only when it describes this
    very checkpoint."""
    found = _resume(directory, load_updater=load_updater)
    return found[0] if found else None


def restore_into(model, directory, load_updater: bool = True
                 ) -> Optional[dict]:
    """Load the newest valid checkpoint INTO an existing (already
    initialized) model — params, updater state, iteration and epoch —
    and return its metadata ``{path, iteration, epoch,
    iteration_in_epoch}``, or None when there is nothing to resume
    from.  The in-place flavor ``fit(resume=True)`` uses: the model
    keeps its listeners, conf and jit caches.

    A type mismatch (checkpoint of a different model class) raises —
    resuming a ComputationGraph from a MultiLayerNetwork checkpoint is
    a config error, not a recoverable fault."""
    found = _resume(directory, load_updater=load_updater)
    if found is None:
        return None
    loaded, meta = found
    if type(loaded).__name__ != type(model).__name__:
        raise ValueError(
            f"checkpoint in {directory} holds a {type(loaded).__name__}, "
            f"cannot resume a {type(model).__name__} from it")
    # set_params/set_updater_state_flat redistribute the flat host
    # vector onto the restoring model's OWN mesh (or plain single-device
    # arrays) — the host-side reshard that makes a checkpoint written on
    # one mesh resume on any other
    from deeplearning4j_tpu import monitor as _monitor
    with _monitor.span("checkpoint/restore", phase="reshard"):
        model.set_params(loaded.params())
        if load_updater and getattr(loaded, "opt_states", None) is not None:
            model.set_updater_state_flat(loaded.updater_state_flat())
    try:
        from deeplearning4j_tpu.parallel import fsdp
        fsdp.note_reshard(model, meta.get("sharding"))
    except Exception:
        pass
    if meta.get("dist"):
        # written under a cluster placement (possibly another world
        # size): the flat-vector restore above already redistributed —
        # log the cross-world provenance for the resume audit trail
        log.info("restoring checkpoint written by cluster worker %s "
                 "(generation %s, world %s)", meta["dist"].get("worker"),
                 meta["dist"].get("generation"), meta["dist"].get("world"))
    model.iteration = loaded.iteration
    model.epoch = getattr(loaded, "epoch", 0)
    _fast_forward_rng(model)
    if meta.get("epoch") is None:
        meta["epoch"] = getattr(loaded, "epoch", 0)
    return meta


def maybe_auto_resume(model) -> Tuple[int, int]:
    """The fit loops' ``conf.fault_tolerance(resume=True)`` hook.

    When resume is enabled and this model is fresh (iteration 0 — i.e.
    a restarted process, not a continuing in-process fit), restore the
    newest valid checkpoint into it and return ``(epochs_to_skip,
    batches_to_skip)``: the number of already-completed epochs fit must
    not re-run, and how many batches into the following epoch the
    checkpoint landed.  Returns ``(0, 0)`` when there is nothing to
    resume — a fresh run trains normally.

    The checkpoint directory comes from ``conf.ft_checkpoint_dir`` or,
    by default, the attached :class:`CheckpointListener`."""
    g = model.conf.global_conf
    if not getattr(g, "ft_resume", False):
        return 0, 0
    if int(getattr(model, "iteration", 0) or 0) > 0:
        return 0, 0
    directory = getattr(g, "ft_checkpoint_dir", None)
    if directory is None:
        for lst in getattr(model, "listeners", []):
            if isinstance(lst, CheckpointListener):
                directory = lst.dir
                break
    if directory is None or not Path(directory).is_dir():
        return 0, 0
    meta = restore_into(model, directory)
    if meta is None:
        return 0, 0
    skip_epochs = int(meta.get("epoch") or 0)
    skip_batches = int(meta.get("iteration_in_epoch") or 0)
    log.info("resumed from %s (iteration %d, epoch %d + %d batches); "
             "skipping the already-trained prefix",
             meta.get("path"), meta["iteration"], skip_epochs, skip_batches)
    # a resume means the PREVIOUS run died: journal the restore and dump
    # the black box so whatever the journal still holds about the crash
    # (plus the registry at restart) is preserved next to the new run
    try:
        from deeplearning4j_tpu.monitor import events, flight
        events.emit("checkpoint.restored", severity="warn",
                    path=str(meta.get("path")),
                    iteration=int(meta["iteration"]),
                    epoch=skip_epochs, batches=skip_batches)
        flight.dump("resume_from_checkpoint", extra={
            "path": str(meta.get("path")),
            "iteration": int(meta["iteration"]),
            "skip_epochs": skip_epochs, "skip_batches": skip_batches})
    except Exception:
        pass
    return skip_epochs, skip_batches


def _fast_forward_rng(model) -> None:
    """Replay the per-batch PRNG splits up to the restored iteration so
    stochastic layers (dropout/drop-connect) continue the SAME key
    sequence an uninterrupted run would have used — without this,
    resume is correct but not bit-reproducible for stochastic nets."""
    key = getattr(model, "_key", None)
    it = int(getattr(model, "iteration", 0) or 0)
    if key is None or it <= 0 or it > 100_000:
        return  # unknown key shape or absurdly long replay: skip
    import jax
    fresh = jax.random.PRNGKey(model.conf.global_conf.seed)
    for _ in range(it):
        fresh, _ = jax.random.split(fresh)
    model._key = fresh
