"""Periodic checkpointing + resume — the failure-recovery mechanism
(SURVEY §5: "Recovery story is checkpoint-based: save via
ModelSerializer, resume by reloading"; ref: util/ModelSerializer.java +
the early-stopping savers' persist pattern,
earlystopping/saver/LocalFileModelSaver.java).

``CheckpointListener`` saves the full training state (config, params,
updater state) every N iterations/epochs and prunes old checkpoints;
``resume_from_checkpoint`` restores the newest one, so a crashed run
continues from the last save with its optimizer moments intact."""

from __future__ import annotations

import json
import re
import time
from pathlib import Path
from typing import List, Optional

from deeplearning4j_tpu.nn.listeners import TrainingListener

_CKPT_RE = re.compile(r"checkpoint_it(\d+)\.zip$")


class CheckpointListener(TrainingListener):
    """Save every ``save_every_n_iterations`` iterations (or every epoch
    when ``save_every_epoch``), keeping only the last ``keep_last``
    checkpoint zips."""

    def __init__(self, directory, save_every_n_iterations: Optional[int] = None,
                 save_every_epoch: bool = False, keep_last: int = 3,
                 save_updater: bool = True):
        if save_every_n_iterations is None and not save_every_epoch:
            raise ValueError("enable at least one of save_every_n_iterations "
                             "/ save_every_epoch")
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.every_n = save_every_n_iterations
        self.every_epoch = save_every_epoch
        self.keep_last = max(1, keep_last)
        self.save_updater = save_updater

    # -- listener hooks ----------------------------------------------------
    def iteration_done(self, model, iteration):
        if self.every_n and iteration % self.every_n == 0:
            # mid-epoch save: model.epoch COMPLETED epochs so far
            self._save(model, iteration, getattr(model, "epoch", 0))

    def on_epoch_end(self, model):
        if self.every_epoch:
            # on_epoch_end fires before the engine increments model.epoch,
            # so the just-finished epoch counts as completed here
            self._save(model, model.iteration,
                       getattr(model, "epoch", 0) + 1)

    # -- internals ---------------------------------------------------------
    def _save(self, model, iteration: int, epochs_completed: int) -> Path:
        from deeplearning4j_tpu.nn.serialization import write_model
        path = self.dir / f"checkpoint_it{iteration}.zip"
        tmp = path.with_suffix(".tmp")
        write_model(model, tmp, save_updater=self.save_updater)
        tmp.replace(path)  # atomic publish — a crash never leaves a
        # half-written "latest" checkpoint
        meta = {"iteration": iteration, "epoch": epochs_completed,
                "timestamp": int(time.time() * 1000),
                "model_class": type(model).__name__}
        (self.dir / "checkpoint_index.json").write_text(json.dumps(meta))
        self._prune()
        return path

    def _prune(self) -> None:
        ckpts = self.checkpoints(self.dir)
        for old in ckpts[:-self.keep_last]:
            old.unlink(missing_ok=True)

    @staticmethod
    def checkpoints(directory) -> List[Path]:
        """All checkpoints oldest→newest."""
        d = Path(directory)
        found = [(int(m.group(1)), p) for p in d.glob("checkpoint_it*.zip")
                 if (m := _CKPT_RE.search(p.name))]
        return [p for _, p in sorted(found)]

    @staticmethod
    def last_checkpoint(directory) -> Optional[Path]:
        ckpts = CheckpointListener.checkpoints(directory)
        return ckpts[-1] if ckpts else None


def resume_from_checkpoint(directory, load_updater: bool = True):
    """Restore the newest checkpoint in ``directory`` (model type sniffed
    from the zip) with its iteration counter, or None when none exists —
    the crash-recovery entry point.  The zip FILENAME is authoritative
    for the iteration (a crash between zip publish and index write —
    exactly the window this module exists for — can leave a stale
    checkpoint_index.json); the index contributes the epoch only when it
    describes this very checkpoint."""
    from deeplearning4j_tpu.nn.serialization import load_model
    path = CheckpointListener.last_checkpoint(directory)
    if path is None:
        return None
    model = load_model(path, load_updater=load_updater)
    m = _CKPT_RE.search(path.name)
    if m:
        model.iteration = int(m.group(1))
    idx = Path(directory) / "checkpoint_index.json"
    if idx.exists():
        try:
            meta = json.loads(idx.read_text())
            if int(meta.get("iteration", -1)) == model.iteration:
                model.epoch = int(meta.get("epoch",
                                           getattr(model, "epoch", 0)))
        except (ValueError, OSError):
            pass
    return model
