"""ComputationGraph configuration: DAG of vertices.

(ref: nn/conf/ComputationGraphConfiguration.java (750 LoC),
nn/graph/vertex/impl/{LayerVertex, MergeVertex, ElementWiseVertex,
StackVertex, UnstackVertex, SubsetVertex, ScaleVertex, ShiftVertex,
L2Vertex, L2NormalizeVertex, PreprocessorVertex}.java and
rnn/{LastTimeStepVertex, DuplicateToTimeSeriesVertex}.java)

Each vertex is a dataclass with ``initialize`` (params/state) and
``forward(params, state, inputs, ...)`` over a LIST of input arrays —
the whole DAG traces into one XLA computation in topological order.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import Layer
from deeplearning4j_tpu.nn.conf.network import GlobalConf, merge_layer_conf
from deeplearning4j_tpu.nn.conf import preprocessors as pp

VERTEX_REGISTRY: Dict[str, type] = {}


def register_vertex(cls):
    VERTEX_REGISTRY[cls.__name__] = cls
    return cls


@dataclasses.dataclass
class GraphVertexConf:
    def initialize(self, key, input_types: List[InputType], dtype=jnp.float32
                   ) -> Tuple[dict, dict, InputType]:
        return {}, {}, self.output_type(input_types)

    def forward(self, params, state, inputs: List, *, train, rng, masks=None):
        raise NotImplementedError

    def output_type(self, input_types: List[InputType]) -> InputType:
        raise NotImplementedError

    def output_mask(self, masks):
        return masks[0] if masks else None

    def has_params(self) -> bool:
        return False

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["@class"] = type(self).__name__
        return d

    @staticmethod
    def from_dict(d: dict) -> "GraphVertexConf":
        d = dict(d)
        cls = VERTEX_REGISTRY[d.pop("@class")]
        return cls(**d)


@register_vertex
@dataclasses.dataclass
class LayerVertex(GraphVertexConf):
    """Wraps a layer config (ref: nn/graph/vertex/impl/LayerVertex.java)."""

    layer: Optional[dict] = None  # serialized Layer

    def layer_conf(self) -> Layer:
        return Layer.from_dict(self.layer)

    def has_params(self):
        return self.layer_conf().has_params()

    def initialize(self, key, input_types, dtype=jnp.float32):
        p, s, out = self.layer_conf().initialize(key, input_types[0], dtype)
        return p, s, out

    def forward(self, params, state, inputs, *, train, rng, masks=None):
        mask = masks[0] if masks else None
        y, ns, m = self.layer_conf().forward(params, state, inputs[0],
                                             train=train, rng=rng, mask=mask)
        return y, ns, m

    def output_type(self, input_types):
        return self.layer_conf().output_type(input_types[0])

    @staticmethod
    def of(layer: Layer) -> "LayerVertex":
        return LayerVertex(layer=layer.to_dict())


@register_vertex
@dataclasses.dataclass
class MergeVertex(GraphVertexConf):
    """Concatenate along the feature axis (ref: MergeVertex.java) —
    axis 1 for FF/CNN(NCHW), axis 2 for RNN [N,T,C]."""

    def forward(self, params, state, inputs, *, train, rng, masks=None):
        axis = 2 if inputs[0].ndim == 3 else 1
        return jnp.concatenate(inputs, axis=axis), state, self.output_mask(masks)

    def output_type(self, input_types):
        t = input_types[0]
        if t.kind == "cnn":
            return InputType.convolutional(t.height, t.width,
                                           sum(i.channels for i in input_types))
        if t.kind == "rnn":
            return InputType.recurrent(sum(i.size for i in input_types), t.timesteps)
        return InputType.feed_forward(sum(i.flat_size() for i in input_types))


@register_vertex
@dataclasses.dataclass
class ElementWiseVertex(GraphVertexConf):
    """(ref: ElementWiseVertex.java) op: add|subtract|product|average|max."""

    op: str = "add"

    def forward(self, params, state, inputs, *, train, rng, masks=None):
        op = self.op.lower()
        if op == "add":
            out = sum(inputs[1:], inputs[0])
        elif op == "subtract":
            out = inputs[0] - inputs[1]
        elif op in ("product", "mul"):
            out = inputs[0]
            for i in inputs[1:]:
                out = out * i
        elif op in ("average", "avg"):
            out = sum(inputs[1:], inputs[0]) / len(inputs)
        elif op == "max":
            out = jnp.stack(inputs).max(axis=0)
        else:
            raise ValueError(f"Unknown ElementWise op '{self.op}'")
        return out, state, self.output_mask(masks)

    def output_type(self, input_types):
        return input_types[0]


@register_vertex
@dataclasses.dataclass
class StackVertex(GraphVertexConf):
    """Stack along batch dim (ref: StackVertex.java)."""

    def forward(self, params, state, inputs, *, train, rng, masks=None):
        m = None
        if masks and any(mm is not None for mm in masks):
            ref = next(mm for mm in masks if mm is not None)
            # branches with no mask contribute all-ones (fully valid)
            filled = [mm if mm is not None
                      else jnp.ones((x.shape[0],) + ref.shape[1:], ref.dtype)
                      for mm, x in zip(masks, inputs)]
            m = jnp.concatenate(filled, axis=0)
        return jnp.concatenate(inputs, axis=0), state, m

    def output_type(self, input_types):
        return input_types[0]


@register_vertex
@dataclasses.dataclass
class UnstackVertex(GraphVertexConf):
    """Take slice `from_idx` of `stack_size` equal batch chunks
    (ref: UnstackVertex.java)."""

    from_idx: int = 0
    stack_size: int = 1

    def forward(self, params, state, inputs, *, train, rng, masks=None):
        x = inputs[0]
        step = x.shape[0] // self.stack_size
        sl = slice(self.from_idx * step, (self.from_idx + 1) * step)
        m = masks[0][sl] if (masks and masks[0] is not None) else None
        return x[sl], state, m

    def output_type(self, input_types):
        return input_types[0]


@register_vertex
@dataclasses.dataclass
class SubsetVertex(GraphVertexConf):
    """Feature-range subset [from, to] inclusive (ref: SubsetVertex.java)."""

    from_idx: int = 0
    to_idx: int = 0

    def forward(self, params, state, inputs, *, train, rng, masks=None):
        x = inputs[0]
        sl = slice(self.from_idx, self.to_idx + 1)
        if x.ndim == 3:
            out = x[:, :, sl]
        elif x.ndim == 4:
            out = x[:, sl]
        else:
            out = x[:, sl]
        return out, state, self.output_mask(masks)

    def output_type(self, input_types):
        n = self.to_idx - self.from_idx + 1
        t = input_types[0]
        if t.kind == "rnn":
            return InputType.recurrent(n, t.timesteps)
        if t.kind == "cnn":
            return InputType.convolutional(t.height, t.width, n)
        return InputType.feed_forward(n)


@register_vertex
@dataclasses.dataclass
class ScaleVertex(GraphVertexConf):
    """(ref: ScaleVertex.java)"""

    scale: float = 1.0

    def forward(self, params, state, inputs, *, train, rng, masks=None):
        return inputs[0] * self.scale, state, self.output_mask(masks)

    def output_type(self, input_types):
        return input_types[0]


@register_vertex
@dataclasses.dataclass
class ShiftVertex(GraphVertexConf):
    """(ref: ShiftVertex.java)"""

    shift: float = 0.0

    def forward(self, params, state, inputs, *, train, rng, masks=None):
        return inputs[0] + self.shift, state, self.output_mask(masks)

    def output_type(self, input_types):
        return input_types[0]


@register_vertex
@dataclasses.dataclass
class L2Vertex(GraphVertexConf):
    """Pairwise L2 distance between two inputs → [N, 1] (ref: L2Vertex.java)."""

    eps: float = 1e-8

    def forward(self, params, state, inputs, *, train, rng, masks=None):
        a, b = inputs[0], inputs[1]
        d = a.reshape(a.shape[0], -1) - b.reshape(b.shape[0], -1)
        out = jnp.sqrt(jnp.sum(d * d, axis=1, keepdims=True) + self.eps)
        return out, state, None

    def output_type(self, input_types):
        return InputType.feed_forward(1)


@register_vertex
@dataclasses.dataclass
class L2NormalizeVertex(GraphVertexConf):
    """x / ||x||_2 per example (ref: L2NormalizeVertex.java)."""

    eps: float = 1e-8

    def forward(self, params, state, inputs, *, train, rng, masks=None):
        x = inputs[0]
        flat = x.reshape(x.shape[0], -1)
        norm = jnp.linalg.norm(flat, axis=1, keepdims=True)
        out = (flat / (norm + self.eps)).reshape(x.shape)
        return out, state, self.output_mask(masks)

    def output_type(self, input_types):
        return input_types[0]


@register_vertex
@dataclasses.dataclass
class PreprocessorVertex(GraphVertexConf):
    """Standalone InputPreProcessor as a vertex (ref: PreprocessorVertex.java)."""

    preprocessor: Optional[dict] = None

    def forward(self, params, state, inputs, *, train, rng, masks=None):
        proc = pp.InputPreProcessor.from_dict(self.preprocessor)
        m = masks[0] if masks else None
        y, m = proc(inputs[0], m)
        return y, state, m

    def output_type(self, input_types):
        return pp.InputPreProcessor.from_dict(self.preprocessor).output_type(input_types[0])

    @staticmethod
    def of(proc: pp.InputPreProcessor) -> "PreprocessorVertex":
        return PreprocessorVertex(preprocessor=proc.to_dict())


@register_vertex
@dataclasses.dataclass
class LastTimeStepVertex(GraphVertexConf):
    """[N,T,C] → [N,C] at the last unmasked step
    (ref: rnn/LastTimeStepVertex.java); mask comes from the named input."""

    mask_input: Optional[str] = None

    def forward(self, params, state, inputs, *, train, rng, masks=None):
        x = inputs[0]
        mask = masks[0] if masks else None
        if mask is None:
            out = x[:, -1]
        else:
            idx = jnp.maximum(jnp.sum(mask > 0, axis=1).astype(jnp.int32) - 1, 0)
            out = x[jnp.arange(x.shape[0]), idx]
        return out, state, None

    def output_type(self, input_types):
        return InputType.feed_forward(input_types[0].size)


@register_vertex
@dataclasses.dataclass
class DuplicateToTimeSeriesVertex(GraphVertexConf):
    """[N,C] → [N,T,C] by duplication; T from a reference input
    (ref: rnn/DuplicateToTimeSeriesVertex.java).  The engine passes the
    reference sequence as inputs[1]."""

    ts_input: Optional[str] = None

    def forward(self, params, state, inputs, *, train, rng, masks=None):
        x = inputs[0]
        T = inputs[1].shape[1]
        out = jnp.broadcast_to(x[:, None, :], (x.shape[0], T, x.shape[-1]))
        m = masks[1] if masks and len(masks) > 1 else None
        return out, state, m

    def output_type(self, input_types):
        t = input_types[1].timesteps if len(input_types) > 1 else None
        return InputType.recurrent(input_types[0].flat_size(), t)


@register_vertex
@dataclasses.dataclass
class ReshapeVertex(GraphVertexConf):
    """Reshape trailing dims, batch preserved (ref: ReshapeVertex.java)."""

    shape: Optional[tuple] = None  # new shape excluding batch

    def forward(self, params, state, inputs, *, train, rng, masks=None):
        x = inputs[0]
        return x.reshape((x.shape[0],) + tuple(self.shape)), state, self.output_mask(masks)

    def output_type(self, input_types):
        import math
        n = math.prod(self.shape)
        if len(self.shape) == 3:
            return InputType.convolutional(self.shape[1], self.shape[2], self.shape[0])
        return InputType.feed_forward(n)


# ==========================================================================
# Configuration + builder
# ==========================================================================

@dataclasses.dataclass
class ComputationGraphConfiguration:
    """(ref: nn/conf/ComputationGraphConfiguration.java)"""

    network_inputs: List[str]
    network_outputs: List[str]
    vertices: Dict[str, GraphVertexConf]
    vertex_inputs: Dict[str, List[str]]
    global_conf: GlobalConf
    input_types: Optional[List[InputType]] = None
    backprop_type: str = "standard"
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20

    def topological_order(self) -> List[str]:
        """Kahn's algorithm over vertex dependencies
        (ref: ComputationGraph.topologicalOrder :122)."""
        indeg = {name: 0 for name in self.vertices}
        for name, ins in self.vertex_inputs.items():
            indeg[name] = sum(1 for i in ins if i in self.vertices)
        ready = sorted([n for n, d in indeg.items() if d == 0])
        order = []
        consumers: Dict[str, List[str]] = {}
        for name, ins in self.vertex_inputs.items():
            for i in ins:
                if i in self.vertices:
                    consumers.setdefault(i, []).append(name)
        while ready:
            n = ready.pop(0)
            order.append(n)
            for c in sorted(consumers.get(n, [])):
                indeg[c] -= 1
                if indeg[c] == 0:
                    ready.append(c)
        if len(order) != len(self.vertices):
            raise ValueError("Cycle detected in ComputationGraph")
        return order

    def to_dict(self) -> dict:
        return {
            "global": dataclasses.asdict(self.global_conf),
            "network_inputs": self.network_inputs,
            "network_outputs": self.network_outputs,
            "vertices": {k: v.to_dict() for k, v in self.vertices.items()},
            "vertex_inputs": self.vertex_inputs,
            "input_types": ([t.to_dict() for t in self.input_types]
                            if self.input_types else None),
            "backprop_type": self.backprop_type,
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "tbptt_back_length": self.tbptt_back_length,
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    def to_yaml(self) -> str:
        """(ref: ComputationGraphConfiguration.toYaml)"""
        import yaml
        return yaml.safe_dump(self.to_dict(), sort_keys=False)

    @staticmethod
    def from_yaml(s: str) -> "ComputationGraphConfiguration":
        import yaml
        return ComputationGraphConfiguration.from_dict(yaml.safe_load(s))

    @staticmethod
    def from_dict(d: dict) -> "ComputationGraphConfiguration":
        return ComputationGraphConfiguration(
            network_inputs=list(d["network_inputs"]),
            network_outputs=list(d["network_outputs"]),
            vertices={k: GraphVertexConf.from_dict(v)
                      for k, v in d["vertices"].items()},
            vertex_inputs={k: list(v) for k, v in d["vertex_inputs"].items()},
            global_conf=GlobalConf(**d["global"]),
            input_types=([InputType.from_dict(t) for t in d["input_types"]]
                         if d.get("input_types") else None),
            backprop_type=d.get("backprop_type", "standard"),
            tbptt_fwd_length=d.get("tbptt_fwd_length", 20),
            tbptt_back_length=d.get("tbptt_back_length", 20),
        )

    @staticmethod
    def from_json(s: str) -> "ComputationGraphConfiguration":
        return ComputationGraphConfiguration.from_dict(json.loads(s))


class GraphBuilder:
    """(ref: ComputationGraphConfiguration.GraphBuilder via
    NeuralNetConfiguration.Builder.graphBuilder())"""

    def __init__(self, g: Optional[GlobalConf] = None):
        self._g = g or GlobalConf()
        self._inputs: List[str] = []
        self._outputs: List[str] = []
        self._vertices: Dict[str, GraphVertexConf] = {}
        self._vertex_inputs: Dict[str, List[str]] = {}
        self._input_types: Optional[List[InputType]] = None
        self._bp_type = "standard"
        self._tf = 20
        self._tb = 20

    def add_inputs(self, *names: str) -> "GraphBuilder":
        self._inputs.extend(names)
        return self

    def add_layer(self, name: str, layer: Layer, *inputs: str) -> "GraphBuilder":
        merged = merge_layer_conf(layer, self._g)
        self._vertices[name] = LayerVertex.of(merged)
        self._vertex_inputs[name] = list(inputs)
        return self

    def add_vertex(self, name: str, vertex: GraphVertexConf, *inputs: str) -> "GraphBuilder":
        self._vertices[name] = vertex
        self._vertex_inputs[name] = list(inputs)
        return self

    def set_outputs(self, *names: str) -> "GraphBuilder":
        self._outputs = list(names)
        return self

    def set_input_types(self, *types: InputType) -> "GraphBuilder":
        self._input_types = list(types)
        return self

    def backprop_type(self, t: str) -> "GraphBuilder":
        self._bp_type = t.lower()
        return self

    def t_bptt_forward_length(self, n: int) -> "GraphBuilder":
        self._tf = n
        return self

    def t_bptt_backward_length(self, n: int) -> "GraphBuilder":
        self._tb = n
        return self

    def build(self) -> ComputationGraphConfiguration:
        if not self._inputs:
            raise ValueError("GraphBuilder needs at least one input")
        if not self._outputs:
            raise ValueError("GraphBuilder needs at least one output")
        known = set(self._inputs) | set(self._vertices)
        for name, ins in self._vertex_inputs.items():
            for i in ins:
                if i not in known:
                    raise ValueError(
                        f"Vertex '{name}' wired to unknown input '{i}' "
                        f"(known: {sorted(known)})")
        for name in self._outputs:
            if name not in self._vertices:
                raise ValueError(f"Output '{name}' is not a vertex")
        conf = ComputationGraphConfiguration(
            network_inputs=self._inputs, network_outputs=self._outputs,
            vertices=self._vertices, vertex_inputs=self._vertex_inputs,
            global_conf=self._g, input_types=self._input_types,
            backprop_type=self._bp_type, tbptt_fwd_length=self._tf,
            tbptt_back_length=self._tb)
        conf.topological_order()  # validate acyclicity early
        _infer_graph_nin(conf)
        return conf


def _infer_graph_nin(conf: ComputationGraphConfiguration) -> None:
    """Infer nIn for LayerVertex layers from upstream output types, and
    auto-insert flatten preprocessors between CNN activations and dense
    layers (the reference's graph-level addPreProcessors pass)."""
    if conf.input_types is None:
        return
    from deeplearning4j_tpu.nn.conf.network import _needs
    types: Dict[str, InputType] = dict(zip(conf.network_inputs, conf.input_types))
    for name in conf.topological_order():
        v = conf.vertices[name]
        in_names = conf.vertex_inputs[name]
        in_types = [types[i] for i in in_names]
        if isinstance(v, LayerVertex):
            layer = v.layer_conf()
            if _needs(layer) == "ff" and in_types[0].kind == "cnn":
                # insert CnnToFeedForward between upstream and this layer
                t = in_types[0]
                proc = pp.CnnToFeedForwardPreProcessor(t.height, t.width,
                                                       t.channels)
                pv_name = f"{name}-cnn2ff"
                conf.vertices[pv_name] = PreprocessorVertex.of(proc)
                conf.vertex_inputs[pv_name] = [in_names[0]]
                conf.vertex_inputs[name] = [pv_name] + in_names[1:]
                types[pv_name] = proc.output_type(t)
                in_types[0] = types[pv_name]
            updates = {}
            if hasattr(layer, "n_in") and getattr(layer, "n_in") is None:
                t = in_types[0]
                updates["n_in"] = t.channels if t.kind == "cnn" else t.flat_size()
            from deeplearning4j_tpu.nn.conf.layers import BatchNormalization
            if isinstance(layer, BatchNormalization) and layer.n_features is None:
                t = in_types[0]
                updates["n_features"] = t.channels if t.kind == "cnn" else t.flat_size()
            if updates:
                layer = dataclasses.replace(layer, **updates)
                conf.vertices[name] = LayerVertex.of(layer)
        types[name] = conf.vertices[name].output_type(in_types)
