"""Network configuration: builder DSL + MultiLayerConfiguration.

Mirrors the reference's Jackson-serializable config stack
(ref: nn/conf/NeuralNetConfiguration.java:539+ builder,
nn/conf/MultiLayerConfiguration.java) — global hyperparameters with
per-layer overrides, automatic nIn inference and preprocessor insertion
from ``InputType`` (ref: nn/conf/layers/InputTypeUtil.java), JSON
round-trip for checkpoint parity.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import BatchNormalization, Layer
from deeplearning4j_tpu.nn.conf import preprocessors as pp


@dataclasses.dataclass
class GlobalConf:
    """Global hyperparameters (the reference's NeuralNetConfiguration fields)."""

    seed: int = 12345
    iterations: int = 1
    learning_rate: float = 1e-1
    bias_learning_rate: Optional[float] = None
    updater: str = "sgd"
    momentum: float = 0.9
    rho: float = 0.95
    rms_decay: float = 0.95
    adam_mean_decay: float = 0.9
    adam_var_decay: float = 0.999
    epsilon: Optional[float] = None
    activation: str = "sigmoid"
    weight_init: str = "xavier"
    bias_init: float = 0.0
    dist: Optional[dict] = None
    l1: float = 0.0
    l2: float = 0.0
    l1_bias: float = 0.0
    l2_bias: float = 0.0
    dropout: float = 0.0
    use_regularization: bool = False
    use_drop_connect: bool = False
    minimize: bool = True
    mini_batch: bool = True
    optimization_algo: str = "stochastic_gradient_descent"
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: float = 1.0
    lr_policy: Optional[str] = None
    lr_policy_decay_rate: Optional[float] = None
    lr_policy_steps: Optional[float] = None
    lr_policy_power: Optional[float] = None
    learning_rate_schedule: Optional[dict] = None
    # Mixed-precision policy for the compiled step: None = auto (bf16
    # compute on TPU, f32 elsewhere); 'float32' | 'bfloat16' | 'float64'.
    # Master params/updater state stay float32 either way (ops/dtypes.py).
    precision: Optional[str] = None
    # Weight-only quantized inference (ops/quantize.py): 'int8' | 'fp8'
    # quantizes every ndim>=2 float param per-output-channel once on the
    # host and dequantizes in-trace, so output()/serving hold ~4x
    # smaller resident weights.  None = dense serving, byte-identical
    # to the pre-tier path.  Selection goes through the precision-tier
    # registry (ops/helpers.py): DL4J_PRECISION_{INT8,FP8}=0 kills it.
    precision_infer_quant: Optional[str] = None
    # Rematerialization: recompute each layer's forward during backward
    # instead of keeping its activations in HBM (jax.checkpoint per
    # layer/vertex) — the FLOPs-for-memory trade for deep nets on TPU.
    gradient_checkpointing: bool = False
    # Shape bucketing (ops/bucketing.py): pad ragged batch/time dims up
    # to a small ladder of buckets so jitted entry points compile once
    # per bucket instead of once per exact shape.  None ladders mean
    # powers of two.  Padded rows/timesteps are mask-excluded; outputs
    # and scores are un-padded, so results match the unbucketed run.
    shape_bucketing: bool = False
    bucket_batch_sizes: Optional[List[int]] = None
    bucket_time_sizes: Optional[List[int]] = None
    # Input pipeline (datasets/iterators.AsyncDataSetIterator): number of
    # parallel ETL worker threads the fit loops wrap iterators with
    # (0 = synchronous, no wrapper), raw-batch prefetch queue depth, and
    # how many already-device_put batches may be staged ahead of the
    # consumer (None = prefetch depth).  See docs/PERFORMANCE.md.
    pipeline_workers: int = 1
    pipeline_prefetch: int = 4
    pipeline_staging_depth: Optional[int] = None
    # Fault tolerance (resilience/, nn/checkpoint.py): ``ft_resume``
    # makes fit() auto-restore the newest valid checkpoint from the
    # attached CheckpointListener's directory (or ``ft_checkpoint_dir``)
    # and skip the already-trained prefix of the stream, so a crashed
    # run restarted with the same script converges like an
    # uninterrupted one.  ``ft_reader_retries`` retries transient
    # reader failures inside the input-pipeline feeder with exponential
    # backoff instead of surfacing them.  See docs/RESILIENCE.md.
    ft_resume: bool = False
    ft_reader_retries: int = 0
    ft_checkpoint_dir: Optional[str] = None
    # Sharded training (parallel/fsdp.py): ``sharding_enabled`` makes
    # fit() train FSDP-style on the device mesh — the batch shards over
    # data×fsdp, large params and their updater state shard over the
    # ``fsdp`` axis (ZeRO weight-update sharding: reduce-scatter grads →
    # per-shard updater → all-gather params, arXiv 2004.13336), arrays
    # under ``sharding_replicate_below`` elements stay replicated.
    # data=-1 means "all remaining devices".  Degrades to replica-style
    # on a single device or an unsatisfiable mesh.  TBPTT nets ignore
    # sharding (time-segmented stepping keeps replica semantics).
    sharding_enabled: bool = False
    sharding_data: int = -1
    sharding_fsdp: int = 1
    sharding_model: int = 1
    sharding_replicate_below: int = 2048
    # Elastic multi-host training (distributed/): ``dist_enabled`` makes
    # fit() train as one worker of a coordinator-backed cluster — each
    # global batch is shard-sliced by (rank, world) of the current
    # cluster generation, gradients all-reduce through the coordinator
    # barrier, and membership changes (a preempted worker, a returning
    # one) roll the generation and re-slice live.  ``dist_processes`` is
    # the initial formation size; ``dist_coordinator`` the coordinator
    # URL (the launcher exports DL4J_DIST_COORDINATOR instead).  Without
    # a reachable coordinator the conf is inert — single-process fit()
    # is byte-identical to a non-distributed one.  See
    # docs/DISTRIBUTED.md.
    dist_enabled: bool = False
    dist_processes: int = 0
    dist_coordinator: Optional[str] = None
    dist_heartbeat_ms: float = 250.0
    dist_lease_ms: float = 2000.0
    # Quantized gradient all-reduce (ops/quantize.py): 'int8' makes the
    # worker's barrier contribution int8 codes + per-block scales with a
    # persistent error-feedback residual (~4x fewer cross-host bytes;
    # the coordinator dequantizes per contribution before its rank-order
    # accumulation, so mixed fleets interoperate).  None = fp32 wire,
    # byte-identical to the pre-tier path.  DL4J_DIST_QUANT=0 kills it.
    dist_grad_quant: Optional[str] = None


_MERGE_FIELDS = [
    "activation", "weight_init", "bias_init", "dist", "learning_rate",
    "bias_learning_rate", "l1", "l2", "l1_bias", "l2_bias", "dropout",
    "use_drop_connect", "updater", "momentum", "rho", "rms_decay",
    "adam_mean_decay", "adam_var_decay", "epsilon",
    "gradient_normalization", "gradient_normalization_threshold",
]


def merge_layer_conf(layer: Layer, g: GlobalConf) -> Layer:
    """Fill a layer's unset (None) hyperparams from the global conf —
    the reference's global-then-override merge."""
    updates = {}
    for f in _MERGE_FIELDS:
        if getattr(layer, f, None) is None and hasattr(g, f):
            updates[f] = getattr(g, f)
    # L1/L2 are inert unless regularization is enabled (reference semantics:
    # per-layer values are ignored too when the flag is off).
    if not g.use_regularization:
        for f in ("l1", "l2", "l1_bias", "l2_bias"):
            updates[f] = 0.0
    return dataclasses.replace(layer, **{k: v for k, v in updates.items()
                                         if hasattr(layer, k)})


@dataclasses.dataclass
class MultiLayerConfiguration:
    """(ref: nn/conf/MultiLayerConfiguration.java)"""

    layers: List[Layer]
    global_conf: GlobalConf
    input_type: Optional[InputType] = None
    preprocessors: Dict[int, pp.InputPreProcessor] = dataclasses.field(default_factory=dict)
    backprop: bool = True
    pretrain: bool = False
    backprop_type: str = "standard"  # 'standard' | 'truncatedbptt'
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20

    # ---- serde (checkpoint parity: configuration.json) ----
    def to_dict(self) -> dict:
        return {
            "global": dataclasses.asdict(self.global_conf),
            "layers": [l.to_dict() for l in self.layers],
            "input_type": self.input_type.to_dict() if self.input_type else None,
            "preprocessors": {str(k): v.to_dict() for k, v in self.preprocessors.items()},
            "backprop": self.backprop,
            "pretrain": self.pretrain,
            "backprop_type": self.backprop_type,
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "tbptt_back_length": self.tbptt_back_length,
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    def to_yaml(self) -> str:
        """(ref: MultiLayerConfiguration.toYaml — Jackson YAML mapper)"""
        import yaml
        return yaml.safe_dump(self.to_dict(), sort_keys=False)

    @staticmethod
    def from_yaml(s: str) -> "MultiLayerConfiguration":
        import yaml
        return MultiLayerConfiguration.from_dict(yaml.safe_load(s))

    @staticmethod
    def from_dict(d: dict) -> "MultiLayerConfiguration":
        return MultiLayerConfiguration(
            layers=[Layer.from_dict(ld) for ld in d["layers"]],
            global_conf=GlobalConf(**d["global"]),
            input_type=InputType.from_dict(d["input_type"]) if d.get("input_type") else None,
            preprocessors={int(k): pp.InputPreProcessor.from_dict(v)
                           for k, v in d.get("preprocessors", {}).items()},
            backprop=d.get("backprop", True),
            pretrain=d.get("pretrain", False),
            backprop_type=d.get("backprop_type", "standard"),
            tbptt_fwd_length=d.get("tbptt_fwd_length", 20),
            tbptt_back_length=d.get("tbptt_back_length", 20),
        )

    @staticmethod
    def from_json(s: str) -> "MultiLayerConfiguration":
        return MultiLayerConfiguration.from_dict(json.loads(s))


class NeuralNetConfiguration:
    """Entry point: ``NeuralNetConfiguration.builder()`` — the reference's
    fluent DSL (ref: nn/conf/NeuralNetConfiguration.java Builder)."""

    @staticmethod
    def builder() -> "Builder":
        return Builder()


class Builder:
    def __init__(self):
        self._g = GlobalConf()

    # Fluent setters — names follow the reference's builder methods.
    def seed(self, s):
        self._g.seed = int(s); return self

    def iterations(self, n):
        self._g.iterations = int(n); return self

    def learning_rate(self, lr):
        self._g.learning_rate = float(lr); return self

    def bias_learning_rate(self, lr):
        self._g.bias_learning_rate = float(lr); return self

    def updater(self, u: str):
        self._g.updater = u.lower(); return self

    def momentum(self, m):
        self._g.momentum = float(m); return self

    def rho(self, r):
        self._g.rho = float(r); return self

    def rms_decay(self, r):
        self._g.rms_decay = float(r); return self

    def adam_mean_decay(self, b):
        self._g.adam_mean_decay = float(b); return self

    def adam_var_decay(self, b):
        self._g.adam_var_decay = float(b); return self

    def epsilon(self, e):
        self._g.epsilon = float(e); return self

    def activation(self, a: str):
        self._g.activation = a; return self

    def weight_init(self, w: str):
        self._g.weight_init = w; return self

    def bias_init(self, b):
        self._g.bias_init = float(b); return self

    def dist(self, d: dict):
        self._g.dist = d; return self

    def regularization(self, on: bool = True):
        self._g.use_regularization = bool(on); return self

    def l1(self, v):
        self._g.l1 = float(v); return self

    def l2(self, v):
        self._g.l2 = float(v); return self

    def drop_out(self, v):
        self._g.dropout = float(v); return self

    def use_drop_connect(self, on: bool = True):
        """Reuse the dropout probability on weights instead of activations
        (ref: NeuralNetConfiguration.Builder.useDropConnect /
        util/Dropout.java applyDropConnect)."""
        self._g.use_drop_connect = bool(on); return self

    def minimize(self, on: bool = True):
        self._g.minimize = bool(on); return self

    def mini_batch(self, on: bool = True):
        self._g.mini_batch = bool(on); return self

    def optimization_algo(self, algo: str):
        self._g.optimization_algo = algo.lower(); return self

    def gradient_normalization(self, mode: str, threshold: float = 1.0):
        self._g.gradient_normalization = mode
        self._g.gradient_normalization_threshold = float(threshold)
        return self

    _UNSET = object()

    def precision(self, p=_UNSET, *, compute: Optional[str] = None,
                  infer_quant=_UNSET, grad_allreduce=_UNSET):
        """Precision tiers (docs/PERFORMANCE.md "Precision tiers").

        ``compute`` (or the positional ``p``): mixed-precision policy
        for the compiled step — 'bfloat16' (TPU fast path: bf16
        activations/matmuls, f32 master weights, f32 accumulation),
        'float32', 'float64', or None/'auto' (bf16 on TPU, f32
        elsewhere).  ``infer_quant``: 'int8' | 'fp8' weight-only
        quantized serving (dequant-in-trace, ~4x smaller resident
        weights).  ``grad_allreduce``: 'int8' block-quantized
        error-feedback gradient collectives for distributed fit.
        Every tier is byte-identical to the dense path when unset."""
        if compute is not None:
            self._g.precision = compute
        elif p is not Builder._UNSET:
            self._g.precision = p
        if infer_quant is not Builder._UNSET:
            self._g.precision_infer_quant = infer_quant
        if grad_allreduce is not Builder._UNSET:
            self._g.dist_grad_quant = grad_allreduce
        return self

    def gradient_checkpointing(self, on: bool = True):
        """Recompute layer forwards in the backward pass (jax.checkpoint)
        — trades ~33% more FLOPs for O(depth) less activation HBM, the
        standard remat recipe for deep nets on TPU."""
        self._g.gradient_checkpointing = bool(on)
        return self

    def shape_bucketing(self, on: bool = True, batch_sizes=None,
                        time_sizes=None):
        """Pad ragged batch/time dims up to a bucket ladder (powers of
        two unless given) so every jitted path compiles once per bucket
        — see ops/bucketing.py and docs/PERFORMANCE.md."""
        self._g.shape_bucketing = bool(on)
        if batch_sizes is not None:
            self._g.bucket_batch_sizes = [int(s) for s in batch_sizes]
        if time_sizes is not None:
            self._g.bucket_time_sizes = [int(s) for s in time_sizes]
        return self

    def input_pipeline(self, workers: Optional[int] = None,
                       prefetch: Optional[int] = None,
                       staging_depth: Optional[int] = None):
        """Tune the async input pipeline the fit loops wrap iterators
        with: ``workers`` parallel ETL threads (0 disables the wrapper),
        ``prefetch`` raw batches queued ahead, ``staging_depth`` device-
        resident batches staged ahead of the consumer."""
        if workers is not None:
            self._g.pipeline_workers = int(workers)
        if prefetch is not None:
            self._g.pipeline_prefetch = int(prefetch)
        if staging_depth is not None:
            self._g.pipeline_staging_depth = int(staging_depth)
        return self

    def fault_tolerance(self, resume: Optional[bool] = None,
                        reader_retries: Optional[int] = None,
                        checkpoint_dir=None):
        """Crash-safe training (docs/RESILIENCE.md): ``resume=True``
        auto-restores fit() from the newest valid checkpoint (written
        by an attached ``CheckpointListener``, or found in
        ``checkpoint_dir``) and replays the input stream past the
        already-trained prefix; ``reader_retries=N`` retries transient
        reader failures in the input-pipeline feeder up to N times with
        seeded exponential backoff before surfacing them."""
        if resume is not None:
            self._g.ft_resume = bool(resume)
        if reader_retries is not None:
            self._g.ft_reader_retries = max(0, int(reader_retries))
        if checkpoint_dir is not None:
            self._g.ft_checkpoint_dir = str(checkpoint_dir)
        return self

    def sharding(self, data: Optional[int] = None,
                 fsdp: Optional[int] = None,
                 model: Optional[int] = None,
                 replicate_below: Optional[int] = None,
                 enabled: bool = True):
        """Promote fit() to sharded (FSDP/ZeRO) training on the device
        mesh (docs/PERFORMANCE.md "Sharded training"): the global batch
        shards over ``data``×``fsdp`` devices, large weight matrices AND
        their updater state shard over ``fsdp`` (reduce-scatter grads →
        per-shard updater update → all-gather params inside the one
        compiled step), ``model`` adds Megatron-style tensor
        parallelism, and arrays under ``replicate_below`` elements
        (biases, BN stats) stay replicated.  ``data=-1`` (default)
        takes all remaining devices.  On a single device or an
        unsatisfiable mesh the conf is inert — fit() stays
        replica-style with identical numerics."""
        self._g.sharding_enabled = bool(enabled)
        if data is not None:
            self._g.sharding_data = int(data)
        if fsdp is not None:
            self._g.sharding_fsdp = int(fsdp)
        if model is not None:
            self._g.sharding_model = int(model)
        if replicate_below is not None:
            self._g.sharding_replicate_below = max(0, int(replicate_below))
        return self

    def distributed(self, processes: Optional[int] = None,
                    coordinator: Optional[str] = None,
                    heartbeat_ms: Optional[float] = None,
                    lease_ms: Optional[float] = None,
                    enabled: bool = True):
        """Route fit() through the elastic multi-worker cluster runtime
        (docs/DISTRIBUTED.md) — the modern equivalent of the reference's
        Spark ``TrainingMaster`` tier: N workers (usually spawned by
        ``python -m deeplearning4j_tpu.distributed.launch``) slice each
        global batch by their generation's (rank, world), all-reduce
        gradients through the coordinator barrier, tolerate preemption
        (survivors continue on N−1 within the run) and absorb returning
        workers from an in-memory state snapshot.  ``processes`` is the
        initial formation size; ``coordinator`` overrides the
        ``DL4J_DIST_COORDINATOR`` env the launcher exports.  Without a
        coordinator the conf is inert (replica semantics)."""
        self._g.dist_enabled = bool(enabled)
        if processes is not None:
            self._g.dist_processes = max(0, int(processes))
        if coordinator is not None:
            self._g.dist_coordinator = str(coordinator)
        if heartbeat_ms is not None:
            self._g.dist_heartbeat_ms = float(heartbeat_ms)
        if lease_ms is not None:
            self._g.dist_lease_ms = float(lease_ms)
        return self

    def data_type(self, p: Optional[str]):  # reference-style alias
        return self.precision(p)

    def learning_rate_policy(self, policy: str, decay_rate=None, steps=None,
                             power=None, schedule: Optional[dict] = None):
        self._g.lr_policy = policy
        self._g.lr_policy_decay_rate = decay_rate
        self._g.lr_policy_steps = steps
        self._g.lr_policy_power = power
        self._g.learning_rate_schedule = schedule
        return self

    def list(self) -> "ListBuilder":
        return ListBuilder(self._g)


class ListBuilder:
    """(ref: NeuralNetConfiguration.ListBuilder / MultiLayerConfiguration.Builder)"""

    def __init__(self, g: GlobalConf):
        self._g = g
        self._layers: List[Layer] = []
        self._input_type: Optional[InputType] = None
        self._preprocs: Dict[int, pp.InputPreProcessor] = {}
        self._backprop = True
        self._pretrain = False
        self._bp_type = "standard"
        self._tbptt_f = 20
        self._tbptt_b = 20

    def layer(self, idx_or_layer, layer: Optional[Layer] = None) -> "ListBuilder":
        if layer is None:
            self._layers.append(idx_or_layer)
        else:
            idx = int(idx_or_layer)
            while len(self._layers) <= idx:
                self._layers.append(None)  # type: ignore
            self._layers[idx] = layer
        return self

    def set_input_type(self, it: InputType) -> "ListBuilder":
        self._input_type = it
        return self

    def input_pre_processor(self, idx: int, proc: pp.InputPreProcessor) -> "ListBuilder":
        self._preprocs[idx] = proc
        return self

    def backprop(self, on: bool) -> "ListBuilder":
        self._backprop = on
        return self

    def pretrain(self, on: bool) -> "ListBuilder":
        self._pretrain = on
        return self

    def backprop_type(self, t: str) -> "ListBuilder":
        self._bp_type = t.lower()
        return self

    def t_bptt_forward_length(self, n: int) -> "ListBuilder":
        self._tbptt_f = int(n)
        return self

    def t_bptt_backward_length(self, n: int) -> "ListBuilder":
        self._tbptt_b = int(n)
        return self

    def build(self) -> MultiLayerConfiguration:
        if any(l is None for l in self._layers):
            raise ValueError("Gap in layer indices")
        layers = [merge_layer_conf(l, self._g) for l in self._layers]
        preprocs = dict(self._preprocs)
        if self._input_type is not None:
            layers, preprocs = _infer_shapes(layers, self._input_type, preprocs)
        return MultiLayerConfiguration(
            layers=layers, global_conf=self._g, input_type=self._input_type,
            preprocessors=preprocs, backprop=self._backprop,
            pretrain=self._pretrain, backprop_type=self._bp_type,
            tbptt_fwd_length=self._tbptt_f, tbptt_back_length=self._tbptt_b)


def _needs(layer: Layer) -> str:
    """Which input family a layer consumes: 'ff' | 'cnn' | 'rnn' | 'any'."""
    from deeplearning4j_tpu.nn.conf import layers as L
    if isinstance(layer, (L.ConvolutionLayer, L.SubsamplingLayer,
                          L.ZeroPaddingLayer, L.LocalResponseNormalization)):
        return "cnn"
    if isinstance(layer, (L.GravesLSTM, L.GravesBidirectionalLSTM, L.RnnOutputLayer)):
        return "rnn"
    if isinstance(layer, (L.DenseLayer, L.EmbeddingLayer)):
        return "ff"
    return "any"


def _adapter(cur: InputType, needed: str) -> Optional[pp.InputPreProcessor]:
    if needed == "any" or cur.kind == needed or (needed == "ff" and cur.kind == "cnnflat"):
        return None
    if cur.kind == "cnn" and needed == "ff":
        return pp.CnnToFeedForwardPreProcessor(cur.height, cur.width, cur.channels)
    if cur.kind == "cnnflat" and needed == "cnn":
        return pp.FeedForwardToCnnPreProcessor(cur.height, cur.width, cur.channels)
    if cur.kind == "ff" and needed == "rnn":
        return pp.FeedForwardToRnnPreProcessor(cur.timesteps)
    if cur.kind == "rnn" and needed == "ff":
        return pp.RnnToFeedForwardPreProcessor()
    if cur.kind == "cnn" and needed == "rnn":
        return pp.CnnToRnnPreProcessor()
    if cur.kind == "rnn" and needed == "cnn":
        raise ValueError("RnnToCnn requires explicit preprocessor with target shape")
    raise ValueError(f"No automatic preprocessor from {cur.kind} to {needed}")


def _infer_shapes(layers: List[Layer], input_type: InputType,
                  preprocs: Dict[int, pp.InputPreProcessor]):
    """Walk the stack inferring nIn and inserting preprocessors — the
    reference's setInputType pass (MultiLayerConfiguration.Builder)."""
    cur = input_type
    out_layers = []
    for i, layer in enumerate(layers):
        if i not in preprocs:
            adapter = _adapter(cur, _needs(layer))
            if adapter is not None:
                preprocs[i] = adapter
        if i in preprocs:
            cur = preprocs[i].output_type(cur)
        updates = {}
        if hasattr(layer, "n_in") and getattr(layer, "n_in") is None:
            updates["n_in"] = cur.flat_size() if cur.kind != "cnn" else cur.channels
        if isinstance(layer, BatchNormalization) and layer.n_features is None:
            updates["n_features"] = cur.channels if cur.kind == "cnn" else cur.flat_size()
        if updates:
            layer = dataclasses.replace(layer, **updates)
        out_layers.append(layer)
        cur = layer.output_type(cur)
    return out_layers, preprocs
