"""Layer configuration types — the reference's ``nn/conf/layers`` surface.

Each config is a dataclass that is simultaneously (a) the JSON-serializable
hyperparameter record (parity with the reference's Jackson-polymorphic layer
configs, ref: nn/conf/layers/*.java) and (b) the functional layer
implementation: ``initialize`` builds the param/state pytrees,
``forward`` is the pure apply.  Unlike the reference's Layer impl class
hierarchy with mutable param views (ref: nn/layers/BaseLayer.java), there
is no separate impl object — the whole forward pass composes into one
traced function that XLA compiles and fuses.

Custom layers register via ``@register_layer`` (the analog of the
reference's classpath-scanned subtype registration,
ref: nn/conf/NeuralNetConfiguration.java:340-367).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.ops import activations as act_ops
from deeplearning4j_tpu.ops import convolution as conv_ops
from deeplearning4j_tpu.ops import helpers as helper_ops
from deeplearning4j_tpu.ops import initializers
from deeplearning4j_tpu.ops import losses as loss_ops
from deeplearning4j_tpu.ops import normalization as norm_ops
from deeplearning4j_tpu.ops import recurrent as rnn_ops

LAYER_REGISTRY: Dict[str, type] = {}


def register_layer(cls):
    LAYER_REGISTRY[cls.__name__] = cls
    return cls


def field(default=None, **kw):
    return dataclasses.field(default=default, **kw)


@dataclasses.dataclass
class Layer:
    """Base hyperparameters every layer config can carry.

    ``None`` means "inherit from the global NeuralNetConfiguration" —
    mirroring the reference's global-conf-then-per-layer-override merge
    (ref: NeuralNetConfiguration.Builder.layer handling).
    ``dropout`` is the RETAIN probability as in the reference 0.8.x
    (0.0 = disabled; ref: util/Dropout.java).
    """

    name: Optional[str] = None
    activation: Optional[str] = None
    weight_init: Optional[str] = None
    bias_init: Optional[float] = None
    dist: Optional[dict] = None
    learning_rate: Optional[float] = None
    bias_learning_rate: Optional[float] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    l1_bias: Optional[float] = None
    l2_bias: Optional[float] = None
    dropout: Optional[float] = None
    use_drop_connect: Optional[bool] = None
    updater: Optional[str] = None
    momentum: Optional[float] = None
    rho: Optional[float] = None
    rms_decay: Optional[float] = None
    adam_mean_decay: Optional[float] = None
    adam_var_decay: Optional[float] = None
    epsilon: Optional[float] = None
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: Optional[float] = None

    # ---- capability flags ----
    def has_params(self) -> bool:
        return True

    def is_pretrain_layer(self) -> bool:
        return False

    # ---- functional API ----
    def initialize(self, key, input_type: InputType, dtype=jnp.float32
                   ) -> Tuple[dict, dict, InputType]:
        raise NotImplementedError

    def forward(self, params: dict, state: dict, x, *, train: bool, rng,
                mask=None) -> Tuple[Any, dict, Any]:
        raise NotImplementedError

    def output_type(self, input_type: InputType) -> InputType:
        raise NotImplementedError

    # ---- shared helpers ----
    def _act(self, x):
        return act_ops.get(self.activation or "identity")(x)

    def _maybe_dropout(self, x, train: bool, rng):
        # DropConnect reuses the dropout probability on WEIGHTS instead of
        # activations — mutually exclusive with input dropout (ref:
        # util/Dropout.java applyDropConnect vs applyDropout; BaseLayer
        # applies one or the other depending on conf.isUseDropConnect())
        if self.use_drop_connect:
            return x
        if train and self.dropout and 0.0 < self.dropout < 1.0 and rng is not None:
            # helper selection (ops/helpers.py): in-kernel threshold
            # dropout on TPU, jax.random.bernoulli fallback elsewhere
            return helper_ops.dropout(x, self.dropout, rng)
        return x

    def _maybe_drop_connect(self, params: dict, train: bool, rng):
        """DropConnect (Wan et al.; ref: util/Dropout.java:applyDropConnect):
        zero each weight with retain probability ``dropout``, inverted
        scaling, leaving biases intact."""
        if not (self.use_drop_connect and train and self.dropout
                and 0.0 < self.dropout < 1.0 and rng is not None and
                "W" in params):
            return params
        return {**params,
                "W": norm_ops.dropout(params["W"], self.dropout,
                                      jax.random.fold_in(rng, 0x0D20))}

    def _winit(self, key, shape, dtype, fan_in=None, fan_out=None):
        return initializers.init(
            key, self.weight_init or "xavier", shape, dtype,
            fan_in=fan_in, fan_out=fan_out, distribution=self.dist)

    def _binit(self, shape, dtype):
        return jnp.full(shape, self.bias_init or 0.0, dtype)

    # ---- serde ----
    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["@class"] = type(self).__name__
        return d

    @staticmethod
    def from_dict(d: dict) -> "Layer":
        d = dict(d)
        cls = LAYER_REGISTRY[d.pop("@class")]
        return cls(**d)


# ==========================================================================
# Feed-forward layers
# ==========================================================================

@register_layer
@dataclasses.dataclass
class DenseLayer(Layer):
    """Fully connected: y = act(x @ W + b)
    (ref: nn/conf/layers/DenseLayer.java; impl nn/layers/BaseLayer.java:373)."""

    n_in: Optional[int] = None
    n_out: int = 0

    def initialize(self, key, input_type, dtype=jnp.float32):
        n_in = self.n_in or input_type.flat_size()
        kW, _ = jax.random.split(key)
        params = {"W": self._winit(kW, (n_in, self.n_out), dtype),
                  "b": self._binit((self.n_out,), dtype)}
        return params, {}, InputType.feed_forward(self.n_out)

    def forward(self, params, state, x, *, train, rng, mask=None):
        x = self._maybe_dropout(x, train, rng)
        p = self._maybe_drop_connect(params, train, rng)
        return self._act(x @ p["W"] + p["b"]), state, mask

    def output_type(self, input_type):
        return InputType.feed_forward(self.n_out)


@dataclasses.dataclass
class BaseOutputLayer(DenseLayer):
    """Shared loss machinery for output layers
    (ref: nn/layers/BaseOutputLayer computeScore)."""

    loss: str = "mcxent"

    def compute_score(self, labels, preout, mask=None):
        """Per-example loss [N] from pre-activations (stable fused path)."""
        return loss_ops.get(self.loss)(labels, preout,
                                       self.activation or "softmax", mask)

    def preoutput(self, params, x):
        return x @ params["W"] + params["b"]


@register_layer
@dataclasses.dataclass
class OutputLayer(BaseOutputLayer):
    """Dense + loss head (ref: nn/conf/layers/OutputLayer.java)."""


@register_layer
@dataclasses.dataclass
class LossLayer(Layer):
    """Loss without params: activation + loss on raw input
    (ref: nn/conf/layers/LossLayer.java)."""

    loss: str = "mcxent"

    def has_params(self):
        return False

    def initialize(self, key, input_type, dtype=jnp.float32):
        return {}, {}, input_type

    def forward(self, params, state, x, *, train, rng, mask=None):
        return self._act(x), state, mask

    def output_type(self, input_type):
        return input_type

    def compute_score(self, labels, preout, mask=None):
        return loss_ops.get(self.loss)(labels, preout,
                                       self.activation or "identity", mask)

    def preoutput(self, params, x):
        return x


@register_layer
@dataclasses.dataclass
class ActivationLayer(Layer):
    """Pure activation (ref: nn/conf/layers/ActivationLayer.java)."""

    def has_params(self):
        return False

    def initialize(self, key, input_type, dtype=jnp.float32):
        return {}, {}, input_type

    def forward(self, params, state, x, *, train, rng, mask=None):
        return self._act(x), state, mask

    def output_type(self, input_type):
        return input_type


@register_layer
@dataclasses.dataclass
class DropoutLayer(Layer):
    """Standalone dropout (ref: nn/conf/layers/DropoutLayer.java)."""

    def has_params(self):
        return False

    def initialize(self, key, input_type, dtype=jnp.float32):
        return {}, {}, input_type

    def forward(self, params, state, x, *, train, rng, mask=None):
        return self._maybe_dropout(x, train, rng), state, mask

    def output_type(self, input_type):
        return input_type


@register_layer
@dataclasses.dataclass
class EmbeddingLayer(Layer):
    """Index → embedding row lookup; input is int indices [N] or one-hot
    (ref: nn/layers/feedforward/embedding/EmbeddingLayer.java — mathematically
    a dense layer with one-hot input; here a gather, which XLA lowers to a
    dynamic-slice on TPU)."""

    n_in: Optional[int] = None  # vocab size
    n_out: int = 0

    def initialize(self, key, input_type, dtype=jnp.float32):
        n_in = self.n_in or input_type.flat_size()
        kW, _ = jax.random.split(key)
        params = {"W": self._winit(kW, (n_in, self.n_out), dtype),
                  "b": self._binit((self.n_out,), dtype)}
        return params, {}, InputType.feed_forward(self.n_out)

    def forward(self, params, state, x, *, train, rng, mask=None):
        if jnp.issubdtype(x.dtype, jnp.integer):
            idx = x.reshape(x.shape[0]) if x.ndim > 1 else x
            emb = params["W"][idx]
        else:
            # one-hot [N, vocab] input
            emb = x @ params["W"]
        return self._act(emb + params["b"]), state, mask

    def output_type(self, input_type):
        return InputType.feed_forward(self.n_out)


# ==========================================================================
# Convolutional family (NCHW)
# ==========================================================================

@register_layer
@dataclasses.dataclass
class ConvolutionLayer(Layer):
    """2D convolution (ref: nn/conf/layers/ConvolutionLayer.java; impl
    nn/layers/convolution/ConvolutionLayer.java — im2col+gemm replaced by a
    single conv HLO on the MXU).  Weights OIHW [n_out, c_in, kh, kw]."""

    n_in: Optional[int] = None
    n_out: int = 0
    kernel: Tuple[int, int] = (3, 3)
    stride: Tuple[int, int] = (1, 1)
    padding: Tuple[int, int] = (0, 0)
    dilation: Tuple[int, int] = (1, 1)
    convolution_mode: str = "truncate"  # 'truncate' | 'same'

    def initialize(self, key, input_type, dtype=jnp.float32):
        c_in = self.n_in or input_type.channels
        kh, kw = self.kernel
        fan_in = c_in * kh * kw
        fan_out = self.n_out * kh * kw
        kW, _ = jax.random.split(key)
        params = {
            "W": self._winit(kW, (self.n_out, c_in, kh, kw), dtype,
                             fan_in=fan_in, fan_out=fan_out),
            "b": self._binit((self.n_out,), dtype),
        }
        return params, {}, self.output_type(input_type)

    def forward(self, params, state, x, *, train, rng, mask=None):
        x = self._maybe_dropout(x, train, rng)
        p = self._maybe_drop_connect(params, train, rng)
        # helper selection (ops/helpers.py): conv+bias+activation as one
        # fused Pallas VMEM pass when the conv tier selects; the dense
        # conv-HLO → bias → activation chain otherwise
        y = helper_ops.conv2d_bias_act(
            x, p["W"], p["b"], self.stride, self.padding, self.dilation,
            self.convolution_mode, self.activation or "identity")
        return y, state, mask

    def output_type(self, input_type):
        oh, ow = conv_ops.conv2d_output_shape(
            (input_type.height, input_type.width), self.kernel, self.stride,
            self.padding, self.dilation, self.convolution_mode)
        return InputType.convolutional(oh, ow, self.n_out)


@register_layer
@dataclasses.dataclass
class SubsamplingLayer(Layer):
    """Pooling (ref: nn/conf/layers/SubsamplingLayer.java)."""

    pooling_type: str = "max"  # max | avg | sum | pnorm
    kernel: Tuple[int, int] = (2, 2)
    stride: Tuple[int, int] = (2, 2)
    padding: Tuple[int, int] = (0, 0)
    convolution_mode: str = "truncate"
    pnorm: int = 2

    def has_params(self):
        return False

    def initialize(self, key, input_type, dtype=jnp.float32):
        return {}, {}, self.output_type(input_type)

    def forward(self, params, state, x, *, train, rng, mask=None):
        y = conv_ops.pool2d(x, self.pooling_type, self.kernel, self.stride,
                            self.padding, self.convolution_mode, self.pnorm)
        return y, state, mask

    def output_type(self, input_type):
        oh, ow = conv_ops.conv2d_output_shape(
            (input_type.height, input_type.width), self.kernel, self.stride,
            self.padding, (1, 1), self.convolution_mode)
        return InputType.convolutional(oh, ow, input_type.channels)


@register_layer
@dataclasses.dataclass
class ZeroPaddingLayer(Layer):
    """(ref: nn/conf/layers/ZeroPaddingLayer.java)"""

    pad: Tuple[int, int, int, int] = (0, 0, 0, 0)  # top, bottom, left, right

    def has_params(self):
        return False

    def initialize(self, key, input_type, dtype=jnp.float32):
        return {}, {}, self.output_type(input_type)

    def forward(self, params, state, x, *, train, rng, mask=None):
        t, b, l, r = self.pad
        return conv_ops.zero_pad2d(x, t, b, l, r), state, mask

    def output_type(self, input_type):
        t, b, l, r = self.pad
        return InputType.convolutional(input_type.height + t + b,
                                       input_type.width + l + r,
                                       input_type.channels)


@register_layer
@dataclasses.dataclass
class BatchNormalization(Layer):
    """(ref: nn/conf/layers/BatchNormalization.java; impl
    nn/layers/normalization/BatchNormalization.java:228 — BN applies NO
    activation; activation defaults to identity here rather than
    inheriting the global default).  Running statistics are carried in
    the functional `state` pytree instead of mutated."""

    activation: Optional[str] = "identity"
    decay: float = 0.9
    eps: float = 1e-5
    lock_gamma_beta: bool = False
    n_features: Optional[int] = None

    def _nfeat(self, input_type):
        return self.n_features or (
            input_type.channels if input_type.kind == "cnn" else input_type.flat_size())

    def initialize(self, key, input_type, dtype=jnp.float32):
        n = self._nfeat(input_type)
        params = {} if self.lock_gamma_beta else {
            "gamma": jnp.ones((n,), dtype), "beta": jnp.zeros((n,), dtype)}
        state = {"mean": jnp.zeros((n,), dtype), "var": jnp.ones((n,), dtype)}
        return params, state, input_type

    def forward(self, params, state, x, *, train, rng, mask=None):
        n = state["mean"].shape[0]
        gamma = params.get("gamma", jnp.ones((n,), x.dtype))
        beta = params.get("beta", jnp.zeros((n,), x.dtype))
        if train:
            y, m, v = norm_ops.batch_norm_train(
                x, gamma, beta, state["mean"], state["var"],
                decay=self.decay, eps=self.eps)
            return self._act(y), {"mean": m, "var": v}, mask
        y = norm_ops.batch_norm_infer(x, gamma, beta, state["mean"],
                                      state["var"], eps=self.eps)
        return self._act(y), state, mask

    def output_type(self, input_type):
        return input_type


@register_layer
@dataclasses.dataclass
class LocalResponseNormalization(Layer):
    """(ref: nn/layers/normalization/LocalResponseNormalization.java:69)"""

    k: float = 2.0
    n: int = 5
    alpha: float = 1e-4
    beta: float = 0.75

    def has_params(self):
        return False

    def initialize(self, key, input_type, dtype=jnp.float32):
        return {}, {}, input_type

    def forward(self, params, state, x, *, train, rng, mask=None):
        return norm_ops.local_response_norm(
            x, k=self.k, n=self.n, alpha=self.alpha, beta=self.beta), state, mask

    def output_type(self, input_type):
        return input_type


@register_layer
@dataclasses.dataclass
class GlobalPoolingLayer(Layer):
    """Collapse spatial/time dims (ref: nn/layers/pooling/GlobalPoolingLayer.java);
    mask-aware for variable-length RNN input (MaskedReductionUtil semantics)."""

    pooling_type: str = "max"
    pnorm: int = 2
    collapse_dimensions: bool = True

    def has_params(self):
        return False

    def initialize(self, key, input_type, dtype=jnp.float32):
        return {}, {}, self.output_type(input_type)

    def forward(self, params, state, x, *, train, rng, mask=None):
        if x.ndim == 4:   # CNN NCHW → pool over H,W
            y = conv_ops.global_pool(x, self.pooling_type, (2, 3), self.pnorm)
        elif x.ndim == 3:  # RNN [N, T, C] → pool over T, mask-aware
            m = mask[..., None] if mask is not None else None
            y = conv_ops.global_pool(x, self.pooling_type, (1,), self.pnorm, m)
        else:
            y = x
        return y, state, None  # mask consumed

    def output_type(self, input_type):
        if input_type.kind == "cnn":
            return InputType.feed_forward(input_type.channels)
        if input_type.kind == "rnn":
            return InputType.feed_forward(input_type.size)
        return input_type


# ==========================================================================
# Recurrent family  (native layout [N, T, C])
# ==========================================================================

@register_layer
@dataclasses.dataclass
class GravesLSTM(Layer):
    """Peephole LSTM over the full sequence as one lax.scan
    (ref: nn/conf/layers/GravesLSTM.java; impl
    nn/layers/recurrent/LSTMHelpers.java:60-526)."""

    n_in: Optional[int] = None
    n_out: int = 0
    gate_activation: str = "sigmoid"
    forget_gate_bias_init: float = 1.0

    def initialize(self, key, input_type, dtype=jnp.float32):
        n_in = self.n_in or input_type.size
        H = self.n_out
        kW, kR, kP = jax.random.split(key, 3)
        b = jnp.zeros((4 * H,), dtype)
        # forget-gate block [H:2H] gets forget_gate_bias_init (ref default 1.0)
        b = b.at[H:2 * H].set(self.forget_gate_bias_init)
        params = {
            "W": self._winit(kW, (n_in, 4 * H), dtype, fan_in=n_in, fan_out=4 * H),
            "RW": self._winit(kR, (H, 4 * H), dtype, fan_in=H, fan_out=4 * H),
            "b": b,
            "pI": jnp.zeros((H,), dtype),
            "pF": jnp.zeros((H,), dtype),
            "pO": jnp.zeros((H,), dtype),
        }
        return params, {}, InputType.recurrent(H, input_type.timesteps)

    def forward(self, params, state, x, *, train, rng, mask=None):
        x = self._maybe_dropout(x, train, rng)
        gate = act_ops.get(self.gate_activation)
        cell = act_ops.get(self.activation or "tanh")
        init = state.get("rnn_state") if state else None
        hs, final = rnn_ops.lstm_scan(params, x, init, mask,
                                      gate_act=gate, cell_act=cell)
        new_state = dict(state) if state else {}
        new_state["rnn_state"] = final  # for rnnTimeStep stateful inference
        return hs, new_state, mask

    def output_type(self, input_type):
        return InputType.recurrent(self.n_out, input_type.timesteps)


@register_layer
@dataclasses.dataclass
class GravesBidirectionalLSTM(Layer):
    """Fwd + bwd peephole LSTMs with separate params; the two directions'
    outputs are SUMMED, giving output size n_out (ref:
    nn/layers/recurrent/GravesBidirectionalLSTM.java:204
    ``fwdOutput.addi(backOutput)``)."""

    n_in: Optional[int] = None
    n_out: int = 0
    gate_activation: str = "sigmoid"
    forget_gate_bias_init: float = 1.0

    def initialize(self, key, input_type, dtype=jnp.float32):
        sub = GravesLSTM(n_in=self.n_in, n_out=self.n_out,
                         activation=self.activation,
                         weight_init=self.weight_init, dist=self.dist,
                         gate_activation=self.gate_activation,
                         forget_gate_bias_init=self.forget_gate_bias_init)
        kf, kb = jax.random.split(key)
        pf, _, out = sub.initialize(kf, input_type, dtype)
        pb, _, _ = sub.initialize(kb, input_type, dtype)
        params = {f"f_{k}": v for k, v in pf.items()}
        params.update({f"b_{k}": v for k, v in pb.items()})
        return params, {}, out

    def forward(self, params, state, x, *, train, rng, mask=None):
        x = self._maybe_dropout(x, train, rng)
        gate = act_ops.get(self.gate_activation)
        cell = act_ops.get(self.activation or "tanh")
        pf = {k[2:]: v for k, v in params.items() if k.startswith("f_")}
        pb = {k[2:]: v for k, v in params.items() if k.startswith("b_")}
        hf, _ = rnn_ops.lstm_scan(pf, x, None, mask, gate_act=gate, cell_act=cell)
        hb, _ = rnn_ops.lstm_scan(pb, x, None, mask, reverse=True,
                                  gate_act=gate, cell_act=cell)
        return hf + hb, state, mask

    def output_type(self, input_type):
        return InputType.recurrent(self.n_out, input_type.timesteps)


@register_layer
@dataclasses.dataclass
class RnnOutputLayer(BaseOutputLayer):
    """Per-timestep dense + loss over [N, T, C]
    (ref: nn/conf/layers/RnnOutputLayer.java)."""

    def initialize(self, key, input_type, dtype=jnp.float32):
        n_in = self.n_in or input_type.size
        kW, _ = jax.random.split(key)
        params = {"W": self._winit(kW, (n_in, self.n_out), dtype),
                  "b": self._binit((self.n_out,), dtype)}
        return params, {}, InputType.recurrent(self.n_out, input_type.timesteps)

    def forward(self, params, state, x, *, train, rng, mask=None):
        x = self._maybe_dropout(x, train, rng)
        return self._act(x @ params["W"] + params["b"]), state, mask

    def compute_score(self, labels, preout, mask=None):
        # labels/preout: [N, T, C]; mask [N, T].  Score per example sums
        # over time (masked), matching reference RnnOutputLayer scoring.
        m = mask[..., None] if mask is not None else None
        return loss_ops.get(self.loss)(labels, preout,
                                       self.activation or "softmax", m)

    def output_type(self, input_type):
        return InputType.recurrent(self.n_out, input_type.timesteps)


@register_layer
@dataclasses.dataclass
class LastTimeStepLayer(Layer):
    """[N,T,C] → [N,C] at the last unmasked timestep (sequential-network
    analog of the reference's rnn/LastTimeStepVertex.java; used e.g. for
    Keras LSTM(return_sequences=False) import)."""

    def has_params(self):
        return False

    def initialize(self, key, input_type, dtype=jnp.float32):
        return {}, {}, self.output_type(input_type)

    def forward(self, params, state, x, *, train, rng, mask=None):
        if mask is None:
            return x[:, -1], state, None
        idx = jnp.maximum(jnp.sum(mask > 0, axis=1).astype(jnp.int32) - 1, 0)
        return x[jnp.arange(x.shape[0]), idx], state, None

    def output_type(self, input_type):
        return InputType.feed_forward(input_type.size)


# ==========================================================================
# Attention (long-context extension — SURVEY.md §5: the reference's only
# long-sequence mechanism is TBPTT; this layer plus parallel/sequence.py
# adds exact ring / all-to-all sequence-parallel attention over the mesh).
# ==========================================================================

@register_layer
@dataclasses.dataclass
class SelfAttentionLayer(Layer):
    """Multi-head self-attention over recurrent input [B, T, F].

    The attention core dispatches through
    ``parallel.sequence.attention``: dense on one device, ring /
    all-to-all sequence-parallel when a mesh with a non-trivial 'seq'
    axis is active (``parallel.sequence.sequence_mesh``).

    Under the engines' carried decode step
    (``parallel.sequence.kv_decode_scope`` — entered by
    ``rnn_time_step`` and the serving decode pool), the layer instead
    decodes INCREMENTALLY against a per-stream KV ring carried in
    ``rnn_state``: each new token appends its K/V at ``pos % window``
    and attends over only the valid ring entries
    (``parallel.sequence.attend_cached``) — O(window) per token, flat
    in stream length, instead of re-running the whole window.
    Streaming decode is inherently causal: with ``cache_window >=``
    the stream length the step-by-step outputs match full causal
    ``dense_attention``; older tokens fall out of the ring (sliding
    window).  ``cache_window=None`` resolves to the declared input
    timesteps at init (128 when variable-length).
    """

    n_in: Optional[int] = None
    n_out: int = 0
    n_heads: int = 1
    causal: bool = False
    strategy: str = "auto"      # auto | ring | ulysses | dense
    project_output: bool = True
    cache_window: Optional[int] = None   # KV-ring length for decode

    def initialize(self, key, input_type, dtype=jnp.float32):
        n_in = self.n_in or input_type.size
        if self.n_out % self.n_heads:
            raise ValueError(f"n_out={self.n_out} % n_heads={self.n_heads}")
        if self.cache_window is None:
            self.cache_window = int(getattr(input_type, "timesteps", None)
                                    or 128)
        kq, kk, kv, ko = jax.random.split(key, 4)
        params = {
            "Wq": self._winit(kq, (n_in, self.n_out), dtype),
            "Wk": self._winit(kk, (n_in, self.n_out), dtype),
            "Wv": self._winit(kv, (n_in, self.n_out), dtype),
            "bq": self._binit((self.n_out,), dtype),
            "bk": self._binit((self.n_out,), dtype),
            "bv": self._binit((self.n_out,), dtype),
        }
        if self.project_output:
            params["Wo"] = self._winit(ko, (self.n_out, self.n_out), dtype)
            params["bo"] = self._binit((self.n_out,), dtype)
        return params, {}, InputType.recurrent(self.n_out, input_type.timesteps)

    def forward(self, params, state, x, *, train, rng, mask=None):
        from deeplearning4j_tpu.parallel import sequence as seq_ops
        x = self._maybe_dropout(x, train, rng)
        B, T, _ = x.shape
        H, Dh = self.n_heads, self.n_out // self.n_heads

        def split(a):  # [B, T, n_out] -> [B, H, T, Dh]
            return a.reshape(B, T, H, Dh).transpose(0, 2, 1, 3)

        q = split(x @ params["Wq"] + params["bq"])
        k = split(x @ params["Wk"] + params["bk"])
        v = split(x @ params["Wv"] + params["bv"])
        new_state = state
        if seq_ops.kv_decode_active() and not train:
            # incremental decode: append this chunk's K/V to the
            # per-stream ring and attend over valid entries only —
            # O(window)/token instead of O(T)/token re-runs.  The ring
            # is the layer's rnn_state carry, so it lives on device in
            # the decode pool's slot buffer and rides migration.
            W = int(self.cache_window or 128)
            tape = seq_ops.paged_tape()
            if tape is not None:
                # paged decode: K/V pages live in the pool-shared arena
                # (drawn from the trace-time tape); the carry holds only
                # the int32 block table + write position.  `aid` is the
                # layer's arena id, encoded in the leaf's trailing dim
                # (shape survives eval_shape templates, values do not)
                # so export/import can map a carry node back to its
                # arena without relying on pytree walk order.
                _, nbs = seq_ops.block_geometry(W, tape.block_size)
                aid, arena, tbl = tape.next_layer(H, Dh, W, x.dtype)
                if tbl is None:
                    tbl = jnp.zeros((B, nbs), jnp.int32)
                prev = state.get("rnn_state") if state else None
                pos = (prev["pos"] if isinstance(prev, dict)
                       and "pos" in prev else jnp.zeros((B,), jnp.int32))
                if tape.record_undo:
                    out, pos, arena, journal = seq_ops.attend_paged(
                        q, k, v, pos, tbl, arena, window=W,
                        key_mask=mask, undo=True)
                    tape.put_undo(aid, journal)
                else:
                    out, pos, arena = seq_ops.attend_paged(
                        q, k, v, pos, tbl, arena, window=W, key_mask=mask)
                tape.put(aid, arena)
                new_state = dict(state) if state else {}
                new_state["rnn_state"] = {
                    "aid": jnp.full((B, aid + 1), aid, jnp.int32),
                    "pos": pos, "tbl": tbl}
            else:
                ring = state.get("rnn_state") if state else None
                if ring is None:
                    ring = seq_ops.kv_ring_init(B, H, W, Dh, x.dtype)
                out, ring = seq_ops.attend_cached(q, k, v, ring,
                                                  key_mask=mask)
                new_state = dict(state) if state else {}
                new_state["rnn_state"] = ring
        else:
            out = seq_ops.attention(q, k, v, causal=self.causal,
                                    key_mask=mask, strategy=self.strategy)
        out = out.transpose(0, 2, 1, 3).reshape(B, T, self.n_out)
        if self.project_output:
            out = out @ params["Wo"] + params["bo"]
        out = self._act(out)
        if mask is not None:
            out = out * mask[:, :, None].astype(out.dtype)
        return out, new_state, mask

    def output_type(self, input_type):
        return InputType.recurrent(self.n_out, input_type.timesteps)


@register_layer
@dataclasses.dataclass
class MixtureOfExpertsLayer(Layer):
    """Sparse mixture-of-experts feed-forward block (GShard-style top-1
    dispatch).  No reference analog — DL4J predates MoE; this layer
    exists so the mesh's 'expert' axis is a first-class layout: expert
    weight stacks [E, ...] shard over 'expert'
    (parallel/mesh.param_sharding) and XLA partitions the dispatch/
    combine einsums into expert-parallel all-to-alls.

    Routing: softmax gate → top-1 expert per token, fixed capacity
    ``capacity_factor·N/E`` per expert; overflow tokens pass through
    unchanged (residual).  Aux load-balancing loss is returned in state
    under "moe_aux_loss" (mean over experts of fraction·probability,
    scaled by ``aux_loss_weight``)."""

    n_in: Optional[int] = None
    n_out: int = 0
    n_experts: int = 4
    hidden: Optional[int] = None       # expert MLP width (default 4×n_out)
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01

    def initialize(self, key, input_type, dtype=jnp.float32):
        n_in = self.n_in or input_type.size
        if self.n_out != n_in:
            raise ValueError("MoE block is residual: n_out must equal n_in "
                             f"(got n_in={n_in}, n_out={self.n_out})")
        H = self.hidden or 4 * self.n_out
        kg, k1, k2 = jax.random.split(key, 3)
        E = self.n_experts
        params = {
            "Wg": self._winit(kg, (n_in, E), dtype),
            "W1": self._winit(k1, (E, n_in, H), dtype, fan_in=n_in,
                              fan_out=H),
            "b1": jnp.zeros((E, H), dtype),
            "W2": self._winit(k2, (E, H, self.n_out), dtype, fan_in=H,
                              fan_out=self.n_out),
            "b2": jnp.zeros((E, self.n_out), dtype),
        }
        # aux loss lives in state from step 0 so the state pytree
        # structure never changes (jit/sharding trees are built once)
        state = {"moe_aux_loss": jnp.zeros((), dtype)}
        return params, state, input_type

    def forward(self, params, state, x, *, train, rng, mask=None):
        x = self._maybe_dropout(x, train, rng)
        shape = x.shape
        D = shape[-1]
        tokens = x.reshape(-1, D)                       # [N, D]
        N = tokens.shape[0]
        E = self.n_experts
        C = max(1, int(self.capacity_factor * N / E))

        gates = jax.nn.softmax(tokens @ params["Wg"], axis=-1)   # [N, E]
        top_p = gates.max(axis=-1)                               # [N]
        top_e = gates.argmax(axis=-1)                            # [N]
        onehot = jax.nn.one_hot(top_e, E, dtype=x.dtype)         # [N, E]
        # padding tokens must not claim capacity or train the gate
        if mask is not None and x.ndim == 3:
            tok_mask = mask.reshape(-1).astype(x.dtype)          # [N]
            onehot = onehot * tok_mask[:, None]
        else:
            tok_mask = None

        # position of each token within its expert's queue
        pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot        # [N, E]
        in_cap = (pos < C).astype(x.dtype) * onehot
        pos_idx = pos.sum(axis=-1).astype(jnp.int32)             # [N]
        cap_oh = jax.nn.one_hot(pos_idx, C, dtype=x.dtype)       # [N, C]
        dispatch = in_cap[:, :, None] * cap_oh[:, None, :]       # [N, E, C]

        # dispatch → per-expert batch, expert MLP, combine (GShard einsums;
        # the E dimension is sharded over 'expert' — XLA inserts a2a)
        expert_in = jnp.einsum("nec,nd->ecd", dispatch, tokens)
        h = jax.nn.gelu(
            jnp.einsum("ecd,edh->ech", expert_in, params["W1"])
            + params["b1"][:, None, :])
        expert_out = (jnp.einsum("ech,eho->eco", h, params["W2"])
                      + params["b2"][:, None, :])
        combine = dispatch * top_p[:, None, None]
        routed = jnp.einsum("nec,eco->no", combine, expert_out)

        # residual: routed contribution is zero for overflow/unrouted
        # tokens, so they pass through unchanged
        out = tokens + routed
        out = out.reshape(shape[:-1] + (self.n_out,))

        # load-balance aux loss (Switch/GShard): E·Σ_e fraction_e·prob_e
        # — averaged over VALID tokens only
        if tok_mask is not None:
            n_valid = jnp.maximum(tok_mask.sum(), 1.0)
            frac = onehot.sum(axis=0) / n_valid
            prob = (gates * tok_mask[:, None]).sum(axis=0) / n_valid
        else:
            frac = onehot.mean(axis=0)
            prob = gates.mean(axis=0)
        aux = self.aux_loss_weight * E * jnp.sum(frac * prob)
        new_state = dict(state) if state else {}
        new_state["moe_aux_loss"] = aux
        out = self._act(out)
        if mask is not None and out.ndim == 3:
            out = out * mask[:, :, None].astype(out.dtype)
        return out, new_state, mask

    def output_type(self, input_type):
        return input_type


# ==========================================================================
# Misc
# ==========================================================================

@register_layer
@dataclasses.dataclass
class FrozenLayerConf(Layer):
    """Wraps another layer; gradients are zeroed by the engine
    (ref: nn/layers/FrozenLayer.java — transfer learning)."""

    inner: Optional[dict] = None  # serialized inner layer

    def _inner(self) -> Layer:
        return Layer.from_dict(self.inner)

    def has_params(self):
        return self._inner().has_params()

    def initialize(self, key, input_type, dtype=jnp.float32):
        return self._inner().initialize(key, input_type, dtype)

    def forward(self, params, state, x, *, train, rng, mask=None):
        # Frozen layers run in inference mode (no dropout) per the reference.
        return self._inner().forward(params, state, x, train=False, rng=rng, mask=mask)

    def output_type(self, input_type):
        return self._inner().output_type(input_type)

    @staticmethod
    def wrap(layer: Layer) -> "FrozenLayerConf":
        return FrozenLayerConf(inner=layer.to_dict())
