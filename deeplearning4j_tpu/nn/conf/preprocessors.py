"""Input preprocessors — shape adapters between layer families.

(ref: nn/conf/preprocessor/{CnnToFeedForwardPreProcessor,
FeedForwardToCnnPreProcessor, RnnToFeedForwardPreProcessor,
CnnToRnnPreProcessor, RnnToCnnPreProcessor,
ComposableInputPreProcessor}.java).  In the reference each carries a
hand-written backprop; here they are pure reshapes under jax.grad.

Note on RNN layout: native recurrent layout is [N, T, C] (reference is
[N, C, T]); the Rnn* preprocessors reshape accordingly.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType

PREPROC_REGISTRY: dict[str, type] = {}


def register_preproc(cls):
    PREPROC_REGISTRY[cls.__name__] = cls
    return cls


@dataclasses.dataclass
class InputPreProcessor:
    def __call__(self, x, mask=None):
        raise NotImplementedError

    def output_type(self, input_type: InputType) -> InputType:
        raise NotImplementedError

    def to_dict(self):
        d = dataclasses.asdict(self)
        d["@class"] = type(self).__name__
        return d

    @staticmethod
    def from_dict(d):
        d = dict(d)
        cls = PREPROC_REGISTRY[d.pop("@class")]
        return cls(**d)


@register_preproc
@dataclasses.dataclass
class CnnToFeedForwardPreProcessor(InputPreProcessor):
    height: int = 0
    width: int = 0
    channels: int = 0

    def __call__(self, x, mask=None):
        return x.reshape(x.shape[0], -1), mask

    def output_type(self, input_type):
        return InputType.feed_forward(
            input_type.height * input_type.width * input_type.channels)


@register_preproc
@dataclasses.dataclass
class FeedForwardToCnnPreProcessor(InputPreProcessor):
    height: int = 0
    width: int = 0
    channels: int = 0

    def __call__(self, x, mask=None):
        if x.ndim == 4:
            return x, mask
        return x.reshape(x.shape[0], self.channels, self.height, self.width), mask

    def output_type(self, input_type):
        return InputType.convolutional(self.height, self.width, self.channels)


@register_preproc
@dataclasses.dataclass
class RnnToFeedForwardPreProcessor(InputPreProcessor):
    """[N, T, C] → [N*T, C] (the reference flattens time into batch).
    The known timestep count is propagated through the ff InputType so a
    later FeedForwardToRnn adapter can restore the sequence shape."""

    def __call__(self, x, mask=None):
        return x.reshape(-1, x.shape[-1]), (mask.reshape(-1) if mask is not None else None)

    def output_type(self, input_type):
        return InputType("ff", size=input_type.size, timesteps=input_type.timesteps)


@register_preproc
@dataclasses.dataclass
class FeedForwardToRnnPreProcessor(InputPreProcessor):
    timesteps: Optional[int] = None

    def __call__(self, x, mask=None):
        t = self.timesteps
        if t is None:
            raise ValueError("FeedForwardToRnnPreProcessor needs static timesteps")
        return x.reshape(-1, t, x.shape[-1]), (mask.reshape(-1, t) if mask is not None else None)

    def output_type(self, input_type):
        return InputType.recurrent(input_type.flat_size(), self.timesteps)


@register_preproc
@dataclasses.dataclass
class CnnToRnnPreProcessor(InputPreProcessor):
    """NCHW [N,C,H,W] where N = batch*T → [batch, T, C*H*W]."""

    timesteps: Optional[int] = None

    def __call__(self, x, mask=None):
        t = self.timesteps
        if t is None:
            raise ValueError(
                "CnnToRnnPreProcessor needs an explicit timestep count "
                "(DL4J derives it from the runtime minibatch; e.g. a "
                "migrated zip imports with timesteps=None) — set "
                "CnnToRnnPreProcessor(timesteps=T) on conf.preprocessors")
        flat = x.reshape(x.shape[0], -1)
        return flat.reshape(-1, t, flat.shape[-1]), mask

    def output_type(self, input_type):
        return InputType.recurrent(
            input_type.height * input_type.width * input_type.channels, self.timesteps)


@register_preproc
@dataclasses.dataclass
class RnnToCnnPreProcessor(InputPreProcessor):
    height: int = 0
    width: int = 0
    channels: int = 0

    def __call__(self, x, mask=None):
        return x.reshape(-1, self.channels, self.height, self.width), mask

    def output_type(self, input_type):
        return InputType.convolutional(self.height, self.width, self.channels)


@register_preproc
@dataclasses.dataclass
class ComposableInputPreProcessor(InputPreProcessor):
    parts: list = dataclasses.field(default_factory=list)  # serialized parts

    def __call__(self, x, mask=None):
        for d in self.parts:
            x, mask = InputPreProcessor.from_dict(d)(x, mask)
        return x, mask

    def output_type(self, input_type):
        for d in self.parts:
            input_type = InputPreProcessor.from_dict(d).output_type(input_type)
        return input_type

    @staticmethod
    def compose(*procs: InputPreProcessor) -> "ComposableInputPreProcessor":
        return ComposableInputPreProcessor(parts=[p.to_dict() for p in procs])
