"""Unsupervised / pretrain layer family + center loss + 1D conv family.

Parity targets:
  - VariationalAutoencoder (ref: nn/conf/layers/variational/VariationalAutoencoder.java;
    impl nn/layers/variational/VariationalAutoencoder.java, 1107 LoC)
  - AutoEncoder — denoising AE (ref: nn/conf/layers/AutoEncoder.java;
    impl nn/layers/feedforward/autoencoder/AutoEncoder.java)
  - RBM — contrastive divergence (ref: nn/layers/feedforward/rbm/RBM.java, 504 LoC)
  - CenterLossOutputLayer (ref: nn/layers/training/CenterLossOutputLayer.java)
  - Convolution1DLayer / Subsampling1DLayer (ref: nn/conf/layers/Convolution1DLayer.java)

TPU-first design: each pretrain layer exposes a pure, differentiable
``pretrain_loss(params, x, rng)``; the engine jits grad-of-that-loss into
one XLA step per layer (layerwise pretraining,
ref: MultiLayerNetwork.pretrainLayer :197).  The RBM's CD-k update — which
in the reference is an explicit hand-derived gradient — is expressed here
via the standard free-energy/stop-gradient trick so jax.grad reproduces
the CD gradient while the Gibbs chain itself stays inside the same traced
computation.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (
    BaseOutputLayer, Layer, register_layer)
from deeplearning4j_tpu.ops import activations as act_ops
from deeplearning4j_tpu.ops import convolution as conv_ops
from deeplearning4j_tpu.ops import losses as loss_ops
from deeplearning4j_tpu.ops import vae_distributions as vae_dist


@register_layer
@dataclasses.dataclass
class AutoEncoder(Layer):
    """Denoising autoencoder with tied decode weights (W^T)
    (ref: nn/layers/feedforward/autoencoder/AutoEncoder.java — ``decode``
    uses W.transpose, corruption via ``getCorruptedInput``)."""

    n_in: Optional[int] = None
    n_out: int = 0
    corruption_level: float = 0.3
    sparsity: float = 0.0
    loss: str = "mse"

    def is_pretrain_layer(self):
        return True

    def initialize(self, key, input_type, dtype=jnp.float32):
        n_in = self.n_in or input_type.flat_size()
        kW, _ = jax.random.split(key)
        params = {"W": self._winit(kW, (n_in, self.n_out), dtype),
                  "b": self._binit((self.n_out,), dtype),
                  "vb": jnp.zeros((n_in,), dtype)}
        return params, {}, InputType.feed_forward(self.n_out)

    def forward(self, params, state, x, *, train, rng, mask=None):
        x = self._maybe_dropout(x, train, rng)
        return self._act(x @ params["W"] + params["b"]), state, mask

    def encode(self, params, x):
        return self._act(x @ params["W"] + params["b"])

    def decode(self, params, h):
        return self._act(h @ params["W"].T + params["vb"])

    def pretrain_loss(self, params, x, rng):
        """Mean reconstruction loss on masking-corrupted input."""
        if self.corruption_level > 0.0:
            keep = jax.random.bernoulli(rng, 1.0 - self.corruption_level, x.shape)
            x_in = x * keep.astype(x.dtype)
        else:
            x_in = x
        h = self.encode(params, x_in)
        pre_recon = h @ params["W"].T + params["vb"]
        per_ex = loss_ops.get(self.loss)(x, pre_recon,
                                         self.activation or "sigmoid", None)
        loss = jnp.mean(per_ex)
        if self.sparsity > 0.0:
            rho_hat = jnp.clip(jnp.mean(h, axis=0), 1e-7, 1.0 - 1e-7)
            rho = self.sparsity
            kl = rho * jnp.log(rho / rho_hat) + \
                (1 - rho) * jnp.log((1 - rho) / (1 - rho_hat))
            loss = loss + jnp.sum(kl)
        return loss

    def output_type(self, input_type):
        return InputType.feed_forward(self.n_out)


@register_layer
@dataclasses.dataclass
class RBM(Layer):
    """Restricted Boltzmann machine trained by CD-k
    (ref: nn/layers/feedforward/rbm/RBM.java:504 — ``contrastiveDivergence``
    :gibbhVh chain; hidden/visible unit kinds from nn/conf/layers/RBM.java).

    The CD-k gradient  E_data[dF/dθ] - E_model[dF/dθ]  is produced by
    autodiff of  F(v_data) - F(stop_grad(v_model))  where F is the free
    energy and v_model the end of the Gibbs chain — numerically identical
    to the reference's hand-rolled update, but one fused XLA program.
    """

    n_in: Optional[int] = None
    n_out: int = 0
    hidden_unit: str = "binary"    # binary | gaussian | relu
    visible_unit: str = "binary"   # binary | gaussian | linear
    k: int = 1
    sparsity: float = 0.0

    def is_pretrain_layer(self):
        return True

    def initialize(self, key, input_type, dtype=jnp.float32):
        n_in = self.n_in or input_type.flat_size()
        kW, _ = jax.random.split(key)
        params = {"W": self._winit(kW, (n_in, self.n_out), dtype),
                  "b": self._binit((self.n_out,), dtype),
                  "vb": jnp.zeros((n_in,), dtype)}
        return params, {}, InputType.feed_forward(self.n_out)

    def forward(self, params, state, x, *, train, rng, mask=None):
        """Supervised use: propUp activations (ref: RBM.activate)."""
        x = self._maybe_dropout(x, train, rng)
        return self._hidden_mean(params, x), state, mask

    def _hidden_mean(self, params, v):
        pre = v @ params["W"] + params["b"]
        if self.hidden_unit == "relu":
            return jax.nn.relu(pre)
        if self.hidden_unit == "gaussian":
            return pre
        return jax.nn.sigmoid(pre)

    def _sample_hidden(self, params, v, rng):
        mean = self._hidden_mean(params, v)
        if self.hidden_unit == "binary":
            return jax.random.bernoulli(rng, mean).astype(v.dtype), mean
        if self.hidden_unit == "gaussian":
            return mean + jax.random.normal(rng, mean.shape, v.dtype), mean
        return mean, mean  # relu: mean-field

    def _visible_mean(self, params, h):
        pre = h @ params["W"].T + params["vb"]
        if self.visible_unit in ("gaussian", "linear"):
            return pre
        return jax.nn.sigmoid(pre)

    def _sample_visible(self, params, h, rng):
        mean = self._visible_mean(params, h)
        if self.visible_unit == "binary":
            return jax.random.bernoulli(rng, mean).astype(h.dtype), mean
        if self.visible_unit == "gaussian":
            return mean + jax.random.normal(rng, mean.shape, mean.dtype), mean
        return mean, mean

    def free_energy(self, params, v):
        """Free energy with the hidden units analytically marginalized:
        binary hidden → -Σ softplus(pre); gaussian hidden → -½Σ pre²;
        relu hidden uses the softplus form (the standard NReLU surrogate).
        Gaussian visible adds ||v||²/2.  Monitoring/scoring metric."""
        pre_h = v @ params["W"] + params["b"]
        if self.hidden_unit == "gaussian":
            marg = 0.5 * jnp.sum(pre_h * pre_h, axis=-1)
        else:
            marg = jnp.sum(jax.nn.softplus(pre_h), axis=-1)
        fe = -(v @ params["vb"]) - marg
        if self.visible_unit in ("gaussian", "linear"):
            fe = fe + 0.5 * jnp.sum(v * v, axis=-1)
        return fe

    def _energy(self, params, v, h):
        """Joint energy E(v,h) = -v·vb - h·hb - vᵀWh (+½||v||² gaussian
        visible).  Used only through the CD surrogate below."""
        e = -(v @ params["vb"]) - (h @ params["b"]) - \
            jnp.sum((v @ params["W"]) * h, axis=-1)
        if self.visible_unit in ("gaussian", "linear"):
            e = e + 0.5 * jnp.sum(v * v, axis=-1)
        return e

    def pretrain_loss(self, params, x, rng):
        """CD-k surrogate: E(v_d, sg(h_d)) - E(sg(v_m), sg(h_m)) with mean
        hidden activations, so jax.grad reproduces the classic CD update
        (dW = v_dᵀh_d - v_mᵀh_m, reference RBM.java contrastiveDivergence
        uses the hidden PROBABILITIES the same way) for every hidden-unit
        kind — binary, gaussian, and relu alike."""
        v = x

        def gibbs(i, carry):
            v, r = carry
            r, rh, rv = jax.random.split(r, 3)
            h, _ = self._sample_hidden(params, v, rh)
            v2, _ = self._sample_visible(params, h, rv)
            return (v2, r)

        v_model, _ = jax.lax.fori_loop(0, self.k, gibbs, (v, rng))
        sg = jax.lax.stop_gradient
        v_model = sg(v_model)
        h_data = sg(self._hidden_mean(params, x))
        h_model = sg(self._hidden_mean(params, v_model))
        loss = jnp.mean(self._energy(params, x, h_data) -
                        self._energy(params, v_model, h_model))
        if self.sparsity > 0.0:
            rho_hat = jnp.clip(jnp.mean(self._hidden_mean(params, x)),
                               1e-7, 1.0 - 1e-7)
            loss = loss + (self.sparsity - rho_hat) ** 2
        return loss

    def reconstruction_error(self, params, x):
        """Monitoring metric: one-step reconstruction MSE (the CD loss
        itself is not a bounded quantity)."""
        h = self._hidden_mean(params, x)
        v = self._visible_mean(params, h)
        return float(jnp.mean((x - v) ** 2))

    def output_type(self, input_type):
        return InputType.feed_forward(self.n_out)


@register_layer
@dataclasses.dataclass
class VariationalAutoencoder(Layer):
    """VAE (Kingma & Welling) with MLP encoder/decoder stacks
    (ref: nn/conf/layers/variational/VariationalAutoencoder.java —
    encoderLayerSizes/decoderLayerSizes/pzxActivationFn/
    reconstructionDistribution/numSamples; impl
    nn/layers/variational/VariationalAutoencoder.java).

    As a layer inside a supervised net, ``forward`` emits the mean of
    q(z|x) passed through pzx_activation (matching the reference's
    ``activate`` which uses only the mean path).  ``pretrain_loss`` is the
    negative ELBO with the reparameterization trick, averaged over
    ``num_samples`` MC samples.
    """

    n_in: Optional[int] = None
    n_out: int = 0                     # latent size
    encoder_layer_sizes: Tuple[int, ...] = (100,)
    decoder_layer_sizes: Tuple[int, ...] = (100,)
    pzx_activation: str = "identity"
    reconstruction_distribution: Optional[dict] = None
    num_samples: int = 1

    def is_pretrain_layer(self):
        return True

    def _dist(self):
        return vae_dist.make(self.reconstruction_distribution)

    def initialize(self, key, input_type, dtype=jnp.float32):
        n_in = self.n_in or input_type.flat_size()
        dist = self._dist()
        params = {}
        keys = jax.random.split(key, len(self.encoder_layer_sizes) +
                                len(self.decoder_layer_sizes) + 3)
        ki = 0
        prev = n_in
        for i, sz in enumerate(self.encoder_layer_sizes):
            params[f"eW{i}"] = self._winit(keys[ki], (prev, sz), dtype)
            params[f"eb{i}"] = jnp.zeros((sz,), dtype)
            prev, ki = sz, ki + 1
        params["pZXMeanW"] = self._winit(keys[ki], (prev, self.n_out), dtype)
        params["pZXMeanb"] = jnp.zeros((self.n_out,), dtype)
        ki += 1
        params["pZXLogStd2W"] = self._winit(keys[ki], (prev, self.n_out), dtype)
        params["pZXLogStd2b"] = jnp.zeros((self.n_out,), dtype)
        ki += 1
        prev = self.n_out
        for i, sz in enumerate(self.decoder_layer_sizes):
            params[f"dW{i}"] = self._winit(keys[ki], (prev, sz), dtype)
            params[f"db{i}"] = jnp.zeros((sz,), dtype)
            prev, ki = sz, ki + 1
        n_dist = dist.n_dist_params(n_in)
        params["pXZW"] = self._winit(keys[ki], (prev, n_dist), dtype)
        params["pXZb"] = jnp.zeros((n_dist,), dtype)
        return params, {}, InputType.feed_forward(self.n_out)

    # ---- encoder / decoder stacks (hidden activation = self.activation) ----
    def _encode_hidden(self, params, x):
        h = x
        for i in range(len(self.encoder_layer_sizes)):
            h = self._act(h @ params[f"eW{i}"] + params[f"eb{i}"])
        return h

    def encode_mean_logvar(self, params, x):
        h = self._encode_hidden(params, x)
        mean = h @ params["pZXMeanW"] + params["pZXMeanb"]
        logvar = h @ params["pZXLogStd2W"] + params["pZXLogStd2b"]
        return mean, logvar

    def decode(self, params, z):
        h = z
        for i in range(len(self.decoder_layer_sizes)):
            h = self._act(h @ params[f"dW{i}"] + params[f"db{i}"])
        return h @ params["pXZW"] + params["pXZb"]  # distribution preout

    def forward(self, params, state, x, *, train, rng, mask=None):
        x = self._maybe_dropout(x, train, rng)
        mean, _ = self.encode_mean_logvar(params, x)
        return act_ops.get(self.pzx_activation)(mean), state, mask

    def pretrain_loss(self, params, x, rng):
        """Negative ELBO = E_q[-log p(x|z)] + KL(q(z|x) || N(0,I))."""
        dist = self._dist()
        mean, logvar = self.encode_mean_logvar(params, x)
        pzx_act = act_ops.get(self.pzx_activation)
        mean_a = pzx_act(mean)
        kl = 0.5 * jnp.sum(mean_a ** 2 + jnp.exp(logvar) - 1.0 - logvar, axis=-1)
        recon = 0.0
        for s in range(self.num_samples):
            eps = jax.random.normal(jax.random.fold_in(rng, s), mean.shape,
                                    mean.dtype)
            z = mean_a + jnp.exp(0.5 * logvar) * eps
            recon = recon + dist.neg_log_prob(x, self.decode(params, z))
        recon = recon / self.num_samples
        return jnp.mean(recon + kl)

    # ---- reference inference surface ----
    def reconstruction_log_probability(self, params, x, rng, num_samples=None):
        """Per-example MC estimate of log p(x)
        (ref: VariationalAutoencoder.reconstructionLogProbability)."""
        ns = num_samples or max(self.num_samples, 1)
        dist = self._dist()
        mean, logvar = self.encode_mean_logvar(params, x)
        mean_a = act_ops.get(self.pzx_activation)(mean)
        std = jnp.exp(0.5 * logvar)
        lps = []
        for s in range(ns):
            eps = jax.random.normal(jax.random.fold_in(rng, s), mean.shape,
                                    mean.dtype)
            z = mean_a + std * eps
            log_pxz = -dist.neg_log_prob(x, self.decode(params, z))
            log_pz = -0.5 * jnp.sum(z ** 2 + jnp.log(2 * jnp.pi), axis=-1)
            log_qzx = -0.5 * jnp.sum(eps ** 2 + jnp.log(2 * jnp.pi) + logvar,
                                     axis=-1)
            lps.append(log_pxz + log_pz - log_qzx)
        stacked = jnp.stack(lps)  # [S, N]
        return jax.scipy.special.logsumexp(stacked, axis=0) - jnp.log(float(ns))

    def generate_at_mean_given_z(self, params, z):
        """(ref: generateAtMeanGivenZ)"""
        return self._dist().mean(self.decode(params, jnp.asarray(z)))

    def generate_random_given_z(self, params, z, rng):
        return self._dist().sample(self.decode(params, jnp.asarray(z)), rng)

    def reconstruction_error(self, params, x):
        """(ref: reconstructionError — deterministic, mean path)"""
        mean, _ = self.encode_mean_logvar(params, x)
        mean_a = act_ops.get(self.pzx_activation)(mean)
        recon = self._dist().mean(self.decode(params, mean_a))
        return jnp.sum((x - recon) ** 2, axis=-1)

    def output_type(self, input_type):
        return InputType.feed_forward(self.n_out)


@register_layer
@dataclasses.dataclass
class CenterLossOutputLayer(BaseOutputLayer):
    """Softmax + center loss head (ref:
    nn/layers/training/CenterLossOutputLayer.java — score adds
    lambda/2 · ||f - c_y||²; centers updated toward class feature means
    at rate alpha, params ``cL`` in CenterLossParamInitializer).

    Functional form: the score is  interclass + (lambda/2)·||f - c_y||²
    (exactly the reference's computeScore), realized so autodiff yields
    the reference's asymmetric updates — features pulled at rate lambda,
    centers moved at rate alpha — via stop_gradient plus a zero-valued
    center term.  ``gradient_check=True`` switches to the plain
    full-autodiff quadratic (the reference's Builder.gradientCheck flag,
    which exists for exactly this FD-consistency reason).
    """

    alpha: float = 0.05
    lambda_: float = 2e-4
    gradient_check: bool = False

    requires_features_for_score = True

    def initialize(self, key, input_type, dtype=jnp.float32):
        n_in = self.n_in or input_type.flat_size()
        kW, _ = jax.random.split(key)
        params = {"W": self._winit(kW, (n_in, self.n_out), dtype),
                  "b": self._binit((self.n_out,), dtype),
                  "cL": jnp.zeros((self.n_out, n_in), dtype)}
        return params, {}, InputType.feed_forward(self.n_out)

    def compute_score(self, labels, preout, mask=None):
        raise NotImplementedError(
            "CenterLossOutputLayer needs the pre-output features for its "
            "score; it is supported in MultiLayerNetwork (which routes "
            "through compute_score_with_features) but not yet as a "
            "ComputationGraph output layer.")

    def compute_score_with_features(self, labels, preout, features, params,
                                    mask=None):
        base = loss_ops.get(self.loss)(labels, preout,
                                       self.activation or "softmax", mask)
        centers_for_ex = labels @ params["cL"]  # one-hot labels [N, C] @ [C, F]
        if self.gradient_check:
            intra = 0.5 * self.lambda_ * jnp.sum(
                (features - centers_for_ex) ** 2, axis=-1)
        else:
            sg = jax.lax.stop_gradient
            # value = (lambda/2)||f-c||² ; df = lambda(f-c)
            pull = 0.5 * self.lambda_ * jnp.sum(
                (features - sg(centers_for_ex)) ** 2, axis=-1)
            # value = 0 ; dc = alpha(c-f)   (the reference's center update)
            diff = sg(features) - centers_for_ex
            move = 0.5 * self.alpha * (jnp.sum(diff ** 2, axis=-1) -
                                       sg(jnp.sum(diff ** 2, axis=-1)))
            intra = pull + move
        if mask is not None and mask.ndim == base.ndim:
            intra = intra * mask
        return base + intra


# ==========================================================================
# 1D convolution family (sequence data [N, T, C])
# ==========================================================================

@register_layer
@dataclasses.dataclass
class Convolution1DLayer(Layer):
    """1D conv over RNN-format sequences (ref:
    nn/conf/layers/Convolution1DLayer.java).  Weights [K, C_in, C_out]."""

    n_in: Optional[int] = None
    n_out: int = 0
    kernel: int = 3
    stride: int = 1
    padding: int = 0
    dilation: int = 1
    convolution_mode: str = "same"

    def initialize(self, key, input_type, dtype=jnp.float32):
        c_in = self.n_in or input_type.size
        kW, _ = jax.random.split(key)
        fan_in = c_in * self.kernel
        params = {"W": self._winit(kW, (self.kernel, c_in, self.n_out), dtype,
                                   fan_in=fan_in, fan_out=self.n_out * self.kernel),
                  "b": self._binit((self.n_out,), dtype)}
        return params, {}, self.output_type(input_type)

    def forward(self, params, state, x, *, train, rng, mask=None):
        x = self._maybe_dropout(x, train, rng)
        if mask is not None:
            x = x * mask[..., None].astype(x.dtype)
        y = conv_ops.conv1d(x, params["W"], params["b"], self.stride,
                            self.padding, self.dilation, self.convolution_mode)
        if mask is not None and self.stride == 1 and \
                self.convolution_mode == "same":
            out_mask = mask
        else:
            out_mask = None
        return self._act(y), state, out_mask

    def output_type(self, input_type):
        t = input_type.timesteps
        if t is not None:
            t = conv_ops.conv1d_output_len(t, self.kernel, self.stride,
                                           self.padding, self.dilation,
                                           self.convolution_mode)
        return InputType.recurrent(self.n_out, t)


@register_layer
@dataclasses.dataclass
class Subsampling1DLayer(Layer):
    """1D pooling over sequences (ref: nn/conf/layers/Subsampling1DLayer.java)."""

    pooling_type: str = "max"
    kernel: int = 2
    stride: int = 2
    padding: int = 0
    convolution_mode: str = "truncate"
    pnorm: int = 2

    def has_params(self):
        return False

    def initialize(self, key, input_type, dtype=jnp.float32):
        return {}, {}, self.output_type(input_type)

    def forward(self, params, state, x, *, train, rng, mask=None):
        kind = self.pooling_type.lower()
        if mask is None:
            y = conv_ops.pool1d(x, kind, self.kernel, self.stride,
                                self.padding, self.convolution_mode,
                                self.pnorm)
            return y, state, None
        # Mask-aware pooling (MaskedReductionUtil semantics): padded
        # timesteps must not contribute, and the output mask is the
        # max-pool of the input mask (window valid ⟺ any valid step).
        mf = mask[..., None].astype(x.dtype)
        if kind == "max":
            fill = jnp.finfo(x.dtype).min
            xm = jnp.where(mf > 0, x, fill)
            y = conv_ops.pool1d(xm, "max", self.kernel, self.stride,
                                self.padding, self.convolution_mode)
        elif kind in ("avg", "mean"):
            s = conv_ops.pool1d(x * mf, "sum", self.kernel, self.stride,
                                self.padding, self.convolution_mode)
            cnt = conv_ops.pool1d(jnp.broadcast_to(mf, x.shape), "sum",
                                  self.kernel, self.stride, self.padding,
                                  self.convolution_mode)
            y = s / jnp.maximum(cnt, 1.0)
        elif kind == "sum":
            y = conv_ops.pool1d(x * mf, "sum", self.kernel, self.stride,
                                self.padding, self.convolution_mode)
        else:  # pnorm
            y = conv_ops.pool1d(x * mf, "pnorm", self.kernel, self.stride,
                                self.padding, self.convolution_mode,
                                self.pnorm)
        out_mask = conv_ops.pool1d(mask[..., None].astype(x.dtype), "max",
                                   self.kernel, self.stride, self.padding,
                                   self.convolution_mode)[..., 0]
        y = y * (out_mask[..., None] > 0).astype(x.dtype)
        return y, state, out_mask

    def output_type(self, input_type):
        t = input_type.timesteps
        if t is not None:
            t = conv_ops.conv1d_output_len(t, self.kernel, self.stride,
                                           self.padding, 1,
                                           self.convolution_mode)
        return InputType.recurrent(input_type.size, t)
