"""Input type inference — the reference's ``InputType`` system.

``InputType.convolutional(h, w, c)`` etc. drive automatic nIn inference
and preprocessor insertion between layer families
(ref: nn/conf/inputs/InputType.java, nn/conf/layers/InputTypeUtil.java).

Native data layouts (TPU-idiomatic, differing from the reference where
noted): FF [N, C]; CNN NCHW [N, C, H, W]; RNN **[N, T, C]** (the
reference uses [N, C, T]; time-last is hostile to XLA batched matmuls, so
the native layout here is time-second with conversion utilities for
reference-format data).
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class InputType:
    kind: str  # 'ff' | 'rnn' | 'cnn' | 'cnnflat'
    size: int = 0            # ff/rnn feature size
    height: int = 0
    width: int = 0
    channels: int = 0
    timesteps: Optional[int] = None  # rnn, optional (None = variable)

    @staticmethod
    def feed_forward(size: int) -> "InputType":
        return InputType("ff", size=size)

    @staticmethod
    def recurrent(size: int, timesteps: Optional[int] = None) -> "InputType":
        return InputType("rnn", size=size, timesteps=timesteps)

    @staticmethod
    def convolutional(height: int, width: int, channels: int) -> "InputType":
        return InputType("cnn", height=height, width=width, channels=channels)

    @staticmethod
    def convolutional_flat(height: int, width: int, channels: int) -> "InputType":
        return InputType("cnnflat", size=height * width * channels,
                         height=height, width=width, channels=channels)

    def flat_size(self) -> int:
        if self.kind in ("ff", "rnn", "cnnflat"):
            return self.size if self.kind != "cnnflat" else self.height * self.width * self.channels
        return self.height * self.width * self.channels

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @staticmethod
    def from_dict(d: dict) -> "InputType":
        return InputType(**d)
