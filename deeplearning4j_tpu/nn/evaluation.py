"""Evaluation metrics — classification, regression, ROC.

(ref: eval/Evaluation.java:47, ConfusionMatrix.java, RegressionEvaluation.java,
ROC.java, ROCBinary.java, ROCMultiClass.java, EvaluationBinary.java)

Accumulation happens host-side in numpy (cheap vs. the model forward);
the model forward producing predictions is the jitted path.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


class ConfusionMatrix:
    """(ref: eval/ConfusionMatrix.java)"""

    def __init__(self, n_classes: int):
        self.matrix = np.zeros((n_classes, n_classes), dtype=np.int64)

    def add(self, actual: int, predicted: int, count: int = 1):
        self.matrix[actual, predicted] += count

    def get_count(self, actual: int, predicted: int) -> int:
        return int(self.matrix[actual, predicted])

    def __str__(self):
        return str(self.matrix)


class Prediction:
    """One example's (actual, predicted, metadata) triple
    (ref: eval/meta/Prediction.java — per-example attribution so
    misclassified examples can be traced back to their source records)."""

    __slots__ = ("actual", "predicted", "record_meta_data")

    def __init__(self, actual: int, predicted: int, record_meta_data=None):
        self.actual = actual
        self.predicted = predicted
        self.record_meta_data = record_meta_data

    def __repr__(self):
        return (f"Prediction(actual={self.actual}, "
                f"predicted={self.predicted}, "
                f"meta={self.record_meta_data!r})")


class Evaluation:
    """Multi-class classification metrics (ref: eval/Evaluation.java)."""

    def __init__(self, n_classes: Optional[int] = None,
                 labels: Optional[List[str]] = None):
        self.n_classes = n_classes
        self.label_names = labels
        self.confusion: Optional[ConfusionMatrix] = None

    def _ensure(self, n: int):
        if self.confusion is None:
            self.n_classes = self.n_classes or n
            self.confusion = ConfusionMatrix(self.n_classes)
        if not hasattr(self, "predictions"):
            self.predictions: List["Prediction"] = []

    def eval(self, labels, predictions, mask=None, record_meta_data=None):
        """labels: one-hot [N,C] (or [N,T,C] with mask [N,T]);
        predictions: probabilities same shape.  record_meta_data: one
        metadata object per example — recorded per prediction for
        attribution (ref: eval/meta/Prediction.java,
        Evaluation.eval(..., List<RecordMetaData>))."""
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        meta = record_meta_data
        if labels.ndim == 3:  # time series: flatten valid steps
            if mask is not None:
                m = np.asarray(mask).astype(bool).reshape(-1)
            else:
                m = np.ones(labels.shape[0] * labels.shape[1], dtype=bool)
            if meta is not None:  # replicate per timestep, then mask
                T = labels.shape[1]
                meta = [md for md in meta for _ in range(T)]
                meta = [md for md, keep in zip(meta, m) if keep]
            labels = labels.reshape(-1, labels.shape[-1])[m]
            predictions = predictions.reshape(-1, predictions.shape[-1])[m]
        self._ensure(labels.shape[-1])
        a = np.argmax(labels, axis=-1)
        p = np.argmax(predictions, axis=-1)
        np.add.at(self.confusion.matrix, (a, p), 1)
        if meta is not None:
            for actual, predicted, md in zip(a, p, meta):
                self.predictions.append(
                    Prediction(int(actual), int(predicted), md))

    # -- per-example attribution (ref: eval/meta/) -------------------------
    def get_prediction_errors(self) -> List["Prediction"]:
        """(ref: Evaluation.getPredictionErrors)"""
        return [p for p in getattr(self, "predictions", [])
                if p.actual != p.predicted]

    def get_predictions_by_actual_class(self, cls: int) -> List["Prediction"]:
        return [p for p in getattr(self, "predictions", [])
                if p.actual == cls]

    def get_predictions_by_predicted_class(self, cls: int
                                           ) -> List["Prediction"]:
        return [p for p in getattr(self, "predictions", [])
                if p.predicted == cls]

    def merge(self, other: "Evaluation") -> "Evaluation":
        """Combine counts from another Evaluation (ref:
        eval/Evaluation.java merge — the distributed-eval reduce)."""
        if other.confusion is None:
            return self
        self._ensure(other.n_classes)
        if other.n_classes != self.n_classes:
            raise ValueError(
                f"class-count mismatch: {self.n_classes} vs {other.n_classes}")
        self.confusion.matrix += other.confusion.matrix
        self.predictions.extend(getattr(other, "predictions", []))
        return self

    # ---- metrics ----
    def _tp(self):
        return np.diag(self.confusion.matrix).astype(np.float64)

    def accuracy(self) -> float:
        m = self.confusion.matrix
        total = m.sum()
        return float(np.trace(m) / total) if total else 0.0

    def precision(self, cls: Optional[int] = None) -> float:
        m = self.confusion.matrix
        col = m.sum(axis=0).astype(np.float64)
        with np.errstate(divide="ignore", invalid="ignore"):
            per = np.where(col > 0, self._tp() / col, np.nan)
        return float(per[cls]) if cls is not None else float(np.nanmean(per))

    def recall(self, cls: Optional[int] = None) -> float:
        m = self.confusion.matrix
        row = m.sum(axis=1).astype(np.float64)
        with np.errstate(divide="ignore", invalid="ignore"):
            per = np.where(row > 0, self._tp() / row, np.nan)
        return float(per[cls]) if cls is not None else float(np.nanmean(per))

    def f1(self, cls: Optional[int] = None) -> float:
        p = self.precision(cls)
        r = self.recall(cls)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def false_positive_rate(self, cls: int) -> float:
        m = self.confusion.matrix
        fp = m[:, cls].sum() - m[cls, cls]
        tn = m.sum() - m[cls, :].sum() - m[:, cls].sum() + m[cls, cls]
        return float(fp / (fp + tn)) if (fp + tn) else 0.0

    def stats(self) -> str:
        lines = [
            "==========================Scores========================================",
            f" Accuracy:  {self.accuracy():.4f}",
            f" Precision: {self.precision():.4f}",
            f" Recall:    {self.recall():.4f}",
            f" F1 Score:  {self.f1():.4f}",
            "========================================================================",
        ]
        return "\n".join(lines)


class EvaluationBinary:
    """Per-output independent binary metrics (ref: eval/EvaluationBinary.java)."""

    def __init__(self, threshold: float = 0.5):
        self.threshold = threshold
        self.tp = self.fp = self.tn = self.fn = None

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        pred = (np.asarray(predictions) >= self.threshold)
        lab = labels >= 0.5
        if mask is not None:
            m = np.asarray(mask).astype(bool)
        else:
            m = np.ones_like(lab, dtype=bool)
        if self.tp is None:
            n = labels.shape[-1]
            self.tp = np.zeros(n)
            self.fp = np.zeros(n)
            self.tn = np.zeros(n)
            self.fn = np.zeros(n)
        axes = tuple(range(labels.ndim - 1))
        self.tp += np.sum(pred & lab & m, axis=axes)
        self.fp += np.sum(pred & ~lab & m, axis=axes)
        self.tn += np.sum(~pred & ~lab & m, axis=axes)
        self.fn += np.sum(~pred & lab & m, axis=axes)

    def accuracy(self, i: int) -> float:
        tot = self.tp[i] + self.fp[i] + self.tn[i] + self.fn[i]
        return float((self.tp[i] + self.tn[i]) / tot) if tot else 0.0

    def precision(self, i: int) -> float:
        d = self.tp[i] + self.fp[i]
        return float(self.tp[i] / d) if d else 0.0

    def recall(self, i: int) -> float:
        d = self.tp[i] + self.fn[i]
        return float(self.tp[i] / d) if d else 0.0

    def f1(self, i: int) -> float:
        p, r = self.precision(i), self.recall(i)
        return 2 * p * r / (p + r) if (p + r) else 0.0


class RegressionEvaluation:
    """Column-wise regression metrics (ref: eval/RegressionEvaluation.java)."""

    def __init__(self, n_columns: Optional[int] = None):
        self.n = 0
        self.sum_abs = None
        self.sum_sq = None
        self.sum_label = None
        self.sum_label_sq = None
        self.sum_pred = None
        self.sum_pred_sq = None
        self.sum_label_pred = None

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels, np.float64)
        pred = np.asarray(predictions, np.float64)
        labels = labels.reshape(-1, labels.shape[-1])
        pred = pred.reshape(-1, pred.shape[-1])
        if self.sum_abs is None:
            c = labels.shape[-1]
            for attr in ("sum_abs", "sum_sq", "sum_label", "sum_label_sq",
                         "sum_pred", "sum_pred_sq", "sum_label_pred"):
                setattr(self, attr, np.zeros(c))
        err = pred - labels
        self.n += labels.shape[0]
        self.sum_abs += np.abs(err).sum(axis=0)
        self.sum_sq += (err ** 2).sum(axis=0)
        self.sum_label += labels.sum(axis=0)
        self.sum_label_sq += (labels ** 2).sum(axis=0)
        self.sum_pred += pred.sum(axis=0)
        self.sum_pred_sq += (pred ** 2).sum(axis=0)
        self.sum_label_pred += (labels * pred).sum(axis=0)

    def mean_squared_error(self, col: int) -> float:
        return float(self.sum_sq[col] / self.n)

    def mean_absolute_error(self, col: int) -> float:
        return float(self.sum_abs[col] / self.n)

    def root_mean_squared_error(self, col: int) -> float:
        return float(np.sqrt(self.sum_sq[col] / self.n))

    def correlation_r2(self, col: int) -> float:
        n = self.n
        num = n * self.sum_label_pred[col] - self.sum_label[col] * self.sum_pred[col]
        den = np.sqrt(n * self.sum_label_sq[col] - self.sum_label[col] ** 2) * \
            np.sqrt(n * self.sum_pred_sq[col] - self.sum_pred[col] ** 2)
        return float((num / den) ** 2) if den else 0.0


class ROC:
    """Binary ROC / AUC by threshold sweep (ref: eval/ROC.java)."""

    def __init__(self, threshold_steps: int = 100):
        self.steps = threshold_steps
        self.scores: List[np.ndarray] = []
        self.labels: List[np.ndarray] = []

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        pred = np.asarray(predictions)
        if labels.ndim > 1 and labels.shape[-1] == 2:
            labels = labels[..., 1]
            pred = pred[..., 1]
        self.labels.append(labels.reshape(-1))
        self.scores.append(pred.reshape(-1))

    def roc_curve(self):
        lab = np.concatenate(self.labels)
        sc = np.concatenate(self.scores)
        thresholds = np.linspace(0, 1, self.steps + 1)
        pos = lab >= 0.5
        n_pos = pos.sum()
        n_neg = (~pos).sum()
        tpr, fpr = [], []
        for t in thresholds:
            p = sc >= t
            tpr.append((p & pos).sum() / n_pos if n_pos else 0.0)
            fpr.append((p & ~pos).sum() / n_neg if n_neg else 0.0)
        return np.array(fpr), np.array(tpr), thresholds

    def auc(self) -> float:
        fpr, tpr, _ = self.roc_curve()
        order = np.argsort(fpr)
        return float(np.trapezoid(tpr[order], fpr[order]))


class ROCMultiClass:
    """One-vs-all ROC per class (ref: eval/ROCMultiClass.java)."""

    def __init__(self, threshold_steps: int = 100):
        self.steps = threshold_steps
        self.per_class: Dict[int, ROC] = {}

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels).reshape(-1, np.asarray(labels).shape[-1])
        pred = np.asarray(predictions).reshape(-1, labels.shape[-1])
        for c in range(labels.shape[-1]):
            self.per_class.setdefault(c, ROC(self.steps)).eval(
                labels[:, c], pred[:, c])

    def auc(self, cls: int) -> float:
        return self.per_class[cls].auc()


class ROCBinary:
    """Per-output-column ROC for independent binary outputs (multi-label
    networks with sigmoid heads; ref: eval/ROCBinary.java — distinct from
    ROCMultiClass's one-vs-all over a softmax)."""

    def __init__(self, threshold_steps: int = 100):
        self.steps = threshold_steps
        self.per_output: Dict[int, ROC] = {}

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        pred = np.asarray(predictions)
        if labels.ndim == 1:
            labels = labels[:, None]
            pred = pred[:, None]
        orig_shape = labels.shape               # pre-flatten, for mask match
        labels = labels.reshape(-1, labels.shape[-1])
        pred = pred.reshape(-1, pred.shape[-1])
        if mask is not None:
            m = np.asarray(mask).astype(bool)
            if m.shape == orig_shape:            # per-element mask
                m = m.reshape(labels.shape)      # applied per column below
            else:                                # per-example/timestep mask
                m = m.reshape(-1)
                labels, pred = labels[m], pred[m]
                m = None
        else:
            m = None
        for c in range(labels.shape[-1]):
            if m is not None:
                keep = m[:, c]
                self.per_output.setdefault(c, ROC(self.steps)).eval(
                    labels[keep, c], pred[keep, c])
            else:
                self.per_output.setdefault(c, ROC(self.steps)).eval(
                    labels[:, c], pred[:, c])

    def num_outputs(self) -> int:
        return len(self.per_output)

    def auc(self, output: int = 0) -> float:
        return self.per_output[output].auc()

    def roc_curve(self, output: int = 0):
        return self.per_output[output].roc_curve()
