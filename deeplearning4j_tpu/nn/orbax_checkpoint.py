"""Sharded (multi-host-safe) checkpointing via Orbax — the pod-scale
companion to ``nn/serialization.py``.

The zip format (ref: util/ModelSerializer.java) gathers every parameter
to one host as a flat vector — right for single-host models, a
host-memory and IO bottleneck for mesh-sharded ones.  Orbax writes each
device shard from the process that owns it (OCDBT/tensorstore under
the hood), preserves the array shardings on restore, and coordinates
across the `jax.distributed` process group — the checkpoint story that
matches the scaleout tier (`scaleout/multislice.py`).

API mirrors the zip pair:

    save_sharded(model, dir)            # params + updater + model state
    restore_sharded(model, dir)         # in-place, shardings preserved
"""

from __future__ import annotations

import json
from pathlib import Path


import contextlib


@contextlib.contextmanager
def _ckptr():
    import orbax.checkpoint as ocp
    with ocp.StandardCheckpointer() as ck:
        yield ck
        # orbax 0.11 finalizes (tmp-dir → atomic rename) in the
        # background; block so callers see a complete checkpoint
        if hasattr(ck, "wait_until_finished"):
            ck.wait_until_finished()


def _state_tree(model) -> dict:
    return {
        "params": model.net_params,
        "opt_states": model.opt_states,
        "net_state": model.net_state,
    }


def save_sharded(model, directory) -> Path:
    """Write params/updater/model-state as an Orbax checkpoint plus the
    JSON config (the `configuration.json` role) and a small meta file.
    Returns the checkpoint directory.

    Publish order matters for crash-safety: the JSON sidecars land
    FIRST (process 0 only — they are tiny, identical everywhere, and a
    shared filesystem must not see N concurrent writers), then Orbax's
    atomically-renamed ``state`` dir is the commit point — a preemption
    mid-save leaves either no loadable checkpoint or a complete one."""
    import jax
    from deeplearning4j_tpu.nn.serialization import tagged_conf_dict

    directory = Path(directory).resolve()
    directory.mkdir(parents=True, exist_ok=True)
    if jax.process_index() == 0:
        (directory / "configuration.json").write_text(
            json.dumps(tagged_conf_dict(model), indent=2))
        (directory / "meta.json").write_text(json.dumps({
            "iteration": int(getattr(model, "iteration", 0)),
            "epoch": int(getattr(model, "epoch", 0)),
        }))
    with _ckptr() as ck:  # orbax coordinates all processes + atomic rename
        ck.save(directory / "state", _state_tree(model), force=True)
    return directory


def restore_sharded(model, directory):
    """Restore in place onto ``model`` (already init()-ed and, for mesh
    runs, already placed — restored arrays take the shardings of the
    model's current arrays, so a ParallelWrapper-placed model comes back
    sharded without a host gather)."""
    directory = Path(directory).resolve()
    if model.net_params is None:
        model.init()
    with _ckptr() as ck:
        restored = ck.restore(directory / "state",
                              target=_state_tree(model))
    model.net_params = restored["params"]
    model.opt_states = restored["opt_states"]
    model.net_state = restored["net_state"]
    meta_path = directory / "meta.json"
    if meta_path.exists():
        meta = json.loads(meta_path.read_text())
        model.iteration = int(meta.get("iteration", model.iteration))
        model.epoch = int(meta.get("epoch", getattr(model, "epoch", 0)))
    return model


def load_sharded(directory):
    """Rebuild the model from the stored configuration, then restore —
    the ``load_model`` analog (model type sniffed from the config via
    the shared serialization helper)."""
    from deeplearning4j_tpu.nn.serialization import model_from_conf_dict

    directory = Path(directory).resolve()
    conf_dict = json.loads((directory / "configuration.json").read_text())
    model = model_from_conf_dict(conf_dict).init()
    return restore_sharded(model, directory)
