"""Model checkpointing — the reference's ModelSerializer zip format.

(ref: util/ModelSerializer.java:39-41,52-120) — a zip container holding
{configuration.json, coefficients.bin (flat param vector),
updaterState.bin (flat updater state), normalizer.bin} — kept
byte-layout-compatible in spirit: coefficients are the canonical flat
view (deeplearning4j_tpu.nn.params ordering), stored little-endian
float32, so checkpoints survive process/version changes.  ModelGuesser
sniffing (ref: deeplearning4j-core ModelGuesser.java) is `load_model`,
which detects the model type from the config JSON.
"""

from __future__ import annotations

import io
import json
import zipfile
from pathlib import Path
from typing import Optional, Union

import numpy as np

CONFIG_NAME = "configuration.json"
COEFFICIENTS_NAME = "coefficients.bin"
UPDATER_NAME = "updaterState.bin"
NORMALIZER_NAME = "normalizer.bin"


def _write_array(zf: zipfile.ZipFile, name: str, arr) -> None:
    zf.writestr(name, np.asarray(arr, dtype=np.float32).tobytes())


def _read_array(zf: zipfile.ZipFile, name: str) -> np.ndarray:
    return np.frombuffer(zf.read(name), dtype=np.float32)


def write_model(model, path: Union[str, Path], save_updater: bool = True,
                normalizer=None) -> None:
    """(ref: ModelSerializer.writeModel)"""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    conf_dict = tagged_conf_dict(model)
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr(CONFIG_NAME, json.dumps(conf_dict, indent=2))
        _write_array(zf, COEFFICIENTS_NAME, model.params())
        if save_updater and model.opt_states is not None:
            _write_array(zf, UPDATER_NAME, model.updater_state_flat())
        if normalizer is not None:
            zf.writestr(NORMALIZER_NAME, json.dumps(normalizer.to_dict()))


def restore_multi_layer_network(path: Union[str, Path], load_updater: bool = True):
    """(ref: ModelSerializer.restoreMultiLayerNetwork)"""
    from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    with zipfile.ZipFile(path, "r") as zf:
        conf_dict = json.loads(zf.read(CONFIG_NAME))
        if "confs" in conf_dict:
            # a zip the ORIGINAL Java DL4J wrote (Jackson schema with a
            # confs[] array) — migrate it (nn/dl4j_migration.py) instead
            # of parsing it as this framework's own tagged schema
            from deeplearning4j_tpu.nn import dl4j_migration
            return dl4j_migration.restore_multi_layer_network(
                path, load_updater=load_updater)
        conf_dict.pop("@model", None)
        conf = MultiLayerConfiguration.from_dict(conf_dict)
        net = MultiLayerNetwork(conf).init()
        net.set_params(_read_array(zf, COEFFICIENTS_NAME))
        if load_updater and UPDATER_NAME in zf.namelist():
            net.set_updater_state_flat(_read_array(zf, UPDATER_NAME))
    return net


def restore_computation_graph(path: Union[str, Path], load_updater: bool = True):
    """(ref: ModelSerializer.restoreComputationGraph)"""
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.conf.graph_conf import ComputationGraphConfiguration

    with zipfile.ZipFile(path, "r") as zf:
        conf_dict = json.loads(zf.read(CONFIG_NAME))
        if "networkInputs" in conf_dict and "vertices" in conf_dict:
            # a zip the ORIGINAL Java DL4J wrote (Jackson camelCase
            # graph schema) — migrate it (nn/dl4j_migration.py)
            from deeplearning4j_tpu.nn import dl4j_migration
            return dl4j_migration.restore_computation_graph(
                path, load_updater=load_updater)
        conf_dict.pop("@model", None)
        conf = ComputationGraphConfiguration.from_dict(conf_dict)
        net = ComputationGraph(conf).init()
        net.set_params(_read_array(zf, COEFFICIENTS_NAME))
        if load_updater and UPDATER_NAME in zf.namelist():
            net.set_updater_state_flat(_read_array(zf, UPDATER_NAME))
    return net


def restore_normalizer(path: Union[str, Path]):
    """(ref: ModelSerializer.restoreNormalizerFromFile)"""
    from deeplearning4j_tpu.datasets.normalizers import Normalizer
    with zipfile.ZipFile(path, "r") as zf:
        if NORMALIZER_NAME not in zf.namelist():
            return None
        return Normalizer.from_dict(json.loads(zf.read(NORMALIZER_NAME)))


def tagged_conf_dict(model) -> dict:
    """Model config dict tagged with the concrete model type — the
    shared serialization header for the zip AND Orbax formats."""
    conf_dict = model.conf.to_dict()
    conf_dict["@model"] = type(model).__name__
    return conf_dict


def is_graph_conf(conf_dict: dict) -> bool:
    """Model-type sniffing (ref: util/ModelGuesser.java) — one place."""
    return (conf_dict.get("@model") == "ComputationGraph"
            or "vertices" in conf_dict)


def model_from_conf_dict(conf_dict: dict):
    """Build an UNinitialized-params model of the right type from a
    tagged config dict."""
    conf_dict = {k: v for k, v in conf_dict.items() if k != "@model"}
    if is_graph_conf(conf_dict):
        from deeplearning4j_tpu.nn.conf.graph_conf import (
            ComputationGraphConfiguration)
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        return ComputationGraph(
            ComputationGraphConfiguration.from_dict(conf_dict))
    from deeplearning4j_tpu.nn.conf.network import MultiLayerConfiguration
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    return MultiLayerNetwork(MultiLayerConfiguration.from_dict(conf_dict))


def load_model(path: Union[str, Path], load_updater: bool = True):
    """Sniff the model type from the checkpoint and restore it
    (ref: deeplearning4j-core util/ModelGuesser.java)."""
    with zipfile.ZipFile(path, "r") as zf:
        conf_dict = json.loads(zf.read(CONFIG_NAME))
    if is_graph_conf(conf_dict):
        return restore_computation_graph(path, load_updater=load_updater)
    return restore_multi_layer_network(path, load_updater=load_updater)
