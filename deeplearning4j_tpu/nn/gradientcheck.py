"""Numeric-vs-analytic gradient checking — the correctness backbone.

(ref: gradientcheck/GradientCheckUtil.java:77 — perturbs each param ±ε in
double precision and compares relative error; the reference's test suites
in deeplearning4j-core/src/test/java/org/deeplearning4j/gradientcheck/
are the model for tests/test_gradientcheck.py.)

TPU f64 is emulated/slow, so checks run under the CPU backend with x64
enabled (the cuDNN-vs-builtin cross-validation pattern of
CuDNNGradientChecks.java becomes TPU-vs-CPU here: same code, two
backends).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn import params as param_util


def _enable_x64():
    """jax.enable_x64 across versions (top-level export is recent;
    older jax ships the context manager in jax.experimental)."""
    if hasattr(jax, "enable_x64"):
        return jax.enable_x64(True)
    from jax.experimental import enable_x64
    return enable_x64()


def check_gradients(net, x, y, *, epsilon: float = 1e-6,
                    max_rel_error: float = 1e-3, min_abs_error: float = 1e-8,
                    fmask=None, lmask=None, subset: Optional[int] = 128,
                    seed: int = 0, print_results: bool = False) -> bool:
    """Compare jax.grad of the training loss against central finite
    differences, param by param (ref: GradientCheckUtil.checkGradients).

    subset: max number of randomly-chosen scalar params to check per layer
    (None = exhaustive, as the reference does).
    Returns True if every checked param's relative error is within bounds.

    float64 is enabled locally via the jax.experimental.enable_x64 context
    (the reference forces double precision the same way,
    GradientCheckUtil.java:87-92) so callers/tests don't leak x64 into the
    rest of the process.
    """
    with _enable_x64():
        return _check_gradients_x64(
            net, x, y, epsilon=epsilon, max_rel_error=max_rel_error,
            min_abs_error=min_abs_error, fmask=fmask, lmask=lmask,
            subset=subset, seed=seed, print_results=print_results)


def _check_gradients_x64(net, x, y, *, epsilon, max_rel_error, min_abs_error,
                         fmask, lmask, subset, seed, print_results) -> bool:
    if net.net_params is None:
        net.init()
    out_layer = net.layers[-1]
    g = net.conf.global_conf
    rng = jax.random.PRNGKey(seed)

    params64 = jax.tree_util.tree_map(
        lambda a: jnp.asarray(np.asarray(a), jnp.float64), net.net_params)
    state64 = jax.tree_util.tree_map(
        lambda a: jnp.asarray(np.asarray(a), jnp.float64), net.net_state)
    x64 = jnp.asarray(np.asarray(x), jnp.float64)
    y64 = jnp.asarray(np.asarray(y), jnp.float64)

    def score(p):
        preout, _, m, feats = net._forward_to_preout(p, state64, x64, fmask,
                                                     True, rng)
        lm = lmask if lmask is not None else (
            m if (m is not None and m.ndim == preout.ndim - 1) else None)
        if getattr(out_layer, "requires_features_for_score", False):
            per_ex = out_layer.compute_score_with_features(
                y64, preout, feats, p[-1], lm)
        else:
            per_ex = out_layer.compute_score(y64, preout, lm)
        s = jnp.mean(per_ex) if g.mini_batch else jnp.sum(per_ex)
        return s + net._reg_penalty(p)

    score_jit = jax.jit(score)
    analytic = jax.grad(score)(params64)

    nprng = np.random.default_rng(seed)
    total_checked = 0
    failures = []
    for li, lp in enumerate(params64):
        for k in param_util.ordered_keys(lp):
            fails, checked = _fd_check_one(
                lp[k], np.asarray(analytic[li][k]),
                lambda arr, li=li, k=k: float(
                    score_jit(_with(params64, li, k, arr))),
                epsilon, max_rel_error, min_abs_error, subset, nprng)
            total_checked += checked
            failures.extend((f"layer {li} {k}", i, a, num, rel)
                            for i, a, num, rel in fails)

    if print_results or failures:
        print(f"Gradient check: {total_checked} params checked, "
              f"{len(failures)} failures")
        for label, i, a, num, rel in failures[:20]:
            print(f"  {label}[{i}]: analytic={a:.3e} numeric={num:.3e} "
                  f"rel={rel:.3e}")
    return not failures


def _fd_check_one(arr, analytic, eval_with, epsilon, max_rel_error,
                  min_abs_error, subset, nprng):
    """Central-difference check of one param tensor.  ``eval_with(new_arr)``
    evaluates the scalar loss with the tensor replaced.  Returns
    ([(flat_idx, analytic, numeric, rel_err)...] failures, n_checked)."""
    shape = arr.shape
    # NB: reshape on an np.array-of-jax-array can silently COPY, so
    # the flat buffer is the single mutable source of truth here.
    flat = np.array(arr, dtype=np.float64).reshape(-1).copy()
    an = analytic.reshape(-1)
    n = flat.size
    idxs = (np.arange(n) if subset is None or n <= subset
            else nprng.choice(n, subset, replace=False))
    failures = []
    for i in idxs:
        orig = flat[i]
        flat[i] = orig + epsilon
        plus = eval_with(flat.reshape(shape))
        flat[i] = orig - epsilon
        minus = eval_with(flat.reshape(shape))
        flat[i] = orig
        numeric = (plus - minus) / (2 * epsilon)
        a = an[i]
        denom = max(abs(a), abs(numeric))
        rel = abs(a - numeric) / denom if denom > 0 else 0.0
        if rel > max_rel_error and abs(a - numeric) > min_abs_error:
            failures.append((int(i), float(a), numeric, rel))
    return failures, len(idxs)


def check_computation_graph_gradients(
        graph, inputs, labels, *, epsilon: float = 1e-6,
        max_rel_error: float = 1e-3, min_abs_error: float = 1e-8,
        fmasks=None, lmasks=None, subset: Optional[int] = 64,
        seed: int = 0, print_results: bool = False) -> bool:
    """ComputationGraph analog of :func:`check_gradients` — rebuilds the
    training score exactly as ComputationGraph._build_step_raw's loss
    closure does (multi-output sum, masks, regularization, MoE aux loss)
    and central-differences every vertex's params in f64 on CPU
    (ref: GradientCheckUtil.checkGradients(ComputationGraph...):238,
    GradientCheckTestsComputationGraph.java).

    inputs/labels: list-like ordered by network_inputs/network_outputs.
    """
    with _enable_x64():
        return _check_cg_x64(graph, inputs, labels, epsilon=epsilon,
                             max_rel_error=max_rel_error,
                             min_abs_error=min_abs_error, fmasks=fmasks,
                             lmasks=lmasks, subset=subset, seed=seed,
                             print_results=print_results)


def _check_cg_x64(graph, inputs, labels, *, epsilon, max_rel_error,
                  min_abs_error, fmasks, lmasks, subset, seed,
                  print_results) -> bool:
    if graph.net_params is None:
        graph.init()
    g = graph.conf.global_conf
    rng = jax.random.PRNGKey(seed)
    out_confs = graph._output_layer_confs()
    out_names = list(out_confs)
    out_pos = {n: graph.conf.network_outputs.index(n) for n in out_names}

    to64 = lambda t: jax.tree_util.tree_map(  # noqa: E731
        lambda a: jnp.asarray(np.asarray(a), jnp.float64)
        if np.asarray(a).dtype.kind == "f" else jnp.asarray(a), t)
    params64 = to64(graph.net_params)
    state64 = to64(graph.net_state)
    xs64 = [jnp.asarray(np.asarray(x), jnp.float64) for x in inputs]
    ys64 = [jnp.asarray(np.asarray(y), jnp.float64) for y in labels]

    def score(p):
        ins = dict(zip(graph.conf.network_inputs, xs64))
        masks = (dict(zip(graph.conf.network_inputs, fmasks))
                 if fmasks is not None else {})
        acts, preouts, new_states, out_masks = graph._forward_all(
            p, state64, ins, masks, True, rng, preout_for=out_names)
        # the SAME loss assembly the training step compiles
        # (ComputationGraph._assemble_training_score) — no drift between
        # checked and trained functions
        return graph._assemble_training_score(
            p, preouts, new_states, out_masks, ys64, lmasks,
            out_confs, out_pos)

    score_jit = jax.jit(score)
    analytic = jax.grad(score)(params64)

    nprng = np.random.default_rng(seed)
    total_checked = 0
    failures = []
    for name in graph.order:
        lp = params64[name]
        if not lp:
            continue
        for k in param_util.ordered_keys(lp):
            if np.asarray(lp[k]).dtype.kind != "f":
                continue

            def eval_with(arr, name=name, k=k):
                pp = dict(params64)
                pp[name] = {**pp[name], k: jnp.asarray(arr)}
                return float(score_jit(pp))

            fails, checked = _fd_check_one(
                lp[k], np.asarray(analytic[name][k]), eval_with,
                epsilon, max_rel_error, min_abs_error, subset, nprng)
            total_checked += checked
            failures.extend((f"vertex {name} {k}", i, a, num, rel)
                            for i, a, num, rel in fails)

    if print_results or failures:
        print(f"CG gradient check: {total_checked} params checked, "
              f"{len(failures)} failures")
        for label, i, a, num, rel in failures[:20]:
            print(f"  {label}[{i}]: analytic={a:.3e} numeric={num:.3e} "
                  f"rel={rel:.3e}")
    return not failures


def check_pretrain_gradients(layer, params, x, *, epsilon: float = 1e-6,
                             max_rel_error: float = 1e-3,
                             min_abs_error: float = 1e-8,
                             subset: Optional[int] = 64, seed: int = 0) -> bool:
    """Gradient-check a pretrain layer's unsupervised loss
    (ref: VaeGradientCheckTests.java — checks the pretrain path).

    Stochastic pieces (corruption masks, MC samples, Gibbs chains) are made
    deterministic by fixing the rng across both analytic and numeric
    evaluation, so the finite difference probes the same realized loss.
    """
    with _enable_x64():
        rng = jax.random.PRNGKey(seed)
        p64 = jax.tree_util.tree_map(
            lambda a: jnp.asarray(np.asarray(a), jnp.float64), params)
        x64 = jnp.asarray(np.asarray(x), jnp.float64)

        def loss(p):
            return layer.pretrain_loss(p, x64, rng)

        loss_jit = jax.jit(loss)
        analytic = jax.grad(loss)(p64)
        nprng = np.random.default_rng(seed)
        failures = []
        for k in param_util.ordered_keys(p64):
            def eval_with(arr, k=k):
                pp = dict(p64)
                pp[k] = jnp.asarray(arr)
                return float(loss_jit(pp))

            fails, _ = _fd_check_one(
                p64[k], np.asarray(analytic[k]), eval_with, epsilon,
                max_rel_error, min_abs_error, subset, nprng)
            failures.extend((k, i, a, num, rel) for i, a, num, rel in fails)
        if failures:
            print(f"Pretrain gradient check: {len(failures)} failures")
            for k, i, a, num, rel in failures[:20]:
                print(f"  {k}[{i}]: analytic={a:.3e} numeric={num:.3e} "
                      f"rel={rel:.3e}")
        return not failures


def _with(params, li, k, arr):
    """Rebuild the param pytree with layer li's key k replaced by arr
    (arr is the mutated numpy buffer; re-wrap to jnp)."""
    out = []
    for i, lp in enumerate(params):
        if i == li:
            lp = dict(lp)
            lp[k] = jnp.asarray(arr)
        out.append(lp)
    return out
