"""ComputationGraph — arbitrary-DAG networks with multi-input/multi-output.

(ref: nn/graph/ComputationGraph.java (2897 LoC): topologicalOrder :122,
init :312, fit(MultiDataSetIterator) :828, feedForward :1212,
calcBackpropGradients :1421).  As with MultiLayerNetwork, the eager
vertex-by-vertex dispatch becomes one traced function over the topological
order, compiled once by XLA; gradients come from jax.value_and_grad over
the summed output-layer losses instead of the reference's hand-scheduled
reverse pass.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.analysis import sanitizer
from deeplearning4j_tpu.monitor import events
from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.nn import params as param_util
from deeplearning4j_tpu.nn.conf.graph_conf import (
    ComputationGraphConfiguration, GraphVertexConf, LayerVertex)
from deeplearning4j_tpu.nn.conf.layers import BaseOutputLayer, LossLayer
from deeplearning4j_tpu.nn.listeners import IterationListener
from deeplearning4j_tpu.ops import bucketing
from deeplearning4j_tpu.ops import dtypes as dtype_ops
from deeplearning4j_tpu.ops import updaters as upd_ops
from deeplearning4j_tpu.nn.multilayer import (
    BIAS_KEYS, WEIGHT_KEYS, _updater_for)


class ComputationGraph:
    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self.order = conf.topological_order()
        self.net_params: Optional[Dict[str, dict]] = None
        self.net_state: Optional[Dict[str, dict]] = None
        self.opt_states: Optional[Dict[str, Any]] = None
        self.updaters: Dict[str, upd_ops.Updater] = {}
        self.iteration = 0
        self.epoch = 0
        self.listeners: List[IterationListener] = []
        self._score = float("nan")
        self._key = jax.random.PRNGKey(conf.global_conf.seed)
        self._step_fn = None
        self._output_fn = None
        self._score_fn = None
        self._ext_grad_fn = None
        self._apply_fn = None
        self.last_batch_size = 0
        self.last_etl_time_ms = 0.0
        self.compile_telemetry = bucketing.CompileTelemetry()
        self._bucket_train_ok: Optional[bool] = None

    # ------------------------------------------------------------------
    def init(self, params: Optional[Dict[str, dict]] = None) -> "ComputationGraph":
        conf = self.conf
        types: Dict[str, Any] = {}
        if conf.input_types:
            types.update(dict(zip(conf.network_inputs, conf.input_types)))
        key = jax.random.PRNGKey(conf.global_conf.seed)
        ps: Dict[str, dict] = {}
        ss: Dict[str, dict] = {}
        for name in self.order:
            v = conf.vertices[name]
            in_names = conf.vertex_inputs[name]
            in_types = [types.get(i) for i in in_names]
            if any(t is None for t in in_types):
                # inputs without declared types: best effort via layer n_in
                if isinstance(v, LayerVertex):
                    lc = v.layer_conf()
                    from deeplearning4j_tpu.nn.conf.layers import FrozenLayerConf
                    if isinstance(lc, FrozenLayerConf):
                        lc = lc._inner()
                    n_in = getattr(lc, "n_in", None)
                    if n_in:
                        from deeplearning4j_tpu.nn.conf.inputs import InputType
                        from deeplearning4j_tpu.nn.conf import layers as L
                        if isinstance(lc, (L.GravesLSTM, L.GravesBidirectionalLSTM,
                                           L.RnnOutputLayer)):
                            in_types = [InputType.recurrent(n_in)]
                        else:
                            in_types = [InputType.feed_forward(n_in)]
                    else:
                        raise ValueError(
                            f"Vertex '{name}': set_input_types() required or "
                            f"explicit n_in on the layer")
                else:
                    raise ValueError(
                        f"Vertex '{name}': upstream type unknown — call "
                        f"set_input_types() on the GraphBuilder")
            key, sub = jax.random.split(key)
            p, s, out_t = v.initialize(sub, in_types)
            ps[name] = p
            ss[name] = s
            types[name] = out_t
        self.net_params = params if params is not None else ps
        self.net_state = ss
        self.updaters = {name: _updater_for(self._vertex_layer(name))
                         if isinstance(conf.vertices[name], LayerVertex)
                         else upd_ops.make("sgd")
                         for name in self.order}
        self.opt_states = {name: self.updaters[name].init(self.net_params[name])
                           for name in self.order}
        return self

    def _vertex_layer(self, name: str):
        return self.conf.vertices[name].layer_conf()

    def _output_layer_confs(self) -> Dict[str, Any]:
        out = {}
        for name in self.conf.network_outputs:
            v = self.conf.vertices[name]
            if isinstance(v, LayerVertex):
                lc = v.layer_conf()
                if isinstance(lc, (BaseOutputLayer, LossLayer)):
                    out[name] = lc
        return out

    # ------------------------------------------------------------------
    def _forward_all(self, params, state, inputs: Dict[str, Any],
                     masks: Dict[str, Any], train: bool, rng,
                     preout_for: Sequence[str] = ()):
        """Activate every vertex in topological order.  For vertices named
        in `preout_for` (output layers), record PRE-activations instead."""
        from deeplearning4j_tpu.nn.conf.graph_conf import (
            DuplicateToTimeSeriesVertex, LastTimeStepVertex)
        acts: Dict[str, Any] = dict(inputs)
        out_masks: Dict[str, Any] = dict(masks)
        new_states: Dict[str, dict] = {}
        preouts: Dict[str, Any] = {}
        for vi, name in enumerate(self.order):
            v = self.conf.vertices[name]
            in_names = self.conf.vertex_inputs[name]
            ins = [acts[i] for i in in_names]
            ms = [out_masks.get(i) for i in in_names]
            # named-input semantics (ref: rnn/LastTimeStepVertex.java takes
            # its mask from a NAMED network input; DuplicateToTimeSeries
            # takes T from a named reference sequence)
            if isinstance(v, LastTimeStepVertex) and v.mask_input:
                ms = [out_masks.get(v.mask_input)]
            if isinstance(v, DuplicateToTimeSeriesVertex) and v.ts_input \
                    and len(ins) == 1:
                ins = ins + [acts[v.ts_input]]
                ms = ms + [out_masks.get(v.ts_input)]
            r = jax.random.fold_in(rng, vi)
            if name in preout_for:
                lc = v.layer_conf()
                x = ins[0]
                if train:
                    x = lc._maybe_dropout(x, True, r)
                pre = lc.preoutput(
                    lc._maybe_drop_connect(params[name], train, r), x)
                preouts[name] = pre
                new_states[name] = state[name]
                acts[name] = lc._act(pre)
                out_masks[name] = ms[0] if ms else None
            else:
                def fwd(p, s, ins_, ms_, v=v, r=r):
                    return v.forward(p, s, ins_, train=train, rng=r,
                                     masks=ms_)
                if train and self.conf.global_conf.gradient_checkpointing:
                    # per-vertex remat: recompute this vertex's forward in
                    # the backward pass instead of storing activations
                    fwd = jax.checkpoint(fwd)
                y, ns, m = fwd(params[name], state[name], ins, ms)
                acts[name] = y
                new_states[name] = ns
                out_masks[name] = m
        return acts, preouts, new_states, out_masks

    def _reg_penalty(self, params):
        total = 0.0
        for name in self.order:
            v = self.conf.vertices[name]
            if not isinstance(v, LayerVertex):
                continue
            layer = v.layer_conf()
            lp = params[name]
            l1 = layer.l1 or 0.0
            l2 = layer.l2 or 0.0
            for k, val in lp.items():
                if k in WEIGHT_KEYS:
                    if l1:
                        total = total + l1 * jnp.sum(jnp.abs(val))
                    if l2:
                        total = total + 0.5 * l2 * jnp.sum(val * val)
                elif k in BIAS_KEYS:
                    if layer.l1_bias:
                        total = total + layer.l1_bias * jnp.sum(jnp.abs(val))
                    if layer.l2_bias:
                        total = total + 0.5 * layer.l2_bias * jnp.sum(val * val)
        return total

    # ------------------------------------------------------------------
    @staticmethod
    def _resolve_label_mask(preout, lm, out_mask):
        """Label-mask resolution shared by the training step and the
        gradient checker (nn/gradientcheck._check_cg_x64) so the checked
        function IS the trained function.  compute_score owns any
        [..., None] expansion (RnnOutputLayer expands [N,T] itself);
        only an already-expanded [N,T,1] TIME mask is squeezed — a
        per-example [N,1] mask on a 2-D output broadcasts as-is."""
        if lm is None:
            lm = out_mask if (out_mask is not None
                              and out_mask.ndim == preout.ndim - 1) else None
        if lm is not None and preout.ndim == 3 and lm.ndim == 3 \
                and lm.shape[-1] == 1:
            lm = lm[..., 0]
        return lm

    def _assemble_training_score(self, params, preouts, new_states,
                                 out_masks, ys, lmasks, out_confs, out_pos):
        """Multi-output training score from forward results: per-output
        loss (masked), minibatch reduction, regularization penalty, and
        layer-surfaced aux losses (MoE load balancing).  Single source of
        truth for the step AND the gradient checker."""
        g = self.conf.global_conf
        score = 0.0
        for name, lc in out_confs.items():
            oi = out_pos[name]
            pre = preouts[name]
            lm = self._resolve_label_mask(
                pre, lmasks[oi] if lmasks is not None else None,
                out_masks.get(name))
            per_ex = lc.compute_score(ys[oi], pre, lm)
            score = score + (jnp.mean(per_ex) if g.mini_batch
                             else jnp.sum(per_ex))
        score = score + self._reg_penalty(params)
        for s in new_states.values():
            if isinstance(s, dict) and "moe_aux_loss" in s:
                score = score + s["moe_aux_loss"]
        return score

    def _build_grad_raw(self):
        """The loss-and-gradient half of the graph train step — same
        split and contract as ``MultiLayerNetwork._build_grad_raw``
        (the distributed runtime's all-reduce seam)."""
        g = self.conf.global_conf
        policy = dtype_ops.resolve(g.precision)
        out_confs = self._output_layer_confs()
        if not out_confs:
            raise ValueError("ComputationGraph.fit() needs >=1 output layer "
                             "vertex (OutputLayer/LossLayer)")
        out_names = list(out_confs)
        # labels/masks arrive ordered by conf.network_outputs — index by that
        # position, NOT by position in the (filtered) out_confs dict
        out_pos = {n: self.conf.network_outputs.index(n) for n in out_names}

        def grad_step(params, state, xs, ys, fmasks, lmasks, rng):
            xs_c, fmasks_c = policy.cast_to_compute((xs, fmasks))

            def loss_fn(p):
                pc = policy.cast_to_compute(p)
                inputs = dict(zip(self.conf.network_inputs, xs_c))
                masks = dict(zip(self.conf.network_inputs, fmasks_c)) \
                    if fmasks_c is not None else {}
                acts, preouts, new_states, out_masks = self._forward_all(
                    pc, state, inputs, masks, True, rng, preout_for=out_names)
                preouts = {n: policy.cast_to_accum(v) for n, v in preouts.items()}
                new_states = policy.cast_to_param(new_states)
                score = self._assemble_training_score(
                    p, preouts, new_states, out_masks, ys, lmasks,
                    out_confs, out_pos)
                if not g.minimize:
                    score = -score  # maximize: parity with the MLN step
                return score, new_states

            (score, new_states), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            return score, new_states, grads

        return grad_step

    def _build_step_raw(self):
        grad_step = self._build_grad_raw()

        def step(params, state, opts, xs, ys, fmasks, lmasks, it, rng):
            score, new_states, grads = grad_step(params, state, xs, ys,
                                                 fmasks, lmasks, rng)
            new_params, new_opts = self._apply_updates(params, opts, grads, it)
            return new_params, new_states, new_opts, score

        return step

    def _apply_updates(self, params, opts, grads, it):
        """Traceable gradient→param update over the vertex dict (per-layer
        normalization, LR schedule, learning rule).  Shared by the fused
        train step and the external-gradients path (apply_gradients)."""
        g = self.conf.global_conf
        plan = getattr(self, "_sharding_plan", None)
        new_params, new_opts = {}, {}
        for name in self.order:
            gi = grads[name]
            if not gi:
                new_params[name] = params[name]
                new_opts[name] = opts[name]
                continue
            v = self.conf.vertices[name]
            layer = v.layer_conf() if isinstance(v, LayerVertex) else None
            if type(layer).__name__ == "FrozenLayerConf":
                # frozen vertex (transfer learning): params must not move
                new_params[name] = params[name]
                new_opts[name] = opts[name]
                continue
            if plan is not None:
                # ZeRO reduce-scatter point — see
                # MultiLayerNetwork._apply_updates
                gi = plan.constrain_grads(gi)
            if layer is not None:
                gi = upd_ops.normalize_gradient(
                    gi, layer.gradient_normalization,
                    layer.gradient_normalization_threshold or 1.0)
                lr_base = (layer.learning_rate
                           if layer.learning_rate is not None
                           else g.learning_rate)
            else:
                lr_base = g.learning_rate
            lr = upd_ops.schedule_lr(
                lr_base, g.lr_policy, it,
                decay_rate=g.lr_policy_decay_rate, steps=g.lr_policy_steps,
                power=g.lr_policy_power, schedule_map=g.learning_rate_schedule)
            upd, new_opt = self.updaters[name].apply(gi, opts[name], lr, it)
            new_params[name] = {k: params[name][k] - upd[k]
                                for k in params[name]}
            new_opts[name] = new_opt
        return new_params, new_opts

    def _build_step(self):
        plan = getattr(self, "_sharding_plan", None)
        if plan is not None:
            from deeplearning4j_tpu.parallel import fsdp
            return fsdp.jit_sharded_step(self._build_step_raw(), plan,
                                         self.net_params, self.opt_states)
        return jax.jit(self._build_step_raw(), donate_argnums=(0, 1, 2))

    # ------------------------------------------------------------------
    def fit(self, data, labels=None, epochs: int = 1,
            fused_steps: int = 1):
        """fit(MultiDataSet | DataSet | iterator | (features, labels))
        (ref: ComputationGraph.fit :828).  ``fused_steps=K>1`` fuses K
        same-shape batches into one compiled lax.scan launch — same
        semantics and caveats as MultiLayerNetwork.fit(fused_steps=K):
        listeners fire once per launch, ragged/mixed groups fall back,
        TBPTT and iterations>1 ignore the flag."""
        bucketing.maybe_enable_persistent_cache()
        if labels is not None:
            data = MultiDataSet([np.asarray(data)], [np.asarray(labels)])
        if isinstance(data, DataSet):
            data = MultiDataSet([data.features], [data.labels],
                                [data.features_mask], [data.labels_mask])
        from deeplearning4j_tpu.nn.listeners import TrainingListener

        def epoch_hook(which):
            for lst in self.listeners:
                if isinstance(lst, TrainingListener):
                    getattr(lst, which)(self)

        fuse = (max(1, int(fused_steps))
                if (self.conf.backprop_type != "truncatedbptt"
                    and self.conf.global_conf.iterations <= 1) else 1)
        if self.net_params is None:
            self.init()
        # warm-validate the fused-kernel helper tier (ops/helpers.py) —
        # same contract as MultiLayerNetwork.fit: a kernel rejection
        # disables its tier before the first step traces
        from deeplearning4j_tpu.ops import helpers as pallas_helpers
        pallas_helpers.ensure_validated()
        self._check_trace_token()
        self._ensure_sharding()
        # elastic cluster training (conf.distributed(...)) — same
        # contract as MultiLayerNetwork.fit: batches route through the
        # coordinator barrier step; inert without a coordinator
        if getattr(self, "_dist_session", None) is None \
                and getattr(self.conf.global_conf, "dist_enabled", False):
            from deeplearning4j_tpu import distributed as dist_mod
            self._dist_session = dist_mod.maybe_session(
                self.conf.global_conf)
        dist_sess = getattr(self, "_dist_session", None)
        if dist_sess is not None:
            dist_sess.attach(self)
            fuse = 1   # the distributed step barriers per batch
        # crash-safe resume (conf.fault_tolerance(resume=True)) — same
        # contract as MultiLayerNetwork.fit: restore the newest valid
        # checkpoint, then skip the already-trained epochs/batches
        from deeplearning4j_tpu.nn import checkpoint as ckpt_mod
        skip_epochs, skip_batches = ckpt_mod.maybe_auto_resume(self)
        if dist_sess is not None:
            skip_epochs, skip_batches = dist_sess.resume_position(
                self, skip_epochs, skip_batches)
        if isinstance(data, MultiDataSet):
            batches = [data]
            with sanitizer.armed_fit(self), \
                    monitor.profile_if_configured("fit"), \
                    events.scope(fit_id=events.new_request_id(),
                                 model=type(self).__name__):
                events.emit("fit.start", epochs=epochs,
                            iteration=self.iteration)
                for ep_i in range(epochs):
                    if ep_i < skip_epochs:
                        continue
                    to_skip = skip_batches if ep_i == skip_epochs else 0
                    self._epoch_start_iter = self.iteration - to_skip
                    epoch_hook("on_epoch_start")
                    for mds in batches:
                        if to_skip > 0:
                            to_skip -= 1
                            continue
                        self._fit_batch(mds)
                    epoch_hook("on_epoch_end")
                    self.epoch += 1
                events.emit("fit.end", iteration=self.iteration,
                            epoch=self.epoch)
            return self
        # iterator of DataSet or MultiDataSet — wrapped in the parallel
        # input pipeline so ETL + H2D overlap the jitted step (the MLN
        # fit path's AsyncDataSetIterator, multi-head flavored)
        from deeplearning4j_tpu.datasets.iterators import (
            AsyncDataSetIterator, AsyncMultiDataSetIterator,
            reader_retry_from_conf)
        it = data
        g = self.conf.global_conf
        if (g.pipeline_workers > 0
                and not isinstance(it, AsyncDataSetIterator)
                and getattr(it, "async_supported", lambda: True)()):
            bucket_on = self._bucket_train_enabled()
            gg = self.conf.global_conf
            plan = getattr(self, "_sharding_plan", None)
            min_mult = plan.n_data if plan is not None else 1

            def to_mds(item):
                if isinstance(item, DataSet):
                    item = MultiDataSet(
                        [item.features], [item.labels],
                        [item.features_mask], [item.labels_mask])
                if bucket_on:  # pad on the worker, off the critical path
                    # (lifted to a data-degree multiple under sharding)
                    item = bucketing.bucket_train_multidataset(
                        item, gg, min_multiple=min_mult)[0]
                return item
            it = AsyncMultiDataSetIterator(
                it, queue_size=g.pipeline_prefetch,
                workers=g.pipeline_workers,
                staging_depth=g.pipeline_staging_depth,
                # sharded fit scatters batches across the mesh itself
                device_put=(plan is None), transform=to_mds,
                reader_retry=reader_retry_from_conf(g))
        # MultiDataSetIterator protocol when available; plain
        # __iter__-only iterables (duck-typed inputs) still work
        has_protocol = (callable(getattr(it, "has_next", None))
                        and callable(getattr(it, "next", None)))

        def batches():
            if has_protocol:
                while it.has_next():
                    with monitor.span("fit/step", phase="data_wait"):
                        item = it.next()
                    yield item
            else:
                yield from it

        try:
            # DL4J_SANITIZE: debug-nans/rank checks for the duration,
            # retrace-budget assertion on clean exit (analysis/sanitizer);
            # the events.scope correlates every span/event under one fit
            with sanitizer.armed_fit(self), \
                    monitor.profile_if_configured("fit"), \
                    events.scope(fit_id=events.new_request_id(),
                                 model=type(self).__name__):
                events.emit("fit.start", epochs=epochs,
                            iteration=self.iteration)
                for ep_i in range(epochs):
                    if ep_i < skip_epochs:
                        continue  # resumed past this epoch entirely
                    to_skip = skip_batches if ep_i == skip_epochs else 0
                    self._epoch_start_iter = self.iteration - to_skip
                    epoch_hook("on_epoch_start")
                    if callable(getattr(it, "reset", None)):
                        it.reset()
                    pending = []
                    for item in batches():
                        if to_skip > 0:
                            # replay-skip the already-trained prefix —
                            # consume to keep stream position, don't fit
                            to_skip -= 1
                            continue
                        if isinstance(item, DataSet):
                            item = MultiDataSet(
                                [item.features], [item.labels],
                                [item.features_mask], [item.labels_mask])
                        if fuse > 1:
                            pending.append(item)
                            if len(pending) == fuse:
                                self._fit_fused_group(pending)
                                pending = []
                        else:
                            self._fit_batch(item)
                    for item in pending:
                        self._fit_batch(item)
                    epoch_hook("on_epoch_end")
                    self.epoch += 1
                events.emit("fit.end", iteration=self.iteration,
                            epoch=self.epoch)
        finally:
            if isinstance(it, AsyncDataSetIterator):
                it.close()
        return self

    def _build_fused_step(self, k: int):
        """K graph train steps in one lax.scan launch (see
        MultiLayerNetwork._build_fused_step — identical contract over
        the vertex-dict carry)."""
        raw = self._build_step_raw()

        def strip_rnn(state):
            return {n: {kk: v for kk, v in s.items() if kk != "rnn_state"}
                    for n, s in state.items()}

        def k_steps(params, state, opts, xs, ys, fms, lms, it0, key):
            def body(carry, inp):
                p, s, o = carry
                i, x, y, fm, lm = inp
                p, s, o, score = raw(p, s, o, x, y, fm, lm, it0 + i,
                                     jax.random.fold_in(key, i))
                return (p, strip_rnn(s), o), score
            (params, state, opts), scores = jax.lax.scan(
                body, (params, strip_rnn(state), opts),
                (jnp.arange(k), xs, ys, fms, lms))
            return params, state, opts, scores[-1]

        return jax.jit(k_steps, donate_argnums=(0, 1, 2))  # dl4j: noqa[DL4J104] one jitted fn per k, cached in _fused_fns[k]

    def _fit_fused_group(self, group):
        if self.net_params is None:
            self.init()
        self._check_trace_token()
        if getattr(self, "_sharding_plan", None) is not None:
            # stacking the multi-head tuple batches for a sharded scan is
            # not supported yet — per-step keeps exact sharded numerics
            for m in group:
                self._fit_batch(m)
            return
        sizes = [m.num_examples() for m in group]
        # ragged groups become bucket-uniform and stay on the fused scan
        # path instead of degrading to per-step (see MultiLayerNetwork)
        group = [self._maybe_bucket_train(m)[0] for m in group]

        def shape_sig(m):
            # per-ELEMENT mask presence: MultiDataSet wraps a missing
            # mask as [None], so a top-level None check alone would fuse
            # masked and unmasked batches together (wrong gradients)
            def mask_sig(ms):
                return None if ms is None else tuple(
                    x is None for x in ms)
            return (tuple((f.shape, f.dtype) for f in m.features),
                    tuple((l.shape, l.dtype) for l in m.labels),
                    mask_sig(m.features_masks), mask_sig(m.labels_masks))
        if len({shape_sig(m) for m in group}) != 1:
            for m in group:
                self._fit_batch(m)
            return
        if getattr(self, "_fused_fns", None) is None:
            self._fused_fns = {}
            self._fit_batch(group[0])   # carried-state structure warmup
            group, sizes = group[1:], sizes[1:]
            if not group:
                return
        k = len(group)
        if k not in self._fused_fns:
            self._fused_fns[k] = self._build_fused_step(k)

        def stack_tuple(get, present):
            if not present:
                return None
            n_el = len(get(group[0]))
            return tuple(
                (jnp.stack([jnp.asarray(get(m)[i]) for m in group])
                 if get(group[0])[i] is not None else None)
                for i in range(n_el))

        xs = tuple(jnp.stack([jnp.asarray(m.features[i]) for m in group])
                   for i in range(len(group[0].features)))
        ys = tuple(jnp.stack([jnp.asarray(m.labels[i]) for m in group])
                   for i in range(len(group[0].labels)))
        fms = stack_tuple(lambda m: m.features_masks,
                          group[0].features_masks is not None)
        lms = stack_tuple(lambda m: m.labels_masks,
                          group[0].labels_masks is not None)
        fresh = self.compile_telemetry.record(f"fused_step_k{k}",
                                              (xs, ys, fms, lms))
        self._key, sub = jax.random.split(self._key)
        it_arr = jnp.asarray(self.iteration, jnp.int32)
        t_step = time.perf_counter()
        with monitor.span("fit/step", phase="jit_call"), \
                sanitizer.guard_step(compiling=fresh):
            (self.net_params, self.net_state, self.opt_states,
             score) = self._fused_fns[k](
                self.net_params, self.net_state, self.opt_states,
                xs, ys, fms, lms, it_arr, sub)
        with monitor.span("fit/step", phase="block_until_ready"):
            jax.block_until_ready(score)
        self._strip_rnn_state()
        self._score = score
        self.iteration += k
        self.last_batch_size = sum(sizes)
        monitor.record_fit_step(self.last_batch_size,
                                time.perf_counter() - t_step, score)
        with monitor.span("fit/step", phase="listeners"):
            for lst in self.listeners:
                lst.iteration_done(self, self.iteration)

    def _check_trace_token(self):
        """See MultiLayerNetwork._check_trace_token — retrace when the
        ambient sequence-parallel regime or precision policy changes."""
        from deeplearning4j_tpu.parallel import fsdp
        from deeplearning4j_tpu.parallel import sequence as seq_ops
        tok = (seq_ops.cache_token(),
               dtype_ops.resolve(self.conf.global_conf.precision),
               self.conf.global_conf.gradient_checkpointing,
               fsdp.conf_key(self.conf.global_conf),
               getattr(self, "_infer_quant", None))
        if tok != getattr(self, "_trace_token", None):
            self._trace_token = tok
            self._step_fn = self._score_fn = self._output_fn = None
            self._rnn_step_fn = None
            self._ext_grad_fn = self._apply_fn = None
            self._score_ex_fn = None
            self._dist_cache = None
            self._fused_fns = None
            self.compile_telemetry.invalidate()

    def _ensure_sharding(self):
        """Activate/deactivate the conf-declared sharding plan — see
        MultiLayerNetwork._ensure_sharding (same contract over the
        vertex-dict pytrees)."""
        from deeplearning4j_tpu.parallel import fsdp
        plan = (None if self.conf.backprop_type == "truncatedbptt"
                else fsdp.plan_from_conf(self.conf.global_conf))
        if fsdp.plan_key(plan) == fsdp.plan_key(
                getattr(self, "_sharding_plan", None)):
            return
        self._sharding_plan = plan
        self._step_fn = None
        self._fused_fns = None
        # inference entry points re-jit too: the output path carries the
        # plan's in/out_shardings (sharded serving, ROADMAP 3a)
        self._output_fn = None
        self._rnn_step_fn = None
        if plan is not None and self.net_params is not None:
            fsdp.place_model(plan, self)

    def _replace_on_mesh(self):
        """Re-commit params/updater/state to the active plan's layout
        after a host-side overwrite (set_params / checkpoint restore)."""
        plan = getattr(self, "_sharding_plan", None)
        if plan is not None:
            from deeplearning4j_tpu.parallel import fsdp
            fsdp.place_model(plan, self)

    # ------------------------------------------------------------------
    # Shape bucketing (ops/bucketing.py) — see MultiLayerNetwork
    # ------------------------------------------------------------------
    def _bucket_train_enabled(self) -> bool:
        g = self.conf.global_conf
        if not g.shape_bucketing or self.conf.backprop_type == "truncatedbptt":
            return False
        if self._bucket_train_ok is None:
            self._bucket_train_ok = bucketing.pad_supported(self)
        return self._bucket_train_ok

    def _maybe_bucket_train(self, mds, scale_loss: bool = True):
        if self._bucket_train_enabled():
            return bucketing.bucket_train_multidataset(
                mds, self.conf.global_conf, scale_loss=scale_loss)
        return mds, None

    def _fit_batch(self, mds: MultiDataSet):
        if self.net_params is None:
            self.init()
        if self.conf.backprop_type == "truncatedbptt" \
                and any(f.ndim == 3 for f in mds.features):
            self._fit_tbptt(mds)
            return
        dist_sess = getattr(self, "_dist_session", None)
        if dist_sess is not None:
            # cluster step — see MultiLayerNetwork._fit_batch
            from deeplearning4j_tpu.distributed import worker as dist_worker
            dist_worker.fit_batch(self, mds, dist_sess, is_graph=True)
            return
        self._check_trace_token()
        if self._step_fn is None:
            self._step_fn = self._build_step()
        self.last_batch_size = mds.num_examples()
        t_step = time.perf_counter()
        plan = getattr(self, "_sharding_plan", None)
        if plan is not None:
            from deeplearning4j_tpu.parallel import fsdp
            with monitor.span("fit/step", phase="bucket"):
                norm = fsdp.normalize_batch(self, mds, plan.n_data,
                                            is_graph=True)
            if norm is None:
                return
            batch, n, bucket = norm
            self.last_batch_size = n
            fresh = self.compile_telemetry.record("sharded_step", batch,
                                                  bucket=bucket)
            with monitor.span("fit/step", phase="shard_h2d"):
                xs, ys, fm, lm = fsdp.shard_put(plan, batch)
        else:
            with monitor.span("fit/step", phase="bucket"):
                mds, bucket = self._maybe_bucket_train(mds)
            with monitor.span("fit/step", phase="h2d"):
                xs = tuple(jnp.asarray(f) for f in mds.features)
                ys = tuple(jnp.asarray(l) for l in mds.labels)
                fm = (tuple(None if m is None else jnp.asarray(m)
                            for m in mds.features_masks)
                      if mds.features_masks is not None else None)
                lm = (tuple(None if m is None else jnp.asarray(m)
                            for m in mds.labels_masks)
                      if mds.labels_masks is not None else None)
            fresh = self.compile_telemetry.record(
                "train_step", (xs, ys, fm, lm), bucket=bucket)
        self._key, sub = jax.random.split(self._key)
        # the iteration scalar moves H2D here, OUTSIDE the guarded
        # dispatch — inside it every transfer is a bug
        it_arr = jnp.asarray(self.iteration, jnp.int32)
        with monitor.span("fit/step", phase="jit_call"), \
                sanitizer.guard_step(compiling=fresh):
            (self.net_params, self.net_state, self.opt_states,
             score) = self._step_fn(
                self.net_params, self.net_state, self.opt_states, xs, ys,
                fm, lm, it_arr, sub)
        with monitor.span("fit/step", phase="block_until_ready"):
            jax.block_until_ready(score)
        self._strip_rnn_state()
        self._score = score
        self.iteration += 1
        monitor.record_fit_step(self.last_batch_size,
                                time.perf_counter() - t_step, score)
        with monitor.span("fit/step", phase="listeners"):
            for lst in self.listeners:
                lst.iteration_done(self, self.iteration)

    def _strip_rnn_state(self):
        if self.net_state is None:
            return
        self.net_state = {n: {k: v for k, v in s.items() if k != "rnn_state"}
                          for n, s in self.net_state.items()}

    def _fit_tbptt(self, mds: MultiDataSet):
        """Truncated BPTT over time segments with carried RNN state —
        the graph analog of MultiLayerNetwork._fit_tbptt
        (ref: ComputationGraph.doTruncatedBPTT :1476).  Time-major-3D
        features [N, T, C] are segmented along T; the per-vertex
        rnn_state carries across segments inside one batch and is
        cleared between batches."""
        if self.net_params is None:
            self.init()
        self._check_trace_token()
        if self._step_fn is None:
            self._step_fn = self._build_step()
        self.last_batch_size = mds.num_examples()
        T = max(f.shape[1] for f in mds.features if f.ndim == 3)
        L = self.conf.tbptt_fwd_length
        self.rnn_clear_previous_state()

        def seg(arr, sl):
            return arr[:, sl] if (arr is not None and arr.ndim == 3) else arr

        def seg_mask(m, sl):
            # masks are [N, T] (or [N, T, 1]); slice any mask whose time
            # axis matches the full length — 2-D masks included
            # (MultiLayerNetwork._fit_tbptt slices its masks the same way)
            if m is None or m.ndim < 2 or m.shape[1] != T:
                return m
            return m[:, sl]

        for t0 in range(0, T, L):
            sl = slice(t0, min(t0 + L, T))
            xs = tuple(jnp.asarray(seg(f, sl)) for f in mds.features)
            ys = tuple(jnp.asarray(seg(l, sl)) for l in mds.labels)
            fm = (tuple(None if m is None else jnp.asarray(seg_mask(m, sl))
                        for m in mds.features_masks)
                  if mds.features_masks is not None else None)
            lm = (tuple(None if m is None else jnp.asarray(seg_mask(m, sl))
                        for m in mds.labels_masks)
                  if mds.labels_masks is not None else None)
            self._key, sub = jax.random.split(self._key)
            (self.net_params, self.net_state, self.opt_states,
             score) = self._step_fn(
                self.net_params, self.net_state, self.opt_states, xs, ys,
                fm, lm, jnp.asarray(self.iteration, jnp.int32), sub)
            self._score = score
            self.iteration += 1
            for lst in self.listeners:
                lst.iteration_done(self, self.iteration)

    # ------------------------------------------------------------------
    # Stateful RNN inference (ref: ComputationGraph.rnnTimeStep :1569)
    # ------------------------------------------------------------------
    def _rnn_step_raw(self):
        """The pure carried decode step — the seam shared by
        :meth:`rnn_time_step` and the serving decode pool
        (``server/decode.py``): ``(params, base_state, carries, xs, ms)
        -> (outs, new_carries)`` with ``carries`` a dict keyed by the
        recurrent vertices' names.  Explicit carries keep the traced
        structure closed under iteration: one compiled program serves
        every step of an autoregressive stream (see
        MultiLayerNetwork._rnn_step_raw).  The forward traces under
        ``kv_decode_scope``: attention vertices decode incrementally
        against a KV-ring carry leaf instead of re-running their
        window."""
        from deeplearning4j_tpu.parallel import sequence as seq_ops
        policy = dtype_ops.resolve(self.conf.global_conf.precision)

        def rnn_fn(params, state, carries, xs, ms):
            pc, cc, xs_c, ms_c = policy.cast_to_compute(
                (params, carries, xs, ms))
            st = {}
            for n, s in state.items():
                s = {k: v for k, v in s.items() if k != "rnn_state"}
                if n in cc:
                    s["rnn_state"] = cc[n]
                st[n] = s
            ins = dict(zip(self.conf.network_inputs, xs_c))
            masks = ({n: m for n, m in zip(self.conf.network_inputs, ms_c)
                      if m is not None} if ms_c is not None else {})
            with seq_ops.kv_decode_scope():
                acts, _, new_states, _ = self._forward_all(
                    pc, st, ins, masks, False, jax.random.PRNGKey(0))
            outs = tuple(policy.cast_to_param(acts[n])
                         for n in self.conf.network_outputs)
            new_carries = {n: ns["rnn_state"]
                           for n, ns in new_states.items()
                           if isinstance(ns, dict) and "rnn_state" in ns}
            return outs, policy.cast_to_param(new_carries)

        return rnn_fn

    def rnn_carry_template(self, n: int, feature_tails=None,
                           dtype=jnp.float32):
        """Zero-initialized carry dict (vertex name → carry pytree) for
        ``n`` concurrent streams, discovered via ``jax.eval_shape`` over
        the carried step.  ``feature_tails`` is one per-example shape
        tail per network input (``(T, C)``); defaults from the conf's
        declared input types."""
        if self.net_params is None:
            self.init()
        if feature_tails is None:
            if not self.conf.input_types:
                raise ValueError("rnn_carry_template needs explicit "
                                 "feature_tails= (no set_input_types())")
            feature_tails = [(1, it.size) if it.kind == "rnn"
                             else (it.size,)
                             for it in self.conf.input_types]
        xs = tuple(jax.ShapeDtypeStruct(
            (int(n),) + tuple(int(d) for d in t), dtype)
            for t in feature_tails)
        base = {k: {kk: v for kk, v in s.items() if kk != "rnn_state"}
                for k, s in self.net_state.items()}
        _, spec = jax.eval_shape(self._rnn_step_raw(), self.net_params,
                                 base, {}, xs, None)
        return jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), spec)

    def rnn_time_step(self, *inputs):
        """Single/multi-step stateful inference: each call consumes
        [N, T, C] sequences, returns the network outputs, and carries
        every recurrent vertex's hidden state to the next call.

        Every call re-dispatches ONE cached jitted step: the first call
        materializes a zero carry template so the carry structure (and
        therefore the trace) is identical with and without stored state
        — token-by-token sampling pays neither op-by-op dispatch nor a
        second steady-state retrace."""
        if self.net_params is None:
            self.init()
        self._check_trace_token()
        if getattr(self, "_rnn_step_fn", None) is None:
            self._rnn_step_fn = jax.jit(self._rnn_step_raw())
        xs = tuple(jnp.asarray(x) for x in inputs)
        carries = {n: s["rnn_state"] for n, s in self.net_state.items()
                   if "rnn_state" in s}
        if not carries:
            carries = self.rnn_carry_template(
                xs[0].shape[0],
                feature_tails=[tuple(x.shape[1:]) for x in xs],
                dtype=xs[0].dtype)
        self.compile_telemetry.record("rnn_time_step", (xs, carries))
        outs, new_carries = self._rnn_step_fn(
            self.net_params,
            {n: {k: v for k, v in s.items() if k != "rnn_state"}
             for n, s in self.net_state.items()},
            carries, xs, None)
        merged = {}
        for name, old in self.net_state.items():
            s = {k: v for k, v in old.items() if k != "rnn_state"}
            if name in new_carries:
                s["rnn_state"] = new_carries[name]
            merged[name] = s
        self.net_state = merged
        return outs

    def rnn_clear_previous_state(self):
        """(ref: ComputationGraph.rnnClearPreviousState :1608)"""
        self._strip_rnn_state()

    # ------------------------------------------------------------------
    def quantize_inference(self, mode: str = "int8"):
        """Weight-only quantized serving — see
        MultiLayerNetwork.quantize_inference (same tier registry,
        kill switches and lazy re-quantization over the vertex-dict
        param pytree)."""
        from deeplearning4j_tpu.ops import helpers as pallas_helpers
        if mode is None:
            self._infer_quant = None
            self._q_params = None
            self._check_trace_token()
            return self
        if self.net_params is None:
            self.init()
        self._ensure_sharding()
        mode = str(mode).lower()
        if mode not in ("int8", "fp8"):
            raise ValueError(f"unknown inference quantization '{mode}' "
                             "(known: int8, fp8)")
        if getattr(self, "_sharding_plan", None) is not None:
            return self  # sharded serving keeps the dense fsdp layout
        tier = f"{mode}_infer"
        if not (pallas_helpers.precision_enabled(tier, True)
                and pallas_helpers.ensure_precision_validated(tier)):
            self._infer_quant = None
            self._q_params = None
            self._check_trace_token()
            return self
        self._infer_quant = mode
        self._q_params = None
        self._check_trace_token()
        return self

    def _infer_params(self):
        """See MultiLayerNetwork._infer_params."""
        quant = getattr(self, "_infer_quant", None)
        if quant is None:
            return self.net_params
        if getattr(self, "_q_params", None) is None \
                or getattr(self, "_q_iteration", -1) != self.iteration:
            from deeplearning4j_tpu.ops import quantize as qz
            self._q_params, self._q_stats = qz.quantize_params(
                self.net_params, quant)
            self._q_iteration = self.iteration
        return self._q_params

    def output(self, *inputs, train: bool = False):
        """Multi-output inference in topological order
        (ref: ComputationGraph feedForward/outputs)."""
        if self.net_params is None:
            self.init()
        self._check_trace_token()
        self._ensure_sharding()
        if self._output_fn is None:
            policy = dtype_ops.resolve(self.conf.global_conf.precision)
            quant = getattr(self, "_infer_quant", None)

            def out_fn(params, state, xs, ms):
                if quant is not None:
                    # dequant-in-trace: int8/fp8 codes + per-channel
                    # scales expand inside the compiled program
                    from deeplearning4j_tpu.ops import quantize as qz
                    params = qz.dequantize_params(params)
                pc, xs_c, ms_c = policy.cast_to_compute((params, xs, ms))
                ins = dict(zip(self.conf.network_inputs, xs_c))
                masks = ({n: m for n, m in zip(self.conf.network_inputs,
                                               ms_c) if m is not None}
                         if ms_c is not None else {})
                acts, _, _, _ = self._forward_all(pc, state, ins, masks,
                                                  False, jax.random.PRNGKey(0))
                return tuple(policy.cast_to_param(acts[n])
                             for n in self.conf.network_outputs)
            out_plan = getattr(self, "_sharding_plan", None)
            if out_plan is not None:
                # sharded serving (ROADMAP 3a): pjit'd output with the
                # plan's in/out shardings — see MultiLayerNetwork.output
                from deeplearning4j_tpu.parallel import fsdp
                self._output_fn = fsdp.jit_sharded_output(
                    out_fn, out_plan, self.net_params)
            else:
                self._output_fn = jax.jit(out_fn)
        state = {n: {k: v for k, v in s.items() if k != "rnn_state"}
                 for n, s in self.net_state.items()}
        g = self.conf.global_conf
        plan = getattr(self, "_sharding_plan", None)
        masks = unpad = bucket = None
        ms_p = [None] * len(inputs)
        if g.shape_bucketing:
            xs_p, ms_p, pairs, n = [], [], [], None
            for x in inputs:
                xp, mp, n, t, b = bucketing.bucket_inference_features(
                    x, None, g)
                xs_p.append(xp)
                ms_p.append(mp)
                pairs.append((t, b[1]))
            inputs = xs_p
            bucket = (b[0], tuple(tb for _, tb in pairs))
            unpad = (n, pairs)
        if plan is not None:
            # batch rows must divide the mesh's data degree; zero rows
            # are exact at inference and sliced back off below
            from deeplearning4j_tpu.parallel import fsdp
            padded = [fsdp.pad_inference_rows(x, m, plan.n_data)
                      for x, m in zip(inputs, ms_p)]
            if any(nr is not None for _, _, nr in padded):
                n0 = next(nr for _, _, nr in padded if nr is not None)
                inputs = [x for x, _, _ in padded]
                ms_p = [m for _, m, _ in padded]
                if unpad is None:
                    unpad = (n0, [])
        if any(m is not None for m in ms_p):
            # explicit H2D for the masks, like the inputs below — a
            # numpy mask handed to the jitted fn transfers implicitly
            masks = tuple(None if m is None else jnp.asarray(m)
                          for m in ms_p)
        xs = tuple(jnp.asarray(x) for x in inputs)
        self.compile_telemetry.record("output", (xs, masks), bucket=bucket)
        outs = self._output_fn(self._infer_params(), state, xs, masks)
        if unpad is not None:
            n, pairs = unpad
            outs = tuple(self._unpad_graph_output(o, n, pairs)
                         for o in outs)
        return outs

    def warmup_inference(self, feature_dims, max_batch: int = 32,
                         batch_sizes=None, dtype=np.float32) -> dict:
        """ComputationGraph analog of
        ``MultiLayerNetwork.warmup_inference``: pre-compile the jitted
        multi-input ``output`` path for every batch bucket on the
        serving ladder.  ``feature_dims`` is one per-example shape tail
        per network input (a single tail is broadcast to all inputs)."""
        if self.net_params is None:
            self.init()
        dims = list(feature_dims)
        if not dims or not isinstance(dims[0], (tuple, list)):
            dims = [tuple(dims)] * len(self.conf.network_inputs)
        dims = [tuple(int(d) for d in t) for t in dims]
        g = self.conf.global_conf
        ladder = bucketing.warmup_ladder(
            batch_sizes or g.bucket_batch_sizes, max_batch)
        t0 = time.perf_counter()
        for nb in ladder:
            outs = self.output(*[np.zeros((nb,) + t, dtype) for t in dims])
            jax.block_until_ready(outs)
        return {"buckets": ladder,
                "warmup_sec": round(time.perf_counter() - t0, 3)}

    @staticmethod
    def _unpad_graph_output(out, n, time_pairs):
        """Slice one padded graph output back to the real extent: rows
        always; the time axis when it matches a padded input's time
        bucket (multi-input graphs may mix time lengths)."""
        out = out[:n]
        for t, tb in time_pairs:
            if t is not None and tb != t and out.ndim >= 3 \
                    and out.shape[1] == tb:
                return out[:, :t]
        return out

    def feed_forward(self, *inputs, train: bool = False):
        """All vertex activations by name (ref: ComputationGraph.feedForward
        :1143) — the UI's conv-activation capture reads these."""
        if self.net_params is None:
            self.init()
        ins = dict(zip(self.conf.network_inputs,
                       (jnp.asarray(x) for x in inputs)))
        state = {n: {k: v for k, v in s.items() if k != "rnn_state"}
                 for n, s in self.net_state.items()}
        acts, _, _, _ = self._forward_all(self.net_params, state, ins, {},
                                          train, jax.random.PRNGKey(0))
        return acts

    def score(self, data: Optional[Union[DataSet, MultiDataSet]] = None) -> float:
        if data is None:
            return float(self._score)
        if isinstance(data, DataSet):
            data = MultiDataSet([data.features], [data.labels],
                                [data.features_mask], [data.labels_mask])
        self._check_trace_token()
        if self._score_fn is None:
            out_confs = self._output_layer_confs()
            out_pos = {n: self.conf.network_outputs.index(n) for n in out_confs}
            g = self.conf.global_conf
            policy = dtype_ops.resolve(g.precision)

            def score_fn(params, state, xs, ys, fms, lms):
                pc, xs_c, fm_c = policy.cast_to_compute((params, xs, fms))
                inputs = dict(zip(self.conf.network_inputs, xs_c))
                masks = ({n: m for n, m in zip(self.conf.network_inputs,
                                               fm_c) if m is not None}
                         if fm_c is not None else {})
                _, preouts, _, out_masks = self._forward_all(
                    pc, state, inputs, masks, False, jax.random.PRNGKey(0),
                    preout_for=list(out_confs))
                total = 0.0
                for name, lc in out_confs.items():
                    pre = policy.cast_to_accum(preouts[name])
                    lm = self._resolve_label_mask(
                        pre, lms[out_pos[name]] if lms is not None else None,
                        out_masks.get(name))
                    per_ex = lc.compute_score(ys[out_pos[name]], pre, lm)
                    total = total + (jnp.mean(per_ex) if g.mini_batch
                                     else jnp.sum(per_ex))
                return total + self._reg_penalty(params)

            self._score_fn = jax.jit(score_fn)
        data, bucket = self._maybe_bucket_train(data)
        xs = tuple(jnp.asarray(f) for f in data.features)
        ys = tuple(jnp.asarray(l) for l in data.labels)

        def mask_tuple(ms):
            if ms is None or all(m is None for m in ms):
                return None
            return tuple(None if m is None else jnp.asarray(m) for m in ms)

        fms = mask_tuple(data.features_masks)
        lms = mask_tuple(data.labels_masks)
        self.compile_telemetry.record("score", (xs, ys, fms, lms),
                                      bucket=bucket)
        return float(self._score_fn(self.net_params, self.net_state,
                                    xs, ys, fms, lms))

    def evaluate(self, iterator_or_dataset, output_idx: int = 0):
        from deeplearning4j_tpu.nn.evaluation import Evaluation
        ev = Evaluation()
        if isinstance(iterator_or_dataset, (DataSet, MultiDataSet)):
            batches = [iterator_or_dataset]
        else:
            iterator_or_dataset.reset()
            batches = list(iterator_or_dataset)
        for ds in batches:
            if isinstance(ds, DataSet):
                feats, labels = [ds.features], [ds.labels]
            else:
                feats, labels = ds.features, ds.labels
            outs = self.output(*feats)
            ev.eval(labels[output_idx], jax.device_get(outs[output_idx]))
        return ev

    # ------------------------------------------------------------------
    def params(self) -> jnp.ndarray:
        """Canonical flat view: vertices in topological order."""
        plist = [self.net_params[n] for n in self.order]
        return param_util.flatten(plist)

    def set_params(self, flat) -> None:
        plist = [self.net_params[n] for n in self.order]
        new = param_util.unflatten(flat, plist)
        self.net_params = {n: new[i] for i, n in enumerate(self.order)}
        self._replace_on_mesh()

    def num_params(self) -> int:
        return param_util.num_params([self.net_params[n] for n in self.order])

    def param_table(self) -> Dict[str, jnp.ndarray]:
        """Named param map keyed ``"<vertexName>_<paramName>"`` (ref:
        Model.paramTable on ComputationGraph)."""
        if self.net_params is None:
            self.init()
        return {f"{n}_{k}": v for n in self.order
                for k, v in self.net_params[n].items()}

    def _split_param_key(self, key: str):
        # vertex names may themselves contain '_' and so may param names
        # (f_W, b_RW) — match the longest vertex-name prefix
        for n in sorted(self.net_params, key=len, reverse=True):
            if key.startswith(n + "_"):
                return n, key[len(n) + 1:]
        raise KeyError(f"no vertex owns param key '{key}'")

    def get_param(self, key: str) -> jnp.ndarray:
        name, k = self._split_param_key(key)
        return self.net_params[name][k]

    def set_param(self, key: str, value) -> None:
        name, k = self._split_param_key(key)
        cur = self.net_params[name][k]
        value = jnp.asarray(value, cur.dtype)
        if value.shape != cur.shape:
            raise ValueError(f"setParam('{key}'): shape {value.shape} != "
                             f"{cur.shape}")
        self.net_params[name] = {**self.net_params[name], k: value}

    def updater_state_flat(self) -> jnp.ndarray:
        leaves = jax.tree_util.tree_leaves(
            [self.opt_states[n] for n in self.order])
        if not leaves:
            return jnp.zeros((0,), jnp.float32)
        # host-side gather for concrete arrays: op-by-op concatenate
        # over the mixed NamedShardings an FSDP model carries
        # miscomputes (see nn/params.flatten)
        if any(isinstance(l, jax.core.Tracer) for l in leaves):
            return jnp.concatenate([jnp.ravel(l) for l in leaves])
        return jnp.asarray(np.concatenate(
            [np.ravel(np.asarray(l)) for l in leaves]))

    def set_updater_state_flat(self, flat) -> None:
        ordered = [self.opt_states[n] for n in self.order]
        leaves, treedef = jax.tree_util.tree_flatten(ordered)
        out, off = [], 0
        flat = jnp.asarray(flat).reshape(-1)
        for l in leaves:
            size = int(np.prod(l.shape))
            out.append(flat[off:off + size].reshape(l.shape).astype(l.dtype))
            off += size
        restored = jax.tree_util.tree_unflatten(treedef, out)
        self.opt_states = {n: restored[i] for i, n in enumerate(self.order)}
        self._replace_on_mesh()

    def set_listeners(self, *listeners):
        self.listeners = list(listeners)
        return self

    # ------------------------------------------------------------------
    def score_examples(self, data, add_regularization_terms: bool = False):
        """Per-example scores without minibatch averaging, summed over all
        output layers (ref: ComputationGraph.scoreExamples — the
        anomaly-detection API; addRegularizationTerms adds the graph's
        l1/l2 penalty to every example)."""
        if self.net_params is None:
            self.init()
        self._check_trace_token()
        if getattr(self, "_score_ex_fn", None) is None:
            g = self.conf.global_conf
            policy = dtype_ops.resolve(g.precision)
            out_confs = self._output_layer_confs()
            out_names = list(out_confs)
            out_pos = {n: self.conf.network_outputs.index(n)
                       for n in out_names}

            def score_ex(params, state, xs, ys, fmasks, lmasks, add_reg):
                pc, xs_c, fm_c = policy.cast_to_compute((params, xs, fmasks))
                inputs = dict(zip(self.conf.network_inputs, xs_c))
                masks = dict(zip(self.conf.network_inputs, fm_c)) \
                    if fm_c is not None else {}
                _, preouts, _, out_masks = self._forward_all(
                    pc, state, inputs, masks, False, jax.random.PRNGKey(0),
                    preout_for=out_names)
                total = 0.0
                for name, lc in out_confs.items():
                    pre = policy.cast_to_accum(preouts[name])
                    lm = self._resolve_label_mask(
                        pre, lmasks[out_pos[name]] if lmasks is not None
                        else None, out_masks.get(name))
                    total = total + lc.compute_score(ys[out_pos[name]], pre,
                                                     lm)
                return total + jnp.where(add_reg,
                                         self._reg_penalty(params), 0.0)

            self._score_ex_fn = jax.jit(score_ex)
        if isinstance(data, DataSet):
            data = MultiDataSet([data.features], [data.labels],
                                [data.features_mask], [data.labels_mask])
        batches = [data] if isinstance(data, MultiDataSet) else data
        g = self.conf.global_conf
        bucket_ok = (g.shape_bucketing
                     and bucketing.pad_supported(self, require_mean=False))
        out = []
        for mds in batches:
            if isinstance(mds, DataSet):
                mds = MultiDataSet([mds.features], [mds.labels],
                                   [mds.features_mask], [mds.labels_mask])
            n = mds.num_examples()
            bucket = None
            if bucket_ok:
                # per-example scoring: masks stay unscaled, padded rows
                # are sliced back off below
                mds, bucket = bucketing.bucket_train_multidataset(
                    mds, g, scale_loss=False)
            args = (tuple(mds.features), tuple(mds.labels),
                    tuple(mds.features_masks) if mds.features_masks else None,
                    tuple(mds.labels_masks) if mds.labels_masks else None)
            self.compile_telemetry.record("score_examples", args,
                                          bucket=bucket)
            per = np.asarray(self._score_ex_fn(
                self.net_params, self.net_state, *args,
                jnp.asarray(add_regularization_terms)))
            out.append(per[:n] if bucket is not None else per)
        return np.concatenate(out)

    # ------------------------------------------------------------------
    # Layerwise unsupervised pretraining over the DAG
    # ------------------------------------------------------------------
    def pretrain(self, data, epochs: int = 1):
        """Layerwise pretrain of every pretrain-capable layer vertex in
        topological order (ref: ComputationGraph.pretrain :549-561)."""
        for name in self.order:
            v = self.conf.vertices[name]
            if isinstance(v, LayerVertex) and \
                    v.layer_conf().is_pretrain_layer():
                self.pretrain_layer(name, data, epochs=epochs)
        return self

    def pretrain_layer(self, name: str, data, epochs: int = 1):
        """Unsupervised fit of one layer vertex on the activations of its
        upstream subgraph (ref: ComputationGraph.pretrainLayer).  The
        upstream forward runs inside the same jitted step; XLA dead-code-
        eliminates every vertex the target doesn't depend on."""
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        layer = self._vertex_layer(name)
        if not layer.is_pretrain_layer():
            return self
        if self.net_params is None:
            self.init()
        in_name = self.conf.vertex_inputs[name][0]
        updater = self.updaters[name]
        g = self.conf.global_conf

        def pre_step(lp, opt, all_params, state, xs, it, rng):
            ins = dict(zip(self.conf.network_inputs, xs))
            acts, _, _, _ = self._forward_all(
                all_params, state, ins, {}, False, rng)
            feats = jax.lax.stop_gradient(acts[in_name])

            def full_loss(p):
                loss = layer.pretrain_loss(p, feats, rng) + \
                    MultiLayerNetwork._layer_reg_penalty(layer, p)
                return loss if g.minimize else -loss

            loss, grads = jax.value_and_grad(full_loss)(lp)
            grads = upd_ops.normalize_gradient(
                grads, layer.gradient_normalization,
                layer.gradient_normalization_threshold or 1.0)
            lr = upd_ops.schedule_lr(
                layer.learning_rate if layer.learning_rate is not None
                else g.learning_rate,
                g.lr_policy, it,
                decay_rate=g.lr_policy_decay_rate, steps=g.lr_policy_steps,
                power=g.lr_policy_power,
                schedule_map=g.learning_rate_schedule)
            upd, new_opt = updater.apply(grads, opt, lr, it)
            return {k: lp[k] - upd[k] for k in lp}, new_opt, loss

        # no donation: the target vertex's params are passed BOTH as the
        # trained leaf (lp) and inside all_params for the upstream forward
        step_jit = jax.jit(pre_step)
        if isinstance(data, (np.ndarray, jax.Array)):
            data = DataSet(np.asarray(data), np.asarray(data))
        if isinstance(data, DataSet):
            data = MultiDataSet([data.features], [data.labels])
        batches = [data] if isinstance(data, MultiDataSet) else None
        for _ in range(epochs):
            it_ = batches if batches is not None else (data.reset() or data)
            for item in it_:
                if isinstance(item, DataSet):
                    item = MultiDataSet([item.features], [item.labels])
                self._key, sub = jax.random.split(self._key)
                lp, opt, loss = step_jit(
                    self.net_params[name], self.opt_states[name],
                    self.net_params, self.net_state, tuple(item.features),
                    jnp.asarray(self.iteration, jnp.int32), sub)
                self.net_params[name] = lp
                self.opt_states[name] = opt
                self._score = loss
                self.iteration += 1
                for lst in self.listeners:
                    lst.iteration_done(self, self.iteration)
        return self

    # ------------------------------------------------------------------
    # External-errors backprop (the RL pattern: caller owns the loss)
    # ------------------------------------------------------------------
    def backprop_gradient(self, inputs, epsilons, masks=None,
                          train: bool = False):
        """Vertex-param gradients + per-input epsilons from EXTERNAL error
        signals dL/d(output_i) — no labels/loss (ref:
        ComputationGraph.calcBackpropGradients external epsilons,
        nn/graph/ComputationGraph.java:1421).  ``inputs`` and ``epsilons``
        are sequences ordered like network_inputs / network_outputs.
        Returns ``(grads, input_epsilons)``.  ``train=False`` (default)
        reproduces output()'s exact forward; ``train=True`` samples fresh
        dropout masks and folds updated carried state (BN running stats)
        back into the network (see MultiLayerNetwork.backprop_gradient)."""
        if self.net_params is None:
            self.init()
        self._check_trace_token()
        if self._ext_grad_fn is None:
            self._ext_grad_fn = {}
        if train not in self._ext_grad_fn:
            policy = dtype_ops.resolve(self.conf.global_conf.precision)

            def ext_grad(params, state, xs, eps, ms, rng, _train=train):
                def fwd(p, xs_):
                    # same precision-policy cast as the fused step /
                    # output(): under bf16 the VJP differentiates the
                    # forward the caller actually saw, and grads come
                    # back in the f32 master-param dtype
                    pc = policy.cast_to_compute(p)
                    xs_c, ms_c = policy.cast_to_compute((xs_, ms))
                    ins = dict(zip(self.conf.network_inputs, xs_c))
                    mdict = dict(zip(self.conf.network_inputs, ms_c)) \
                        if ms_c is not None else {}
                    acts, _, ns, _ = self._forward_all(
                        pc, state, ins, mdict, _train, rng)
                    return tuple(acts[n]
                                 for n in self.conf.network_outputs), ns
                outs, vjp, ns = jax.vjp(fwd, params, xs, has_aux=True)
                cot = tuple(e.astype(o.dtype) for e, o in zip(eps, outs))
                g, dxs = vjp(cot)
                return g, dxs, policy.cast_to_param(ns)
            self._ext_grad_fn[train] = jax.jit(ext_grad)
        if train:
            self._key, sub = jax.random.split(self._key)
        else:
            sub = jax.random.PRNGKey(0)
        xs = tuple(jnp.asarray(x) for x in inputs)
        eps = tuple(jnp.asarray(e) for e in epsilons)
        grads, dxs, new_states = self._ext_grad_fn[train](
            self.net_params, self.net_state, xs, eps, masks, sub)
        if train:
            self.net_state = new_states
            self._strip_rnn_state()
        return grads, dxs

    def apply_gradients(self, grads):
        """Apply externally computed vertex gradients through the
        configured updaters — one jitted step (see
        MultiLayerNetwork.apply_gradients: l1/l2 regularization gradients
        are added here and ``minimize=False`` negates, matching fit())."""
        if self.net_params is None:
            self.init()
        self._check_trace_token()
        if self._apply_fn is None:
            g_conf = self.conf.global_conf

            def apply(p, o, gr, it):
                reg = jax.grad(
                    lambda p_: jnp.asarray(self._reg_penalty(p_),
                                           jnp.float32))(p)
                gr = jax.tree_util.tree_map(jnp.add, gr, reg)
                if not g_conf.minimize:
                    gr = jax.tree_util.tree_map(jnp.negative, gr)
                return self._apply_updates(p, o, gr, it)

            self._apply_fn = jax.jit(apply, donate_argnums=(0, 1))
        self.net_params, self.opt_states = self._apply_fn(
            self.net_params, self.opt_states, grads,
            jnp.asarray(self.iteration, jnp.int32))
        self.iteration += 1
        return self

    # ------------------------------------------------------------------
    def summary(self) -> str:
        """Printable vertex table in topological order: name, vertex type,
        inputs, param count (ref: ComputationGraph.summary)."""
        if self.net_params is None:
            self.init()
        rows = [("VertexName", "VertexType", "Inputs", "ParamCount")]
        total = 0
        for name in self.order:
            v = self.conf.vertices[name]
            lp = self.net_params[name]
            n = sum(int(np.prod(a.shape)) for a in lp.values()) if lp else 0
            total += n
            vtype = (type(v.layer_conf()).__name__
                     if isinstance(v, LayerVertex) else type(v).__name__)
            rows.append((name, vtype,
                         ",".join(self.conf.vertex_inputs[name]) or "-",
                         f"{n:,}"))
        from deeplearning4j_tpu.nn.multilayer import render_table
        return render_table(rows, [
            f"Total parameters: {total:,}",
            f"Inputs: {', '.join(self.conf.network_inputs)}",
            f"Outputs: {', '.join(self.conf.network_outputs)}"])

    def clone(self) -> "ComputationGraph":
        import copy
        net = ComputationGraph(copy.deepcopy(self.conf))
        if self.net_params is not None:
            copy_tree = lambda t: jax.tree_util.tree_map(  # noqa: E731
                lambda a: jnp.array(a, copy=True), t)
            net.init()
            net.net_params = copy_tree(self.net_params)
            net.net_state = copy_tree(self.net_state)
            net.opt_states = copy_tree(self.opt_states)
        net.iteration = self.iteration
        return net
