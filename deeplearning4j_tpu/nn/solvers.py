"""Line-search optimizer family
(ref: optimize/Solver.java:43, optimize/solvers/BaseOptimizer.java,
BackTrackLineSearch.java (369 LoC), ConjugateGradient.java, LBFGS.java,
LineGradientDescent.java; enum nn/api/OptimizationAlgorithm.java).

The reference's normal path is SGD (the jitted train step in
nn/multilayer.py); these full-batch second-order-ish methods are the
rest of the ConvexOptimizer surface.  They operate on the flat parameter
vector through ONE jitted value-and-grad of the network's score — each
outer iteration is a handful of XLA calls, with the line search's
repeated evaluations hitting the same compiled program."""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn import params as param_util


def _flat_objective(net, dataset) -> Tuple[Callable, Callable]:
    """→ (value_fn(flat)→score, vg_fn(flat)→(score, grad_flat)), both
    jitted once.  Mask semantics match MultiLayerNetwork._build_score_fn
    (features_mask/labels_mask respected), so these optimizers minimize
    exactly what net.score(dataset) reports."""
    template = net.net_params
    fmask = dataset.features_mask
    lmask = dataset.labels_mask

    def score_of(params_tree):
        out_layer = net.layers[-1]
        g = net.conf.global_conf
        preout, _, m, feats = net._forward_to_preout(
            params_tree, net.net_state, dataset.features, fmask, False,
            jax.random.PRNGKey(0))
        lm = lmask if lmask is not None else (
            m if (m is not None and m.ndim == preout.ndim - 1) else None)
        if getattr(out_layer, "requires_features_for_score", False):
            per_ex = out_layer.compute_score_with_features(
                dataset.labels, preout, feats, params_tree[-1], lm)
        else:
            per_ex = out_layer.compute_score(dataset.labels, preout, lm)
        score = jnp.mean(per_ex) if g.mini_batch else jnp.sum(per_ex)
        return score + net._reg_penalty(params_tree)

    def value(flat):
        return score_of(param_util.unflatten(flat, template))

    def vg(flat):
        s, g = jax.value_and_grad(score_of)(
            param_util.unflatten(flat, template))
        return s, param_util.flatten(g)

    return jax.jit(value), jax.jit(vg)


class BackTrackLineSearch:
    """Armijo backtracking along a search direction
    (ref: optimize/solvers/BackTrackLineSearch.java — step max, alpha
    shrink, sufficient-decrease c1)."""

    def __init__(self, c1: float = 1e-4, shrink: float = 0.5,
                 max_iterations: int = 20, initial_step: float = 1.0,
                 max_step: float = 100.0):
        self.c1 = c1
        self.shrink = shrink
        self.max_iterations = max_iterations
        self.initial_step = initial_step
        self.max_step = max_step

    def optimize(self, value_fn: Callable, vg: Callable, flat, score, grad,
                 direction) -> Tuple[jnp.ndarray, float, jnp.ndarray, float]:
        """→ (new_flat, new_score, new_grad, step_used); falls back to
        step 0 (no move) when no decrease is found.  Trial points pay
        only a forward pass; the gradient is computed once for the
        accepted point."""
        slope = float(jnp.vdot(grad, direction))
        if slope >= 0:  # not a descent direction: flip to steepest
            direction = -grad
            slope = float(jnp.vdot(grad, direction))
        dnorm = float(jnp.linalg.norm(direction))
        step = min(self.initial_step,
                   self.max_step / dnorm if dnorm > 0 else self.initial_step)
        for _ in range(self.max_iterations):
            cand = flat + step * direction
            s = value_fn(cand)
            if float(s) <= float(score) + self.c1 * step * slope:
                s, g = vg(cand)
                return cand, float(s), g, step
            step *= self.shrink
        return flat, float(score), grad, 0.0


class _BaseLineSearchOptimizer:
    """(ref: optimize/solvers/BaseOptimizer.java gradientAndScore loop)"""

    def __init__(self, max_iterations: int = 100, tolerance: float = 1e-5,
                 line_search: Optional[BackTrackLineSearch] = None):
        self.max_iterations = max_iterations
        self.tolerance = tolerance
        self.line_search = line_search or BackTrackLineSearch()
        self.score_history: List[float] = []

    def optimize(self, net, dataset) -> float:
        value_fn, vg = _flat_objective(net, dataset)
        flat = net.params()
        score, grad = vg(flat)
        score = float(score)
        state = self._init_state(flat, grad)
        for it in range(self.max_iterations):
            direction, state = self._direction(flat, grad, state)
            flat_new, score_new, grad_new, step = self.line_search.optimize(
                value_fn, vg, flat, score, grad, direction)
            self.score_history.append(score_new)
            if step == 0.0 or abs(score - score_new) < self.tolerance:
                flat, score, grad = flat_new, score_new, grad_new
                break
            state = self._post_step(state, flat, flat_new, grad, grad_new)
            flat, score, grad = flat_new, score_new, grad_new
        net.set_params(flat)
        net._score = score
        return score

    # -- strategy hooks -----------------------------------------------------
    def _init_state(self, flat, grad):
        return None

    def _direction(self, flat, grad, state):
        raise NotImplementedError

    def _post_step(self, state, flat_old, flat_new, grad_old, grad_new):
        return state


class LineGradientDescent(_BaseLineSearchOptimizer):
    """Steepest descent + line search
    (ref: optimize/solvers/LineGradientDescent.java)."""

    def _direction(self, flat, grad, state):
        return -grad, state


class ConjugateGradient(_BaseLineSearchOptimizer):
    """Polak-Ribière nonlinear CG
    (ref: optimize/solvers/ConjugateGradient.java)."""

    def _init_state(self, flat, grad):
        return {"prev_grad": grad, "prev_dir": -grad, "first": True}

    def _direction(self, flat, grad, state):
        if state["first"]:
            state = dict(state, first=False)
            return -grad, state
        pg = state["prev_grad"]
        beta = float(jnp.vdot(grad, grad - pg)
                     / jnp.maximum(jnp.vdot(pg, pg), 1e-20))
        beta = max(beta, 0.0)  # PR+ restart
        d = -grad + beta * state["prev_dir"]
        return d, state

    def _post_step(self, state, flat_old, flat_new, grad_old, grad_new):
        d = flat_new - flat_old
        dn = float(jnp.linalg.norm(d))
        return {"prev_grad": grad_new,
                "prev_dir": d / dn if dn > 0 else -grad_new,
                "first": False}


class LBFGS(_BaseLineSearchOptimizer):
    """Limited-memory BFGS, two-loop recursion
    (ref: optimize/solvers/LBFGS.java — default memory m=4..10)."""

    def __init__(self, max_iterations: int = 100, tolerance: float = 1e-5,
                 memory: int = 10,
                 line_search: Optional[BackTrackLineSearch] = None):
        super().__init__(max_iterations, tolerance, line_search)
        self.memory = memory

    def _init_state(self, flat, grad):
        return {"s": [], "y": []}

    def _direction(self, flat, grad, state):
        s_list, y_list = state["s"], state["y"]
        q = grad
        alphas = []
        for s, y in zip(reversed(s_list), reversed(y_list)):
            rho = 1.0 / float(jnp.maximum(jnp.vdot(y, s), 1e-20))
            a = rho * float(jnp.vdot(s, q))
            alphas.append((a, rho, s, y))
            q = q - a * y
        if y_list:
            y = y_list[-1]
            s = s_list[-1]
            gamma = float(jnp.vdot(s, y)
                          / jnp.maximum(jnp.vdot(y, y), 1e-20))
            q = gamma * q
        for a, rho, s, y in reversed(alphas):
            b = rho * float(jnp.vdot(y, q))
            q = q + (a - b) * s
        return -q, state

    def _post_step(self, state, flat_old, flat_new, grad_old, grad_new):
        s = flat_new - flat_old
        y = grad_new - grad_old
        if float(jnp.vdot(s, y)) > 1e-10:  # curvature condition
            state["s"].append(s)
            state["y"].append(y)
            if len(state["s"]) > self.memory:
                state["s"].pop(0)
                state["y"].pop(0)
        return state


class StochasticGradientDescent:
    """The normal path — delegates to the jitted train step
    (ref: optimize/solvers/StochasticGradientDescent.java:53-75)."""

    def __init__(self, max_iterations: int = 1):
        self.max_iterations = max_iterations

    def optimize(self, net, dataset) -> float:
        for _ in range(self.max_iterations):
            net.fit(dataset)
        return float(net.score())


class Solver:
    """(ref: optimize/Solver.java — builds a ConvexOptimizer from the
    configured OptimizationAlgorithm)"""

    ALGOS = {
        "STOCHASTIC_GRADIENT_DESCENT": StochasticGradientDescent,
        "LINE_GRADIENT_DESCENT": LineGradientDescent,
        "CONJUGATE_GRADIENT": ConjugateGradient,
        "LBFGS": LBFGS,
    }

    def __init__(self, algorithm: str = "STOCHASTIC_GRADIENT_DESCENT",
                 **kwargs):
        key = algorithm.upper()
        if key not in self.ALGOS:
            raise ValueError(f"unknown optimization algorithm {algorithm!r}; "
                             f"one of {sorted(self.ALGOS)}")
        self.optimizer = self.ALGOS[key](**kwargs)

    def optimize(self, net, dataset) -> float:
        return self.optimizer.optimize(net, dataset)
