"""Core NN engine — the reference's `deeplearning4j-nn` re-realized TPU-first.

Pure functional layers over param pytrees, one jitted+donated train step
per model, config objects JSON-serializable for checkpoint parity
(ref: nn/conf/NeuralNetConfiguration.java).
"""
