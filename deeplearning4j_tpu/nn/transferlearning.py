"""Transfer learning — clone + fine-tune + freeze + replace outputs.

(ref: nn/transferlearning/TransferLearning.java:34 — Builder with
fineTuneConfiguration / setFeatureExtractor (freeze up to layer N) /
removeOutputLayer / addLayer / nOutReplace; FineTuneConfiguration.java;
TransferLearningHelper.java — featurization by running frozen layers once)
"""

from __future__ import annotations

import copy
import dataclasses
from typing import List, Optional

import jax

from deeplearning4j_tpu.nn.conf.layers import FrozenLayerConf, Layer
from deeplearning4j_tpu.nn.conf.network import (
    GlobalConf, MultiLayerConfiguration, merge_layer_conf)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


@dataclasses.dataclass
class FineTuneConfiguration:
    """Hyperparameter overrides applied to every non-frozen layer
    (ref: nn/transferlearning/FineTuneConfiguration.java)."""

    learning_rate: Optional[float] = None
    updater: Optional[str] = None
    momentum: Optional[float] = None
    l1: Optional[float] = None
    l2: Optional[float] = None
    dropout: Optional[float] = None
    seed: Optional[int] = None
    activation: Optional[str] = None
    weight_init: Optional[str] = None

    def apply_to_global(self, g: GlobalConf) -> GlobalConf:
        g = copy.deepcopy(g)
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if v is not None and hasattr(g, f.name):
                setattr(g, f.name, v)
        if self.l1 is not None or self.l2 is not None:
            g.use_regularization = True
        return g

    def apply_to_layer(self, layer: Layer) -> Layer:
        updates = {}
        for f in ("learning_rate", "updater", "momentum", "l1", "l2", "dropout"):
            v = getattr(self, f)
            if v is not None and hasattr(layer, f):
                updates[f] = v
        return dataclasses.replace(layer, **updates) if updates else layer


class TransferLearningBuilder:
    """(ref: TransferLearning.Builder)"""

    def __init__(self, net: MultiLayerNetwork):
        self._net = net
        self._fine_tune: Optional[FineTuneConfiguration] = None
        self._freeze_until: Optional[int] = None
        self._n_out_replace: dict = {}
        self._remove_from: Optional[int] = None
        self._added: List[Layer] = []

    def fine_tune_configuration(self, ftc: FineTuneConfiguration):
        self._fine_tune = ftc
        return self

    def set_feature_extractor(self, layer_idx: int):
        """Freeze layers [0..layer_idx] (ref: setFeatureExtractor)."""
        self._freeze_until = layer_idx
        return self

    def n_out_replace(self, layer_idx: int, n_out: int,
                      weight_init: Optional[str] = None):
        """Replace layer's nOut (and reinit it + nIn of the next layer)."""
        self._n_out_replace[layer_idx] = (n_out, weight_init)
        return self

    def remove_output_layer(self):
        return self.remove_layers_from_output(1)

    def remove_layers_from_output(self, n: int):
        self._remove_from = len(self._net.layers) - n
        return self

    def add_layer(self, layer: Layer):
        self._added.append(layer)
        return self

    def build(self) -> MultiLayerNetwork:
        import jax.numpy as jnp
        src = self._net
        conf = copy.deepcopy(src.conf)
        layers = list(conf.layers)
        # copy arrays: the new net's donated train step must not invalidate
        # the source net's buffers (donation aliasing)
        params = ([{k: jnp.array(v, copy=True) for k, v in p.items()}
                   for p in src.net_params] if src.net_params else None)

        if self._remove_from is not None:
            layers = layers[:self._remove_from]
            if params:
                params = params[:self._remove_from]

        g = conf.global_conf
        if self._fine_tune:
            g = self._fine_tune.apply_to_global(g)

        def _replace_unwrapped(lc, **changes):
            # chained transfer learning: the layer may already be a
            # FrozenLayerConf wrapper (no n_out/n_in field) — edit the
            # inner conf and re-wrap so frozen status survives the edit
            if isinstance(lc, FrozenLayerConf):
                return FrozenLayerConf.wrap(
                    dataclasses.replace(lc._inner(), **changes))
            return dataclasses.replace(lc, **changes)

        reinit: set = set()
        for idx, (n_out, winit) in self._n_out_replace.items():
            layers[idx] = _replace_unwrapped(
                layers[idx], n_out=n_out,
                **({"weight_init": winit} if winit else {}))
            reinit.add(idx)
            if idx + 1 < len(layers):
                nxt = layers[idx + 1]
                inner = nxt._inner() if isinstance(nxt, FrozenLayerConf) \
                    else nxt
                if hasattr(inner, "n_in"):
                    layers[idx + 1] = _replace_unwrapped(nxt, n_in=n_out)
                    reinit.add(idx + 1)

        for layer in self._added:
            layers.append(merge_layer_conf(layer, g))
            if params is not None:
                params.append(None)  # initialize below
            reinit.add(len(layers) - 1)

        if self._fine_tune:
            layers = [l if (self._freeze_until is not None and i <= self._freeze_until)
                      else self._fine_tune.apply_to_layer(l)
                      for i, l in enumerate(layers)]

        if self._freeze_until is not None:
            layers = [FrozenLayerConf.wrap(l) if (i <= self._freeze_until and
                                                  not isinstance(l, FrozenLayerConf))
                      else l for i, l in enumerate(layers)]

        new_conf = MultiLayerConfiguration(
            layers=layers, global_conf=g, input_type=conf.input_type,
            preprocessors=conf.preprocessors, backprop=conf.backprop,
            pretrain=conf.pretrain, backprop_type=conf.backprop_type,
            tbptt_fwd_length=conf.tbptt_fwd_length,
            tbptt_back_length=conf.tbptt_back_length)
        net = MultiLayerNetwork(new_conf)
        net.init()
        if params is not None:
            # keep source weights wherever shape-compatible and not re-initialized
            kept = []
            for i, (old, fresh) in enumerate(zip(params, net.net_params)):
                if i in reinit or old is None:
                    kept.append(fresh)
                elif all(k in old and old[k].shape == fresh[k].shape for k in fresh):
                    kept.append({k: old[k] for k in fresh})
                else:
                    kept.append(fresh)
            net.net_params = kept
            net.opt_states = [net.updaters[i].init(net.net_params[i])
                              for i in range(len(net.layers))]
        return net


class TransferLearningGraphBuilder:
    """ComputationGraph transfer learning (ref: TransferLearning.java:425
    GraphBuilder — fineTuneConfiguration / setFeatureExtractor(vertices) /
    removeVertexAndConnections / addLayer / addVertex / nOutReplace /
    setOutputs)."""

    def __init__(self, net):
        self._net = net
        self._fine_tune: Optional[FineTuneConfiguration] = None
        self._frozen_at: List[str] = []
        self._n_out_replace: dict = {}
        self._removed: List[str] = []
        self._added: List[tuple] = []  # (name, vertex_conf_or_layer, inputs)
        self._outputs: Optional[List[str]] = None

    def fine_tune_configuration(self, ftc: FineTuneConfiguration):
        self._fine_tune = ftc
        return self

    def set_feature_extractor(self, *vertex_names: str):
        """Freeze the named vertices and every ancestor vertex
        (ref: GraphBuilder.setFeatureExtractor)."""
        self._frozen_at = list(vertex_names)
        return self

    def n_out_replace(self, vertex_name: str, n_out: int,
                      weight_init: Optional[str] = None):
        self._n_out_replace[vertex_name] = (n_out, weight_init)
        return self

    def remove_vertex_and_connections(self, name: str):
        self._removed.append(name)
        return self

    def add_layer(self, name: str, layer: Layer, *inputs: str):
        self._added.append((name, layer, list(inputs)))
        return self

    def add_vertex(self, name: str, vertex, *inputs: str):
        self._added.append((name, vertex, list(inputs)))
        return self

    def set_outputs(self, *names: str):
        self._outputs = list(names)
        return self

    def _ancestors(self, conf, roots: List[str]) -> set:
        seen = set()
        stack = list(roots)
        while stack:
            n = stack.pop()
            if n in seen or n not in conf.vertices:
                continue
            seen.add(n)
            stack.extend(conf.vertex_inputs.get(n, []))
        return seen

    def build(self):
        import dataclasses as dc

        import jax.numpy as jnp

        from deeplearning4j_tpu.nn.conf.graph_conf import (
            ComputationGraphConfiguration, LayerVertex)
        from deeplearning4j_tpu.nn.graph import ComputationGraph

        src = self._net
        conf = copy.deepcopy(src.conf)
        vertices = dict(conf.vertices)
        vertex_inputs = {k: list(v) for k, v in conf.vertex_inputs.items()}
        outputs = list(conf.network_outputs)

        reinit: set = set()
        for name in self._removed:
            vertices.pop(name, None)
            vertex_inputs.pop(name, None)
            outputs = [o for o in outputs if o != name]

        g = conf.global_conf
        if self._fine_tune:
            g = self._fine_tune.apply_to_global(g)

        def _replace_unwrapped(lc, **changes):
            # chained transfer learning hands us vertices that are already
            # FrozenLayerConf wrappers (no n_out/n_in field) — edit the
            # inner conf and re-wrap so frozen status survives the edit
            if isinstance(lc, FrozenLayerConf):
                return FrozenLayerConf.wrap(
                    dc.replace(lc._inner(), **changes))
            return dc.replace(lc, **changes)

        for name, (n_out, winit) in self._n_out_replace.items():
            lv = vertices[name]
            lc = _replace_unwrapped(
                lv.layer_conf(), n_out=n_out,
                **({"weight_init": winit} if winit else {}))
            vertices[name] = LayerVertex(layer=lc.to_dict())
            reinit.add(name)
            for k, ins in vertex_inputs.items():
                if name in ins and isinstance(vertices.get(k), LayerVertex):
                    dlc = vertices[k].layer_conf()
                    inner = dlc._inner() if isinstance(dlc, FrozenLayerConf) \
                        else dlc
                    if getattr(inner, "n_in", None):
                        vertices[k] = LayerVertex(layer=_replace_unwrapped(
                            dlc, n_in=n_out).to_dict())
                        reinit.add(k)

        for name, v, ins in self._added:
            if isinstance(v, Layer):
                v = LayerVertex(layer=merge_layer_conf(v, g).to_dict())
            vertices[name] = v
            vertex_inputs[name] = ins
            reinit.add(name)

        # dangling-edge validation AFTER all removals/additions so
        # multi-vertex edits are order-independent
        known = set(vertices) | set(conf.network_inputs)
        for k, ins in vertex_inputs.items():
            for i in ins:
                if i not in known:
                    raise ValueError(
                        f"vertex '{k}' consumes removed/unknown vertex "
                        f"'{i}' — remove or rewire downstream vertices too")

        frozen: set = set()
        if self._frozen_at:
            tmp = ComputationGraphConfiguration(
                network_inputs=conf.network_inputs, network_outputs=outputs,
                vertices=vertices, vertex_inputs=vertex_inputs, global_conf=g)
            frozen = self._ancestors(tmp, self._frozen_at)

        new_vertices = {}
        for name, v in vertices.items():
            if isinstance(v, LayerVertex):
                lc = v.layer_conf()
                if name in frozen:
                    if not isinstance(lc, FrozenLayerConf):
                        lc = FrozenLayerConf.wrap(lc)
                elif self._fine_tune:
                    lc = self._fine_tune.apply_to_layer(lc)
                new_vertices[name] = LayerVertex(layer=lc.to_dict())
            else:
                new_vertices[name] = v

        new_conf = ComputationGraphConfiguration(
            network_inputs=conf.network_inputs,
            network_outputs=self._outputs if self._outputs is not None
            else outputs,
            vertices=new_vertices, vertex_inputs=vertex_inputs,
            global_conf=g, input_types=conf.input_types,
            backprop_type=conf.backprop_type,
            tbptt_fwd_length=conf.tbptt_fwd_length,
            tbptt_back_length=conf.tbptt_back_length)
        net = ComputationGraph(new_conf).init()
        if src.net_params is not None:
            for name in net.order:
                if name in reinit or name not in src.net_params:
                    continue
                old, fresh = src.net_params[name], net.net_params[name]
                if all(k in old and old[k].shape == fresh[k].shape
                       for k in fresh):
                    net.net_params[name] = {
                        k: jnp.array(old[k], copy=True) for k in fresh}
            net.opt_states = {n: net.updaters[n].init(net.net_params[n])
                              for n in net.order}
        return net


class TransferLearning:
    """Entry point mirroring the reference's nested Builder API."""

    Builder = TransferLearningBuilder
    GraphBuilder = TransferLearningGraphBuilder


class TransferLearningHelper:
    """Featurization helper: run the frozen bottom once per dataset, train
    only the unfrozen top (ref: nn/transferlearning/TransferLearningHelper.java)."""

    def __init__(self, net: MultiLayerNetwork, frozen_until: int):
        self.full = net
        self.frozen_until = frozen_until

    def featurize(self, dataset):
        """Run inputs through the frozen layers → features for the top."""
        from deeplearning4j_tpu.datasets.dataset import DataSet
        import numpy as np
        acts = self.full.feed_forward(dataset.features, train=False)
        feat = np.asarray(acts[self.frozen_until])
        return DataSet(feat, dataset.labels, dataset.features_mask,
                       dataset.labels_mask)

    def unfrozen_network(self) -> MultiLayerNetwork:
        """A network of only the unfrozen top layers (shares weights)."""
        conf = copy.deepcopy(self.full.conf)
        top_layers = conf.layers[self.frozen_until + 1:]
        preprocs = {i - (self.frozen_until + 1): p
                    for i, p in conf.preprocessors.items()
                    if i > self.frozen_until}
        new_conf = MultiLayerConfiguration(
            layers=top_layers, global_conf=conf.global_conf,
            input_type=None, preprocessors=preprocs)
        import jax.numpy as jnp
        net = MultiLayerNetwork(new_conf)
        net.init(params=[{k: jnp.array(v, copy=True) for k, v in p.items()}
                         for p in self.full.net_params[self.frozen_until + 1:]])
        return net
