"""Gateway entry point for external (non-framework) processes
(ref: deeplearning4j-keras — keras/Server.java:15-18 starts a py4j
GatewayServer around DeepLearning4jEntryPoint;
DeepLearning4jEntryPoint.fit() :21-33 trains a Keras-saved model on
batches streamed from disk; HDF5MiniBatchDataSetIterator reads them).

The reference's wire tech (py4j JVM gateway) is replaced by a JSON-RPC
HTTP endpoint — the natural cross-process seam for a Python-hosted
runtime.  The entry-point surface is preserved: ``fit`` takes a saved
model (Keras .h5 via keras_import, or a framework .zip checkpoint) plus
a directory of exported minibatches, trains, and writes the result
checkpoint."""

from __future__ import annotations

import json
import threading
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Optional


class DeepLearning4jEntryPoint:
    """(ref: keras/DeepLearning4jEntryPoint.java:21-33 — the object the
    gateway exposes; one method per remote operation)."""

    def _load_model(self, model_path: str):
        p = Path(model_path)
        if p.suffix in (".h5", ".hdf5"):
            from deeplearning4j_tpu.keras_import import KerasModelImport
            return KerasModelImport.import_keras_model_and_weights(str(p))
        from deeplearning4j_tpu.nn.serialization import load_model
        return load_model(str(p))

    @staticmethod
    def _data_iterator(data_dir: str):
        """Minibatch source for a data directory, by layout:

        * ``features/`` + ``labels/`` subdirs of ``batch_%d.h5`` — the
          reference's HDF5 layout (HDF5MiniBatchDataSetIterator.java:24);
        * ``batch_%d.h5`` files carrying features+labels datasets;
        * ``.npz`` exports (scaleout.data.PathDataSetIterator).
        """
        from deeplearning4j_tpu.scaleout.data import PathDataSetIterator
        from deeplearning4j_tpu.keras_import.hdf5_data import (
            _BATCH_RE, HDF5MiniBatchDataSetIterator)
        d = Path(data_dir)
        if (d / "features").is_dir() and (d / "labels").is_dir():
            return HDF5MiniBatchDataSetIterator(d / "features", d / "labels")
        # the iterator's own strict batch_%d.h5 pattern decides — a stray
        # non-conforming .h5 must not hijack a directory of .npz exports
        if any(_BATCH_RE.match(p.name) for p in d.iterdir()):
            return HDF5MiniBatchDataSetIterator(d)
        return PathDataSetIterator.from_dir(data_dir)

    def fit(self, model_path: str, data_dir: str, epochs: int = 1,
            save_path: Optional[str] = None,
            shape_bucketing: Optional[bool] = None) -> dict:
        """Train ``model_path`` on the minibatches in ``data_dir``
        (HDF5 ``batch_%d.h5`` layouts or .npz exports —
        :meth:`_data_iterator`).  Exported minibatch directories are the
        canonical ragged stream (the last shard is short), so
        ``shape_bucketing=True`` pads every batch up to its bucket and
        the step compiles once per bucket (ops/bucketing.py); retrace
        telemetry is returned alongside the score."""
        from deeplearning4j_tpu.nn.serialization import write_model
        from deeplearning4j_tpu.ops import bucketing
        bucketing.maybe_enable_persistent_cache()
        model = self._load_model(model_path)
        if shape_bucketing is not None:
            model.conf.global_conf.shape_bucketing = bool(shape_bucketing)
        it = self._data_iterator(data_dir)
        for _ in range(int(epochs)):
            it.reset()
            while it.has_next():
                model.fit(it.next())
        out = save_path or model_path
        if not out.endswith(".zip"):
            out = str(Path(out).with_suffix(".zip"))
        write_model(model, out)
        result = {"score": float(model.score()), "model_path": out}
        tel = getattr(model, "compile_telemetry", None)
        if tel is not None:
            result["compile_telemetry"] = tel.snapshot()
        return result

    def evaluate(self, model_path: str, data_dir: str) -> dict:
        model = self._load_model(model_path)
        ev = model.evaluate(self._data_iterator(data_dir))
        return {"accuracy": ev.accuracy(), "f1": ev.f1()}

    def predict(self, model_path: str, data_dir: str) -> dict:
        import numpy as np
        model = self._load_model(model_path)
        it = self._data_iterator(data_dir)
        outs = []
        while it.has_next():
            outs.append(np.asarray(model.output(it.next().features)))
        stacked = np.concatenate(outs) if outs else np.zeros((0,))
        return {"predictions": stacked.tolist()}


class Server:
    """(ref: keras/Server.java — `new GatewayServer(new
    DeepLearning4jEntryPoint()).start()`).  JSON-RPC over HTTP:

    POST / {"method": "fit", "params": {...}} →
        {"result": {...}} or {"error": "..."}
    """

    def __init__(self, entry_point: Optional[DeepLearning4jEntryPoint] = None,
                 host: str = "127.0.0.1", port: int = 0):
        ep = entry_point or DeepLearning4jEntryPoint()
        self.entry_point = ep

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def do_POST(self):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    method = req.get("method", "")
                    if method.startswith("_") or not hasattr(ep, method):
                        raise AttributeError(f"no method {method!r}")
                    result = getattr(ep, method)(**req.get("params", {}))
                    payload = json.dumps({"result": result}).encode()
                    code = 200
                except Exception as e:
                    payload = json.dumps(
                        {"error": f"{type(e).__name__}: {e}",
                         "traceback": traceback.format_exc()}).encode()
                    code = 500
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "Server":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
