"""Gateway entry point for external (non-framework) processes
(ref: deeplearning4j-keras — keras/Server.java:15-18 starts a py4j
GatewayServer around DeepLearning4jEntryPoint;
DeepLearning4jEntryPoint.fit() :21-33 trains a Keras-saved model on
batches streamed from disk; HDF5MiniBatchDataSetIterator reads them).

The reference's wire tech (py4j JVM gateway) is replaced by a JSON-RPC
HTTP endpoint — the natural cross-process seam for a Python-hosted
runtime.  The entry-point surface is preserved (``fit`` takes a saved
model plus a directory of exported minibatches, trains, and writes the
result checkpoint) and extended into a real inference server:

* **model cache** (``server/model_cache.py``): models load and jit-warm
  once, keyed by ``(path, mtime)``, with LRU eviction and an
  ``invalidate`` RPC;
* **dynamic micro-batching** (``server/batcher.py``): concurrent
  ``predict`` requests with inline ``features`` coalesce into one
  jitted ``output`` call, padded to the bucket ladder;
* **bucket warmup**: the first predict for a model pre-compiles the
  serving ladder (``warmup_inference``), so cold compiles happen once
  at load, not on the request path;
* **serving metrics** (``stats`` RPC): latency percentiles, batch-size
  histogram, model-cache counters, each model's ``CompileTelemetry``
  snapshot, and the process-wide metrics registry;
* **Prometheus exposition** (``metrics`` RPC / ``GET /metrics``): the
  unified registry (monitor/) as text-format v0.0.4 or JSON — one
  scrape sees retraces, step-phase timings, serving latencies, cache
  hit rates and device memory (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import json
import os
import threading
import time
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Optional

import numpy as np

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.monitor import events, flight
from deeplearning4j_tpu.resilience import (
    CircuitBreaker, CircuitOpenError, OverloadedError, RetryPolicy, faults)
from deeplearning4j_tpu.resilience.errors import DeadlineExceededError
from deeplearning4j_tpu.server.batcher import MicroBatcher
from deeplearning4j_tpu.server.model_cache import ModelCache


class DeepLearning4jEntryPoint:
    """(ref: keras/DeepLearning4jEntryPoint.java:21-33 — the object the
    gateway exposes; one method per remote operation).

    ``max_batch``/``max_wait_ms`` configure the per-model micro-batcher;
    ``coalesce`` is the default for ``predict(features=...)`` requests
    (overridable per request).

    Overload posture (docs/RESILIENCE.md): ``max_queue_rows`` bounds the
    rows queued across batchers — a ``predict`` that would push past it
    is rejected with :class:`OverloadedError` (HTTP 503 +
    ``Retry-After: retry_after_s``) instead of queuing without bound;
    per-request ``deadline_ms`` propagates into the batcher so requests
    that expire while queued are shed before compute.  When this entry
    point builds its own :class:`ModelCache`, checkpoint loads get a
    retry policy and a circuit breaker (``/readyz`` goes unready while
    that breaker is open)."""

    def __init__(self, model_cache: Optional[ModelCache] = None,
                 max_batch: int = 32, max_wait_ms: float = 5.0,
                 min_batch: int = 1, coalesce: bool = True,
                 max_queue_rows: int = 1024, retry_after_s: float = 1.0,
                 min_ready_models: int = 0,
                 tenant_quota_rows: Optional[int] = None,
                 decode_slots: int = 32, decode_ttl_s: float = 600.0,
                 decode_max_wait_ms: float = 2.0,
                 blue_green: bool = False,
                 slo=None, slo_interval_s: float = 5.0):
        if model_cache is None:
            model_cache = ModelCache(
                load_retry=RetryPolicy(max_attempts=3, base_delay_ms=25,
                                       name="cache.load"),
                load_breaker=CircuitBreaker(cooldown_s=10.0,
                                            name="cache.load"),
                blue_green=blue_green)
        self.model_cache = model_cache
        self.max_batch = max(1, int(max_batch))
        self.max_wait_ms = float(max_wait_ms)
        self.min_batch = max(1, int(min_batch))
        self.coalesce = bool(coalesce)
        self.max_queue_rows = max(1, int(max_queue_rows))
        self.retry_after_s = max(0.0, float(retry_after_s))
        self.min_ready_models = max(0, int(min_ready_models))
        # per-tenant fair share: one tenant may hold at most this many
        # queued rows (predict + decode) — None disables the per-tenant
        # check, the global max_queue_rows bound always applies
        self.tenant_quota_rows = (None if tenant_quota_rows is None
                                  else max(1, int(tenant_quota_rows)))
        from deeplearning4j_tpu.server.decode import DecodeManager
        self.decode = DecodeManager(
            self.model_cache, max_slots=decode_slots, ttl_s=decode_ttl_s,
            max_wait_ms=decode_max_wait_ms, retry_after_s=self.retry_after_s)
        self._t_start = time.time()
        self._batchers: dict = {}
        self._batcher_lock = threading.Lock()
        # speculative decoders, one per (vocab, k, draft config) — the
        # per-session drafting state lives inside them
        self._spec_decoders: dict = {}
        self._spec_lock = threading.Lock()
        self._last_ready: Optional[bool] = None
        self._c_shed = monitor.get_registry().counter(
            "dl4j_resilience_shed_total",
            "requests shed instead of served", labels=("reason",))
        # SLO monitoring (docs/OBSERVABILITY.md "Fleet federation &
        # SLOs"): slo=True arms the stock serving objectives, a list of
        # Objectives (or a ready SloTracker) customizes them; the
        # evaluator thread watches this process's registry and meters
        # dl4j_slo_* / journals slo.state_changed / flight-dumps on a
        # fast-burn flip
        self.slo = None
        if slo:
            from deeplearning4j_tpu.monitor.slo import SloTracker
            self.slo = (slo if isinstance(slo, SloTracker)
                        else SloTracker(None if slo is True else slo))
            self.slo.start(interval_s=slo_interval_s)

    def _load_model(self, model_path: str):
        return self.model_cache.get(model_path)

    @staticmethod
    def _data_iterator(data_dir: str):
        """Minibatch source for a data directory, by layout:

        * ``features/`` + ``labels/`` subdirs of ``batch_%d.h5`` — the
          reference's HDF5 layout (HDF5MiniBatchDataSetIterator.java:24);
        * ``batch_%d.h5`` files carrying features+labels datasets;
        * ``.npz`` exports (scaleout.data.PathDataSetIterator).
        """
        from deeplearning4j_tpu.scaleout.data import PathDataSetIterator
        from deeplearning4j_tpu.keras_import.hdf5_data import (
            _BATCH_RE, HDF5MiniBatchDataSetIterator)
        d = Path(data_dir)
        if (d / "features").is_dir() and (d / "labels").is_dir():
            return HDF5MiniBatchDataSetIterator(d / "features", d / "labels")
        # the iterator's own strict batch_%d.h5 pattern decides — a stray
        # non-conforming .h5 must not hijack a directory of .npz exports
        if any(_BATCH_RE.match(p.name) for p in d.iterdir()):
            return HDF5MiniBatchDataSetIterator(d)
        return PathDataSetIterator.from_dir(data_dir)

    def fit(self, model_path: str, data_dir: str, epochs: int = 1,
            save_path: Optional[str] = None,
            shape_bucketing: Optional[bool] = None) -> dict:
        """Train ``model_path`` on the minibatches in ``data_dir``
        (HDF5 ``batch_%d.h5`` layouts or .npz exports —
        :meth:`_data_iterator`).  Exported minibatch directories are the
        canonical ragged stream (the last shard is short), so
        ``shape_bucketing=True`` pads every batch up to its bucket and
        the step compiles once per bucket (ops/bucketing.py); retrace
        telemetry is returned alongside the score."""
        from deeplearning4j_tpu.nn.serialization import write_model
        from deeplearning4j_tpu.ops import bucketing
        bucketing.maybe_enable_persistent_cache()
        model = self.model_cache.get(model_path)
        if shape_bucketing is not None:
            model.conf.global_conf.shape_bucketing = bool(shape_bucketing)
        it = self._data_iterator(data_dir)
        for _ in range(int(epochs)):
            it.reset()
            while it.has_next():
                model.fit(it.next())
        out = save_path or model_path
        if not out.endswith(".zip"):
            out = str(Path(out).with_suffix(".zip"))
        write_model(model, out)
        # training mutated the in-memory instance away from the on-disk
        # file its cache key names — drop it (the written checkpoint
        # re-caches on next use; same-path saves also changed the mtime)
        self.invalidate(model_path)
        result = {"score": float(model.score()), "model_path": out}
        tel = getattr(model, "compile_telemetry", None)
        if tel is not None:
            result["compile_telemetry"] = tel.snapshot()
        return result

    def evaluate(self, model_path: str, data_dir: str) -> dict:
        model = self.model_cache.get(model_path)
        ev = model.evaluate(self._data_iterator(data_dir))
        return {"accuracy": ev.accuracy(), "f1": ev.f1()}

    # ------------------------------------------------------------------
    # Inference
    # ------------------------------------------------------------------
    def predict(self, model_path: str, data_dir: Optional[str] = None,
                features=None, top_k: Optional[int] = None,
                argmax_only: bool = False,
                coalesce: Optional[bool] = None,
                deadline_ms: Optional[float] = None,
                tenant: Optional[str] = None) -> dict:
        """Run inference with the cached, bucket-warmed model.

        Exactly one input source: ``data_dir`` (exported minibatch
        directory — already batched, runs batch-at-a-time) or
        ``features`` (an inline ``[k, ...]`` row batch — the serving
        path; concurrent requests coalesce through the micro-batcher
        unless ``coalesce=False``).

        ``deadline_ms`` is the request's total budget: a request still
        queued in the batcher when it expires is shed before compute
        (``DeadlineExceededError`` → HTTP 504); admission control may
        reject it up front (``OverloadedError`` → HTTP 503 +
        ``Retry-After``) when queued rows exceed ``max_queue_rows``.

        Response shaping for classification clients: ``argmax_only``
        returns class ids; ``top_k=K`` returns the K best class ids +
        probabilities per row — both avoid serializing the full
        ``[n, n_classes]`` probability matrix to JSON."""
        # request-scoped tracing: reuse the request ID the HTTP server
        # minted for this RPC (or mint one for direct in-process calls)
        # so admission, the batcher queue and the coalesced compute all
        # journal under the same correlation ID
        with events.request_scope(tenant=tenant,
                                  model=os.path.basename(str(model_path))):
            return self._predict(model_path, data_dir, features, top_k,
                                 argmax_only, coalesce, deadline_ms, tenant)

    def _predict(self, model_path, data_dir, features, top_k,
                 argmax_only, coalesce, deadline_ms, tenant) -> dict:
        faults.check("gateway.predict")
        if (data_dir is None) == (features is None):
            raise ValueError(
                "predict needs exactly one of data_dir= or features=")
        if features is not None:
            x = np.asarray(features, dtype=np.float32)
            if x.ndim < 1 or x.shape[0] == 0:
                raise ValueError("features must be a non-empty [k, ...] "
                                 "row batch")
            use_batcher = self.coalesce if coalesce is None else bool(coalesce)
            if use_batcher:
                # admission BEFORE the (possibly breaker-guarded) model
                # load: an overloaded server sheds cheap and early
                self._admit(len(x), tenant=tenant)
            model = self.model_cache.get(
                model_path, warmup_dims=tuple(x.shape[1:]),
                max_batch=self.max_batch)
            if use_batcher:
                out = self._batcher_for(model_path, model).predict(
                    x, timeout_ms=deadline_ms, tenant=tenant)
            else:
                out = self._infer_fn(model)(x)
            return self._format_predictions(out, top_k, argmax_only)

        model = self.model_cache.get(model_path)
        it = self._data_iterator(data_dir)
        infer = self._infer_fn(model)
        outs = []
        while it.has_next():
            outs.append(infer(it.next().features))
        if outs:
            stacked = np.concatenate(outs)
        else:
            # keep output rank even with zero minibatches: (0, *out_dims)
            stacked = np.zeros((0,) + self._output_dims(model), np.float32)
        return self._format_predictions(stacked, top_k, argmax_only)

    def warmup(self, model_path: str, feature_dims,
               max_batch: Optional[int] = None,
               spec_k: Optional[int] = None) -> dict:
        """Explicitly pre-compile the serving bucket ladder for
        ``model_path`` (``feature_dims`` is the per-example feature
        shape) — what the first ``features=`` predict does implicitly.
        ``spec_k=K`` additionally warms the decode pool's fused
        speculative-verify program per slot-ladder rung
        (``DecodePool.warmup_spec``) so the first
        ``decode_step(spec=...)`` never pays a cold compile."""
        model = self.model_cache.get(model_path)
        out = model.warmup_inference(
            feature_dims, max_batch=int(max_batch or self.max_batch))
        if spec_k is not None:
            out["spec"] = self.decode.warmup_spec(
                model_path, feature_dims, k=int(spec_k))
        return out

    def invalidate(self, model_path: Optional[str] = None) -> dict:
        """Drop cached model(s) — and their batchers and decode pools
        (open sessions fail) — so the next request reloads from disk
        (explicit cache-invalidation RPC; a changed file mtime
        invalidates implicitly)."""
        n = self.model_cache.invalidate(model_path)
        self.decode.invalidate(model_path)
        with self._batcher_lock:
            keys = ([os.path.abspath(str(model_path))]
                    if model_path is not None else list(self._batchers))
            dropped = [self._batchers.pop(k) for k in keys
                       if k in self._batchers]
        for _, batcher in dropped:
            batcher.stop()
        return {"invalidated": n}

    # ------------------------------------------------------------------
    # Stateful decode sessions (server/decode.py — ROADMAP 3b)
    # ------------------------------------------------------------------
    def open_session(self, model_path: str,
                     tenant: Optional[str] = None) -> dict:
        """Open a stateful decode session: the model's recurrent carry
        for this stream lives on device in the model's slot pool, so
        every subsequent :meth:`decode_step` is O(1) in how much of the
        stream has already been consumed.  503 + Retry-After when every
        slot is held by a live session."""
        with events.request_scope(
                tenant=tenant, model=os.path.basename(str(model_path))):
            return self.decode.open_session(model_path, tenant=tenant)

    def decode_step(self, session_id: str, features,
                    mask=None, tenant: Optional[str] = None,
                    deadline_ms: Optional[float] = None,
                    top_k: Optional[int] = None,
                    argmax_only: bool = False,
                    spec=None, draft=None) -> dict:
        """Feed one ``[T, C]`` chunk (``T=1`` token-by-token; longer
        chunks are the prefill path) to a session and return the
        ``[T, ...]`` outputs.  Concurrent sessions' steps coalesce into
        one jitted slot-pool dispatch (continuous batching); admission
        control and per-tenant fair share apply exactly as for
        ``predict`` (one step = one queue row, matching the decode
        queue's accounting).

        ``spec=`` turns on speculative continuation AFTER the chunk:
        ``spec=N`` (or ``{"tokens": N, "k": K}``) greedily generates N
        more tokens via the fused verify program — draft proposals
        (``draft=`` — ``"ngram"`` by default, see
        ``server/speculative.py``) are scored K at a time in ONE
        compiled dispatch each, with exact greedy parity.  The response
        gains ``spec``: the generated token ids, the pending next
        token, and dispatch/acceptance counts."""
        with events.request_scope(tenant=tenant, session_id=session_id):
            self._admit(1, tenant=tenant)
            outs = self.decode.decode_step(
                session_id, features, mask=mask, timeout_ms=deadline_ms,
                tenant=tenant)
            spec_out = None
            if spec:
                spec_out = self._spec_continue(
                    session_id, outs, spec, draft, tenant=tenant,
                    deadline_ms=deadline_ms)
        result = self._format_predictions(outs[0], top_k, argmax_only)
        if len(outs) > 1:
            result["outputs"] = [np.asarray(o).tolist() for o in outs]
        result["session_id"] = session_id
        if spec_out is not None:
            result["spec"] = spec_out
        return result

    def _spec_continue(self, session_id: str, outs, spec, draft,
                       tenant=None, deadline_ms=None) -> dict:
        """Run the speculative greedy continuation for ``decode_step``'s
        ``spec=`` knob (one :class:`SpeculativeDecoder` per
        vocab/k/draft config, session state keyed inside it)."""
        from deeplearning4j_tpu.server import speculative
        cfg = {"tokens": int(spec)} if not isinstance(spec, dict) else spec
        n_tokens = int(cfg.get("tokens", 0))
        if n_tokens <= 0:
            return {"tokens": [], "dispatches": 0}
        k = int(cfg.get("k", 4))
        last = np.asarray(outs[0])[-1]
        vocab = int(last.shape[-1])
        key = (vocab, k, json.dumps(draft, sort_keys=True)
               if isinstance(draft, dict) else str(draft))
        with self._spec_lock:
            dec = self._spec_decoders.get(key)
            if dec is None:
                dec = speculative.SpeculativeDecoder(
                    self.decode, vocab=vocab, k=k, draft=draft)
                self._spec_decoders[key] = dec
        first = int(np.argmax(last))
        return dec.generate(session_id, first, n_tokens, tenant=tenant,
                            timeout_ms=deadline_ms)

    def close_session(self, session_id: str) -> dict:
        """Release a decode session's slot (its device carry is
        reclaimed for the next session)."""
        with self._spec_lock:
            decoders = list(self._spec_decoders.values())
        for dec in decoders:
            dec.forget(session_id)
        return {"closed": self.decode.close_session(session_id)}

    # ------------------------------------------------------------------
    # Cross-replica session migration (fleet/ tier — docs/FLEET.md)
    # ------------------------------------------------------------------
    def export_session(self, session_id: str) -> dict:
        """Phase one of a migration: snapshot the session's device
        carry as a JSON payload and hold its slot in exported limbo
        (excluded from stats/active counts) until ``finish_export``."""
        return self.decode.export_session(session_id)

    def finish_export(self, session_id: str, ok: bool = True) -> dict:
        """Phase two: ``ok=True`` releases the migrated session's slot;
        ``ok=False`` reinstates it (the import failed — the carry never
        left this replica's device pool)."""
        return {"finished": self.decode.finish_export(session_id,
                                                      ok=bool(ok))}

    def import_session(self, model_path: str, payload: dict,
                       session_id: Optional[str] = None,
                       tenant: Optional[str] = None) -> dict:
        """Restore an exported session onto THIS replica (the target
        half of a migration) — the stream continues from the imported
        carry with next-token parity against the source."""
        return self.decode.import_session(model_path, payload,
                                          session_id=session_id,
                                          tenant=tenant)

    def drain(self, deadline_ms: Optional[float] = None) -> dict:
        """Stop admitting decode session joins (opens and imports shed
        503) and report remaining sessions per pool — the rollout
        forcing function.  ``/readyz`` goes unready while draining so a
        load balancer shifts traffic; ``undrain`` re-admits."""
        deadline_s = None if deadline_ms is None \
            else max(0.0, float(deadline_ms)) / 1e3
        return {"pools": self.decode.drain(deadline_s),
                "draining": True}

    def undrain(self) -> dict:
        """Re-admit decode session joins after a drain (rollout done or
        aborted)."""
        self.decode.resume()
        return {"draining": False}

    def decode_stats(self) -> dict:
        """Per-model decode-pool observability: slots, sessions, step
        counts, the continuous-batching histogram and the bounded
        compiled-program count."""
        return self.decode.stats()

    # ------------------------------------------------------------------
    # Health / readiness (docs/RESILIENCE.md)
    # ------------------------------------------------------------------
    def _admit(self, n_rows: int, tenant: Optional[str] = None) -> None:
        """Bounded-queue admission control: reject (don't queue) when
        the rows already waiting across batchers and decode pools plus
        this request exceed ``max_queue_rows`` — and, with
        ``tenant_quota_rows`` set, when THIS tenant's queued rows would
        exceed its fair share (one tenant flooding the queue gets 503 +
        Retry-After while everyone else keeps being served)."""
        depth = self._queued_rows()
        if depth + n_rows > self.max_queue_rows:
            self._c_shed.labels(reason="queue_full").inc()
            events.emit("request.shed", severity="warn",
                        reason="queue_full", rows=n_rows, queued=depth)
            raise OverloadedError(
                f"queue full ({depth} rows waiting, limit "
                f"{self.max_queue_rows})", retry_after_s=self.retry_after_s)
        if self.tenant_quota_rows is not None:
            t = tenant or "-"
            held = self._tenant_queued_rows().get(t, 0)
            if held + n_rows > self.tenant_quota_rows:
                self._c_shed.labels(reason="tenant_quota").inc()
                events.emit("request.shed", severity="warn",
                            reason="tenant_quota", rows=n_rows, queued=held)
                raise OverloadedError(
                    f"tenant {t!r} over fair-share quota ({held} rows "
                    f"queued, limit {self.tenant_quota_rows})",
                    retry_after_s=self.retry_after_s)
        events.emit("request.admitted", rows=n_rows, queued=depth)

    def _queued_rows(self) -> int:
        with self._batcher_lock:
            batchers = [b for _, b in self._batchers.values()]
        return sum(b.queue_rows() for b in batchers) \
            + self.decode.queue_rows()

    def _tenant_queued_rows(self) -> dict:
        with self._batcher_lock:
            batchers = [b for _, b in self._batchers.values()]
        out: dict = {}
        for b in batchers:
            for t, n in b.queue_rows_by_tenant().items():
                out[t] = out.get(t, 0) + n
        for t, n in self.decode.queue_rows_by_tenant().items():
            out[t] = out.get(t, 0) + n
        return out

    def healthz(self) -> dict:
        """Liveness: the process is up and the RPC loop answers.  Stays
        200 even under injected faults or overload — unhealthy-vs-busy
        is ``readyz``'s distinction, not this one's."""
        return {"status": "ok", "uptime_s": round(time.time() -
                                                  self._t_start, 1)}

    def readyz(self) -> dict:
        """Readiness: should a load balancer send traffic here NOW?
        Ready iff every batcher thread is alive, queued rows are under
        the admission limit, the model-load breaker (if any) is not
        open, and at least ``min_ready_models`` models are resident and
        warm."""
        with self._batcher_lock:
            batchers = list(self._batchers.values())
        queued = sum(b.queue_rows() for _, b in batchers)
        breaker = getattr(self.model_cache, "load_breaker", None)
        cache_stats = self.model_cache.stats()
        warm = sum(1 for m in cache_stats["models"].values()
                   if m.get("warmup") is not None)
        checks = {
            "batchers_alive": all(b.thread_alive for _, b in batchers),
            # decode pools with live sessions must have a live dispatch
            # thread too — a dead decode batcher strands every open
            # session, which is exactly what an LB should drain over
            "decode_alive": self.decode.batchers_alive(),
            # a draining replica is mid-rollout/migration: an LB (or
            # the fleet router) should place sessions elsewhere
            "not_draining": not self.decode.draining,
            "queue_below_limit": queued < self.max_queue_rows,
            "breaker_closed": (breaker is None
                               or breaker.state != CircuitBreaker.OPEN),
            "models_warm": len(cache_stats["models"])
                           >= self.min_ready_models,
        }
        ready = all(checks.values())
        # a flip to not-ready is a crash-adjacent moment: journal it and
        # snapshot the black box while the evidence is still in the ring
        if self._last_ready is not None and ready != self._last_ready:
            failing = sorted(k for k, v in checks.items() if not v)
            events.emit("readyz.flip", severity="warn" if not ready
                        else "info", ready=ready, failing=failing)
            if not ready:
                flight.dump("readyz_not_ready",
                            extra={"checks": checks, "queued_rows": queued})
        self._last_ready = ready
        return {"ready": ready, "checks": checks,
                "queued_rows": queued,
                "models_resident": cache_stats["size"],
                "models_warmed": warm}

    def stats(self) -> dict:
        """Serving observability: model-cache counters, per-model
        batcher metrics (queue/compute/total latency percentiles,
        batch-size histogram), each resident model's
        ``CompileTelemetry`` snapshot, AND the process-wide metrics
        registry — one RPC sees retraces, latencies, phase timings and
        memory together (keys ``model_cache``/``serving`` are unchanged
        for existing clients; ``registry`` is additive)."""
        out = {"model_cache": self.model_cache.stats(), "serving": {}}
        with self._batcher_lock:
            items = list(self._batchers.items())
        for key, (model, batcher) in items:
            s = batcher.metrics.snapshot()
            tel = getattr(model, "compile_telemetry", None)
            if tel is not None:
                s["compile_telemetry"] = tel.snapshot()
            out["serving"][key] = s
        out["decode"] = self.decode.stats()
        if self.slo is not None:
            out["slo"] = self.slo.states()
        out["registry"] = monitor.get_registry().snapshot()
        return out

    def metrics(self, format: str = "prometheus",
                scope: str = "process"):
        """The scrape endpoint as an RPC.  ``format="prometheus"``
        (default) returns ``{"content_type", "body"}`` with text-format
        v0.0.4 (also served raw at ``GET /metrics`` for a stock
        Prometheus scraper / ``curl``); ``format="json"`` returns the
        registry snapshot dict itself.  ``scope`` is accepted for
        surface parity with the fleet router — a single gateway only
        has ``"process"`` scope (``"fleet"`` is served by
        ``fleet.SessionRouter``)."""
        fmt = str(format).lower()
        if str(scope).lower() != "process":
            raise ValueError(
                f"scope {scope!r} is not served by a single gateway — "
                "fleet scope is the fleet router's surface "
                "(fleet/router.py)")
        snap = monitor.get_registry().snapshot()
        if fmt == "json":
            return snap
        if fmt != "prometheus":
            raise ValueError(f"format must be prometheus or json, "
                             f"got {format!r}")
        return {"content_type": monitor.CONTENT_TYPE,
                "body": monitor.render_prometheus(snap)}

    def trace_dump(self, last_n: Optional[int] = None,
                   format: str = "events", request_id: Optional[str] = None,
                   dump: bool = False, reason: str = "manual",
                   scope: str = "local") -> dict:
        """Live access to the structured event journal (the flight
        recorder's source).  ``format="events"`` (default) returns the
        newest ``last_n`` journal events (optionally filtered to one
        ``request_id`` — "what happened to THIS request");
        ``format="chrome"`` returns the Chrome trace-event export under
        ``trace`` (save ``.trace`` to a file and open it in Perfetto /
        ``chrome://tracing`` to see a serving burst or a slow fit epoch
        as real slices).  ``dump=True`` also writes a flight-recorder
        file and returns its path.  ``scope`` is accepted for surface
        parity with the fleet router (which assembles every replica's
        journal); a single gateway only serves its ``"local"``
        journal."""
        fmt = str(format).lower()
        if fmt not in ("events", "chrome"):
            raise ValueError(f"format must be events or chrome, got "
                             f"{format!r}")
        if str(scope).lower() not in ("local", "process"):
            raise ValueError(
                f"scope {scope!r} is not served by a single gateway — "
                "fleet trace assembly is the fleet router's surface "
                "(fleet/router.py)")
        journal = events.get_journal()
        evts = journal.tail(n=last_n, request_id=request_id)
        out: dict = {"count": len(evts),
                     "total_emitted": journal.total_emitted,
                     "dropped": journal.dropped}
        if dump:
            out["path"] = flight.dump(reason, force=True)
        if fmt == "chrome":
            out["trace"] = events.chrome_trace(evts)
        else:
            out["events"] = evts
        return out

    def close(self) -> None:
        """Stop all batcher threads and decode pools (server
        shutdown; open decode sessions fail cleanly)."""
        if self.slo is not None:
            self.slo.stop()
        with self._batcher_lock:
            dropped = list(self._batchers.values())
            self._batchers.clear()
        for _, batcher in dropped:
            batcher.stop()
        self.decode.close()

    # ------------------------------------------------------------------
    # Internals
    # ------------------------------------------------------------------
    @staticmethod
    def _infer_fn(model):
        """Row-aligned numpy inference callable over a model's jitted
        ``output`` (first output for multi-output graphs)."""
        def infer(x):
            out = model.output(x)
            if isinstance(out, tuple):
                out = out[0]
            return np.asarray(out)
        return infer

    def _batcher_for(self, model_path: str, model) -> MicroBatcher:
        """The micro-batcher bound to this model instance; a reloaded
        model (stale mtime / invalidate) gets a fresh batcher."""
        key = os.path.abspath(str(model_path))
        with self._batcher_lock:
            entry = self._batchers.get(key)
            if entry is not None and entry[0] is model:
                return entry[1]
            old = entry[1] if entry is not None else None
            g = model.conf.global_conf
            batcher = MicroBatcher(
                self._infer_fn(model),
                max_batch=self.max_batch, max_wait_ms=self.max_wait_ms,
                min_batch=self.min_batch,
                bucket_sizes=g.bucket_batch_sizes,
                # the model pads internally when bucketing is on — don't
                # pad twice (idempotent, but wasted host work)
                pad_to_bucket=not g.shape_bucketing,
                name=os.path.basename(key))
            self._batchers[key] = (model, batcher)
        if old is not None:
            old.stop()
        return batcher

    @staticmethod
    def _output_dims(model):
        """Per-example output shape when there is no data to infer it
        from (the zero-minibatch fallback must keep output rank)."""
        if hasattr(model, "_output_layer_confs"):  # ComputationGraph
            confs = list(model._output_layer_confs().values())
            n_out = int(getattr(confs[0], "n_out", 0) or 0) if confs else 0
        else:
            n_out = int(getattr(model.layers[-1], "n_out", 0) or 0)
        return (n_out,) if n_out else ()

    @staticmethod
    def _format_predictions(out, top_k=None, argmax_only=False) -> dict:
        out = np.asarray(out)
        if argmax_only:
            cls = np.argmax(out, axis=-1)
            return {"classes": cls.tolist(), "shape": list(cls.shape)}
        if top_k:
            k = max(1, min(int(top_k), out.shape[-1]))
            idx = np.argsort(out, axis=-1)[..., ::-1][..., :k]
            vals = np.take_along_axis(out, idx, axis=-1)
            return {"top_k": k, "classes": idx.tolist(),
                    "probabilities": vals.tolist(), "shape": list(idx.shape)}
        return {"predictions": out.tolist(), "shape": list(out.shape)}


class Server:
    """(ref: keras/Server.java — `new GatewayServer(new
    DeepLearning4jEntryPoint()).start()`).  JSON-RPC over HTTP:

    POST / {"method": "fit", "params": {...}} →
        {"result": {...}} or {"error": "..."}

    ``debug=True`` includes the full traceback in error payloads;
    by default clients only see the exception type and message
    (tracebacks leak host paths and internals).
    """

    def __init__(self, entry_point: Optional[DeepLearning4jEntryPoint] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 debug: bool = False):
        ep = entry_point or DeepLearning4jEntryPoint()
        self.entry_point = ep
        self.debug = bool(debug)
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _respond(self, code, payload, content_type,
                         headers=None):
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(payload)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                """The probe surfaces a stock scraper / load balancer /
                ``curl`` hits without JSON-RPC framing: ``/metrics``
                (Prometheus text), ``/healthz`` (liveness, always 200
                while the process answers), ``/readyz`` (readiness —
                503 while shedding/unwarm/breaker-open, so an LB drains
                this replica instead of feeding it) and ``/trace`` (the
                live event journal; ``?format=chrome`` returns the
                Perfetto-loadable Chrome trace-event export directly,
                ``?request_id=`` filters to one request's events)."""
                path, _, query = self.path.partition("?")
                try:
                    from urllib.parse import parse_qs
                    q = {k: v[-1] for k, v in parse_qs(query).items()}
                    if path == "/trace":
                        fmt = q.get("format", "events")
                        last_n = (int(q["last_n"]) if "last_n" in q
                                  else None)
                        kw = ({"scope": q["scope"]} if "scope" in q
                              else {})
                        r = ep.trace_dump(last_n=last_n, format=fmt,
                                          request_id=q.get("request_id"),
                                          **kw)
                        # chrome format serves the bare trace object so
                        # the response body IS a Perfetto-loadable file
                        body = r["trace"] if fmt == "chrome" else r
                        server._count_request("GET /trace", 200)
                        self._respond(
                            200, json.dumps(body, default=str).encode(),
                            "application/json")
                    elif path == "/metrics":
                        # ?scope=fleet on a fleet router serves the
                        # federated merge; a single gateway only has
                        # process scope
                        kw = ({"scope": q["scope"]} if "scope" in q
                              else {})
                        m = ep.metrics(**kw)
                        server._count_request("GET /metrics", 200)
                        self._respond(200, m["body"].encode(),
                                      m["content_type"])
                    elif path == "/healthz":
                        server._count_request("GET /healthz", 200)
                        self._respond(200, json.dumps(ep.healthz()).encode(),
                                      "application/json")
                    elif path == "/readyz":
                        r = ep.readyz()
                        code = 200 if r["ready"] else 503
                        server._count_request("GET /readyz", code)
                        self._respond(code, json.dumps(r).encode(),
                                      "application/json")
                    else:
                        self._respond(404, b'{"error": "not found"}',
                                      "application/json")
                except Exception as e:
                    server._count_request(f"GET {path}", 500)
                    self._respond(500, json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode(),
                        "application/json")

            def do_POST(self):
                method = ""
                headers = {}
                # the gateway ADOPTS an upstream trace/request ID when
                # the caller sends one (the fleet router's hop header —
                # one request_scope then correlates the full
                # router→replica flow in GET /trace) and mints one
                # otherwise; every event this RPC produces (admission,
                # batcher queue, coalesced compute, decode step)
                # journals under it, and the client gets it back for
                # support-ticket correlation
                rid = (self.headers.get("X-DL4J-Request-ID") or "").strip() \
                    or events.new_request_id()
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    method = req.get("method", "")
                    params = req.get("params", {})
                    if not isinstance(params, dict):
                        raise ValueError("params must be an object")
                    if method.startswith("_") or not hasattr(ep, method):
                        raise AttributeError(f"no method {method!r}")
                    with events.scope(request_id=rid, method=method,
                                      tenant=params.get("tenant")):
                        events.emit("rpc.request")
                        result = getattr(ep, method)(**params)
                        events.emit("rpc.response", code=200)
                    payload = json.dumps({"result": result,
                                          "request_id": rid},
                                         default=str).encode()
                    code = 200
                except Exception as e:
                    err = {"error": f"{type(e).__name__}: {e}",
                           "request_id": rid}
                    # resilience errors carry their HTTP semantics:
                    # shed/short-circuited → 503 + Retry-After (back
                    # off, come back), expired deadline → 504
                    if isinstance(e, (OverloadedError, CircuitOpenError)):
                        code = 503
                        headers["Retry-After"] = str(max(
                            1, int(round(e.retry_after_s or 1.0))))
                        err["retry_after_s"] = e.retry_after_s
                    elif isinstance(e, DeadlineExceededError):
                        code = 504
                    else:
                        code = 500
                        if server.debug:
                            err["traceback"] = traceback.format_exc()
                    with events.scope(request_id=rid, method=method or "?"):
                        events.emit("rpc.response", severity="warn",
                                    code=code, error=type(e).__name__)
                    payload = json.dumps(err).encode()
                headers["X-DL4J-Request-ID"] = rid
                server._count_request(method or "?", code)
                self._respond(code, payload, "application/json", headers)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address
        self._thread: Optional[threading.Thread] = None
        self._requests_c = monitor.get_registry().counter(
            "dl4j_gateway_requests_total", "gateway RPC calls",
            labels=("method", "code"))

    def _count_request(self, method: str, code: int) -> None:
        self._requests_c.labels(method=method, code=str(code)).inc()

    def start(self) -> "Server":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        close = getattr(self.entry_point, "close", None)
        if close is not None:
            close()
