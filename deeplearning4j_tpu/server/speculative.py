"""Speculative greedy decode on top of the slot-pool decode path.

Plain autoregressive serving pays ONE compiled dispatch per emitted
token: step, argmax, feed the winner back, step again.  Speculative
decode batches that loop through the pool's fused verify program
(``server/decode._spec_verify_raw`` — arXiv 1410.0759's
efficient-primitives playbook: fuse the K scoring dispatches into one):

1. a cheap **draft proposer** guesses the next K tokens,
2. ONE fused dispatch feeds ``[pending] + drafts`` through the target
   model token-by-token IN TRACE, computes the longest draft prefix the
   target's own greedy argmax agrees with, and lands the session's
   device carry at exactly that acceptance point,
3. every committed token is, by construction, the token step-by-step
   greedy decode would have emitted — **exact greedy parity**, with up
   to (K+1)-fold fewer dispatches per accepted token.

Draft quality only affects SPEED (acceptance length), never output:
a perfect draft commits K+1 tokens per dispatch, a useless one commits
1 (the known-greedy pending token) — the plain decode rate.

**Sampling mode** (``temperature=``/``top_k=``/``seed=``) extends the
same contract beyond greedy: the verify program samples each position
from the target distribution via a position-keyed Gumbel-argmax draw
(equivalent to ``min(1, p/q)`` rejection sampling against the
deterministic draft, with the residual resample built in), so the
emitted trajectory is EXACTLY the one the non-speculative sampling
loop would emit at the same seed — acceptance length changes only the
dispatch count, never the tokens.

Proposers are pluggable (:class:`DraftProposer`): :class:`NGramDraft`
is the self-drafting default (suffix lookup over the session's own
emitted history — "prompt lookup" drafting: free, and exact-K on
periodic/repetitive streams), :class:`ScriptedDraft` drives tests
through every acceptance length, and :class:`ModelDraft` wraps a
smaller model's own greedy loop (the classic two-model setup).

Token feedback is one-hot by default (``vocab == n_out == n_in``, the
self-feeding language-model loop); pass ``token_to_features=`` for
embedding-fed models.

Metered as ``dl4j_spec_*`` (docs/OBSERVABILITY.md); every verify step
journals ``decode.spec_verified`` with proposed/accepted counts.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np


class DraftProposer:
    """Interface: propose up to ``k`` next tokens given the stream's
    token history (prompt ids if known, plus every committed token).
    Returning fewer than ``k`` (or none) is legal — the verify chunk
    just shrinks toward plain decode."""

    def propose(self, history: Sequence[int], k: int) -> List[int]:
        raise NotImplementedError

    def observe(self, tokens: Sequence[int]) -> None:
        """Committed tokens, in order (drafts keep their own state —
        e.g. ModelDraft advances its carry on exactly the accepted
        prefix)."""


class NGramDraft(DraftProposer):
    """Suffix-lookup self-drafting: find the most recent earlier
    occurrence of the stream's final ``order``-gram and propose the
    tokens that followed it.  Free (no model), deterministic, and
    exact-K on streams that repeat — the common case for structured
    output.  Falls back to shorter suffixes down to 1 token; proposes
    nothing on a cold stream (the verify step degrades to plain
    decode)."""

    def __init__(self, order: int = 3):
        self.order = max(1, int(order))

    def propose(self, history: Sequence[int], k: int) -> List[int]:
        h = list(history)
        n = len(h)
        if n < 2 or k <= 0:
            return []
        for m in range(min(self.order, n - 1), 0, -1):
            suffix = h[n - m:]
            best: List[int] = []
            # most recent earlier occurrence wins, but an older match
            # with a FULL k-token continuation beats a recent one
            # truncated by the end of history (the all-repeats case)
            for i in range(n - m - 1, -1, -1):
                cont = h[i + m:i + m + k] if h[i:i + m] == suffix else []
                if len(cont) == k:
                    return cont
                if len(cont) > len(best):
                    best = cont
            if best:
                return best
        return []


class ScriptedDraft(DraftProposer):
    """Test/bench draft: pops pre-planned proposals in order (each an
    explicit token list), then proposes nothing.  Forces any acceptance
    length deterministically."""

    def __init__(self, proposals: Sequence[Sequence[int]]):
        self._proposals = [list(p) for p in proposals]

    def propose(self, history: Sequence[int], k: int) -> List[int]:
        if not self._proposals:
            return []
        return [int(t) for t in self._proposals.pop(0)[:k]]


class ModelDraft(DraftProposer):
    """Shortened-model drafting: a (smaller, cheaper) draft model runs
    its own greedy loop ``rnn_time_step`` by token to guess the
    target's continuation.  ``observe`` replays exactly the committed
    tokens through the draft model so its carry tracks the real stream
    (rejected guesses are rolled back by re-feeding from the accepted
    history — the draft model is cheap; the TARGET never re-runs)."""

    def __init__(self, model, vocab: int,
                 token_to_features: Optional[Callable] = None):
        self.model = model
        self.vocab = int(vocab)
        self._to_feat = token_to_features or (
            lambda toks: one_hot(toks, self.vocab))
        self._seen = 0           # committed tokens consumed by the carry

    def _feed(self, tokens: Sequence[int]) -> Optional[np.ndarray]:
        if not tokens:
            return None
        out = self.model.rnn_time_step(self._to_feat(list(tokens))[None])
        out = out[0] if isinstance(out, tuple) else out
        return np.asarray(out)[0]

    def observe(self, tokens: Sequence[int]) -> None:
        del tokens  # propose() re-syncs from the authoritative history

    def propose(self, history: Sequence[int], k: int) -> List[int]:
        h = list(history)
        if not h or k <= 0:
            return []
        # re-sync: feed whatever committed tokens the carry hasn't seen
        # (after a rejection the draft carry is AHEAD of the stream —
        # cheapest correct reset is replaying the whole history)
        if self._seen > len(h):
            self.model.rnn_clear_previous_state()
            self._seen = 0
        out = self._feed(h[self._seen:])
        self._seen = len(h)
        if out is None:
            return []
        drafts: List[int] = []
        nxt = int(np.argmax(out[-1]))
        for _ in range(k):
            drafts.append(nxt)
            out = self._feed([nxt])
            nxt = int(np.argmax(out[-1]))
        self._seen += len(drafts)   # carry has consumed its own guesses
        return drafts


def one_hot(tokens: Sequence[int], vocab: int) -> np.ndarray:
    a = np.zeros((len(tokens), int(vocab)), np.float32)
    a[np.arange(len(tokens)), np.asarray(tokens, np.int64)] = 1.0
    return a


def make_draft(spec) -> DraftProposer:
    """Build a proposer from the gateway's ``draft=`` knob: a
    :class:`DraftProposer`, a name (``"ngram"``), or a config dict
    (``{"kind": "ngram", "order": 3}``)."""
    if isinstance(spec, DraftProposer):
        return spec
    if spec is None:
        return NGramDraft()
    if isinstance(spec, str):
        spec = {"kind": spec}
    kind = str(spec.get("kind", "ngram")).lower()
    if kind == "ngram":
        return NGramDraft(order=int(spec.get("order", 3)))
    if kind == "none":
        return ScriptedDraft([])
    raise ValueError(f"unknown draft proposer {kind!r} "
                     "(expected ngram|none or a DraftProposer)")


class SpecSession:
    """Host-side speculative state for one decode session: the token
    history (draft context) and per-session draft proposer.  Device
    state stays entirely in the pool; losing this object (e.g. across a
    migration — it does NOT ride the carry payload) only cold-starts
    drafting, never correctness."""

    __slots__ = ("draft", "history", "dispatches", "proposed", "accepted",
                 "pos")

    def __init__(self, draft: DraftProposer):
        self.draft = draft
        self.history: List[int] = []
        self.dispatches = 0
        self.proposed = 0
        self.accepted = 0
        # absolute sampling position: keys the per-token PRNG so the
        # trajectory is independent of how tokens group into dispatches
        self.pos = 0


class SpeculativeDecoder:
    """Greedy speculative generation against a :class:`DecodePool` (or
    the gateway's :class:`DecodeManager` — anything with
    ``spec_step(sid, feats, token_ids, ...)``)."""

    def __init__(self, stepper, vocab: int, k: int = 4,
                 draft=None, token_to_features: Optional[Callable] = None,
                 temperature: Optional[float] = None, top_k: int = 0,
                 seed: Optional[int] = None):
        self.stepper = stepper
        self.vocab = int(vocab)
        self.k = max(0, int(k))
        self._draft_spec = draft
        self._to_feat = token_to_features or (
            lambda toks: one_hot(toks, self.vocab))
        # sampling mode: either knob switches the verify from greedy
        # argmax to the seeded rejection-sampled acceptance program
        self.temperature = temperature
        self.top_k = max(0, int(top_k))
        self.seed = seed
        self.sampling_on = temperature is not None or seed is not None
        self._lock = threading.Lock()
        self._sessions: Dict[str, SpecSession] = {}

    def _session(self, sid: str) -> SpecSession:
        with self._lock:
            s = self._sessions.get(sid)
            if s is None:
                s = SpecSession(make_draft(self._draft_spec))
                self._sessions[sid] = s
            return s

    def forget(self, sid: str) -> None:
        with self._lock:
            self._sessions.pop(sid, None)

    def generate(self, sid: str, first_token: int, n_tokens: int,
                 tenant: Optional[str] = None,
                 timeout_ms: Optional[float] = None) -> dict:
        """Emit ``n_tokens`` greedy tokens starting from ``first_token``
        (the target's known-greedy next token — the argmax of the last
        prefill output).  Byte-identical to the step-by-step greedy
        loop; dispatches collapse by the acceptance rate."""
        s = self._session(sid)
        out: List[int] = []
        pending = int(first_token)
        dispatches = proposed = 0
        while len(out) < int(n_tokens):
            budget = int(n_tokens) - len(out)
            k = min(self.k, max(0, budget - 1))
            drafts = [int(t) % self.vocab for t in
                      s.draft.propose(s.history + [pending], k)][:k]
            chunk = [pending] + drafts
            feats = self._to_feat(chunk)
            kw = {}
            if self.sampling_on:
                kw["sampling"] = {
                    "temperature": float(
                        1.0 if self.temperature is None
                        else self.temperature),
                    "top_k": self.top_k,
                    "seed": int(self.seed or 0),
                    "pos": s.pos,
                }
            _, greedy, acc = self.stepper.spec_step(
                sid, feats, chunk, timeout_ms=timeout_ms, tenant=tenant,
                **kw)
            acc = max(1, min(int(acc), budget))
            committed = chunk[:acc]
            out.extend(committed)
            s.history.extend(committed)
            s.draft.observe(committed)
            dispatches += 1
            proposed += len(drafts)
            s.pos += acc
            pending = int(greedy[acc - 1])
        s.dispatches += dispatches
        s.proposed += proposed
        s.accepted += len(out)
        return {
            "tokens": out[:int(n_tokens)],
            "next_token": pending,
            "dispatches": dispatches,
            "proposed": proposed,
            "accepted": len(out),
            "tokens_per_dispatch": round(len(out) / dispatches, 3)
            if dispatches else 0.0,
        }
