"""Serving model cache — load and jit-warm a model once, not per request.

The gateway's original entry point re-read the checkpoint from disk (and
re-traced every jitted entry point) on EVERY ``fit``/``evaluate``/
``predict`` call, so the shape-bucketing compile cache never survived a
request.  This cache keys loaded models by ``(abspath, mtime_ns)``:

* a **hit** returns the in-memory model with its jit trace cache (and
  the persistent ``CompileTelemetry``) intact;
* a changed file mtime is a **stale reload** — the checkpoint on disk
  wins, the old instance is dropped;
* **LRU eviction** bounds resident models (``capacity``);
* ``warmup_dims`` triggers **bucket warmup** on load (or lazily on the
  first hit that knows the request's feature shape):
  ``model.warmup_inference`` pre-compiles the configured bucket ladder
  through the real jitted ``output`` path, so first requests never pay
  a cold XLA compile.

Explicit ``invalidate`` mirrors the reference's model-server reload
semantics (a republished checkpoint must take effect without bouncing
the server); the gateway exposes it as an RPC.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Callable, Optional

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.monitor import events
from deeplearning4j_tpu.resilience import faults


def default_loader(path: str):
    """Checkpoint sniffing shared with the gateway: Keras ``.h5`` via
    keras_import, anything else through the framework's load_model."""
    p = str(path)
    if p.endswith((".h5", ".hdf5")):
        from deeplearning4j_tpu.keras_import import KerasModelImport
        return KerasModelImport.import_keras_model_and_weights(p)
    from deeplearning4j_tpu.nn.serialization import load_model
    return load_model(p)


class ModelCache:
    """LRU cache of loaded (and optionally jit-warmed) models keyed by
    ``(abspath, mtime_ns)``.  Thread-safe: concurrent requests for the
    same path load the checkpoint once."""

    def __init__(self, capacity: int = 4,
                 loader: Optional[Callable] = None,
                 load_retry=None, load_breaker=None,
                 blue_green: bool = False):
        """``load_retry`` (a ``resilience.RetryPolicy``) retries
        transient load failures; ``load_breaker`` (a
        ``resilience.CircuitBreaker``) fails fast once loads keep
        failing, so a broken checkpoint path can't pile threads up
        behind the cache lock.  Both default to off; the serving
        gateway arms them on its cache (``/readyz`` reports the breaker
        state).

        ``blue_green=True`` turns a stale-mtime reload into a ROLLOUT
        (ROADMAP 3c): the old version keeps serving while a background
        thread loads the republished checkpoint and jit-warms it
        through ``warmup_inference`` (reusing the dims the old entry
        was warmed with), then the entry flips atomically — no request
        ever blocks on the new version's load/compile, and ``readyz``
        stays ready throughout because the old model remains resident
        and warm."""
        self.capacity = max(1, int(capacity))
        self._loader = loader or default_loader
        self.load_retry = load_retry
        self.load_breaker = load_breaker
        self.blue_green = bool(blue_green)
        self._lock = threading.RLock()
        self._entries: "OrderedDict[str, dict]" = OrderedDict()
        self._rollouts: dict = {}   # key → {"mtime": target mtime_ns}
        self.hits = 0
        self.misses = 0
        self.stale_reloads = 0
        self.evictions = 0
        self.rollouts = 0
        self.rollout_failures = 0
        # mirrored into the process registry (aggregated over caches) so
        # hit rates land in the same /metrics scrape as latencies
        reg = monitor.get_registry()
        self._counters = {
            k: reg.counter(f"dl4j_model_cache_{k}_total",
                           f"model cache {k.replace('_', ' ')}")
            for k in ("hits", "misses", "stale_reloads", "evictions")}
        self._c_rollouts = reg.counter(
            "dl4j_model_cache_rollouts_total",
            "blue/green model version flips completed")
        self._c_rollout_failures = reg.counter(
            "dl4j_model_cache_rollout_failures_total",
            "background rollout loads/warms that failed "
            "(the old version kept serving)")
        self._g_warming = reg.gauge(
            "dl4j_model_cache_warming",
            "blue/green background warms in flight")
        self._g_resident = reg.gauge("dl4j_model_cache_resident",
                                     "models resident across caches")

    def _count(self, what: str) -> None:
        setattr(self, what, getattr(self, what) + 1)
        self._counters[what].inc()

    @staticmethod
    def _apply_quant(model, quantize) -> None:
        """Activate weight-only quantized serving on a freshly loaded
        model.  Precedence: explicit ``quantize`` argument ('off' wins
        over everything and forces dense) > ``DL4J_SERVE_QUANT`` env >
        the checkpoint conf's ``precision_infer_quant``.  Engines
        without the tier (e.g. word2vec wrappers) are skipped; the
        registry's kill switches/self-test still gate the actual
        engagement inside quantize_inference."""
        if quantize is None:
            quantize = os.environ.get("DL4J_SERVE_QUANT")
        if quantize is None and hasattr(model, "conf"):
            quantize = getattr(model.conf.global_conf,
                               "precision_infer_quant", None)
        if quantize is None:
            return
        mode = str(quantize).lower()
        if mode in ("", "0", "off", "none", "false"):
            mode = None
        if hasattr(model, "quantize_inference"):
            model.quantize_inference(mode)

    def get(self, path, shape_bucketing: Optional[bool] = None,
            warmup_dims=None, max_batch: int = 32,
            quantize: Optional[str] = None):
        """The cached model for ``path``, loading (and bucket-warming)
        on first use or when the file changed on disk.

        ``shape_bucketing`` overrides the checkpoint's flag at load time
        (serving wants it on even for models trained without it).
        ``warmup_dims`` — the per-example feature shape — pre-compiles
        the inference bucket ladder up to ``max_batch`` rows; passing it
        on a hit warms lazily if the entry was loaded by a path (fit /
        evaluate) that didn't know the serving shape yet.
        ``quantize`` ('int8' | 'fp8', default ``DL4J_SERVE_QUANT`` or
        the checkpoint conf's ``precision_infer_quant``) serves from
        weight-only quantized params — the ~4x-smaller resident
        weights the precision tiers buy (docs/PERFORMANCE.md)."""
        key = os.path.abspath(str(path))
        mtime = os.stat(key).st_mtime_ns
        with self._lock:
            e = self._entries.get(key)
            if e is not None and e["mtime"] != mtime:
                if self.blue_green:
                    # rollout: OLD keeps serving; the new version loads
                    # and warms on a background thread and flips when
                    # ready (idempotent while one warm is in flight)
                    self._start_rollout_locked(key, mtime, shape_bucketing,
                                               quantize)
                else:
                    self._count("stale_reloads")
                    del self._entries[key]
                    e = None
            if e is not None:
                self._count("hits")
                self._entries.move_to_end(key)
            else:
                self._count("misses")
                model = self._load(key)
                if shape_bucketing is not None:
                    model.conf.global_conf.shape_bucketing = \
                        bool(shape_bucketing)
                self._apply_quant(model, quantize)
                e = {"mtime": mtime, "model": model, "warmup": None,
                     "loaded_at": time.time()}
                self._entries[key] = e
                while len(self._entries) > self.capacity:
                    evicted, _ = self._entries.popitem(last=False)
                    self._count("evictions")
                    events.emit("cache.evicted",
                                model=os.path.basename(evicted))
            self._g_resident.set(len(self._entries))
            if warmup_dims is not None and e["warmup"] is None \
                    and hasattr(e["model"], "warmup_inference"):
                e["warmup"] = e["model"].warmup_inference(
                    warmup_dims, max_batch=max_batch)
                # remembered so a blue/green warm can replay the same
                # serving ladder on the replacement version
                e["warmup_dims"] = tuple(warmup_dims)
                e["warmup_max_batch"] = int(max_batch)
            return e["model"]

    def _start_rollout_locked(self, key: str, mtime: int,
                              shape_bucketing, quantize=None) -> None:
        roll = self._rollouts.get(key)
        if roll is not None and roll.get("mtime") == mtime:
            return   # this version is already warming
        self._rollouts[key] = {"mtime": mtime, "started_at": time.time()}
        self._g_warming.set(len(self._rollouts))
        old = self._entries.get(key) or {}
        warm_dims = old.get("warmup_dims")
        warm_mb = old.get("warmup_max_batch", 32)
        t = threading.Thread(
            target=self._rollout, daemon=True,
            name=f"model-rollout:{os.path.basename(key)}",
            args=(key, mtime, shape_bucketing, warm_dims, warm_mb,
                  quantize))
        t.start()

    def _rollout(self, key, mtime, shape_bucketing, warm_dims, warm_mb,
                 quantize=None):
        """Background leg of a blue/green flip: load + warm OUTSIDE the
        cache lock (requests keep hitting the old entry), then swap the
        entry atomically.  Failure keeps the old version serving and
        counts ``dl4j_model_cache_rollout_failures_total``."""
        try:
            model = self._load(key)
            if shape_bucketing is not None:
                model.conf.global_conf.shape_bucketing = \
                    bool(shape_bucketing)
            self._apply_quant(model, quantize)
            warm = None
            if warm_dims is not None and hasattr(model, "warmup_inference"):
                warm = model.warmup_inference(warm_dims, max_batch=warm_mb)
            new_mtime = os.stat(key).st_mtime_ns
            with self._lock:
                e = {"mtime": new_mtime, "model": model, "warmup": warm,
                     "loaded_at": time.time()}
                if warm_dims is not None:
                    e["warmup_dims"] = tuple(warm_dims)
                    e["warmup_max_batch"] = int(warm_mb)
                self._entries[key] = e
                self._entries.move_to_end(key)
                self._count("stale_reloads")
                self.rollouts += 1
            self._c_rollouts.inc()
            events.emit("rollout.flip", model=os.path.basename(key),
                        mtime_ns=new_mtime)
        except Exception as ex:
            with self._lock:
                self.rollout_failures += 1
            self._c_rollout_failures.inc()
            events.emit("rollout.failed", severity="error",
                        model=os.path.basename(key),
                        error=f"{type(ex).__name__}: {ex}")
        finally:
            with self._lock:
                self._rollouts.pop(key, None)
                self._g_warming.set(len(self._rollouts))

    def _load(self, key: str):
        """One checkpoint load through the resilience stack: the
        ``cache.load`` fault site, then retry (inner — a transient
        flake is absorbed before the breaker sees it), then the breaker
        (outer — it counts exhausted retry sequences, and fails fast
        with ``CircuitOpenError`` while open)."""
        def attempt():
            faults.check("cache.load")
            return self._loader(key)

        def with_retry():
            if self.load_retry is None:
                return attempt()
            return self.load_retry.call(attempt)

        t0 = time.perf_counter()
        try:
            if self.load_breaker is None:
                model = with_retry()
            else:
                model = self.load_breaker.call(with_retry)
        except BaseException as e:
            events.emit("cache.load", severity="error",
                        model=os.path.basename(key), ok=False,
                        error=f"{type(e).__name__}: {e}")
            raise
        events.emit("cache.load", model=os.path.basename(key), ok=True,
                    duration_s=round(time.perf_counter() - t0, 6))
        return model

    def wait_warm(self, path=None, timeout_s: float = 60.0) -> bool:
        """Block until no blue/green background warm is in flight for
        ``path`` (or for any entry when None) — the rollout runbook's
        wait step (docs/FLEET.md): after republishing a checkpoint,
        ``wait_warm`` returning True means the flip happened (or failed
        and was counted) and the next ``get`` serves a settled version.
        Returns False on timeout."""
        key = None if path is None else os.path.abspath(str(path))
        deadline = time.monotonic() + max(0.0, float(timeout_s))
        while True:
            with self._lock:
                warming = (bool(self._rollouts) if key is None
                           else key in self._rollouts)
            if not warming:
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.02)

    def peek(self, path):
        """The cached model if (and only if) it is resident and fresh —
        no load, no counter changes (stats/telemetry introspection)."""
        key = os.path.abspath(str(path))
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return None
            try:
                if os.stat(key).st_mtime_ns != e["mtime"]:
                    return None
            except OSError:
                return None
            return e["model"]

    def invalidate(self, path=None) -> int:
        """Drop one cached model (``path``) or all of them (None).
        Returns how many entries were dropped."""
        with self._lock:
            if path is None:
                n = len(self._entries)
                self._entries.clear()
            else:
                key = os.path.abspath(str(path))
                n = 1 if self._entries.pop(key, None) is not None else 0
            self._g_resident.set(len(self._entries))
            return n

    def stats(self) -> dict:
        with self._lock:
            models = {
                k: {"mtime_ns": e["mtime"],
                    "loaded_at": e["loaded_at"],
                    "warmup": e["warmup"],
                    "warming": k in self._rollouts}
                for k, e in self._entries.items()
            }
            out = {
                "capacity": self.capacity,
                "size": len(models),
                "hits": self.hits,
                "misses": self.misses,
                "stale_reloads": self.stale_reloads,
                "evictions": self.evictions,
                "rollouts": self.rollouts,
                "rollout_failures": self.rollout_failures,
                "warming": len(self._rollouts),
                "models": models,
            }
        if self.load_breaker is not None:
            out["load_breaker"] = self.load_breaker.snapshot()
        return out
