"""Stateful O(1) autoregressive decode: continuous batching over a
device-resident session-slot cache.

The reference's signature streaming-inference capability is
``rnnTimeStep`` — per-layer recurrent state maps that make each step
O(1) in prefix length (ref: MultiLayerNetwork.rnnTimeStep :2383,
ComputationGraph.rnnTimeStep :1569).  That analog
(``MultiLayerNetwork.rnn_time_step``) is host-side and single-stream:
one client's carry lives in ``net_state`` and every concurrent stream
would need its own model instance.  This module is the production form
(ROADMAP item 3b; "Compiler-First State Space Duality and Portable O(1)
Autoregressive Caching for Inference", arXiv 2603.09555 — compile the
carried-state cache instead of re-tracing it):

* **Session-slot pool** (:class:`DecodePool`): recurrent carries for up
  to ``max_slots`` concurrent sessions live ON DEVICE as one pytree of
  ``[S+1, ...]`` arrays (slot ``S`` is a scratch row for padding).  A
  session owns a slot for its lifetime; its carry never round-trips to
  the host between tokens.

* **One pre-compiled step**: each dispatch is a single jitted call —
  gather the active slots' carries (``pool[idx]``), run the engines'
  carried step (``_rnn_step_raw``, the seam shared with
  ``rnn_time_step``), scatter the updated carries back
  (``pool.at[idx].set``) — with the pool buffer DONATED, so the cache
  is updated in place.  Freshly-opened sessions zero their gathered
  carry in-trace (the ``fresh`` mask) so slot reuse needs no host-side
  pool mutation and no extra compiled program.

* **Continuous batching** (:class:`_DecodeBatcher`): sessions join and
  leave the running batch between steps — concurrent ``decode_step``
  calls enqueue with a future, the batcher thread drains at most one
  pending step per session, pads the joined set up to the slot
  bucket-ladder (and each chunk's time axis up to the time ladder, with
  masked pad steps carrying state through unchanged), and dispatches.
  Retraces are bounded by ladder sizes, not by how sessions come and go.

* **Resilience**: slot exhaustion → :class:`OverloadedError` (the
  gateway's 503 + Retry-After), idle sessions expire after ``ttl_s``,
  expired deadlines shed before compute, and a killed batcher thread
  (fault site ``decode.step``) fails every in-flight session cleanly —
  futures error, slots reclaim, the next submit restarts the thread.

* **Session migration** (the fleet tier's seam, docs/FLEET.md): a
  session's entire decode state is one slot slice of the carry pytree —
  an explicit, relocatable object (arXiv 2603.09555's compiled-carry
  contract; arXiv 2112.01075's portable-redistribution view).
  :meth:`DecodePool.export_session` host-gathers that slice (riding the
  batcher's control queue, so device state is only ever touched by the
  thread that owns it) into a JSON-serializable payload;
  :meth:`DecodePool.import_session` restores it into another pool's
  slot on another replica with exact float round-trip — the migrated
  stream continues within 1e-6 of an unmigrated twin.  Export is
  two-phase: the source slot is held in an ``exported`` limbo (excluded
  from stats/active counts, steps rejected as retryable) until
  :meth:`finish_export` confirms the import landed — or reinstates the
  session when it didn't.  :meth:`drain` is the rollout forcing
  function: stop admitting joins, report what remains, let migration
  move it.  Both halves run through the ``fleet.migrate`` fault site.

Metered as the ``dl4j_decode_*`` family (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import base64
import contextlib
import io
import logging
import os
import threading
import time
import uuid
from concurrent.futures import Future
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.analysis import sanitizer
from deeplearning4j_tpu.monitor import events, flight
from deeplearning4j_tpu.ops import bucketing
from deeplearning4j_tpu.parallel import sequence as seq_ops
from deeplearning4j_tpu.resilience import faults
from deeplearning4j_tpu.resilience.errors import (
    DeadlineExceededError, OverloadedError, TransientError)

log = logging.getLogger(__name__)

tree_map = jax.tree_util.tree_map


class DecodeMetrics:
    """Registry-backed telemetry for one decode pool (the
    ``dl4j_decode_*`` family) plus plain counters for the stats RPC."""

    def __init__(self, name: str = ""):
        reg = monitor.get_registry()
        self._name = name or "default"
        lbl = {"model": self._name}
        # request-path counters carry `tenant` (label parity with
        # dl4j_serving_requests_total) so per-tenant decode attribution
        # works straight off /metrics, without the journal
        self._f_opened = reg.counter(
            "dl4j_decode_sessions_opened_total",
            "decode sessions opened, per tenant", ("model", "tenant"))
        self._f_closed = reg.counter(
            "dl4j_decode_sessions_closed_total",
            "decode sessions closed, by reason", ("model", "reason"))
        self.g_active = reg.gauge(
            "dl4j_decode_active_sessions", "decode sessions currently open",
            ("model",)).labels(**lbl)
        self.g_capacity = reg.gauge(
            "dl4j_decode_slot_capacity", "decode slot-pool capacity",
            ("model",)).labels(**lbl)
        self._f_steps = reg.counter(
            "dl4j_decode_steps_total", "decode session-steps served",
            ("model", "tenant"))
        self._f_tokens = reg.counter(
            "dl4j_decode_tokens_total", "timesteps decoded, per tenant",
            ("model", "tenant"))
        self.c_batches = reg.counter(
            "dl4j_decode_batches_total",
            "continuous-batching decode dispatches", ("model",)).labels(**lbl)
        self.h_step = reg.histogram(
            "dl4j_decode_step_seconds",
            "one gather→step→scatter jitted decode call",
            ("model",)).labels(**lbl)
        self.h_queue = reg.histogram(
            "dl4j_decode_queue_seconds", "decode step enqueue → dispatch",
            ("model",)).labels(**lbl)
        self._c_shed = reg.counter(
            "dl4j_resilience_shed_total",
            "requests shed instead of served", labels=("reason",))
        # KV-cache residency (set when the pool materializes its carry)
        self.g_kv_rings = reg.gauge(
            "dl4j_kv_rings", "KV rings in the pool's carry (attention "
            "layers x slots share one ring buffer)", ("model",)).labels(**lbl)
        self.g_kv_bytes = reg.gauge(
            "dl4j_kv_ring_bytes", "device bytes held by KV ring K/V "
            "buffers across all slots", ("model",)).labels(**lbl)
        self.g_kv_window = reg.gauge(
            "dl4j_kv_window", "widest KV ring window (tokens) in the "
            "pool's carry", ("model",)).labels(**lbl)
        # paged KV arena residency (DL4J_KV_PAGED pools): capacity is
        # tokens RESIDENT, not slots x worst-case window
        self.g_arena_blocks = reg.gauge(
            "dl4j_kv_arena_blocks", "paged KV arena capacity in blocks, "
            "summed over attention layers", ("model",)).labels(**lbl)
        self.g_arena_free = reg.gauge(
            "dl4j_kv_arena_blocks_free", "paged KV arena blocks on the "
            "free lists, summed over attention layers",
            ("model",)).labels(**lbl)
        self.g_arena_tokens = reg.gauge(
            "dl4j_kv_arena_tokens_resident", "KV tokens resident across "
            "live sessions (per stream, capped at the widest effective "
            "window)", ("model",)).labels(**lbl)
        self.c_arena_failures = reg.counter(
            "dl4j_kv_arena_alloc_failures_total", "decode steps shed "
            "because the paged KV arena had no free blocks",
            ("model",)).labels(**lbl)
        # speculative decode (the fused verify path)
        self._f_spec_steps = reg.counter(
            "dl4j_spec_steps_total", "fused speculative verify dispatches",
            ("model", "tenant"))
        self._f_spec_proposed = reg.counter(
            "dl4j_spec_tokens_proposed_total",
            "draft tokens scored by verify steps", ("model", "tenant"))
        self._f_spec_accepted = reg.counter(
            "dl4j_spec_tokens_accepted_total",
            "tokens committed by verify steps (pending + accepted draft "
            "prefix)", ("model", "tenant"))
        self.h_spec_accept = reg.histogram(
            "dl4j_spec_accept_len", "tokens committed per fused verify "
            "dispatch", ("model",)).labels(**lbl)
        self._lock = threading.Lock()
        self.steps = 0
        self.tokens = 0
        self.batches = 0
        self.spec_steps = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.batch_size_hist: Dict[int, int] = {}

    def record_opened(self, tenant: Optional[str]) -> None:
        self._f_opened.labels(model=self._name, tenant=tenant or "-").inc()

    def record_step(self, tenant: Optional[str], n_tokens: int = 0) -> None:
        self._f_steps.labels(model=self._name, tenant=tenant or "-").inc()
        if n_tokens:
            # tokens attribute per tenant at the step (the request
            # path), not per batch — per-tenant series sum to the
            # model's total without double counting
            self._f_tokens.labels(model=self._name,
                                  tenant=tenant or "-").inc(n_tokens)
            with self._lock:
                self.tokens += n_tokens

    def record_spec(self, tenant: Optional[str], proposed: int,
                    accepted: int) -> None:
        t = tenant or "-"
        self._f_spec_steps.labels(model=self._name, tenant=t).inc()
        if proposed:
            self._f_spec_proposed.labels(model=self._name,
                                         tenant=t).inc(proposed)
        self._f_spec_accepted.labels(model=self._name, tenant=t).inc(accepted)
        self.h_spec_accept.observe(float(accepted))
        with self._lock:
            self.spec_steps += 1
            self.spec_proposed += proposed
            self.spec_accepted += accepted

    def record_closed(self, reason: str) -> None:
        self._f_closed.labels(model=self._name, reason=reason).inc()

    def record_shed(self, reason: str) -> None:
        self._c_shed.labels(reason=reason).inc()

    def record_batch(self, n_steps: int) -> None:
        with self._lock:
            self.steps += n_steps
            self.batches += 1
            self.batch_size_hist[n_steps] = \
                self.batch_size_hist.get(n_steps, 0) + 1
        self.c_batches.inc()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "steps": self.steps,
                "tokens": self.tokens,
                "batches": self.batches,
                "steps_per_batch_mean":
                    round(self.steps / self.batches, 2) if self.batches
                    else 0.0,
                "batch_size_hist": {str(k): v for k, v in
                                    sorted(self.batch_size_hist.items())},
                "spec_steps": self.spec_steps,
                "spec_tokens_proposed": self.spec_proposed,
                "spec_tokens_accepted": self.spec_accepted,
                "spec_accept_per_dispatch":
                    round(self.spec_accepted / self.spec_steps, 2)
                    if self.spec_steps else 0.0,
            }


class DecodeSession:
    __slots__ = ("sid", "slot", "tenant", "created_at", "last_used",
                 "steps", "started", "migrating", "exported", "importing",
                 "kv_blocks", "kv_pos")

    def __init__(self, sid: str, slot: int, tenant: Optional[str]):
        self.sid = sid
        self.slot = slot
        self.tenant = tenant
        self.created_at = time.monotonic()
        self.last_used = self.created_at
        self.steps = 0
        # paged-KV bookkeeping (kv_paged pools): per-layer lists of
        # arena block ids this session owns (allocation order == the
        # logical block order its table rows are built in), and the
        # host mirror of the stream's device write position — the
        # allocator's ground truth for how many blocks the NEXT chunk
        # needs.  Freed back to the pool exactly once, in _close_locked.
        self.kv_blocks: Optional[List[List[int]]] = None
        self.kv_pos = 0
        # False until the first dispatched step: the pool step zeroes
        # gathered carries for fresh rows in-trace, so a reused slot's
        # stale carry is never observed
        self.started = False
        # migration limbo: `migrating` rejects new steps (retryable)
        # while an export is being prepared; `exported` means the carry
        # snapshot left this pool — the slot is held but the session no
        # longer counts as active until finish_export() closes it (the
        # import landed) or reinstates it (the import failed)
        self.migrating = False
        self.exported = False
        # True between an import's slot claim and its carry scatter
        # landing on the batcher thread — the slot's device state is
        # not this session's yet (the dl4j-check KV probe reads this)
        self.importing = False


class _PendingStep:
    __slots__ = ("session", "xs", "masks", "future", "t_enqueue",
                 "deadline", "tenant", "ctx", "spec_tokens", "sampling")

    def __init__(self, session, xs, masks, future, deadline, tenant,
                 ctx=None, spec_tokens=None, sampling=None):
        self.session = session
        self.xs = xs          # tuple of per-input [T, ...] host arrays
        self.masks = masks    # tuple of per-input [T] masks or None
        self.future = future
        self.t_enqueue = time.perf_counter()
        self.deadline = deadline  # absolute time.monotonic(), or None
        self.tenant = tenant
        # trace context captured at enqueue (request_id etc) — the
        # batcher thread re-attaches it to this step's journal events
        self.ctx = ctx or {}
        # speculative verify: the fed token ids [T] (pending + drafts);
        # None = a normal decode step.  Spec and normal steps never
        # share a dispatch (different compiled programs).
        self.spec_tokens = spec_tokens
        # sampling-mode spec verify: {"temperature","top_k","seed","pos"}
        # or None (greedy).  top_k is a compile-time constant (its own
        # program); temperature/seed/pos are dynamic inputs.
        self.sampling = sampling

    @property
    def request_id(self):
        return self.ctx.get("request_id")


# ---------------------------------------------------------------------------
# Carry payload encoding (the fleet migration hop — docs/FLEET.md).
# Version 2 ships every carry leaf as base64-npy bytes: exact binary
# round-trip (npy preserves shape/dtype/bits) at ~1/8 the wire size of
# the v1 JSON float lists — required now that KV-cache carries make a
# session's state MB-sized.  Import accepts both versions (v1 payloads
# from not-yet-upgraded replicas keep migrating); DL4J_CARRY_PAYLOAD=json
# forces the v1 encoding on export for a mixed-version fleet.
CARRY_PAYLOAD_VERSION = 2


def _encode_carry_leaf(a: np.ndarray, binary: bool) -> dict:
    a = np.asarray(a)
    spec = {"shape": list(a.shape), "dtype": str(a.dtype)}
    if binary:
        buf = io.BytesIO()
        np.save(buf, a, allow_pickle=False)
        spec["npy_b64"] = base64.b64encode(buf.getvalue()).decode("ascii")
    else:
        spec["data"] = a.ravel().tolist()
    return spec


def _decode_carry_leaf(spec: dict) -> np.ndarray:
    if "npy_b64" in spec:
        a = np.load(io.BytesIO(base64.b64decode(spec["npy_b64"])),
                    allow_pickle=False)
    else:
        a = np.asarray(spec["data"], dtype=np.dtype(spec["dtype"]))
    a = a.reshape(tuple(spec["shape"]))
    if str(a.dtype) != spec["dtype"]:
        want = np.dtype(spec["dtype"])
        if a.dtype.kind == "V" and a.dtype.itemsize == want.itemsize:
            # ml_dtypes leaves (bfloat16 carries, fp8) come back from
            # npy as raw void bytes: reinterpret, never cast — the
            # migration hop stays bit-exact
            a = a.view(want)
        else:
            a = a.astype(want)
    return a


def _kv_ring_summary(tree) -> dict:
    """Walk a carry pytree for KV rings (dicts shaped like
    ``kv_ring_init``: k/v/pos) and summarize them for the ``dl4j_kv_*``
    gauges — ring count, K+V device bytes, and the widest window."""
    out = {"rings": 0, "bytes": 0, "window": 0}

    def walk(node):
        if isinstance(node, dict):
            if set(node.keys()) == {"k", "v", "pos"} \
                    and getattr(node["k"], "ndim", 0) == 4:
                out["rings"] += 1
                out["bytes"] += int(node["k"].nbytes + node["v"].nbytes)
                out["window"] = max(out["window"], int(node["k"].shape[2]))
                return
            for v in node.values():
                walk(v)
        elif isinstance(node, (list, tuple)):
            for v in node:
                walk(v)

    walk(tree)
    return out


def _cast_carry(tree, dtype):
    """Store a carry template's non-KV float32 leaves at ``dtype``
    (bf16 halves the resident carry HBM; the step still computes at
    f32 — see :func:`_gather_slots`).  KV rings keep their own storage
    knob (``kv_dtype`` for paged arenas) and are left untouched, as
    are integer/bool leaves (positions, counters: must stay exact)."""
    def is_kv(node):
        return (isinstance(node, dict)
                and set(node.keys()) == {"k", "v", "pos"}
                and getattr(node.get("k"), "ndim", 0) == 4)

    def cast(node):
        if is_kv(node):
            return node
        if getattr(node, "dtype", None) == jnp.float32:
            return node.astype(dtype)
        return node

    return tree_map(cast, tree, is_leaf=is_kv)


def _gather_slots(pool, idx, fresh):
    """Gather the active slots' carries out of the pool, zeroing fresh
    rows in-trace (a slot newly claimed by a session must not inherit
    the previous tenant's state).  Sub-f32 float storage (a bf16 carry
    pool — ``carry_dtype``) is upcast to float32 HERE, so the step
    always computes at f32 regardless of how the carry is stored; the
    scatter side casts back to each pool leaf's dtype.  With an f32
    pool every branch below is a no-op and the trace is byte-identical
    to the pre-knob program."""
    def take(a):
        g = a[idx]
        if jnp.issubdtype(g.dtype, jnp.floating) \
                and jnp.dtype(g.dtype).itemsize < 4:
            g = g.astype(jnp.float32)
        f = fresh.reshape((-1,) + (1,) * (g.ndim - 1))
        return g * (1.0 - f).astype(g.dtype)

    return tree_map(take, pool)


def _pool_step_raw(model, is_graph: bool):
    """The ONE compiled decode program: gather the active slots' carries,
    run the engines' carried step, scatter the carries back.  ``fresh``
    zeroes a gathered carry in-trace (a slot newly claimed by a session
    must not inherit the previous tenant's state), so slot churn needs
    no host-side pool writes and no second compiled program."""
    rnn_raw = model._rnn_step_raw()

    def pool_step(params, state, pool, idx, fresh, xs, fms):
        carries = _gather_slots(pool, idx, fresh)
        if is_graph:
            outs, new_c = rnn_raw(params, state, carries, xs, fms)
        else:
            out, new_c = rnn_raw(params, state, carries, xs[0], fms[0])
            outs = (out,)
        new_pool = tree_map(lambda p, c: p.at[idx].set(c.astype(p.dtype)),
                            pool, new_c)
        return outs, new_pool

    return pool_step


def _paged_pool_step_raw(model, is_graph: bool, block_size: int):
    """The paged-arena twin of :func:`_pool_step_raw`: same gather →
    step → scatter shape, but the attention layers' K/V pages live in
    pool-shared arenas threaded through as explicit donated arguments
    (they cannot ride the per-slot carry — one arena serves every
    slot).  ``tbls`` carries each layer's per-row block table, built
    host-side from the allocator's ground truth each dispatch (the
    gathered carry's table is zeroed for fresh rows, so the device copy
    is never authoritative)."""
    rnn_raw = model._rnn_step_raw()

    def pool_step(params, state, pool, idx, fresh, xs, fms, arenas, tbls):
        carries = _gather_slots(pool, idx, fresh)
        tape = seq_ops.PagedTape(block_size=block_size, arenas=arenas,
                                 tables=tbls)
        with seq_ops.paged_scope(tape):
            if is_graph:
                outs, new_c = rnn_raw(params, state, carries, xs, fms)
            else:
                out, new_c = rnn_raw(params, state, carries, xs[0], fms[0])
                outs = (out,)
        new_pool = tree_map(lambda p, c: p.at[idx].set(c.astype(p.dtype)),
                            pool, new_c)
        return outs, new_pool, tape.collect()

    return pool_step


def _spec_verify_raw(model, is_graph: bool, *, block_size: Optional[int] = None,
                     sampling: bool = False, top_k: int = 0):
    """The ONE fused speculative-verify program (arXiv 1410.0759's
    efficient-primitives playbook: fuse the K scoring dispatches into a
    single compiled call).  The chunk — the known-greedy pending token
    followed by K draft tokens — runs token-by-token inside a
    ``lax.scan`` over the engines' carried step, stacking per-step
    outputs AND carries; the longest draft prefix the target model
    agrees with (greedy argmax) is computed IN TRACE, and the carry at
    exactly that acceptance point is selected and scattered back — so
    the session's device state is as if only the accepted tokens were
    ever fed (exact greedy parity, no rollback dispatch).

    Signature: ``(params, state, pool, idx, fresh, xs, tok, nv) ->
    (outs [B,T,C], greedy [B,T], accept [B], new_pool)`` where ``tok``
    is the fed token ids ``[B, T]`` and ``nv`` the per-row real chunk
    length (pad rows/steps are masked through, state unchanged).

    ``block_size``/``sampling``/``top_k`` select the generalized program
    (:func:`_spec_verify_general`) for paged-KV pools and/or
    temperature/top-k sampling acceptance; the defaults keep this exact
    greedy/dense program (byte-identical trace)."""
    if block_size is not None or sampling:
        return _spec_verify_general(model, is_graph, block_size=block_size,
                                    sampling=sampling, top_k=top_k)
    rnn_raw = model._rnn_step_raw()

    def spec_step(params, state, pool, idx, fresh, xs, tok, nv):
        c0 = _gather_slots(pool, idx, fresh)
        B, T = tok.shape
        valid = jnp.arange(T)[None, :] < nv[:, None]          # [B, T]

        def body(c, inp):
            xts, m_t = inp            # tuple of [B, C...], [B]
            xts = tuple(x[:, None] for x in xts)              # [B, 1, C]
            m = m_t[:, None].astype(jnp.float32)              # [B, 1]
            if is_graph:
                outs_t, c2 = rnn_raw(params, state, c, xts,
                                     tuple(m for _ in xts))
                out_t = outs_t[0]
            else:
                out_t, c2 = rnn_raw(params, state, c, xts[0], m)
            return c2, (out_t[:, 0], c2)

        xs_seq = tuple(jnp.moveaxis(x, 1, 0) for x in xs)     # [T, B, C]
        m_seq = jnp.moveaxis(valid, 1, 0)                     # [T, B]
        _, (outs, c_stack) = jax.lax.scan(body, c0, (xs_seq, m_seq))
        outs = jnp.moveaxis(outs, 0, 1)                       # [B, T, C]
        greedy = jnp.argmax(outs, axis=-1).astype(jnp.int32)  # [B, T]
        # token 0 is the already-known target-greedy pending token —
        # always accepted; draft token t (fed at position t) is
        # accepted iff the target's greedy after position t-1 equals it
        # and every earlier draft token was accepted (longest agreeing
        # prefix via a cumulative product)
        match = jnp.logical_and(greedy[:, :-1] == tok[:, 1:],
                                valid[:, 1:])                 # [B, T-1]
        lead = jnp.cumprod(match.astype(jnp.int32), axis=1)
        accept = jnp.minimum(1 + jnp.sum(lead, axis=1),
                             jnp.maximum(nv, 1)).astype(jnp.int32)
        # carry after exactly `accept` tokens: per-row select from the
        # stacked per-step carries (pad rows select garbage into the
        # scratch slot, which is never read)
        bidx = jnp.arange(B)
        sel = tree_map(lambda s: s[accept - 1, bidx], c_stack)
        new_pool = tree_map(lambda p, c: p.at[idx].set(c.astype(p.dtype)),
                            pool, sel)
        return outs, greedy, accept, new_pool

    return spec_step


def _spec_verify_general(model, is_graph: bool, *,
                         block_size: Optional[int] = None,
                         sampling: bool = False, top_k: int = 0):
    """Generalized fused verify: :func:`_spec_verify_raw` extended to
    paged-KV pools (arenas + block tables as explicit donated inputs,
    with in-trace rollback of rejected tokens' arena writes) and to
    SAMPLING acceptance (temperature/top-k rejection correction, so
    production sampling keeps the multi-token-per-dispatch win with the
    exact target distribution).

    Sampling uses the Gumbel-argmax coupling: ``argmax(log p + g)``
    with ``g ~ Gumbel(key)`` is an exact draw from ``p``, and keying
    ``g`` by ``(seed, absolute stream position)`` makes each position's
    draw independent of chunking — verify accepts draft token ``i`` iff
    the coupled draw at position ``i-1`` picks it (for the deterministic
    draft proposers this IS the ``min(1, p/q)`` rejection-sampling
    acceptance with the residual resample fused in: the emitted
    next-pending token ``pick[accept-1]`` is the coupled draw at the
    first disagreement), and the committed trajectory is bit-equal to
    non-speculative sampling at the same key schedule, for every
    acceptance length.

    Signature: the base ``(params, state, pool, idx, fresh, xs, tok,
    nv)`` plus ``(arenas, tbls)`` when paged plus ``(seed, pos0, temp)``
    when sampling; returns the base 4-tuple plus ``new_arenas`` when
    paged."""
    rnn_raw = model._rnn_step_raw()
    paged = block_size is not None

    def spec_step(params, state, pool, idx, fresh, xs, tok, nv, *rest):
        ri = 0
        arenas = tbls = None
        if paged:
            arenas, tbls = rest[0], rest[1]
            ri = 2
        if sampling:
            seed, pos0, temp = rest[ri], rest[ri + 1], rest[ri + 2]

        c0 = _gather_slots(pool, idx, fresh)
        B, T = tok.shape
        valid = jnp.arange(T)[None, :] < nv[:, None]          # [B, T]

        def body(carry, inp):
            c, ar = carry
            xts, m_t = inp            # tuple of [B, C...], [B]
            xts = tuple(x[:, None] for x in xts)              # [B, 1, C]
            m = m_t[:, None].astype(jnp.float32)              # [B, 1]
            tape = (seq_ops.PagedTape(block_size=block_size, arenas=ar,
                                      tables=tbls, record_undo=True)
                    if paged else None)
            ctx = (seq_ops.paged_scope(tape) if paged
                   else contextlib.nullcontext())
            with ctx:
                if is_graph:
                    outs_t, c2 = rnn_raw(params, state, c, xts,
                                         tuple(m for _ in xts))
                    out_t = outs_t[0]
                else:
                    out_t, c2 = rnn_raw(params, state, c, xts[0], m)
            ar2 = tape.collect() if paged else ar
            undo = tape.collect_undo() if paged else ()
            return (c2, ar2), (out_t[:, 0], c2, undo)

        xs_seq = tuple(jnp.moveaxis(x, 1, 0) for x in xs)     # [T, B, C]
        m_seq = jnp.moveaxis(valid, 1, 0)                     # [T, B]
        (_, arenas_f), (outs, c_stack, undo_stack) = jax.lax.scan(
            body, (c0, arenas if paged else ()), (xs_seq, m_seq))
        outs = jnp.moveaxis(outs, 0, 1)                       # [B, T, C]
        if sampling:
            logits = jnp.log(jnp.maximum(outs.astype(jnp.float32), 1e-30))
            logits = logits / jnp.maximum(
                temp.astype(jnp.float32), 1e-6)[:, None, None]
            C = logits.shape[-1]
            if 0 < top_k < C:
                kth = jnp.sort(logits, axis=-1)[..., C - top_k][..., None]
                logits = jnp.where(logits >= kth, logits, -1e30)
            logp = jax.nn.log_softmax(logits, axis=-1)
            base = jax.vmap(lambda s: jax.random.fold_in(
                jax.random.PRNGKey(0), s))(seed)
            ppos = pos0[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
            gum = jax.vmap(lambda kb, ps: jax.vmap(
                lambda p: jax.random.gumbel(
                    jax.random.fold_in(kb, p), (C,), jnp.float32))(ps))(
                        base, ppos)                           # [B, T, C]
            pick = jnp.argmax(logp + gum, axis=-1).astype(jnp.int32)
        else:
            pick = jnp.argmax(outs, axis=-1).astype(jnp.int32)
        match = jnp.logical_and(pick[:, :-1] == tok[:, 1:],
                                valid[:, 1:])                 # [B, T-1]
        lead = jnp.cumprod(match.astype(jnp.int32), axis=1)
        accept = jnp.minimum(1 + jnp.sum(lead, axis=1),
                             jnp.maximum(nv, 1)).astype(jnp.int32)
        bidx = jnp.arange(B)
        sel = tree_map(lambda s: s[accept - 1, bidx], c_stack)
        new_pool = tree_map(lambda p, c: p.at[idx].set(c.astype(p.dtype)),
                            pool, sel)
        if not paged:
            return outs, pick, accept, new_pool
        # arena rollback: the scan committed EVERY chunk token's K/V
        # write into the shared arenas (they cannot be stacked per step
        # like the per-slot carry) — restore the pre-write contents for
        # each row's rejected steps (j >= accept).  Within one chunk
        # every step writes a distinct ring slot (T <= w_eff), so one
        # masked scatter per layer restores them exactly; kept steps
        # write back their current contents (a no-op), and masked pad
        # rows restore the untouched scratch block over itself.
        jm = jnp.arange(T)[:, None] >= accept[None, :]        # [T, B]
        fixed = []
        for li, ar in enumerate(arenas_f):
            u = undo_stack[li]
            pb, o = u["pb"][:, 0], u["o"][:, 0]               # [T, B]
            ar2 = dict(ar)
            for key in ("k", "v"):
                old = u[key][:, 0]                            # [T, B, H, D]
                cur = ar2[key][pb, :, o, :]
                ar2[key] = ar2[key].at[pb, :, o, :].set(
                    jnp.where(jm[..., None, None], old, cur))
            fixed.append(ar2)
        return outs, pick, accept, new_pool, tuple(fixed)

    return spec_step


class DecodePool:
    """Device-resident slot-pool decode state for ONE model instance,
    with its continuous-batching dispatch thread.

    ``max_slots`` bounds concurrent sessions (exhaustion raises
    :class:`OverloadedError` after expiring idle sessions past
    ``ttl_s``).  ``slot_ladder`` buckets the per-dispatch joined-session
    count (powers of two up to ``max_slots`` by default) so compiled
    programs are bounded by the ladder, not by how many sessions happen
    to join a batch; chunk time axes bucket up to the model conf's time
    ladder the same way (masked pad steps carry state unchanged —
    exact)."""

    SCRATCH_DTYPE = np.float32

    def __init__(self, model, name: str = "", max_slots: int = 32,
                 ttl_s: float = 600.0,
                 slot_ladder: Optional[Sequence[int]] = None,
                 max_wait_ms: float = 2.0, min_batch: int = 1,
                 kv_paged: Optional[bool] = None,
                 kv_block: Optional[int] = None,
                 kv_arena_tokens: Optional[int] = None,
                 kv_dtype=None, carry_dtype=None):
        self.model = model
        self.name = name
        self.max_slots = max(1, int(max_slots))
        self.ttl_s = float(ttl_s)
        self._ladder = bucketing.warmup_ladder(slot_ladder, self.max_slots)
        self.max_wait_s = max(0.0, float(max_wait_ms)) / 1e3
        self.min_batch = max(1, min(int(min_batch), self.max_slots))
        # paged KV arena knobs (ctor > env > default): kv_paged swaps
        # the per-slot dense KV rings for one pool-shared block arena
        # per attention layer; kv_arena_tokens sets the per-layer token
        # capacity (default: max_slots x the widest effective window —
        # dense-equivalent HBM; set LOWER to serve more short sessions
        # in less memory); kv_dtype stores pages at e.g. bfloat16
        # (attention still accumulates at f32)
        if kv_paged is None:
            kv_paged = os.environ.get("DL4J_KV_PAGED", "") == "1"
        self.kv_paged = bool(kv_paged)
        if kv_block is None:
            kv_block = int(os.environ.get("DL4J_KV_BLOCK", "16") or 16)
        self.kv_block = max(1, int(kv_block))
        if kv_arena_tokens is None:
            env = os.environ.get("DL4J_KV_ARENA_TOKENS", "")
            kv_arena_tokens = int(env) if env else None
        self.kv_arena_tokens = (None if kv_arena_tokens is None
                                else max(1, int(kv_arena_tokens)))
        if kv_dtype is None:
            kv_dtype = os.environ.get("DL4J_KV_DTYPE", "") or None
        self._kv_dtype = (None if kv_dtype is None
                          else jnp.dtype(kv_dtype))
        # carry_dtype extends the bf16 storage story from KV pages to
        # the WHOLE per-slot carry: non-KV f32 leaves are stored at
        # this dtype and upcast to f32 at the gather (_gather_slots),
        # so the step computes exactly as before at half the resident
        # carry bytes
        if carry_dtype is None:
            carry_dtype = os.environ.get("DL4J_CARRY_DTYPE", "") or None
        self._carry_dtype = (None if carry_dtype is None
                             else jnp.dtype(carry_dtype))
        self._is_graph = hasattr(model, "_forward_all")
        self.n_inputs = (len(model.conf.network_inputs) if self._is_graph
                         else 1)
        self.metrics = DecodeMetrics(name)
        self.metrics.g_capacity.set(self.max_slots)
        self._cond = threading.Condition()
        self._queue: List[_PendingStep] = []
        self._inflight: List[_PendingStep] = []
        # migration/export ops ride this queue so ONLY the batcher
        # thread ever touches the device pool (tuples of
        # (kind, arg, Future))
        self._control: List[Tuple] = []
        self._sessions: Dict[str, DecodeSession] = {}
        self._free: List[int] = list(range(self.max_slots))
        self._running = True
        self._dead = False
        self._draining = False
        self.deaths = 0
        self.restarts = 0
        # device state — touched ONLY by the batcher thread after init
        # (donated buffers: a concurrent host-side .at[].set would race
        # the in-place update)
        self._pool = None
        self._tails: Optional[Tuple] = None
        self._dtype = np.dtype(np.float32)
        self._step_jit = None
        self._spec_jit = None
        self._kv_summary: dict = {}
        # paged-arena state: device arenas are batcher-thread-only like
        # the pool; the allocator's free lists + per-layer specs are
        # HOST state guarded by self._cond (admission runs under it)
        self._arenas = None
        self._arena_specs: Tuple[dict, ...] = ()
        self._arena_blocks: Tuple[int, ...] = ()
        self._kv_free: List[List[int]] = []
        self._thread = self._spawn_thread()

    # ------------------------------------------------------------------
    # Session lifecycle
    # ------------------------------------------------------------------
    def open_session(self, tenant: Optional[str] = None,
                     retry_after_s: float = 1.0) -> str:
        """Claim a slot; raises :class:`OverloadedError` when every slot
        is held by a live (non-expired) session."""
        with self._cond:
            if not self._running:
                raise RuntimeError("DecodePool is stopped")
            if self._draining:
                self.metrics.record_shed("decode_draining")
                events.emit("request.shed", severity="warn",
                            reason="decode_draining", model=self.name)
                raise OverloadedError(
                    "decode pool draining (rollout/migration in "
                    "progress)", retry_after_s=retry_after_s)
            self._sweep_locked()
            if not self._free:
                self.metrics.record_shed("decode_slots_full")
                events.emit("request.shed", severity="warn",
                            reason="decode_slots_full", model=self.name)
                raise OverloadedError(
                    f"decode slots exhausted ({self.max_slots} sessions "
                    "active)", retry_after_s=retry_after_s)
            slot = self._free.pop()
            sid = uuid.uuid4().hex[:16]
            self._sessions[sid] = DecodeSession(sid, slot, tenant)
            self.metrics.record_opened(tenant)
            self.metrics.g_active.set(self._active_locked())
            # emitted under the lock: journal order == admission order,
            # so a drain started right after this admit journals AFTER
            # it (the dl4j-check drain spec reads that ordering)
            events.emit("decode.session_opened", model=self.name,
                        session_id=sid, slot=slot, tenant=tenant)
        return sid

    def close_session(self, sid: str, reason: str = "closed") -> bool:
        with self._cond:
            closed = self._close_locked(sid, reason)
        return closed

    def _close_locked(self, sid: str, reason: str) -> bool:
        s = self._sessions.pop(sid, None)
        if s is None:
            return False
        self._free.append(s.slot)
        if s.kv_blocks is not None:
            # paged arena blocks return to the free lists EXACTLY once:
            # popping the session above makes this unreachable twice,
            # and the guard skips sessions outliving an arena reset
            # (batcher death drops the whole arena with them)
            for li, blks in enumerate(s.kv_blocks):
                if li < len(self._kv_free):
                    self._kv_free[li].extend(blks)
            s.kv_blocks = None
            self._update_arena_gauges_locked()
        stranded = [p for p in self._queue if p.session.sid == sid]
        self._queue = [p for p in self._queue if p.session.sid != sid]
        for p in stranded:
            if not p.future.done():
                p.future.set_exception(
                    RuntimeError(f"decode session {sid} closed ({reason}) "
                                 "with steps still queued"))
        self.metrics.record_closed(reason)
        self.metrics.g_active.set(self._active_locked())
        self._cond.notify_all()   # wake drain()/export waiters
        events.emit("decode.session_closed", model=self.name,
                    session_id=sid, slot=s.slot, tenant=s.tenant,
                    reason=reason, steps=s.steps,
                    severity="warn" if reason in ("batcher_died", "error")
                    else "info")
        return True

    def _active_locked(self) -> int:
        """Live sessions — exported slots are held but no longer count
        (the session's state has left this pool; counting it would
        double the fleet-wide total during a migration window)."""
        return sum(1 for s in self._sessions.values() if not s.exported)

    def _sweep_locked(self, now: Optional[float] = None) -> int:
        if self.ttl_s <= 0:
            return 0
        now = time.monotonic() if now is None else now
        # sessions in a migration window are NOT idle: TTL-reaping an
        # exported-limbo session frees its slot while the carry is in
        # flight to the target, and a failed import then has nothing to
        # reinstate — the stream dies instead of resuming (surfaced by
        # the dl4j-check session-lifecycle spec: close-from-exported
        # must be a protocol completion, never `ttl`)
        expired = [sid for sid, s in self._sessions.items()
                   if not s.exported and not s.migrating
                   and now - s.last_used > self.ttl_s]
        for sid in expired:
            self._close_locked(sid, reason="ttl")
        return len(expired)

    def sweep(self) -> int:
        """Expire idle sessions past ``ttl_s`` (also runs on every
        ``open_session`` and between batches)."""
        with self._cond:
            return self._sweep_locked()

    @property
    def active_sessions(self) -> int:
        with self._cond:
            return self._active_locked()

    @property
    def held_slots(self) -> int:
        """Slots currently claimed, INCLUDING exported-but-unconfirmed
        sessions (rollout adoption must wait for these too — their
        migration may still abort back onto this pool)."""
        with self._cond:
            return len(self._sessions)

    @property
    def draining(self) -> bool:
        with self._cond:
            return self._draining

    def session_ids(self) -> List[str]:
        with self._cond:
            return list(self._sessions)

    # ------------------------------------------------------------------
    # Client side
    # ------------------------------------------------------------------
    def submit_step(self, sid: str, xs, masks=None,
                    timeout_ms: Optional[float] = None,
                    tenant: Optional[str] = None) -> Future:
        """Enqueue one decode step for a session; the future resolves to
        the tuple of per-output ``[T, ...]`` arrays for that session's
        rows.  ``xs`` is one ``[T, ...]`` array per network input."""
        return self._submit(sid, xs, masks, timeout_ms, tenant, None)

    def submit_spec_step(self, sid: str, xs, token_ids,
                         timeout_ms: Optional[float] = None,
                         tenant: Optional[str] = None,
                         sampling: Optional[dict] = None) -> Future:
        """Enqueue one fused speculative-verify step: ``xs`` carries the
        feature rows for the pending token plus K draft tokens,
        ``token_ids`` their ``[T]`` int ids.  The future resolves to
        ``(outs [T, C], greedy [T], accepted)`` — ``accepted`` tokens
        (>= 1: the pending token is known-greedy) were committed to the
        session's device carry in the ONE dispatch; the rest were
        rolled back in-trace.

        ``sampling`` switches the verify from greedy argmax to exact
        rejection-sampled acceptance: a dict of ``temperature`` (float),
        ``top_k`` (int, 0 = full vocab), ``seed`` (int) and ``pos`` (the
        session's absolute sampling position, keys the per-token PRNG so
        trajectories are chunking-independent)."""
        tok = np.asarray(token_ids, np.int32).ravel()
        xs_n = self._normalize_inputs(xs)
        if any(a.ndim < 2 for a in xs_n):
            raise ValueError("speculative decode needs sequence inputs "
                             "([T, C] per network input)")
        if any(a.shape[0] != tok.shape[0] for a in xs_n):
            raise ValueError(
                f"token_ids has {tok.shape[0]} entries but the feature "
                f"chunk has {xs_n[0].shape[0]} timesteps")
        return self._submit(sid, xs, None, timeout_ms, tenant, tok,
                            sampling=sampling)

    def _submit(self, sid, xs, masks, timeout_ms, tenant,
                spec_tokens, sampling=None) -> Future:
        xs = self._normalize_inputs(xs)
        masks = self._normalize_masks(masks, xs)
        deadline = (None if timeout_ms is None
                    else time.monotonic() + float(timeout_ms) / 1e3)
        with self._cond:
            if not self._running:
                raise RuntimeError("DecodePool is stopped")
            s = self._sessions.get(sid)
            if s is None:
                raise KeyError(f"unknown or expired decode session {sid!r}")
            if s.migrating or s.exported:
                # retryable: the router re-sends once the session lands
                # on its new replica (or is reinstated here)
                raise TransientError(
                    f"decode session {sid} is migrating; retry")
            restarted = False
            if self._dead or not self._thread.is_alive():
                self._dead = False
                self.restarts += 1
                self._thread = self._spawn_thread()
                restarted = True
            # the future is only born once the step is admitted — a
            # rejected submit must not mint one (dl4j-check's resolved-
            # on-all-schedules obligation counts every future)
            fut = Future()
            p = _PendingStep(s, xs, masks, fut, deadline,
                             tenant if tenant is not None else s.tenant,
                             ctx=events.current_context(),
                             spec_tokens=spec_tokens,
                             sampling=sampling)
            self._queue.append(p)
            self._cond.notify_all()
        if restarted:
            events.emit("decode.restarted", model=self.name)
        return fut

    def step(self, sid: str, xs, masks=None, timeout: Optional[float] = 60.0,
             timeout_ms: Optional[float] = None,
             tenant: Optional[str] = None):
        """Blocking convenience wrapper around :meth:`submit_step`."""
        return self.submit_step(sid, xs, masks, timeout_ms=timeout_ms,
                                tenant=tenant).result(timeout)

    def spec_step(self, sid: str, xs, token_ids,
                  timeout: Optional[float] = 60.0,
                  timeout_ms: Optional[float] = None,
                  tenant: Optional[str] = None,
                  sampling: Optional[dict] = None):
        """Blocking convenience wrapper around :meth:`submit_spec_step`."""
        return self.submit_spec_step(
            sid, xs, token_ids, timeout_ms=timeout_ms,
            tenant=tenant, sampling=sampling).result(timeout)

    def _normalize_inputs(self, xs) -> Tuple[np.ndarray, ...]:
        """Per-input ``[T, C]`` chunk arrays.  Single-input models take
        the array itself (a 1-D vector is one timestep); multi-input
        graphs take one array per network input."""
        if self.n_inputs == 1:
            arrs = [xs]
        else:
            if not isinstance(xs, (list, tuple)) \
                    or len(xs) != self.n_inputs:
                raise ValueError(f"decode step needs {self.n_inputs} "
                                 "input arrays (one per network input)")
            arrs = list(xs)
        out = []
        for a in arrs:
            a = np.asarray(a, np.float32)
            if a.ndim == 1:   # a single timestep's feature vector
                a = a[None]
            out.append(a)
        return tuple(out)

    def _normalize_masks(self, masks, xs) -> Tuple[Optional[np.ndarray], ...]:
        if masks is None:
            return tuple(None for _ in xs)
        ms = [masks] if self.n_inputs == 1 else list(masks)
        if len(ms) != len(xs):
            raise ValueError("one mask (or None) per network input")
        return tuple(None if m is None else np.asarray(m, np.float32).ravel()
                     for m in ms)

    def queue_rows(self) -> int:
        with self._cond:
            return len(self._queue)

    def queue_rows_by_tenant(self) -> Dict[str, int]:
        with self._cond:
            out: Dict[str, int] = {}
            for p in self._queue:
                t = p.tenant or "-"
                out[t] = out.get(t, 0) + 1
            return out

    @property
    def thread_alive(self) -> bool:
        return self._thread.is_alive()

    def stop(self, timeout: float = 5.0) -> None:
        with self._cond:
            self._running = False
            self._cond.notify_all()
        self._thread.join(timeout)
        with self._cond:
            leftovers, self._queue = self._queue, []
            ctl, self._control = self._control, []
            sids = list(self._sessions)
            for sid in sids:
                self._close_locked(sid, reason="shutdown")
        for p in leftovers:
            if not p.future.done():
                p.future.set_exception(RuntimeError("DecodePool stopped"))
        for _, _, fut in ctl:
            if not fut.done():
                fut.set_exception(RuntimeError("DecodePool stopped"))

    def stats(self) -> dict:
        with self._cond:
            # exported slots are EXCLUDED: the session's state already
            # left for another replica — a load balancer (or the fleet
            # readyz aggregation) summing per-replica session counts
            # must not see the same stream twice mid-migration
            sessions = {sid: {"slot": s.slot, "tenant": s.tenant,
                              "steps": s.steps,
                              "idle_s": round(time.monotonic() -
                                              s.last_used, 3)}
                        for sid, s in self._sessions.items()
                        if not s.exported}
            exporting = sum(1 for s in self._sessions.values()
                            if s.exported)
            free = len(self._free)
            queued = len(self._queue)
            draining = self._draining
            arena = None
            if self.kv_paged and self._arena_specs:
                w_max = max(int(sp["window_eff"])
                            for sp in self._arena_specs)
                arena = {
                    "block_size": int(self.kv_block),
                    "blocks": int(sum(self._arena_blocks)),
                    "blocks_free": int(sum(len(f) for f in self._kv_free)),
                    "tokens_resident": int(sum(
                        min(s.kv_pos, w_max)
                        for s in self._sessions.values()
                        if s.kv_blocks is not None)),
                }
        out = {
            "slots": self.max_slots,
            "slots_free": free,
            "slot_ladder": list(self._ladder),
            "ttl_s": self.ttl_s,
            "queued_steps": queued,
            "deaths": self.deaths,
            "restarts": self.restarts,
            "draining": draining,
            "exporting": exporting,
            "sessions": sessions,
            **self.metrics.snapshot(),
        }
        tel = getattr(self.model, "compile_telemetry", None)
        if tel is not None:
            by_kind = tel.snapshot()["by_kind"]
            out["decode_programs"] = by_kind.get("decode_step", 0)
            out["spec_programs"] = by_kind.get("spec_step", 0)
        if self._kv_summary:
            out["kv_cache"] = dict(self._kv_summary)
        if arena is not None:
            out["kv_arena"] = arena
        return out

    # ------------------------------------------------------------------
    # Session migration (the fleet tier's seam — docs/FLEET.md)
    # ------------------------------------------------------------------
    def export_session(self, sid: str, timeout: float = 30.0) -> dict:
        """Snapshot one session's decode state as a JSON-serializable
        payload (phase one of a migration).

        The carry slice is host-gathered ON the batcher thread (a
        control op between dispatches — the device pool has exactly one
        owner), after any queued/in-flight steps for the session have
        landed, so the snapshot is the state AFTER the last acknowledged
        token.  On success the session enters ``exported`` limbo: the
        slot stays held, steps are rejected as retryable, and the
        session no longer counts as active — :meth:`finish_export`
        closes it (import confirmed) or reinstates it (import failed).
        """
        deadline = time.monotonic() + max(0.1, float(timeout))
        with self._cond:
            s = self._sessions.get(sid)
            if s is None:
                raise KeyError(f"unknown or expired decode session {sid!r}")
            if s.migrating or s.exported:
                raise TransientError(
                    f"decode session {sid} is already migrating")
            s.migrating = True
        try:
            self._wait_steps_drained(sid, deadline)
            fut = self._submit_control("export", sid)
            payload = fut.result(max(0.1, deadline - time.monotonic()))
        except BaseException:
            with self._cond:
                s2 = self._sessions.get(sid)
                if s2 is not None:
                    s2.migrating = False
            raise
        with self._cond:
            s2 = self._sessions.get(sid)
            if s2 is not None:
                s2.exported = True
                self.metrics.g_active.set(self._active_locked())
                # under the lock: a finish_export racing in right after
                # must journal its close AFTER this export
                events.emit("decode.session_exported", model=self.name,
                            session_id=sid, slot=s.slot, tenant=s.tenant,
                            steps=payload.get("steps"))
        return payload

    def finish_export(self, sid: str, ok: bool = True) -> bool:
        """Phase two of a migration: ``ok=True`` (the import landed on
        the target replica) releases the slot; ``ok=False`` reinstates
        the session — its carry never left this pool's device buffer,
        so it resumes serving exactly where it stopped."""
        with self._cond:
            s = self._sessions.get(sid)
            if s is None:
                return False
            if ok:
                return self._close_locked(sid, reason="migrated")
            s.exported = False
            s.migrating = False
            s.last_used = time.monotonic()   # limbo time is not idle time
            self.metrics.g_active.set(self._active_locked())
            events.emit("decode.session_reinstated", model=self.name,
                        session_id=sid, slot=s.slot, tenant=s.tenant,
                        steps=s.steps)
            return True

    def import_session(self, payload: dict, session_id: Optional[str] = None,
                       tenant: Optional[str] = None,
                       timeout: float = 30.0) -> str:
        """Restore an exported session into THIS pool: claim a slot,
        scatter the payload's carry into it (on the batcher thread), and
        continue the stream — next-token parity with the source is the
        float-exact round trip the migration tests pin.  Keeps the
        source's session id by default so the client's handle survives
        the move."""
        sid = session_id or payload.get("session_id") or uuid.uuid4().hex[:16]
        tenant = tenant if tenant is not None else payload.get("tenant")
        with self._cond:
            if not self._running:
                raise RuntimeError("DecodePool is stopped")
            if self._draining:
                self.metrics.record_shed("decode_draining")
                raise OverloadedError(
                    "decode pool draining — not accepting migrated "
                    "sessions", retry_after_s=1.0)
            if sid in self._sessions:
                raise ValueError(f"decode session {sid!r} already exists "
                                 "in this pool")
            self._sweep_locked()
            if not self._free:
                self.metrics.record_shed("decode_slots_full")
                raise OverloadedError(
                    f"decode slots exhausted ({self.max_slots} sessions "
                    "active)", retry_after_s=1.0)
            slot = self._free.pop()
            s = DecodeSession(sid, slot, tenant)
            s.steps = int(payload.get("steps", 0) or 0)
            s.started = bool(payload.get("started")) \
                and payload.get("carry") is not None
            s.importing = payload.get("carry") is not None
            self._sessions[sid] = s
            self.metrics.record_opened(tenant)
            self.metrics.g_active.set(self._active_locked())
            # emitted at the ADMIT point (under the lock), not after the
            # carry scatter: a drain that starts while the scatter runs
            # must journal after this admit, and a failed scatter follows
            # up with session_closed(error) — journal order stays the
            # protocol order (the dl4j-check specs depend on it)
            events.emit("decode.session_imported", model=self.name,
                        session_id=sid, slot=slot, tenant=tenant,
                        steps=s.steps)
        try:
            if payload.get("carry") is not None:
                fut = self._submit_control("import", (s, payload))
                fut.result(max(0.1, float(timeout)))
        except BaseException:
            self.close_session(sid, reason="error")
            raise
        with self._cond:
            s.importing = False
        return sid

    def drain(self, deadline_s: Optional[float] = None) -> dict:
        """Stop admitting session joins (opens AND imports shed 503)
        and report what remains.  With a deadline, waits that long for
        live sessions to leave on their own (client closes, TTL,
        migration) — the forcing function is the caller's migration
        loop, not this method.  :meth:`resume` re-admits."""
        with self._cond:
            already = self._draining
            self._draining = True
            held = len(self._sessions)
        if not already:
            events.emit("decode.drain", model=self.name, sessions=held)
        deadline = (None if deadline_s is None
                    else time.monotonic() + float(deadline_s))
        with self._cond:
            while self._sessions and deadline is not None \
                    and time.monotonic() < deadline:
                self._sweep_locked()
                self._cond.wait(min(0.05, max(
                    0.0, deadline - time.monotonic())))
            remaining = [sid for sid, s in self._sessions.items()]
        return {"draining": True, "remaining": remaining,
                "drained": not remaining}

    def resume(self) -> None:
        """Clear the draining flag (rollout finished or aborted)."""
        with self._cond:
            was = self._draining
            self._draining = False
            if was:
                # under the lock: a session admitted the instant the
                # flag clears journals AFTER the resumed event
                events.emit("decode.resumed", model=self.name)

    def _wait_steps_drained(self, sid: str, deadline: float) -> None:
        """Block until no queued or in-flight step references ``sid`` —
        an export taken between a step's gather and its scatter would
        snapshot a stale carry."""
        with self._cond:
            def pending():
                return any(p.session.sid == sid
                           for p in self._queue + self._inflight)
            while pending():
                if time.monotonic() >= deadline:
                    raise TimeoutError(
                        f"session {sid} still has steps in flight")
                self._cond.wait(0.02)

    def _submit_control(self, kind: str, arg) -> Future:
        fut: Future = Future()
        with self._cond:
            if not self._running:
                raise RuntimeError("DecodePool is stopped")
            if self._dead or not self._thread.is_alive():
                self._dead = False
                self.restarts += 1
                self._thread = self._spawn_thread()
            self._control.append((kind, arg, fut))
            self._cond.notify_all()
        return fut

    def _handle_control(self, op) -> None:
        """Run one control op on the batcher thread.  A ``mode="kill"``
        fault at ``fleet.migrate`` (a replica dying mid-migration)
        resolves the waiter's future with a clean error FIRST, then
        takes the thread down through the normal crash handler — the
        migration fails loudly, no client hangs."""
        kind, arg, fut = op
        try:
            faults.check("fleet.migrate")
            if kind == "export":
                result = self._do_export(arg)
            elif kind == "import":
                result = self._do_import(*arg)
            else:
                raise ValueError(f"unknown control op {kind!r}")
        except BaseException as e:
            if not fut.done():
                if isinstance(e, Exception):
                    fut.set_exception(e)
                else:
                    fut.set_exception(RuntimeError(
                        "decode batcher killed mid-migration "
                        f"({type(e).__name__}: {e}); session state lost — "
                        "reopen the session and replay"))
            if not isinstance(e, Exception):
                raise
            return
        if not fut.done():
            fut.set_result(result)

    def _do_export(self, sid: str) -> dict:
        """Batcher-thread half of export: slice the session's slot out
        of the device pool and host-gather it (the reshard-path move —
        ``device_get`` gathers sharded leaves too)."""
        with self._cond:
            s = self._sessions.get(sid)
        if s is None:
            raise KeyError(f"unknown or expired decode session {sid!r}")
        import os
        binary = os.environ.get("DL4J_CARRY_PAYLOAD", "").lower() != "json"
        payload = {
            "version": CARRY_PAYLOAD_VERSION if binary else 1,
            "session_id": sid,
            "model": self.name,
            "tenant": s.tenant,
            "steps": s.steps,
            "started": bool(s.started),
            "dtype": str(self._dtype),
            "feature_tails": None,
            "carry": None,
        }
        if s.started and self._pool is not None:
            slot_slice = tree_map(lambda a: a[s.slot], self._pool)
            if self.kv_paged:
                # de-page into the DENSE wire layout: the payload a
                # paged pool exports is byte-compatible with what a
                # dense-ring pool exports, so mixed fleets (paged and
                # not-yet-upgraded replicas) migrate in both directions
                slot_slice = self._depage_carry(slot_slice)
            leaves = jax.tree_util.tree_leaves(slot_slice)
            host = jax.device_get(leaves)
            # v2: base64-npy bytes per leaf — exact binary round trip
            # at a fraction of the JSON-float-list wire size (KV-cache
            # carries are MB-sized); v1 JSON lists behind the env knob
            # for a mixed-version fleet
            payload["carry"] = {"leaves": [
                _encode_carry_leaf(a, binary) for a in host]}
            payload["feature_tails"] = [list(t) for t in self._tails]
        return payload

    def _depage_carry(self, slot_slice):
        """Replace every paged carry node ``{"aid","pos","tbl"}`` in one
        slot's carry with the dense ``{"k","pos","v"}`` ring layout the
        migration wire ships: gather the session's blocks out of the
        arena and lay the live window out at its ring positions
        (token ``p`` at index ``p % W`` — exactly where
        ``kv_ring_init``/``attend_cached`` would hold it).  bf16 arenas
        widen to f32 on the wire (npy/JSON-portable; a paged target
        narrows back losslessly)."""
        bs = int(self.kv_block)

        def walk(node):
            if isinstance(node, dict):
                if set(node.keys()) == {"aid", "pos", "tbl"}:
                    aid = int(node["aid"].shape[-1]) - 1
                    spec = self._arena_specs[aid]
                    H = int(spec["heads"])
                    D = int(spec["head_dim"])
                    W = int(spec["window"])
                    w_eff = int(spec["window_eff"])
                    pos = int(np.asarray(jax.device_get(node["pos"])))
                    tbl = np.asarray(jax.device_get(node["tbl"]))
                    ka = np.asarray(jax.device_get(
                        self._arenas[aid]["k"][jnp.asarray(tbl)]),
                        dtype=np.float32)   # [nbs, H, bs, D]
                    va = np.asarray(jax.device_get(
                        self._arenas[aid]["v"][jnp.asarray(tbl)]),
                        dtype=np.float32)
                    dk = np.zeros((H, W, D), np.float32)
                    dv = np.zeros((H, W, D), np.float32)
                    for p in range(max(0, pos - W), pos):
                        sl = p % w_eff
                        dk[:, p % W, :] = ka[sl // bs, :, sl % bs, :]
                        dv[:, p % W, :] = va[sl // bs, :, sl % bs, :]
                    return {"k": dk, "pos": np.int32(pos), "v": dv}
                return {k: walk(v) for k, v in node.items()}
            if isinstance(node, (list, tuple)):
                return type(node)(walk(v) for v in node)
            return node

        return walk(slot_slice)

    def _do_import_paged(self, session: DecodeSession, carry: dict) -> dict:
        """Paged half of import: consume the DENSE wire leaves in the
        pool's flatten order, re-paging each ring's live window into
        freshly allocated arena blocks.  Allocated blocks are recorded
        on the session IMMEDIATELY (under the lock), so a mid-walk
        failure frees them through the normal close path."""
        in_leaves = carry["leaves"]
        cursor = {"i": 0}
        arenas = list(self._arenas)
        bs = int(self.kv_block)

        def take():
            if cursor["i"] >= len(in_leaves):
                raise ValueError(
                    f"migrated carry has {len(in_leaves)} leaves — "
                    "fewer than this pool's template needs (model "
                    "architectures differ)")
            a = _decode_carry_leaf(in_leaves[cursor["i"]])
            cursor["i"] += 1
            return a

        with self._cond:
            if session.kv_blocks is None:
                session.kv_blocks = [[] for _ in self._arena_specs]

        def walk(node):
            if node is None:
                return None
            if isinstance(node, dict):
                if set(node.keys()) == {"aid", "pos", "tbl"}:
                    # the wire node is {"k","pos","v"} — three leaves in
                    # sorted (flatten) order
                    dk, pos_a, dv = take(), take(), take()
                    aid = int(node["aid"].shape[-1]) - 1
                    spec = self._arena_specs[aid]
                    H = int(spec["heads"])
                    D = int(spec["head_dim"])
                    W = int(spec["window"])
                    w_eff = int(spec["window_eff"])
                    nbs = w_eff // bs
                    pos = int(np.asarray(pos_a).reshape(()))
                    if tuple(dk.shape) != (H, W, D):
                        raise ValueError(
                            f"migrated KV leaf shape {tuple(dk.shape)} "
                            f"!= this pool's ring {(H, W, D)}")
                    need = -(-min(pos, w_eff) // bs)
                    with self._cond:
                        held = session.kv_blocks[aid]
                        while len(held) < need:
                            if not self._kv_free[aid]:
                                self.metrics.record_shed(
                                    "kv_arena_exhausted")
                                self.metrics.c_arena_failures.inc()
                                raise OverloadedError(
                                    "KV arena exhausted re-paging a "
                                    "migrated session", retry_after_s=1.0)
                            held.append(self._kv_free[aid].pop())
                        blocks = list(held)
                        self._update_arena_gauges_locked()
                    adt = arenas[aid]["k"].dtype
                    bk = np.zeros((max(need, 1), H, bs, D), np.float32)
                    bv = np.zeros((max(need, 1), H, bs, D), np.float32)
                    for p in range(max(0, pos - W), pos):
                        sl = p % w_eff
                        bk[sl // bs, :, sl % bs, :] = dk[:, p % W, :]
                        bv[sl // bs, :, sl % bs, :] = dv[:, p % W, :]
                    if need:
                        bidx = jnp.asarray(np.asarray(blocks[:need],
                                                      np.int32))
                        ar = dict(arenas[aid])
                        ar["k"] = ar["k"].at[bidx].set(
                            jnp.asarray(bk[:need]).astype(adt))
                        ar["v"] = ar["v"].at[bidx].set(
                            jnp.asarray(bv[:need]).astype(adt))
                        arenas[aid] = ar
                    row = np.full((nbs,), self._arena_blocks[aid],
                                  np.int32)
                    row[:len(blocks)] = blocks
                    session.kv_pos = pos
                    return {
                        "aid": node["aid"],
                        "pos": node["pos"].at[session.slot].set(pos),
                        "tbl": node["tbl"].at[session.slot].set(
                            jnp.asarray(row)),
                    }
                return {k: walk(v) for k, v in sorted(node.items())}
            if isinstance(node, (list, tuple)):
                return type(node)(walk(v) for v in node)
            # a plain [S+1, ...] pool leaf: one dense wire leaf
            a = take()
            if tuple(a.shape) != tuple(node.shape[1:]):
                raise ValueError(
                    f"migrated carry leaf shape {a.shape} != the pool "
                    f"slot's {tuple(node.shape[1:])}")
            return node.at[session.slot].set(
                jnp.asarray(a).astype(node.dtype))

        new_pool = walk(self._pool)
        if cursor["i"] != len(in_leaves):
            raise ValueError(
                f"migrated carry has {len(in_leaves)} leaves, this "
                f"pool consumed {cursor['i']} — model architectures "
                "differ")
        self._pool = new_pool  # dl4j: noqa[DL4J207] control-queue op: only the batcher thread (the pool's single owner) runs this
        self._arenas = tuple(arenas)  # dl4j: noqa[DL4J207] same control-queue op — batcher-thread-only; the locked writes are the crash paths
        return {"slot": session.slot, "leaves": cursor["i"]}

    def _do_import(self, session: DecodeSession, payload: dict) -> dict:
        """Batcher-thread half of import: materialize the pool's device
        state if needed, then scatter the payload's carry leaves into
        the claimed slot."""
        carry = payload["carry"]
        fts = payload.get("feature_tails")
        if self._pool is None:
            if not fts:
                raise ValueError("carry payload missing feature_tails")
            tails = [(1,) + tuple(int(d) for d in t) for t in fts]
            self._ensure_device_state(
                tails, np.dtype(payload.get("dtype") or "float32"))
        elif fts is not None:
            got = tuple(tuple(int(d) for d in t) for t in fts)
            if got != self._tails:
                raise ValueError(
                    f"migrated carry feature shape {got} != the pool's "
                    f"{self._tails} (one pool serves one input layout)")
        if self.kv_paged:
            return self._do_import_paged(session, carry)
        pool_leaves, treedef = jax.tree_util.tree_flatten(self._pool)
        in_leaves = carry["leaves"]
        if len(in_leaves) != len(pool_leaves):
            raise ValueError(
                f"migrated carry has {len(in_leaves)} leaves, this "
                f"pool's template has {len(pool_leaves)} — model "
                "architectures differ")
        new_leaves = []
        for spec, p in zip(in_leaves, pool_leaves):
            a = _decode_carry_leaf(spec)   # v1 JSON lists or v2 npy+b64
            if tuple(a.shape) != tuple(p.shape[1:]):
                raise ValueError(
                    f"migrated carry leaf shape {a.shape} != the pool "
                    f"slot's {tuple(p.shape[1:])}")
            new_leaves.append(
                p.at[session.slot].set(jnp.asarray(a).astype(p.dtype)))
        self._pool = jax.tree_util.tree_unflatten(treedef, new_leaves)  # dl4j: noqa[DL4J207] control-queue op: only the batcher thread (the pool's single owner) runs this; the locked writes are the crash paths
        return {"slot": session.slot, "leaves": len(new_leaves)}

    # ------------------------------------------------------------------
    # Warmup
    # ------------------------------------------------------------------
    def warmup(self, feature_tails, t_steps: int = 1,
               dtype=np.float32) -> dict:
        """Pre-compile the decode program for every slot-ladder rung so
        first sessions never pay a cold XLA compile.  Warmup steps ride
        the normal batcher queue on synthetic scratch-slot sessions
        (slot = the scratch row, ``fresh`` carries zeroed in-trace), so
        no real session state is touched and no dispatch races the
        batcher thread.  ``feature_tails`` is one per-example ``(T, C)``
        tail per input (a bare tail is broadcast); ``t_steps`` warms
        that chunk length's time bucket."""
        tails = self._broadcast_tails(feature_tails, t_steps)
        xs = tuple(np.zeros(t, dtype) for t in tails)
        masks = tuple(None for _ in tails)
        t0 = time.perf_counter()
        for rung in self._ladder:
            futs = []
            with self._cond:
                if not self._running:
                    break
                for i in range(rung):
                    fut = Future()
                    s = DecodeSession(f"warmup-{rung}-{i}", self.max_slots,
                                      None)
                    s.started = True   # gather the (zero) scratch row
                    self._queue.append(
                        _PendingStep(s, xs, masks, fut, None, None))
                    futs.append(fut)
                self._cond.notify_all()
            for fut in futs:
                fut.result(timeout=600)
        return {"slot_ladder": list(self._ladder),
                "warmup_sec": round(time.perf_counter() - t0, 3)}

    def warmup_spec(self, feature_tails, k: int = 4,
                    dtype=np.float32) -> dict:
        """Mirror of :meth:`warmup` for the fused speculative-verify
        program: pre-compile ``_spec_jit`` for every slot-ladder rung at
        the spec chunk length (the pending token + ``k`` drafts, padded
        to its time bucket) so the first ``decode_step(spec=...)``
        never pays a cold XLA compile.  Warmup verify steps ride the
        normal batcher queue on scratch-slot sessions exactly like the
        plain warmup — no real session state is touched."""
        t_chunk = 1 + max(0, int(k))
        tails = self._broadcast_tails(feature_tails, t_chunk)
        if any(len(t) < 2 for t in tails):
            raise ValueError("speculative warmup needs sequence inputs "
                             "([T, C] per network input)")
        # a live spec step's chunk is 1..1+k tokens long (the drafter
        # may propose fewer than k), and each distinct TIME bucket of
        # that range is its own compiled program — warm one chunk
        # length per distinct bucket, at every slot-ladder rung
        g = self.model.conf.global_conf
        chunks, seen = [], set()
        for t in range(1, t_chunk + 1):
            tb = bucketing.bucket_size(t, g.bucket_time_sizes)
            if tb not in seen:
                seen.add(tb)
                chunks.append(t)
        t0 = time.perf_counter()
        for t in chunks:
            xs = tuple(np.zeros((t,) + tuple(tail[1:]), dtype)
                       for tail in tails)
            masks = tuple(None for _ in tails)
            tok = np.zeros((t,), np.int32)
            for rung in self._ladder:
                futs = []
                with self._cond:
                    if not self._running:
                        break
                    for i in range(rung):
                        fut = Future()
                        s = DecodeSession(f"warmup-spec-{t}-{rung}-{i}",
                                          self.max_slots, None)
                        s.started = True   # gather the (zero) scratch row
                        self._queue.append(
                            _PendingStep(s, xs, masks, fut, None, None,
                                         spec_tokens=tok))
                        futs.append(fut)
                    self._cond.notify_all()
                for fut in futs:
                    fut.result(timeout=600)
        return {"slot_ladder": list(self._ladder), "k": max(0, int(k)),
                "chunks": chunks,
                "warmup_sec": round(time.perf_counter() - t0, 3)}

    def _broadcast_tails(self, feature_tails, t_steps: int):
        dims = list(feature_tails)
        if not dims or not isinstance(dims[0], (tuple, list)):
            dims = [tuple(dims)] * self.n_inputs
        out = []
        for t in dims:
            t = tuple(int(d) for d in t)
            if len(t) == 1:
                t = (int(t_steps),) + t
            out.append(t)
        return out

    # ------------------------------------------------------------------
    # Batcher thread
    # ------------------------------------------------------------------
    def _spawn_thread(self) -> threading.Thread:
        t = threading.Thread(
            target=self._loop_guarded, daemon=True,
            name=f"decode-batcher:{self.name or hex(id(self))}")
        t.start()
        return t

    def _loop_guarded(self) -> None:
        """Batcher body + crash handler: a ``BaseException`` escaping
        the loop (an armed ``mode="kill"`` fault at ``decode.step``, a
        fatal interpreter error) fails every in-flight and queued step,
        closes every session (their device carries may be invalid — the
        pool buffer is donated into the step) and reclaims the slots;
        the next submit restarts the thread."""
        death_err = None
        try:
            self._loop()
        except BaseException as e:
            death_err = e
            log.error("decode batcher %r thread died: %s: %s",
                      self.name, type(e).__name__, e)
        finally:
            with self._cond:
                died = self._running   # normal stop() exits are not deaths
                stranded = self._inflight + self._queue
                self._inflight = []
                ctl = []
                if died:
                    self._queue = []
                    ctl, self._control = self._control, []
                    self.deaths += 1
                    self._dead = True
                    self._pool = None
                    self._step_jit = None
                    self._spec_jit = None
                    # drop the arena WITH the pool: block tables in the
                    # dropped pool are the only map into it, and closing
                    # below must not free blocks into a stale free list
                    self._arenas = None
                    self._arena_specs = ()
                    self._arena_blocks = ()
                    self._kv_free = []
                    for sid in list(self._sessions):
                        self._close_locked(sid, reason="batcher_died")
            for _, _, fut in ctl:
                if not fut.done():
                    fut.set_exception(RuntimeError(
                        "decode batcher thread died; migration aborted"))
            if died:
                for p in stranded:
                    if not p.future.done():
                        p.future.set_exception(RuntimeError(
                            "decode batcher thread died; session state "
                            "lost — reopen the session and replay"))
                # black box: which sessions/tenants/requests were in
                # flight when the decode thread died, then the dump
                rids = [p.request_id for p in stranded if p.request_id]
                sids = sorted({p.session.sid for p in stranded})
                events.emit(
                    "decode.died", severity="error", model=self.name,
                    error=(f"{type(death_err).__name__}: {death_err}"
                           if death_err is not None else "unknown"),
                    stranded=len(stranded), session_ids=sids or None,
                    request_ids=rids or None)
                flight.dump("decode_batcher_died", extra={
                    "pool": self.name, "stranded_request_ids": rids,
                    "stranded_session_ids": sids,
                    "error": repr(death_err)})

    def _loop(self) -> None:
        while True:
            with self._cond:
                ops, self._control = self._control, []
            for op in ops:
                self._handle_control(op)
            taken = self._take_batch()
            if not taken:
                if not self._running:
                    return
                with self._cond:
                    self._sweep_locked()
                continue
            taken = self._shed_expired(taken)
            if not taken:
                continue
            groups: Dict[Tuple, List[_PendingStep]] = {}
            for p in taken:
                # spec and normal steps are different compiled programs
                # — never coalesced into one dispatch
                key = (tuple(a.shape for a in p.xs),
                       p.spec_tokens is not None,
                       # top_k picks the compiled program; greedy rows
                       # (sampling None) must not share a sampling trace
                       None if p.sampling is None
                       else int(p.sampling.get("top_k", 0)))
                groups.setdefault(key, []).append(p)
            for group in groups.values():
                with self._cond:
                    self._inflight = list(group)
                self._dispatch(group)
                with self._cond:
                    self._inflight = []
                    self._cond.notify_all()   # wake export step-drain waits

    def _take_batch(self) -> List[_PendingStep]:
        """Drain at most ONE pending step per session (a session's steps
        are a sequential stream — two steps of the same stream in one
        gather/scatter would collide on its slot), leaving the rest
        queued in order.  With ``min_batch > 1`` the drain waits up to
        ``max_wait_s`` for more sessions to join."""
        with self._cond:
            while self._running and not self._queue and not self._control:
                self._cond.wait(0.1)
                self._sweep_locked()   # idle servers still expire TTLs
            if not self._queue:
                return []
            deadline = time.perf_counter() + self.max_wait_s
            while True:
                taken: List[_PendingStep] = []
                seen = set()
                rest: List[_PendingStep] = []
                for p in self._queue:
                    sid = p.session.sid
                    if sid in seen or len(taken) >= self.max_slots:
                        rest.append(p)
                    else:
                        seen.add(sid)
                        taken.append(p)
                if len(taken) >= self.min_batch or not self._running \
                        or self._control:
                    self._queue = rest
                    return taken
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    self._queue = rest
                    return taken
                self._cond.wait(remaining)

    def _shed_expired(self, taken):
        now = time.monotonic()
        keep = []
        for p in taken:
            if p.deadline is not None and now >= p.deadline:
                self.metrics.record_shed("deadline")
                events.emit("request.shed", severity="warn",
                            reason="deadline", model=self.name,
                            session_id=p.session.sid,
                            request_id=p.request_id, tenant=p.tenant)
                if not p.future.done():
                    p.future.set_exception(DeadlineExceededError(
                        "decode step deadline expired while queued "
                        f"({(now - p.deadline) * 1e3:.1f} ms past budget)"))
            else:
                keep.append(p)
        return keep

    # ------------------------------------------------------------------
    # The dispatch: gather → step → scatter, one jitted call
    # ------------------------------------------------------------------
    def _ensure_device_state(self, tails, dtype) -> None:
        if self._pool is not None:
            return
        n = self.max_slots + 1   # + scratch row for ladder padding
        tape = (seq_ops.PagedTape(block_size=self.kv_block,
                                  dtype=self._kv_dtype)
                if self.kv_paged else None)
        ctx = (seq_ops.paged_scope(tape) if tape is not None
               else contextlib.nullcontext())
        with ctx:
            if self._is_graph:
                tmpl = self.model.rnn_carry_template(
                    n, feature_tails=tails, dtype=dtype)
            else:
                tmpl = self.model.rnn_carry_template(
                    n, feature_tail=tails[0], dtype=dtype)
        if self._carry_dtype is not None:
            tmpl = _cast_carry(tmpl, self._carry_dtype)
        self._pool = tmpl  # dl4j: noqa[DL4J207] batcher-thread-only write: the device pool has ONE owning thread; the locked writes are the crash paths
        self._tails = tuple(tuple(t[1:]) for t in tails)
        self._dtype = np.dtype(dtype)
        if self.kv_paged:
            self._materialize_arenas(tuple(tape.specs))
            self._step_jit = jax.jit(  # dl4j: noqa[DL4J104,DL4J207] one jit per pool over a fixed is_graph, cached by the owning batcher thread for the pool's lifetime; locked writes are the crash paths
                _paged_pool_step_raw(self.model, self._is_graph,
                                     self.kv_block),
                donate_argnums=(2, 7))
            return
        self._step_jit = jax.jit(  # dl4j: noqa[DL4J104,DL4J207] one jit per pool over a fixed is_graph, cached by the owning batcher thread for the pool's lifetime; locked writes are the crash paths
            _pool_step_raw(self.model, self._is_graph),
            donate_argnums=(2,))
        kv = _kv_ring_summary(self._pool)
        self._kv_summary = kv
        self.metrics.g_kv_rings.set(kv["rings"])
        self.metrics.g_kv_bytes.set(kv["bytes"])
        self.metrics.g_kv_window.set(kv["window"])

    def _materialize_arenas(self, specs: Tuple[dict, ...]) -> None:
        """Build the per-layer block arenas + free lists from the specs
        the template tape recorded.  Per-layer capacity is
        ``kv_arena_tokens`` rounded up to whole blocks (default: the
        dense-equivalent ``max_slots x w_eff``), never less than one
        full window (a pool that cannot hold ONE session is a config
        error, not a backpressure state); each arena carries one extra
        scratch block (index ``n_blocks``) for unallocated table
        entries."""
        arenas, free, nblocks = [], [], []
        nbytes = 0
        widest = 0
        for spec in specs:
            we, nbs = spec["window_eff"], spec["blocks_per_slot"]
            widest = max(widest, we)
            tokens = (self.kv_arena_tokens if self.kv_arena_tokens
                      else self.max_slots * we)
            nb = max(nbs, -(-int(tokens) // self.kv_block))
            dt = jnp.dtype(spec["dtype"])
            shape = (nb + 1, spec["heads"], self.kv_block,
                     spec["head_dim"])
            buf = {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
            arenas.append(buf)
            free.append(list(range(nb)))
            nblocks.append(nb)
            nbytes += int(buf["k"].nbytes + buf["v"].nbytes)
        self._arenas = tuple(arenas)  # dl4j: noqa[DL4J207] batcher-thread-only write like _pool: the arena has ONE owning thread; the locked writes are the crash paths
        with self._cond:
            self._arena_specs = tuple(specs)
            self._arena_blocks = tuple(nblocks)
            self._kv_free = free
            self._update_arena_gauges_locked()
        self._kv_summary = {
            "paged": True, "block_size": self.kv_block,
            "layers": len(specs), "blocks": sum(nblocks),
            "bytes": nbytes, "window": widest,
            "dtype": specs[0]["dtype"] if specs else None}
        self.metrics.g_kv_rings.set(len(specs))
        self.metrics.g_kv_bytes.set(nbytes)
        self.metrics.g_kv_window.set(widest)

    def _ensure_spec_jit(self, sampling: bool = False, top_k: int = 0):
        """Fused-verify programs, keyed by ``(sampling, top_k)`` —
        ``top_k`` is a compile-time constant (its own sort/mask trace);
        temperature/seed/position are dynamic inputs of the sampling
        program."""
        if self._spec_jit is None:
            self._spec_jit = {}  # dl4j: noqa[DL4J207] batcher-thread-only cache like _step_jit; the locked writes are the crash resets
        key = (bool(sampling), int(top_k) if sampling else 0)
        fn = self._spec_jit.get(key)
        if fn is None:
            fn = jax.jit(  # dl4j: noqa[DL4J104,DL4J207] one jit per pool per (sampling, top_k) like _step_jit: built once by the owning batcher thread, cached for the pool's lifetime
                _spec_verify_raw(
                    self.model, self._is_graph,
                    block_size=self.kv_block if self.kv_paged else None,
                    sampling=bool(sampling), top_k=int(top_k)),
                donate_argnums=(2, 8) if self.kv_paged else (2,))
            self._spec_jit[key] = fn  # dl4j: noqa[DL4J207] batcher-thread-only cache fill, single owner per pool
        return fn

    def _base_state(self):
        st = self.model.net_state
        if self._is_graph:
            return {n: {k: v for k, v in s.items() if k != "rnn_state"}
                    for n, s in st.items()}
        return [{k: v for k, v in s.items() if k != "rnn_state"}
                for s in st]

    # ------------------------------------------------------------------
    # Paged KV arena: allocation, admission, tables (kv_paged pools)
    # ------------------------------------------------------------------
    def _update_arena_gauges_locked(self) -> None:
        if not self._arena_specs:
            return
        total = sum(self._arena_blocks)
        free = sum(len(f) for f in self._kv_free)
        widest = max(s["window_eff"] for s in self._arena_specs)
        resident = sum(min(s.kv_pos, widest)
                       for s in self._sessions.values()
                       if s.kv_blocks is not None)
        self.metrics.g_arena_blocks.set(total)
        self.metrics.g_arena_free.set(free)
        self.metrics.g_arena_tokens.set(resident)

    def _kv_alloc_locked(self, s: DecodeSession, new_pos: int) -> bool:
        """Grow ``s``'s block holdings so every layer covers ``new_pos``
        resident tokens.  All-or-nothing: either every layer gets its
        blocks or none does (a half-grown session would write into the
        scratch block).  Caller holds ``self._cond``."""
        if s.kv_blocks is None:
            s.kv_blocks = [[] for _ in self._arena_specs]
        need = []
        for li, spec in enumerate(self._arena_specs):
            want = min(int(new_pos), spec["window_eff"])
            nblk = -(-want // self.kv_block) if want > 0 else 0
            need.append(max(0, min(nblk, spec["blocks_per_slot"])
                            - len(s.kv_blocks[li])))
        if any(n > len(self._kv_free[li]) for li, n in enumerate(need)):
            return False
        for li, n in enumerate(need):
            for _ in range(n):
                s.kv_blocks[li].append(self._kv_free[li].pop())
        return True

    def _kv_admit(self, group: List[_PendingStep],
                  t_tokens: int) -> List[_PendingStep]:
        """Admission control before a paged dispatch: allocate each
        row's worst-case block growth (``t_tokens`` more tokens) up
        front; rows the arena cannot cover are shed RETRYABLE (the
        client backs off and retries once blocks free — exactly the
        slot-exhaustion contract, but denominated in tokens)."""
        if not self.kv_paged or not self._arena_specs:
            return group
        kept: List[_PendingStep] = []
        with self._cond:
            for p in group:
                s = p.session
                if s.slot >= self.max_slots:
                    kept.append(p)     # warmup scratch rows: no arena
                    continue
                base = s.kv_pos if s.started else 0
                if self._kv_alloc_locked(s, base + int(t_tokens)):
                    kept.append(p)
                    continue
                self.metrics.record_shed("kv_arena_exhausted")
                self.metrics.c_arena_failures.inc()
                events.emit("decode.arena_alloc_failed", severity="warn",
                            model=self.name, session_id=s.sid,
                            slot=s.slot, tenant=p.tenant,
                            request_id=p.request_id,
                            tokens=base + int(t_tokens))
                if not p.future.done():
                    p.future.set_exception(OverloadedError(
                        "paged KV arena exhausted (no free blocks for "
                        f"{base + int(t_tokens)} resident tokens)",
                        retry_after_s=1.0))
            self._update_arena_gauges_locked()
        return kept

    def _kv_tables(self, group: List[_PendingStep], kb: int) -> Tuple:
        """Per-layer ``[Kb, n_blocks_per_slot]`` device block tables for
        one dispatch, from the allocator's host-side ground truth
        (logical block ``j`` = the ``j``-th block the session
        allocated; unallocated tail entries point at the scratch
        block).  Tables are rebuilt every dispatch — the gathered
        carry's table is zeroed for fresh rows, so the device copy is
        never authoritative."""
        tbls = []
        with self._cond:
            for li, spec in enumerate(self._arena_specs):
                nbs = spec["blocks_per_slot"]
                t = np.full((kb, nbs), self._arena_blocks[li], np.int32)
                for r, p in enumerate(group):
                    blks = p.session.kv_blocks
                    if blks is not None and blks[li]:
                        t[r, :len(blks[li])] = blks[li]
                tbls.append(jnp.asarray(t))
        return tuple(tbls)

    def _dispatch(self, group: List[_PendingStep]) -> None:
        # the ONE compute dispatch is linked to the joined sessions'
        # step requests: their request IDs ride the batcher thread's
        # trace context, so the serve/decode spans (and any injected
        # fault) journal with the coalesced correlation set
        rids = [p.request_id for p in group if p.request_id]
        with events.scope(model=self.name or None,
                          request_ids=rids or None):
            if group[0].spec_tokens is not None:
                self._dispatch_spec(group)
            else:
                self._dispatch_traced(group)

    def _dispatch_traced(self, group: List[_PendingStep]) -> None:
        t_dispatch = time.perf_counter()
        compute_entered = False
        try:
            faults.check("decode.step")
            g = self.model.conf.global_conf
            scratch = self.max_slots
            tails = [tuple(a.shape) for a in group[0].xs]
            feat_tails = tuple(tuple(t[1:]) for t in tails)
            if self._tails is not None and feat_tails != self._tails:
                raise ValueError(
                    f"decode feature shape {feat_tails} != the pool's "
                    f"{self._tails} (one pool serves one input layout)")
            with monitor.span("serve/decode", phase="gather_pad"):
                self._ensure_device_state(tails, group[0].xs[0].dtype)
                # paged arenas admit by TOKENS: grow each row's block
                # tables for the chunk's worst case before any array
                # is built; rows that don't fit shed retryable here
                group = self._kv_admit(group, int(tails[0][0]))
                if not group:
                    return
                K = len(group)
                Kb = bucketing.bucket_size(K, self._ladder)
                idx = np.full((Kb,), scratch, np.int32)
                # pad rows run fresh (zero carries): the scratch row's
                # contents never feed a computation
                fresh = np.ones((Kb,), np.float32)
                xs_h, fms_h, pairs = [], [], []
                for i, tail in enumerate(tails):
                    seq = len(tail) >= 2
                    T = int(tail[0])
                    Tb = (bucketing.bucket_size(T, g.bucket_time_sizes)
                          if seq else T)
                    pairs.append((T, Tb))
                    x = np.zeros((Kb, Tb) + tuple(tail[1:]), np.float32)
                    fm = np.zeros((Kb, Tb), np.float32) if seq else None
                    for r, p in enumerate(group):
                        x[r, :T] = p.xs[i]
                        if fm is not None:
                            fm[r, :T] = (1.0 if p.masks[i] is None
                                         else p.masks[i][:T])
                    xs_h.append(x)
                    fms_h.append(fm)
                for r, p in enumerate(group):
                    idx[r] = p.session.slot
                    fresh[r] = 0.0 if p.session.started else 1.0
                    if self.kv_paged and p.session.slot >= self.max_slots:
                        # warmup scratch rows own no arena blocks: run
                        # them fresh AND fully masked so they never
                        # write the shared scratch block (their purpose
                        # is compiling the program, not its outputs)
                        fresh[r] = 1.0
                        for fm in fms_h:
                            if fm is not None:
                                fm[r] = 0.0
                # explicit H2D before the guarded call (sanitizer
                # transfer-guard contract)
                idx_d = jnp.asarray(idx)
                fresh_d = jnp.asarray(fresh)
                xs_d = tuple(jnp.asarray(x) for x in xs_h)
                fms_d = tuple(None if m is None else jnp.asarray(m)
                              for m in fms_h)
                tbls_d = (self._kv_tables(group, Kb) if self.kv_paged
                          else None)
            tel = getattr(self.model, "compile_telemetry", None)
            compiling = False
            if tel is not None:
                compiling = tel.record("decode_step",
                                       (idx_d, fresh_d, xs_d, fms_d))
            t0 = time.perf_counter()
            compute_entered = True
            with monitor.span("serve/decode", phase="compute"), \
                    sanitizer.guard_step(compiling=compiling):
                if self.kv_paged:
                    outs, self._pool, self._arenas = self._step_jit(
                        self.model.net_params, self._base_state(),
                        self._pool, idx_d, fresh_d, xs_d, fms_d,
                        self._arenas, tbls_d)
                else:
                    outs, self._pool = self._step_jit(
                        self.model.net_params, self._base_state(),
                        self._pool, idx_d, fresh_d, xs_d, fms_d)
                outs = tuple(np.asarray(jax.device_get(o)) for o in outs)
            t1 = time.perf_counter()
            T = next((t for t, _ in pairs), 1)
            sliced = []
            for o in outs:
                o = o[:K]
                for t, tb in pairs:   # mirror _unpad_graph_output
                    if tb != t and o.ndim >= 3 and o.shape[1] == tb:
                        o = o[:, :t]
                        break
                sliced.append(o)
            now = time.monotonic()
            for r, p in enumerate(group):
                p.session.started = True
                p.session.steps += 1
                p.session.last_used = now
                if self.kv_paged and p.session.slot < self.max_slots:
                    # host mirror of the device write position: masked
                    # pad steps advance nothing (allocation already
                    # covered the chunk's worst case)
                    m0 = p.masks[0] if p.masks else None
                    p.session.kv_pos += (T if m0 is None
                                         else int(np.sum(m0[:T] > 0)))
                p.future.set_result(tuple(o[r] for o in sliced))
                self.metrics.record_step(p.tenant, n_tokens=T)
                self.metrics.h_queue.observe(t_dispatch - p.t_enqueue)
                self.metrics.h_step.observe(t1 - t0)
                # every step event carries session ID + slot + tenant
                # (and the request ID captured at enqueue) — per-stream
                # attribution for "which tenant's sessions were in the
                # batch that NaN'd"
                events.emit("decode.step", model=self.name,
                            session_id=p.session.sid, slot=p.session.slot,
                            tenant=p.tenant, request_id=p.request_id,
                            tokens=T, step=p.session.steps)
            self.metrics.record_batch(K)
        except Exception as e:
            for p in group:
                if not p.future.done():
                    p.future.set_exception(e)
            if compute_entered:
                # the pool buffer was donated into a call that failed —
                # its contents are unreliable.  Fail CLOSED: drop the
                # device state and every session holding carries in it.
                with self._cond:
                    self._pool = None
                    self._step_jit = None
                    self._spec_jit = None
                    self._arenas = None
                    self._arena_specs = ()
                    self._arena_blocks = ()
                    self._kv_free = []
                    for sid in list(self._sessions):
                        self._close_locked(sid, reason="error")

    def _dispatch_spec(self, group: List[_PendingStep]) -> None:
        """One fused speculative-verify dispatch for a group of spec
        steps: same gather→…→scatter shape as the normal program, but
        the chunk runs token-by-token in-trace, the accepted prefix is
        computed on device, and each slot's carry lands at exactly its
        acceptance point (``_spec_verify_raw``)."""
        t_dispatch = time.perf_counter()
        compute_entered = False
        try:
            faults.check("decode.step")
            g = self.model.conf.global_conf
            scratch = self.max_slots
            tails = [tuple(a.shape) for a in group[0].xs]
            if any(len(t) < 2 for t in tails):
                raise ValueError("speculative decode needs sequence "
                                 "inputs ([T, C] per network input)")
            feat_tails = tuple(tuple(t[1:]) for t in tails)
            if self._tails is not None and feat_tails != self._tails:
                raise ValueError(
                    f"decode feature shape {feat_tails} != the pool's "
                    f"{self._tails} (one pool serves one input layout)")
            sampling = group[0].sampling is not None
            top_k = (int(group[0].sampling.get("top_k", 0))
                     if sampling else 0)
            with monitor.span("serve/decode", phase="gather_pad"):
                self._ensure_device_state(tails, group[0].xs[0].dtype)
                T = int(tails[0][0])
                # worst-case admission: the verify may commit the whole
                # chunk; kv_pos advances by the ACTUAL acceptance after
                # the dispatch (over-allocated blocks stay held for the
                # stream's future growth — never re-freed mid-stream)
                group = self._kv_admit(group, T)
                if not group:
                    return
                spec_fn = self._ensure_spec_jit(sampling=sampling,
                                                top_k=top_k)
                K = len(group)
                Kb = bucketing.bucket_size(K, self._ladder)
                Tb = bucketing.bucket_size(T, g.bucket_time_sizes)
                idx = np.full((Kb,), scratch, np.int32)
                fresh = np.ones((Kb,), np.float32)
                nv = np.zeros((Kb,), np.int32)
                tok = np.zeros((Kb, Tb), np.int32)
                seed = np.zeros((Kb,), np.int32)
                pos0 = np.zeros((Kb,), np.int32)
                temp = np.ones((Kb,), np.float32)
                xs_h = []
                for i, tail in enumerate(tails):
                    x = np.zeros((Kb, Tb) + tuple(tail[1:]), np.float32)
                    for r, p in enumerate(group):
                        x[r, :T] = p.xs[i]
                    xs_h.append(x)
                for r, p in enumerate(group):
                    idx[r] = p.session.slot
                    fresh[r] = 0.0 if p.session.started else 1.0
                    nv[r] = T
                    tok[r, :T] = p.spec_tokens
                    if sampling:
                        seed[r] = int(p.sampling.get("seed", 0))
                        pos0[r] = int(p.sampling.get("pos", 0))
                        temp[r] = float(p.sampling.get("temperature",
                                                       1.0) or 1.0)
                    if self.kv_paged and p.session.slot >= self.max_slots:
                        # warmup scratch rows: fully masked, no arena
                        # writes (see _dispatch_traced)
                        fresh[r] = 1.0
                        nv[r] = 0
                idx_d = jnp.asarray(idx)
                fresh_d = jnp.asarray(fresh)
                xs_d = tuple(jnp.asarray(x) for x in xs_h)
                tok_d = jnp.asarray(tok)
                nv_d = jnp.asarray(nv)
                args = (idx_d, fresh_d, xs_d, tok_d, nv_d)
                if self.kv_paged:
                    args += (self._arenas, self._kv_tables(group, Kb))
                if sampling:
                    args += (jnp.asarray(seed), jnp.asarray(pos0),
                             jnp.asarray(temp))
            tel = getattr(self.model, "compile_telemetry", None)
            compiling = False
            if tel is not None:
                compiling = tel.record("spec_step", args)
            t0 = time.perf_counter()
            compute_entered = True
            with monitor.span("serve/decode", phase="compute"), \
                    sanitizer.guard_step(compiling=compiling):
                res = spec_fn(self.model.net_params, self._base_state(),
                              self._pool, *args)
                if self.kv_paged:
                    outs, greedy, accept, self._pool, self._arenas = res
                else:
                    outs, greedy, accept, self._pool = res
                outs = np.asarray(jax.device_get(outs))
                greedy = np.asarray(jax.device_get(greedy))
                accept = np.asarray(jax.device_get(accept))
            t1 = time.perf_counter()
            now = time.monotonic()
            for r, p in enumerate(group):
                acc = int(accept[r])
                p.session.started = True
                p.session.steps += 1
                p.session.last_used = now
                if self.kv_paged and p.session.slot < self.max_slots:
                    # rejected tokens were rolled back in-trace, so the
                    # device write position advanced by acc only
                    p.session.kv_pos += acc
                p.future.set_result((outs[r, :T], greedy[r, :T], acc))
                # tokens counted at the step = tokens COMMITTED (the
                # session's stream advanced by `acc`, not by the chunk)
                self.metrics.record_step(p.tenant, n_tokens=acc)
                self.metrics.record_spec(p.tenant, proposed=T - 1,
                                         accepted=acc)
                self.metrics.h_queue.observe(t_dispatch - p.t_enqueue)
                self.metrics.h_step.observe(t1 - t0)
                events.emit("decode.spec_verified", model=self.name,
                            session_id=p.session.sid, slot=p.session.slot,
                            tenant=p.tenant, request_id=p.request_id,
                            proposed=T - 1, accepted=acc,
                            step=p.session.steps)
            self.metrics.record_batch(K)
        except Exception as e:
            for p in group:
                if not p.future.done():
                    p.future.set_exception(e)
            if compute_entered:
                # donated-buffer contract: fail closed like the normal
                # dispatch — the pool's contents are unreliable
                with self._cond:
                    self._pool = None
                    self._step_jit = None
                    self._spec_jit = None
                    self._arenas = None
                    self._arena_specs = ()
                    self._arena_blocks = ()
                    self._kv_free = []
                    for sid in list(self._sessions):
                        self._close_locked(sid, reason="error")


class DecodeManager:
    """Gateway-facing orchestration: session ids → per-model
    :class:`DecodePool`\\ s, sharing the gateway's :class:`ModelCache`.

    Pools are keyed by ``(model path, carry LAYOUT)`` — the carry
    pytree's treedef + per-slot leaf shapes — not by path alone: one
    pool's ``[S+1, ...]`` device buffer serves exactly one carry
    structure, so an attention model (KV-ring carry leaves) and an RNN
    model, or two rollouts of the same path whose carry structure
    changed (say a new attention layer), get SEPARATE pools.  A
    blue/green flip with an UNCHANGED layout still adopts the new model
    instance once the pool drains to zero sessions; a flip with a
    CHANGED layout adopts into a fresh pool immediately — new sessions
    never wait on the drain of an incompatible layout (the old pool
    keeps serving its remaining sessions and is retired once empty)."""

    def __init__(self, model_cache, max_slots: int = 32,
                 ttl_s: float = 600.0, max_wait_ms: float = 2.0,
                 min_batch: int = 1, retry_after_s: float = 1.0,
                 kv_paged: Optional[bool] = None,
                 kv_block: Optional[int] = None,
                 kv_arena_tokens: Optional[int] = None,
                 kv_dtype=None, carry_dtype=None):
        self.model_cache = model_cache
        self.max_slots = max(1, int(max_slots))
        self.ttl_s = float(ttl_s)
        self.max_wait_ms = float(max_wait_ms)
        self.min_batch = int(min_batch)
        self.retry_after_s = float(retry_after_s)
        # paged-KV knobs, forwarded verbatim to every pool (None defers
        # to the DL4J_KV_* env defaults resolved in DecodePool.__init__)
        self.kv_paged = kv_paged
        self.kv_block = kv_block
        self.kv_arena_tokens = kv_arena_tokens
        self.kv_dtype = kv_dtype
        self.carry_dtype = carry_dtype
        self._lock = threading.Lock()
        #: model path -> carry-layout fingerprint -> pool
        self._pools: Dict[str, Dict[str, DecodePool]] = {}
        self._by_sid: Dict[str, DecodePool] = {}
        self._draining = False

    @staticmethod
    def _carry_layout(model) -> str:
        """Fingerprint of the model's decode-carry structure: treedef +
        per-slot leaf shapes/dtypes of ``rnn_carry_template`` — the
        pool-compatibility key.  Models whose template cannot be built
        (no recurrent input type) share the ``-`` bucket (the
        path-keyed behavior they had before)."""
        cached = getattr(model, "_dl4j_carry_layout", None)
        if cached is not None:
            return cached
        try:
            tmpl = model.rnn_carry_template(1)
            leaves, treedef = jax.tree_util.tree_flatten(tmpl)
            desc = f"{treedef}|" + ";".join(
                f"{tuple(a.shape[1:])}:{a.dtype}" for a in leaves)
            import hashlib
            layout = hashlib.blake2b(desc.encode(),
                                     digest_size=6).hexdigest()
        except Exception:
            layout = "-"
        try:
            model._dl4j_carry_layout = layout
        except Exception:
            pass
        return layout

    def _pool_for(self, model_path: str) -> DecodePool:
        import os
        key = os.path.abspath(str(model_path))
        model = self.model_cache.get(key)
        layout = self._carry_layout(model)
        retired = []
        with self._lock:
            by_layout = self._pools.setdefault(key, {})
            pool = by_layout.get(layout)
            if pool is not None and pool.model is not model \
                    and pool.held_slots == 0 and pool.queue_rows() == 0:
                # rolled-out model, same carry layout: adopt the new
                # instance once drained
                retired.append(pool)
                pool = None
            if pool is None:
                pool = DecodePool(
                    model, name=os.path.basename(key),
                    max_slots=self.max_slots, ttl_s=self.ttl_s,
                    max_wait_ms=self.max_wait_ms, min_batch=self.min_batch,
                    kv_paged=self.kv_paged, kv_block=self.kv_block,
                    kv_arena_tokens=self.kv_arena_tokens,
                    kv_dtype=self.kv_dtype,
                    carry_dtype=self.carry_dtype)
                by_layout[layout] = pool
            # retire fully-drained pools of OTHER layouts whose model
            # is no longer cache-current (the changed-layout rollout's
            # tail end)
            for lay, p in list(by_layout.items()):
                if lay != layout and p.model is not model \
                        and p.held_slots == 0 and p.queue_rows() == 0:
                    retired.append(by_layout.pop(lay))
        for p in retired:
            p.stop(timeout=5.0)
        return pool

    def _all_pools(self) -> List[DecodePool]:
        with self._lock:
            return [p for by_layout in self._pools.values()
                    for p in by_layout.values()]

    def open_session(self, model_path: str,
                     tenant: Optional[str] = None) -> dict:
        with self._lock:
            if self._draining:
                raise OverloadedError(
                    "decode draining (rollout/migration in progress)",
                    retry_after_s=self.retry_after_s)
        pool = self._pool_for(model_path)
        sid = pool.open_session(tenant=tenant,
                                retry_after_s=self.retry_after_s)
        with self._lock:
            self._by_sid[sid] = pool
        return {"session_id": sid, "model": pool.name,
                "slots": pool.max_slots,
                "slots_free": pool.max_slots - pool.held_slots}

    def _pool_of(self, session_id: str) -> DecodePool:
        with self._lock:
            pool = self._by_sid.get(session_id)
        if pool is None:
            raise KeyError(
                f"unknown or expired decode session {session_id!r}")
        return pool

    def decode_step(self, session_id: str, x, mask=None,
                    timeout_ms: Optional[float] = None,
                    tenant: Optional[str] = None,
                    timeout: Optional[float] = 60.0):
        pool = self._pool_of(session_id)
        try:
            return pool.step(session_id, x, masks=mask, timeout=timeout,
                             timeout_ms=timeout_ms, tenant=tenant)
        except KeyError:
            with self._lock:
                self._by_sid.pop(session_id, None)
            raise

    def warmup_spec(self, model_path: str, feature_tails,
                    k: int = 4) -> dict:
        """Pre-compile the fused speculative-verify program for
        ``model_path``'s pool (see :meth:`DecodePool.warmup_spec`) —
        the gateway ``warmup(spec_k=...)`` path."""
        pool = self._pool_for(model_path)
        return pool.warmup_spec(feature_tails, k=k)

    def spec_step(self, session_id: str, xs, token_ids,
                  timeout_ms: Optional[float] = None,
                  tenant: Optional[str] = None,
                  timeout: Optional[float] = 60.0,
                  sampling: Optional[dict] = None):
        """One fused speculative-verify step for a session (see
        :meth:`DecodePool.spec_step`)."""
        pool = self._pool_of(session_id)
        try:
            return pool.spec_step(session_id, xs, token_ids,
                                  timeout=timeout, timeout_ms=timeout_ms,
                                  tenant=tenant, sampling=sampling)
        except KeyError:
            with self._lock:
                self._by_sid.pop(session_id, None)
            raise

    def close_session(self, session_id: str) -> bool:
        with self._lock:
            pool = self._by_sid.pop(session_id, None)
        if pool is None:
            return False
        return pool.close_session(session_id)

    # ------------------------------------------------------------------
    # Session migration + drain (the fleet tier's RPC surface)
    # ------------------------------------------------------------------
    def export_session(self, session_id: str) -> dict:
        """Phase one of a cross-replica migration: the session's carry
        snapshot, JSON-serializable (docs/FLEET.md)."""
        pool = self._pool_of(session_id)
        return pool.export_session(session_id)

    def finish_export(self, session_id: str, ok: bool = True) -> bool:
        """Phase two: confirm (release the slot) or abort (reinstate)."""
        with self._lock:
            pool = self._by_sid.get(session_id)
        if pool is None:
            return False
        done = pool.finish_export(session_id, ok=ok)
        if ok and done:
            with self._lock:
                self._by_sid.pop(session_id, None)
        return done

    def import_session(self, model_path: str, payload: dict,
                       session_id: Optional[str] = None,
                       tenant: Optional[str] = None) -> dict:
        """Restore an exported session into this replica's pool for
        ``model_path`` (keeping the source's session id by default)."""
        with self._lock:
            if self._draining:
                raise OverloadedError(
                    "decode draining — not accepting migrated sessions",
                    retry_after_s=self.retry_after_s)
        pool = self._pool_for(model_path)
        sid = pool.import_session(payload, session_id=session_id,
                                  tenant=tenant)
        with self._lock:
            self._by_sid[sid] = pool
        return {"session_id": sid, "model": pool.name,
                "slots": pool.max_slots,
                "slots_free": pool.max_slots - pool.held_slots}

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def drain(self, deadline_s: Optional[float] = None) -> dict:
        """Stop admitting decode session joins across every pool and
        report remaining sessions per model — the rollout forcing
        function (ISSUE: adoption used to wait for a drain that nothing
        forced).  Migration/rollout moves the remainder; :meth:`resume`
        re-admits."""
        with self._lock:
            self._draining = True
            items = [(key, lay, p)
                     for key, by_layout in self._pools.items()
                     for lay, p in by_layout.items()]
        out: Dict[str, dict] = {}
        for key, lay, pool in items:
            k = key if key not in out else f"{key}#{lay}"
            out[k] = pool.drain(deadline_s)
        return out

    def resume(self) -> None:
        with self._lock:
            self._draining = False
        for p in self._all_pools():
            p.resume()

    def queue_rows(self) -> int:
        return sum(p.queue_rows() for p in self._all_pools())

    def queue_rows_by_tenant(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for p in self._all_pools():
            for t, n in p.queue_rows_by_tenant().items():
                out[t] = out.get(t, 0) + n
        return out

    def batchers_alive(self) -> bool:
        pools = [p for p in self._all_pools()
                 if p.held_slots > 0 or p.queue_rows() > 0]
        return all(p.thread_alive for p in pools)

    def sweep(self) -> int:
        n = sum(p.sweep() for p in self._all_pools())
        self._gc_sids()
        return n

    def _gc_sids(self) -> None:
        with self._lock:
            live = {sid for sid, pool in self._by_sid.items()
                    if sid in pool.session_ids()}
            self._by_sid = {sid: p for sid, p in self._by_sid.items()
                            if sid in live}

    def stats(self) -> dict:
        with self._lock:
            items = [(key, lay, p)
                     for key, by_layout in self._pools.items()
                     for lay, p in by_layout.items()]
        out: Dict[str, dict] = {}
        for key, lay, pool in items:
            # single-layout paths keep the plain-path key (the common
            # case and the pre-layout-keying stats surface); a path
            # mid-rollout with two live layouts disambiguates
            k = key if key not in out else f"{key}#{lay}"
            out[k] = pool.stats()
        return out

    def invalidate(self, model_path: Optional[str] = None) -> int:
        """Stop pool(s) — sessions fail, slots free (the cache-
        invalidation RPC semantics)."""
        import os
        with self._lock:
            if model_path is None:
                dropped = [p for by_layout in self._pools.values()
                           for p in by_layout.values()]
                self._pools.clear()
            else:
                key = os.path.abspath(str(model_path))
                by_layout = self._pools.pop(key, None) or {}
                dropped = list(by_layout.values())
            self._by_sid = {sid: p for sid, p in self._by_sid.items()
                            if p not in dropped}
        for p in dropped:
            p.stop(timeout=5.0)
        return len(dropped)

    def close(self) -> None:
        self.invalidate(None)
