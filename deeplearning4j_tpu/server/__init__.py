"""Serving edges (SURVEY.md §2.9): nearest-neighbor HTTP server and the
Python gateway entry point."""

from deeplearning4j_tpu.server.nearestneighbors import (
    NearestNeighbor, NearestNeighborsServer)
from deeplearning4j_tpu.server.gateway import DeepLearning4jEntryPoint, Server

__all__ = ["NearestNeighbor", "NearestNeighborsServer",
           "DeepLearning4jEntryPoint", "Server"]
