"""Serving edges (SURVEY.md §2.9): nearest-neighbor HTTP server and the
Python gateway entry point with its serving subsystem (model cache,
dynamic micro-batcher, bucket-warmed predict path)."""

from deeplearning4j_tpu.server.nearestneighbors import (
    NearestNeighbor, NearestNeighborsServer)
from deeplearning4j_tpu.server.model_cache import ModelCache
from deeplearning4j_tpu.server.batcher import MicroBatcher, ServingMetrics
from deeplearning4j_tpu.server.gateway import DeepLearning4jEntryPoint, Server

__all__ = ["NearestNeighbor", "NearestNeighborsServer", "ModelCache",
           "MicroBatcher", "ServingMetrics", "DeepLearning4jEntryPoint",
           "Server"]
