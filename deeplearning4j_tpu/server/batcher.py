"""Dynamic micro-batching for the serving path.

Concurrent ``predict`` requests arrive one or a few rows at a time; a
jitted XLA ``output`` call costs nearly the same to dispatch for 1 row
as for 32 — so answering requests one-at-a-time leaves most of the
hardware idle ("Array Languages Make Neural Networks Fast": batched,
compile-cached execution is where array frameworks win).  The
:class:`MicroBatcher` coalesces: requests enqueue rows with a future, a
batcher thread gathers up to ``max_batch`` rows (waiting at most
``max_wait_ms`` after the batch's first request), pads the gathered
batch up to the bucket ladder (``ops/bucketing.py``) so the jitted
callable compiles once per bucket instead of once per row-count, runs
ONE ``output`` call, and scatters per-request slices back.

Correctness: rows are independent at inference (no batch statistics —
BatchNorm uses running stats), so zero-row padding and slicing back is
exact, and a request's rows produce the same values whether they ran
alone or co-batched (the concurrent-vs-serial parity test pins this).
Requests whose row shape/dtype differs from their batch-mates are
grouped and run separately rather than failing the whole batch.

Telemetry: per-request queue/compute/total latency
(``nn/listeners.LatencyHistogram`` percentile snapshots) and a
batch-size histogram, surfaced through the gateway's ``stats`` RPC and
``bench.py``'s ``bench_serving`` A/B.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import Future
from typing import Callable, List, Optional, Sequence

import jax
import numpy as np

log = logging.getLogger(__name__)

from deeplearning4j_tpu import monitor
from deeplearning4j_tpu.analysis import sanitizer
from deeplearning4j_tpu.monitor import events, flight
from deeplearning4j_tpu.ops import bucketing
from deeplearning4j_tpu.resilience import faults
from deeplearning4j_tpu.resilience.errors import DeadlineExceededError


class _Pending:
    __slots__ = ("x", "future", "t_enqueue", "deadline", "tenant", "ctx")

    def __init__(self, x, future, t_enqueue, deadline=None, tenant=None,
                 ctx=None):
        self.x = x
        self.future = future
        self.t_enqueue = t_enqueue
        self.deadline = deadline  # absolute time.monotonic(), or None
        self.tenant = tenant      # fair-share admission attribution
        # trace context captured at enqueue: the batcher thread re-
        # attaches it to the events it emits on this request's behalf
        self.ctx = ctx or {}

    @property
    def request_id(self):
        return self.ctx.get("request_id")


class ServingMetrics:
    """Per-batcher serving telemetry: request latency split into queue
    (enqueue → batch dispatch), compute (the jitted call), and total
    (enqueue → result), plus how well coalescing is working (batch-size
    histogram, rows per batch).

    The latency recorders are registry histograms
    (``dl4j_serving_{queue,compute,total}_seconds{model=...}``, each a
    ``LatencyHistogram`` reservoir plus Prometheus buckets) so one
    ``/metrics`` scrape sees every batcher; ``snapshot()`` keeps the
    stats RPC's legacy ``*_ms`` dict shape on top of the same data."""

    def __init__(self, name: str = ""):
        reg = monitor.get_registry()
        self._lock = threading.Lock()
        lbl = {"model": name or "default"}
        self.queue = reg.histogram(
            "dl4j_serving_queue_seconds",
            "request enqueue → batch dispatch", ("model",)).labels(**lbl)
        self.compute = reg.histogram(
            "dl4j_serving_compute_seconds",
            "batched jitted inference call", ("model",)).labels(**lbl)
        self.total = reg.histogram(
            "dl4j_serving_total_seconds",
            "request enqueue → result", ("model",)).labels(**lbl)
        # requests carry a tenant label for fair-share attribution; the
        # family is incremented per request at dispatch (not per batch)
        # so per-tenant series sum to the model's total without double
        # counting
        self._f_requests = reg.counter(
            "dl4j_serving_requests_total", "predict requests served",
            ("model", "tenant"))
        self._model = lbl["model"]
        self._c_rows = reg.counter(
            "dl4j_serving_rows_total", "rows served", ("model",)).labels(**lbl)
        self._c_batches = reg.counter(
            "dl4j_serving_batches_total", "coalesced batches dispatched",
            ("model",)).labels(**lbl)
        self.requests = 0
        self.rows = 0
        self.batches = 0
        self.shed = {}
        self.batch_size_hist = {}

    def record_shed(self, reason: str) -> None:
        with self._lock:
            self.shed[reason] = self.shed.get(reason, 0) + 1

    def record_request(self, tenant=None) -> None:
        self._f_requests.labels(model=self._model,
                                tenant=tenant or "-").inc()

    def record_batch(self, n_requests: int, n_rows: int) -> None:
        with self._lock:
            self.requests += n_requests
            self.rows += n_rows
            self.batches += 1
            self.batch_size_hist[n_rows] = \
                self.batch_size_hist.get(n_rows, 0) + 1
        self._c_rows.inc(n_rows)
        self._c_batches.inc()

    def snapshot(self) -> dict:
        # one acquisition for the whole snapshot: two sequential locked
        # reads could interleave with a record_batch/record_shed and
        # return counters from two different instants
        with self._lock:
            requests, rows, batches = self.requests, self.rows, self.batches
            hist = {str(k): v for k, v in
                    sorted(self.batch_size_hist.items())}
            shed = dict(self.shed)
        return {
            "requests": requests,
            "rows": rows,
            "batches": batches,
            "shed": shed,
            "rows_per_batch_mean": round(rows / batches, 2) if batches else 0.0,
            "requests_per_batch_mean":
                round(requests / batches, 2) if batches else 0.0,
            "batch_size_hist": hist,
            "queue_ms": self.queue.latency_snapshot(),
            "compute_ms": self.compute.latency_snapshot(),
            "total_ms": self.total.latency_snapshot(),
        }


class MicroBatcher:
    """Coalesce concurrent few-row ``predict`` calls into one jitted
    ``output`` call.

    ``infer_fn(x: np.ndarray[B, ...]) -> np.ndarray[B, ...]`` must be
    row-aligned (row i of the output belongs to row i of the input).
    ``max_batch`` bounds gathered rows per dispatch (a single oversized
    request still runs, alone).  Dispatch is backpressure-driven: the
    batcher takes everything queued and runs it immediately — while the
    jitted call executes, new requests pile up and form the next batch,
    so coalescing emerges from load without adding idle wait to the
    request path.  ``min_batch > 1`` opts into explicit coalescing
    windows: the batch is held until it has ``min_batch`` rows or
    ``max_wait_ms`` has passed since its first request — ``max_wait_ms``
    bounds how long a lone request can wait for company, it is never
    stuck waiting for a full batch.  ``pad_to_bucket`` zero-pads the
    gathered batch up to the ``bucket_sizes`` ladder (powers of two when
    None) and slices the padding back off; turn it off when the model
    already buckets internally (``conf.shape_bucketing``)."""

    def __init__(self, infer_fn: Callable[[np.ndarray], np.ndarray],
                 max_batch: int = 32, max_wait_ms: float = 5.0,
                 min_batch: int = 1,
                 bucket_sizes: Optional[Sequence[int]] = None,
                 pad_to_bucket: bool = True, name: str = ""):
        self._infer_fn = infer_fn
        self.max_batch = max(1, int(max_batch))
        self.min_batch = max(1, min(int(min_batch), self.max_batch))
        self.max_wait_s = max(0.0, float(max_wait_ms)) / 1e3
        self._bucket_sizes = (list(bucket_sizes) if bucket_sizes else None)
        self._pad = bool(pad_to_bucket)
        self.metrics = ServingMetrics(name)
        self._queue: List[_Pending] = []
        self._cond = threading.Condition()
        self._running = True
        self._name = name
        self._inflight: List[_Pending] = []
        self._dead = False  # set by the crash handler BEFORE the dying
        # thread's is_alive() goes False — submit() keys restarts off it
        self.deaths = 0
        self.restarts = 0
        reg = monitor.get_registry()
        self._c_shed = reg.counter(
            "dl4j_resilience_shed_total",
            "requests shed instead of served", labels=("reason",))
        self._c_deaths = reg.counter(
            "dl4j_resilience_batcher_deaths_total",
            "micro-batcher threads that died unexpectedly")
        self._c_restarts = reg.counter(
            "dl4j_resilience_batcher_restarts_total",
            "micro-batcher threads restarted after a death")
        self._thread = self._spawn_thread()

    def _spawn_thread(self) -> threading.Thread:
        t = threading.Thread(
            target=self._loop_guarded, daemon=True,
            name=f"micro-batcher:{self._name or hex(id(self))}")
        t.start()
        return t

    # ------------------------------------------------------------------
    # Client side
    # ------------------------------------------------------------------
    def submit(self, features, timeout_ms: Optional[float] = None,
               tenant: Optional[str] = None) -> Future:
        """Enqueue a ``[k, ...]`` row batch; the future resolves to the
        ``[k, ...]`` output slice for exactly those rows.

        ``timeout_ms`` is the request's deadline budget: if it expires
        while the request is still queued, the request is SHED before
        compute (the future fails with :class:`DeadlineExceededError`)
        instead of burning a jitted call on an answer nobody is waiting
        for.  ``tenant`` attributes the queued rows (and the served
        request counter) for the gateway's fair-share admission."""
        x = np.asarray(features)
        if x.ndim < 1 or x.shape[0] == 0:
            raise ValueError("submit() needs a non-empty [k, ...] row batch")
        deadline = (None if timeout_ms is None
                    else time.monotonic() + float(timeout_ms) / 1e3)
        t_enqueue = time.perf_counter()
        ctx = events.current_context()
        restarted = False
        with self._cond:
            if not self._running:
                raise RuntimeError("MicroBatcher is stopped")
            # dead-thread detection: a batcher thread killed by a crash
            # must not strand clients — restart it on the next request
            if self._dead or not self._thread.is_alive():
                self._dead = False
                self.restarts += 1
                self._c_restarts.inc()
                self._thread = self._spawn_thread()
                restarted = True
            # the future is only born once the request is admitted — a
            # rejected submit must not mint one (dl4j-check's resolved-
            # on-all-schedules obligation counts every future)
            fut = Future()
            self._queue.append(_Pending(x, fut, t_enqueue, deadline,
                                        tenant, ctx=ctx))
            self._cond.notify_all()
        if restarted:
            events.emit("batcher.restarted", model=self._name)
        # verbose-only: request.admitted (gateway) already witnessed
        # this request microseconds ago on the same thread, and
        # batch.dispatch's request_ids prove queue membership — a third
        # always-on per-request emit breaks the ≤5% serving budget
        if events.verbose():
            events.emit("request.enqueued", rows=len(x), model=self._name)
        return fut

    def predict(self, features, timeout: Optional[float] = None,
                timeout_ms: Optional[float] = None,
                tenant: Optional[str] = None):
        """Blocking convenience wrapper around :meth:`submit`.
        ``timeout`` (seconds) bounds the client-side wait; ``timeout_ms``
        is the server-side deadline budget (queued past it = shed)."""
        return self.submit(features, timeout_ms=timeout_ms,
                           tenant=tenant).result(timeout)

    def queue_rows(self) -> int:
        """Rows currently waiting for dispatch — the admission-control
        signal the gateway checks against its queue limit."""
        with self._cond:
            return sum(len(p.x) for p in self._queue)

    def queue_rows_by_tenant(self) -> dict:
        """Queued rows attributed per tenant — the fair-share admission
        signal (requests without a tenant pool under ``"-"``)."""
        with self._cond:
            out: dict = {}
            for p in self._queue:
                t = p.tenant or "-"
                out[t] = out.get(t, 0) + len(p.x)
            return out

    @property
    def thread_alive(self) -> bool:
        return self._thread.is_alive()

    def stop(self, timeout: float = 5.0) -> None:
        """Drain in-flight work, stop the batcher thread, and fail any
        requests that could not be drained."""
        with self._cond:
            self._running = False
            self._cond.notify_all()
        self._thread.join(timeout)
        with self._cond:
            leftovers, self._queue = self._queue, []
        for p in leftovers:
            if not p.future.done():
                p.future.set_exception(RuntimeError("MicroBatcher stopped"))

    # ------------------------------------------------------------------
    # Batcher thread
    # ------------------------------------------------------------------
    def _take_batch(self) -> List[_Pending]:
        """Block until work exists, then drain everything queued up to
        ``max_batch`` rows.  A request that would overflow the batch is
        left for the next one (keeps dispatched row counts — and
        therefore compiled bucket shapes — bounded by ``max_batch``),
        unless it would be alone anyway.  With ``min_batch > 1`` the
        drain keeps waiting for more rows until ``min_batch`` is reached
        or ``max_wait_s`` has passed since the batch's first request."""
        with self._cond:
            while self._running and not self._queue:
                self._cond.wait(0.1)
            if not self._queue:
                return []
            deadline = time.perf_counter() + self.max_wait_s
            taken: List[_Pending] = []
            rows = 0
            while True:
                while self._queue:
                    nxt = len(self._queue[0].x)
                    if taken and rows + nxt > self.max_batch:
                        break
                    p = self._queue.pop(0)
                    taken.append(p)
                    rows += nxt
                if rows >= self.min_batch or not self._running:
                    break
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            return taken

    def _shed_expired(self, taken: List[_Pending]) -> List[_Pending]:
        """Drop requests whose deadline budget expired while queued —
        BEFORE compute, so the jitted call never runs for a client that
        has already given up."""
        now = time.monotonic()
        keep: List[_Pending] = []
        for p in taken:
            if p.deadline is not None and now >= p.deadline:
                self.metrics.record_shed("deadline")
                self._c_shed.labels(reason="deadline").inc()
                events.emit("request.shed", severity="warn",
                            reason="deadline", model=self._name,
                            request_id=p.request_id, tenant=p.tenant)
                if not p.future.done():
                    p.future.set_exception(DeadlineExceededError(
                        "request deadline expired while queued "
                        f"({(now - p.deadline) * 1e3:.1f} ms past budget)"))
            else:
                keep.append(p)
        return keep

    def _run_group(self, group: List[_Pending]) -> None:
        t_dispatch = time.perf_counter()
        # the ONE compute span for this batch is linked to the N
        # coalesced request spans by carrying every joined request ID in
        # the batcher thread's trace context — the journal answers
        # "which requests rode the batch that failed/was slow"
        rids = [p.request_id for p in group if p.request_id]
        try:
            with events.scope(model=self._name or None,
                              request_ids=rids or None):
                faults.check("batcher.compute")
                with monitor.span("serve/batch", phase="concat_pad"):
                    xs = [p.x for p in group]
                    x = np.concatenate(xs) if len(xs) > 1 else xs[0]
                    n = len(x)
                    if self._pad:
                        nb = bucketing.bucket_size(n, self._bucket_sizes)
                        if nb != n:
                            x = np.concatenate(
                                [x, np.zeros((nb - n,) + x.shape[1:],
                                             x.dtype)])
                events.emit("batch.dispatch", requests=len(group), rows=n)
                t0 = time.perf_counter()
                with monitor.span("serve/batch", phase="compute"), \
                        sanitizer.guard_step():
                    # explicit device->host pull (jax.device_get), not an
                    # implicit np.asarray sync: the sanitizer's transfer
                    # guard allows explicit transfers, and a non-jax
                    # output (plain numpy infer_fn) passes through
                    # unchanged
                    out = np.asarray(jax.device_get(self._infer_fn(x)))[:n]
                t1 = time.perf_counter()
            i = 0
            for p in group:
                k = len(p.x)
                p.future.set_result(out[i:i + k])
                i += k
            verbose = events.verbose()
            for p in group:
                self.metrics.queue.record(t_dispatch - p.t_enqueue)
                self.metrics.compute.record(t1 - t0)
                self.metrics.total.record(t1 - p.t_enqueue)
                self.metrics.record_request(p.tenant)
                # per-request completion events are verbose-only: the
                # response hop is already witnessed per request by
                # rpc.response (HTTP) and per batch by the compute
                # span.close carrying request_ids — a per-request emit
                # on the batcher's critical path breaks the ≤5% budget
                if verbose:
                    events.emit("request.done", model=self._name,
                                request_id=p.request_id, tenant=p.tenant,
                                rows=len(p.x),
                                total_s=round(t1 - p.t_enqueue, 6))
            self.metrics.record_batch(len(group), n)
        except Exception as e:
            for p in group:
                if not p.future.done():
                    p.future.set_exception(e)

    def _loop_guarded(self) -> None:
        """The batcher thread body plus its crash handler.  A
        ``BaseException`` escaping the loop (a killed thread — e.g. an
        armed ``mode="kill"`` fault, or a fatal interpreter error) used
        to strand every pending future in a forever-block; now the
        handler fails in-flight and queued requests with an error result
        and the next :meth:`submit` restarts the thread."""
        death_err = None
        try:
            self._loop()
        except BaseException as e:
            # recorded here (not re-raised): the death is fully handled
            # below, and a daemon thread's unhandled-exception spew
            # would just double-report it
            death_err = e
            log.error("micro-batcher %r thread died: %s: %s",
                      self._name, type(e).__name__, e)
        finally:
            with self._cond:
                died = self._running  # normal stop() exits are not deaths
                stranded = self._inflight + self._queue
                self._inflight = []
                if died:
                    self._queue = []
                    self.deaths += 1
                    self._dead = True
            if died:
                self._c_deaths.inc()
                for p in stranded:
                    if not p.future.done():
                        p.future.set_exception(RuntimeError(
                            "MicroBatcher thread died; request failed "
                            "(the batcher restarts on the next submit)"))
                # black box: journal the death with the stranded request
                # IDs, then dump the last-N events + registry snapshot
                # so "what happened in the 2s before the batcher died"
                # survives the thread
                rids = [p.request_id for p in stranded if p.request_id]
                events.emit(
                    "batcher.died", severity="error", model=self._name,
                    error=(f"{type(death_err).__name__}: {death_err}"
                           if death_err is not None else "unknown"),
                    stranded=len(stranded), request_ids=rids or None)
                flight.dump("batcher_died", extra={
                    "batcher": self._name,
                    "stranded_request_ids": rids,
                    "error": repr(death_err)})

    def _loop(self) -> None:
        while True:
            taken = self._take_batch()
            if not taken:
                if not self._running:
                    return
                continue
            taken = self._shed_expired(taken)
            if not taken:
                continue
            # one dispatch per (row-shape, dtype) group: a client sending
            # mismatched rows must not fail its batch-mates
            groups: dict = {}
            for p in taken:
                groups.setdefault(
                    (p.x.shape[1:], str(p.x.dtype)), []).append(p)
            for group in groups.values():
                with self._cond:
                    self._inflight = list(group)
                self._run_group(group)
                with self._cond:
                    self._inflight = []
