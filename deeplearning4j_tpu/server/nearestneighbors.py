"""Nearest-neighbors HTTP server
(ref: deeplearning4j-nearestneighbor-server —
server/NearestNeighborsServer.java (Play HTTP server exposing VPTree
k-NN), server/NearestNeighbor.java (the search op),
model/{NearestNeighborRequest,NearestNeighborsResult(s),Base64NDarrayBody}.java).

The reference serves POST /knn (k-NN of a stored point by index) and
POST /knnnew (k-NN of a base64-serialized NDArray payload).  Same
endpoints here over http.server; arrays travel as base64-encoded raw
float32 bytes plus shape — the Base64NDarrayBody analog."""

from __future__ import annotations

import base64
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

import numpy as np

from deeplearning4j_tpu.clustering.vptree import VPTree


def ndarray_to_base64(arr: np.ndarray) -> dict:
    """Base64NDarrayBody analog (ref: model/Base64NDarrayBody.java)."""
    a = np.ascontiguousarray(arr, np.float32)
    return {"ndarray": base64.b64encode(a.tobytes()).decode("ascii"),
            "shape": list(a.shape)}


def base64_to_ndarray(body: dict) -> np.ndarray:
    raw = base64.b64decode(body["ndarray"])
    return np.frombuffer(raw, np.float32).reshape(body["shape"])


class NearestNeighbor:
    """The search op (ref: server/NearestNeighbor.java — runs VPTree
    search and assembles index/distance results)."""

    def __init__(self, points: np.ndarray, distance: str = "euclidean"):
        self.points = np.asarray(points, np.float32)
        self.tree = VPTree(self.points, distance=distance)

    def search_index(self, idx: int, k: int) -> List[dict]:
        return self.search(self.points[idx], k + 1, skip_index=idx)[:k]

    def search(self, query: np.ndarray, k: int,
               skip_index: Optional[int] = None) -> List[dict]:
        idxs, dists = self.tree.knn(query, k)
        out = []
        for i, d in zip(idxs, dists):
            if skip_index is not None and i == skip_index:
                continue
            out.append({"index": int(i), "distance": float(d)})
        return out


class NearestNeighborsServer:
    """(ref: server/NearestNeighborsServer.java) — endpoints:

    POST /knn     {"ndarrayIndex": i, "k": n}
    POST /knnnew  {"k": n, "ndarray": ..., "shape": [...]}  (base64 body)

    both → {"results": [{"index": i, "distance": d}, ...]}
    """

    def __init__(self, points: np.ndarray, distance: str = "euclidean",
                 host: str = "127.0.0.1", port: int = 0):
        self.op = NearestNeighbor(points, distance)
        op = self.op

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _json(self, code: int, obj: dict) -> None:
                payload = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_POST(self):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    body = json.loads(self.rfile.read(n) or b"{}")
                    k = int(body.get("k", 1))
                    if self.path == "/knn":
                        idx = int(body["ndarrayIndex"])
                        results = op.search_index(idx, k)
                    elif self.path == "/knnnew":
                        q = base64_to_ndarray(body).reshape(-1)
                        results = op.search(q, k)
                    else:
                        self._json(404, {"error": f"no route {self.path}"})
                        return
                    self._json(200, {"results": results})
                except Exception as e:  # bad request payloads → 400
                    self._json(400, {"error": str(e)})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
