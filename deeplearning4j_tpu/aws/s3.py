"""S3 object IO (ref: deeplearning4j-aws/.../aws/s3/reader/S3Downloader.java,
uploader/S3Uploader.java — bucket list/download/upload surface).

``s3://bucket/key`` URIs require boto3 (gated); ``file://`` URIs and
plain paths work everywhere so the same call sites run in air-gapped
environments (this image has zero egress)."""

from __future__ import annotations

import shutil
from pathlib import Path
from typing import List, Tuple, Union
from urllib.parse import urlparse


def s3_available() -> bool:
    try:
        import boto3  # noqa: F401
        return True
    except ImportError:
        return False


def _parse(uri: str) -> Tuple[str, str, str]:
    """→ (scheme, bucket-or-root, key-or-path)"""
    u = urlparse(str(uri))
    if u.scheme == "s3":
        return "s3", u.netloc, u.path.lstrip("/")
    if u.scheme == "file":
        return "file", "", u.path
    return "file", "", str(uri)


def _require_boto3():
    if not s3_available():
        raise ImportError(
            "boto3 is not installed (and this environment has no egress); "
            "use file:// URIs or plain paths for local storage")
    import boto3
    return boto3.client("s3")


class S3Downloader:
    """(ref: aws/s3/reader/S3Downloader.java)"""

    def download(self, uri: str, dest: Union[str, Path]) -> Path:
        scheme, bucket, key = _parse(uri)
        dest = Path(dest)
        dest.parent.mkdir(parents=True, exist_ok=True)
        if scheme == "s3":
            _require_boto3().download_file(bucket, key, str(dest))
        else:
            shutil.copyfile(key, dest)
        return dest

    def list_objects(self, uri: str) -> List[str]:
        scheme, bucket, key = _parse(uri)
        if scheme == "s3":
            client = _require_boto3()
            keys: List[str] = []
            kwargs = {"Bucket": bucket, "Prefix": key}
            while True:  # paginate past the 1000-key page limit
                resp = client.list_objects_v2(**kwargs)
                keys.extend(o["Key"] for o in resp.get("Contents", []))
                if not resp.get("IsTruncated"):
                    return keys
                kwargs["ContinuationToken"] = resp["NextContinuationToken"]
        root = Path(key)
        return sorted(str(p) for p in root.rglob("*") if p.is_file())


class S3Uploader:
    """(ref: aws/s3/uploader/S3Uploader.java)"""

    def upload(self, src: Union[str, Path], uri: str) -> str:
        scheme, bucket, key = _parse(uri)
        if scheme == "s3":
            _require_boto3().upload_file(str(src), bucket, key)
        else:
            Path(key).parent.mkdir(parents=True, exist_ok=True)
            shutil.copyfile(src, key)
        return uri
