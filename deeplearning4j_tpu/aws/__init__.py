"""Cloud helpers (ref: deeplearning4j-aws — aws/s3/{reader,uploader}
S3Downloader/S3Uploader over the AWS SDK, aws/ec2 instance provisioning,
aws/dataset S3-backed datasets; SURVEY.md §2.6).

boto3 is not baked into this image and egress is disabled, so the S3
surface is gated (clear error + ``s3_available()``) with a local-path
scheme ("file://" and plain paths) that keeps dataset plumbing working
in air-gapped runs.  EC2 provisioning has no TPU-native equivalent —
capacity comes from the TPU slice, so provision via your cloud tooling;
the class documents that mapping rather than shelling out."""

from deeplearning4j_tpu.aws.s3 import (
    S3Downloader, S3Uploader, s3_available)

__all__ = ["S3Downloader", "S3Uploader", "s3_available"]
