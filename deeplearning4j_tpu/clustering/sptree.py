"""SPTree — generalized octree for Barnes-Hut force approximation
(ref: clustering/sptree/SpTree.java, used by BarnesHutTsne).

Host-side: BH is inherently pointer-chasing.  The TPU path for t-SNE is
the exact O(N²) kernel in plot/tsne.py (dense pairwise on the MXU); this
tree serves the theta-approximation mode for large N.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class SpTree:
    """A node subdivides into 2^d children on demand
    (ref: SpTree.java subdivide/insert/computeNonEdgeForces)."""

    QT_NODE_CAPACITY = 1

    def __init__(self, center: np.ndarray, width: np.ndarray,
                 parent: Optional["SpTree"] = None):
        self.d = len(center)
        self.center = np.asarray(center, np.float64)
        self.width = np.asarray(width, np.float64)
        self.parent = parent
        self.children: Optional[list] = None
        self.cum_size = 0
        self.center_of_mass = np.zeros(self.d)
        self.point: Optional[np.ndarray] = None

    @staticmethod
    def build(data) -> "SpTree":
        data = np.asarray(data, np.float64)
        mins, maxs = data.min(0), data.max(0)
        center = (mins + maxs) / 2.0
        width = (maxs - mins) / 2.0 + 1e-5
        tree = SpTree(center, width)
        for row in data:
            tree.insert(row)
        return tree

    def _contains(self, p) -> bool:
        return bool(np.all(np.abs(p - self.center) <= self.width + 1e-12))

    def insert(self, p) -> bool:
        p = np.asarray(p, np.float64)
        if not self._contains(p):
            return False
        self.cum_size += 1
        self.center_of_mass += (p - self.center_of_mass) / self.cum_size
        if self.children is None and self.point is None:
            self.point = p
            return True
        if self.children is None:
            # duplicate point: just accumulate mass, don't subdivide forever
            if np.allclose(self.point, p):
                return True
            self._subdivide()
        for c in self.children:
            if c.insert(p):
                return True
        return False  # numerically outside every child; mass already counted

    def _subdivide(self):
        self.children = []
        half = self.width / 2.0
        for mask in range(2 ** self.d):
            offs = np.array([(1 if (mask >> i) & 1 else -1) for i in range(self.d)])
            child = SpTree(self.center + offs * half, half, self)
            self.children.append(child)
        old = self.point
        self.point = None
        for c in self.children:
            if c.insert(old):
                break

    def compute_non_edge_forces(self, point, theta: float):
        """Barnes-Hut negative forces for one point: returns
        (neg_force [d], sum_Q contribution)
        (ref: SpTree.computeNonEdgeForces)."""
        neg = np.zeros(self.d)
        sum_q = 0.0
        stack = [self]
        while stack:
            node = stack.pop()
            if node.cum_size == 0:
                continue
            diff = point - node.center_of_mass
            d2 = float(diff @ diff)
            is_self = node.point is not None and d2 < 1e-18
            max_width = float(np.max(node.width)) * 2.0
            if node.children is None or (d2 > 0 and max_width / np.sqrt(d2) < theta):
                if is_self:
                    continue
                q = 1.0 / (1.0 + d2)
                mult = node.cum_size * q
                sum_q += mult
                neg += mult * q * diff
            else:
                stack.extend(node.children)
        return neg, sum_q
