"""Clustering + space-partitioning structures.

Parity with the reference's deeplearning4j-core clustering package
(ref: clustering/{kmeans,kdtree,vptree,quadtree,sptree,cluster}/ — ~4.1k
LoC Java).  TPU-first split: the iterative numeric kernels (K-Means
assignment/update, t-SNE forces) are jitted dense linear algebra on the
MXU; the pointer-chasing trees (KD/VP/SP/Quad) stay host-side with
vectorized NumPy distance evaluation — on TPU a dense batched distance
matrix beats tree traversal for any N that fits in HBM, so the trees
exist for API parity and for host-side serving (NearestNeighborsServer).
"""

from deeplearning4j_tpu.clustering.cluster import (  # noqa: F401
    Cluster, ClusterSet, Point)
from deeplearning4j_tpu.clustering.kmeans import KMeansClustering  # noqa: F401
from deeplearning4j_tpu.clustering.kdtree import KDTree  # noqa: F401
from deeplearning4j_tpu.clustering.vptree import VPTree  # noqa: F401
from deeplearning4j_tpu.clustering.quadtree import QuadTree  # noqa: F401
from deeplearning4j_tpu.clustering.sptree import SpTree  # noqa: F401
