"""K-Means (ref: clustering/kmeans/KMeansClustering.java + the iteration
machinery in clustering/algorithm/BaseClusteringAlgorithm.java).

TPU-first: Lloyd's iteration as ONE jitted lax.while_loop — the [N, K]
distance matrix is a single gemm on the MXU, assignment is an argmin,
and the centroid update is a masked matmul (one-hotᵀ @ points), so the
whole clustering runs on-device without host round-trips.  k-means++
seeding runs in the same program.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.clustering.cluster import Cluster, ClusterSet, Point


@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def _kmeans_kernel(points, key, k, max_iter, tol):
    n, d = points.shape

    def dist2(x, c):
        return (jnp.sum(x * x, -1)[:, None] - 2.0 * x @ c.T +
                jnp.sum(c * c, -1)[None, :])

    # --- k-means++ seeding ---
    def seed_body(i, carry):
        centers, key = carry
        key, sub = jax.random.split(key)
        d2 = dist2(points, centers)
        # distance to nearest already-chosen center; unchosen slots are inf
        valid = jnp.arange(k) < i
        d2 = jnp.where(valid[None, :], d2, jnp.inf)
        nearest = jnp.min(d2, axis=1)
        probs = nearest / jnp.maximum(jnp.sum(nearest), 1e-30)
        idx = jax.random.choice(sub, n, p=probs)
        return centers.at[i].set(points[idx]), key

    key, sub = jax.random.split(key)
    first = points[jax.random.randint(sub, (), 0, n)]
    centers0 = jnp.zeros((k, d), points.dtype).at[0].set(first)
    centers0, key = jax.lax.fori_loop(1, k, seed_body, (centers0, key))

    # --- Lloyd iterations ---
    def cond(carry):
        centers, prev, it = carry
        return (it < max_iter) & (jnp.max(jnp.abs(centers - prev)) > tol)

    def body(carry):
        centers, _, it = carry
        assign = jnp.argmin(dist2(points, centers), axis=1)
        onehot = jax.nn.one_hot(assign, k, dtype=points.dtype)  # [N, K]
        counts = jnp.sum(onehot, axis=0)                        # [K]
        sums = onehot.T @ points                                # [K, D] gemm
        new = jnp.where(counts[:, None] > 0,
                        sums / jnp.maximum(counts[:, None], 1.0), centers)
        return new, centers, it + 1

    centers, _, iters = jax.lax.while_loop(
        cond, body, (centers0, centers0 + 2 * tol + 1.0, jnp.int32(0)))
    assign = jnp.argmin(dist2(points, centers), axis=1)
    return centers, assign, iters


class KMeansClustering:
    """(ref: KMeansClustering.setup(nClusters, maxIterations, distanceFn))"""

    def __init__(self, k: int, max_iter: int = 100,
                 distance: str = "euclidean", tol: float = 1e-4,
                 seed: int = 0):
        if distance != "euclidean":
            # parity note: the reference accepts other distance functions;
            # Lloyd's update is only the mean-minimizer for euclidean, so
            # (like the reference in practice) we support euclidean here.
            raise ValueError("KMeansClustering supports euclidean distance")
        self.k = k
        self.max_iter = max_iter
        self.tol = tol
        self.seed = seed
        self.centers_: Optional[np.ndarray] = None
        self.assignments_: Optional[np.ndarray] = None

    @staticmethod
    def setup(k: int, max_iter: int, distance: str = "euclidean",
              seed: int = 0) -> "KMeansClustering":
        return KMeansClustering(k, max_iter, distance, seed=seed)

    def apply_to(self, points) -> ClusterSet:
        """Cluster a [N, D] matrix or list of Points
        (ref: KMeansClustering.applyTo)."""
        if isinstance(points, list):
            mat = np.stack([p.array for p in points])
            plist = points
        else:
            mat = np.asarray(points, np.float32)
            plist = None
        centers, assign, _ = _kmeans_kernel(
            jnp.asarray(mat, jnp.float32), jax.random.PRNGKey(self.seed),
            self.k, self.max_iter, self.tol)
        self.centers_ = np.asarray(centers)
        self.assignments_ = np.asarray(assign)
        clusters = [Cluster(center=self.centers_[i], id=i)
                    for i in range(self.k)]
        for j, a in enumerate(self.assignments_):
            pt = plist[j] if plist is not None else Point(mat[j], id=str(j))
            clusters[int(a)].points.append(pt)
        return ClusterSet(clusters=clusters)

    def predict(self, points) -> np.ndarray:
        mat = np.asarray(points, np.float32)
        d2 = (np.sum(mat * mat, -1)[:, None] - 2 * mat @ self.centers_.T +
              np.sum(self.centers_ ** 2, -1)[None, :])
        return np.argmin(d2, axis=1)
