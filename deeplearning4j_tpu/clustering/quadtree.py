"""QuadTree — 2D special case of the Barnes-Hut tree
(ref: clustering/quadtree/QuadTree.java).  Same node logic as SpTree
with d=2; kept as its own named type for API parity."""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.clustering.sptree import SpTree


class QuadTree(SpTree):
    @staticmethod
    def build(data) -> "QuadTree":
        data = np.asarray(data, np.float64)
        assert data.shape[1] == 2, "QuadTree is 2D; use SpTree for general d"
        mins, maxs = data.min(0), data.max(0)
        tree = QuadTree((mins + maxs) / 2.0, (maxs - mins) / 2.0 + 1e-5)
        for row in data:
            tree.insert(row)
        return tree
