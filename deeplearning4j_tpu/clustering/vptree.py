"""Vantage-point tree (ref: clustering/vptree/VPTree.java — the k-NN
engine behind the NearestNeighborsServer and wordsNearest).

Host-side build with vectorized distance evaluation; search prunes by
triangle inequality.  For bulk queries on TPU, prefer
``deeplearning4j_tpu.clustering.distances`` dense matrices — the tree is
the serving-path structure for one-off queries.
"""

from __future__ import annotations

import heapq
from typing import List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.clustering.distances import distance_fn


class _VPNode:
    __slots__ = ("index", "radius", "inside", "outside")

    def __init__(self, index):
        self.index = index
        self.radius = 0.0
        self.inside: Optional[_VPNode] = None
        self.outside: Optional[_VPNode] = None


class VPTree:
    def __init__(self, items, distance: str = "euclidean",
                 labels: Optional[Sequence[str]] = None, seed: int = 0):
        self.items = np.asarray(items, np.float64)
        self.labels = list(labels) if labels is not None else None
        self.distance = distance
        # Triangle-inequality pruning requires a METRIC.  Cosine distance
        # is handled by searching in euclidean space over L2-normalized
        # vectors (d² = 2·(1-cos), monotone, and euclidean IS a metric);
        # non-metricizable distances ('dot') are rejected loudly rather
        # than silently returning wrong neighbors.
        self._cosine = distance.lower() in ("cosine", "cosinesimilarity")
        if self._cosine:
            norms = np.maximum(np.linalg.norm(self.items, axis=1,
                                              keepdims=True), 1e-12)
            self._search_items = self.items / norms
            self._dist = distance_fn("euclidean")
        elif distance.lower() == "dot":
            raise ValueError(
                "VPTree cannot prune with the non-metric 'dot' distance; "
                "use a dense distance matrix (clustering.distances) instead")
        else:
            self._search_items = self.items
            self._dist = distance_fn(distance)
        self._rng = np.random.default_rng(seed)
        self.root = self._build(np.arange(len(self.items)))

    def _build(self, idxs: np.ndarray) -> Optional[_VPNode]:
        if len(idxs) == 0:
            return None
        vp_pos = self._rng.integers(0, len(idxs))
        vp = int(idxs[vp_pos])
        rest = np.delete(idxs, vp_pos)
        node = _VPNode(vp)
        if len(rest) == 0:
            return node
        d = np.atleast_1d(self._dist(self._search_items[vp],
                                     self._search_items[rest]))
        node.radius = float(np.median(d))
        node.inside = self._build(rest[d < node.radius])
        node.outside = self._build(rest[d >= node.radius])
        return node

    def knn(self, query, k: int) -> Tuple[List[int], List[float]]:
        """k nearest (indices, distances), ascending
        (ref: VPTree.search)."""
        query = np.asarray(query, np.float64)
        if self._cosine:
            query = query / max(np.linalg.norm(query), 1e-12)
        heap: List[Tuple[float, int]] = []  # max-heap by -dist
        tau = [np.inf]

        def visit(node):
            if node is None:
                return
            d = float(np.atleast_1d(
                self._dist(query, self._search_items[node.index][None, :]))[0])
            if len(heap) < k:
                heapq.heappush(heap, (-d, node.index))
                if len(heap) == k:
                    tau[0] = -heap[0][0]
            elif d < tau[0]:
                heapq.heapreplace(heap, (-d, node.index))
                tau[0] = -heap[0][0]
            if node.inside is None and node.outside is None:
                return
            if d < node.radius:
                visit(node.inside)
                if d + tau[0] >= node.radius:
                    visit(node.outside)
            else:
                visit(node.outside)
                if d - tau[0] <= node.radius:
                    visit(node.inside)

        visit(self.root)
        out = sorted(heap, key=lambda t: -t[0])
        idxs = [i for _, i in out]
        dists = [-nd for nd, _ in out]
        if self._cosine:
            # convert search-space euclidean back to cosine distance:
            # d_euclid² = 2·(1 - cos)  ⇒  1-cos = d²/2
            dists = [d * d / 2.0 for d in dists]
        return idxs, dists

    def knn_labels(self, query, k: int) -> Tuple[List[str], List[float]]:
        idxs, dists = self.knn(query, k)
        return [self.labels[i] for i in idxs], dists
