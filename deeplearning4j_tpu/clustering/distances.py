"""Vectorized distance kernels shared by clustering/trees/serving.

(ref: the nd4j distance ops consumed by clustering —
EuclideanDistance/CosineSimilarity/ManhattanDistance accumulations.)
All functions take (queries [M, D], points [N, D]) → [M, N] or
([D], [N, D]) → [N]; pure NumPy so they run host-side for serving, and
the same formulas are used inside jitted kernels where it matters.
"""

from __future__ import annotations

import numpy as np


def euclidean(q, pts):
    q = np.atleast_2d(q)
    d2 = (np.sum(q * q, -1)[:, None] - 2.0 * q @ pts.T +
          np.sum(pts * pts, -1)[None, :])
    return np.sqrt(np.maximum(d2, 0.0)).squeeze(0) if q.shape[0] == 1 else \
        np.sqrt(np.maximum(d2, 0.0))


def manhattan(q, pts):
    q = np.atleast_2d(q)
    d = np.sum(np.abs(q[:, None, :] - pts[None, :, :]), -1)
    return d.squeeze(0) if q.shape[0] == 1 else d


def cosine_distance(q, pts):
    """1 - cosine_similarity (ref: VPTree 'cosinesimilarity' uses
    similarity as INVERSE distance; we expose the proper metric)."""
    q = np.atleast_2d(q)
    qn = q / np.maximum(np.linalg.norm(q, axis=-1, keepdims=True), 1e-12)
    pn = pts / np.maximum(np.linalg.norm(pts, axis=-1, keepdims=True), 1e-12)
    d = 1.0 - qn @ pn.T
    return d.squeeze(0) if q.shape[0] == 1 else d


def dot_distance(q, pts):
    q = np.atleast_2d(q)
    d = -(q @ pts.T)
    return d.squeeze(0) if q.shape[0] == 1 else d


_DISTANCES = {
    "euclidean": euclidean,
    "manhattan": manhattan,
    "cosine": cosine_distance,
    "cosinesimilarity": cosine_distance,
    "dot": dot_distance,
}


def distance_fn(name: str):
    return _DISTANCES[name.lower()]
