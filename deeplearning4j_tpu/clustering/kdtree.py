"""KD-tree (ref: clustering/kdtree/KDTree.java — insert/nn/knn over
axis-aligned splits).  Host-side structure: serving-path lookups, not a
TPU workload."""

from __future__ import annotations

import heapq
from typing import List, Optional, Tuple

import numpy as np


class _Node:
    __slots__ = ("point", "index", "left", "right", "axis")

    def __init__(self, point, index, axis):
        self.point = point
        self.index = index
        self.axis = axis
        self.left: Optional[_Node] = None
        self.right: Optional[_Node] = None


class KDTree:
    def __init__(self, dims: int):
        self.dims = dims
        self.root: Optional[_Node] = None
        self.size = 0

    @staticmethod
    def build(points) -> "KDTree":
        """Balanced build by median splits (the reference builds by
        repeated insert; balanced build gives the same API with better
        worst-case depth)."""
        pts = np.asarray(points, np.float64)
        tree = KDTree(pts.shape[1])

        def rec(idxs, depth):
            if len(idxs) == 0:
                return None
            axis = depth % tree.dims
            order = idxs[np.argsort(pts[idxs, axis], kind="stable")]
            mid = len(order) // 2
            node = _Node(pts[order[mid]], int(order[mid]), axis)
            node.left = rec(order[:mid], depth + 1)
            node.right = rec(order[mid + 1:], depth + 1)
            return node

        tree.root = rec(np.arange(len(pts)), 0)
        tree.size = len(pts)
        return tree

    def insert(self, point, index: Optional[int] = None):
        """(ref: KDTree.insert)"""
        point = np.asarray(point, np.float64)
        if index is None:
            index = self.size
        if self.root is None:
            self.root = _Node(point, index, 0)
            self.size = 1
            return
        node = self.root
        depth = 0
        while True:
            axis = node.axis
            branch = "left" if point[axis] < node.point[axis] else "right"
            nxt = getattr(node, branch)
            if nxt is None:
                setattr(node, branch, _Node(point, index, (depth + 1) % self.dims))
                self.size += 1
                return
            node = nxt
            depth += 1

    def nn(self, point) -> Tuple[np.ndarray, float, int]:
        """Nearest neighbor: (point, distance, index) (ref: KDTree.nn)."""
        pts, dists, idxs = self.knn(point, 1)
        return pts[0], dists[0], idxs[0]

    def knn(self, point, k: int):
        """k nearest: ([k, D] points, [k] distances, [k] indices)."""
        point = np.asarray(point, np.float64)
        heap: List[Tuple[float, int, np.ndarray]] = []  # max-heap by -dist

        def visit(node):
            if node is None:
                return
            d = float(np.linalg.norm(node.point - point))
            if len(heap) < k:
                heapq.heappush(heap, (-d, node.index, node.point))
            elif d < -heap[0][0]:
                heapq.heapreplace(heap, (-d, node.index, node.point))
            diff = point[node.axis] - node.point[node.axis]
            near, far = (node.left, node.right) if diff < 0 else (node.right, node.left)
            visit(near)
            if len(heap) < k or abs(diff) < -heap[0][0]:
                visit(far)

        visit(self.root)
        out = sorted(heap, key=lambda t: -t[0])
        return (np.stack([t[2] for t in out]),
                np.array([-t[0] for t in out]),
                [t[1] for t in out])
