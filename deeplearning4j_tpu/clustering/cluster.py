"""Cluster model objects (ref: clustering/cluster/{Point,Cluster,ClusterSet}.java)."""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np


@dataclasses.dataclass
class Point:
    """A point with optional id/label (ref: clustering/cluster/Point.java)."""

    array: np.ndarray
    id: Optional[str] = None
    label: Optional[str] = None

    @staticmethod
    def to_points(matrix) -> List["Point"]:
        return [Point(np.asarray(row)) for row in np.asarray(matrix)]


@dataclasses.dataclass
class Cluster:
    """A centroid plus its member points (ref: clustering/cluster/Cluster.java)."""

    center: np.ndarray
    points: List[Point] = dataclasses.field(default_factory=list)
    id: Optional[int] = None

    def distance_to_center(self, point: Point, distance: str = "euclidean") -> float:
        from deeplearning4j_tpu.clustering.distances import distance_fn
        return float(distance_fn(distance)(point.array[None, :],
                                           self.center[None, :])[0])


@dataclasses.dataclass
class ClusterSet:
    """All clusters from one clustering run
    (ref: clustering/cluster/ClusterSet.java)."""

    clusters: List[Cluster] = dataclasses.field(default_factory=list)
    distance: str = "euclidean"

    @property
    def centers(self) -> np.ndarray:
        return np.stack([c.center for c in self.clusters])

    def nearest_cluster(self, point: Point) -> Cluster:
        from deeplearning4j_tpu.clustering.distances import distance_fn
        d = distance_fn(self.distance)(point.array[None, :], self.centers)
        return self.clusters[int(np.argmin(d))]

    def classify_point(self, point: Point) -> int:
        return int(self.nearest_cluster(point).id)
