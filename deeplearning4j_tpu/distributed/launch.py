"""Elastic cluster launcher — coordinator + N supervised worker
processes (docs/DISTRIBUTED.md).

The modern ``TrainingMaster`` entry point: one process hosts the
:class:`Coordinator` (membership/leases/generations + the step
all-reduce) over HTTP, spawns N copies of the user's training script,
and SUPERVISES them — a worker that dies (preemption, a ``dist.worker``
kill fault, OOM) is evicted by its lapsed lease, the survivors roll to
a new generation and keep training on N−1, and the launcher respawns
the dead rank which re-admits through the coordinator's breaker,
restores the survivors' state snapshot, and is absorbed back.  No
operator action at any point.

Worker contract (what the spawned script sees)::

    DL4J_DIST_COORDINATOR   http://host:port of the coordinator
    DL4J_DIST_WORKER_ID     stable per-rank id (w0..wN-1), kept across
                            respawns so re-admission hits the breaker
    DL4J_DIST_EXPECTED      initial formation size N

The script builds a conf with ``.distributed(processes=N)`` and calls
``fit()`` — the engines route every batch through the cluster step
(``distributed/worker.fit_batch``).  On accelerator platforms that
support cross-process XLA collectives the same script may additionally
join ``jax.distributed`` (``scaleout.multislice.initialize_distributed``)
for in-step ICI/DCN collectives; on CPU the coordinator barrier IS the
data plane (the jax CPU backend implements no multi-process
computations).

CLI::

    python -m deeplearning4j_tpu.distributed.launch \
        --processes 2 [--no-respawn] [--max-restarts K] script.py [args]
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time
from typing import Callable, Dict, List, Optional

from deeplearning4j_tpu.distributed.coordinator import Coordinator
from deeplearning4j_tpu.distributed.rpc import CoordinatorServer
from deeplearning4j_tpu.distributed.worker import (
    ENV_COORDINATOR, ENV_EXPECTED, ENV_WORKER_ID)


class WorkerProc:
    """One supervised rank: the live process plus its respawn history."""

    def __init__(self, worker_id: str, argv: List[str],
                 env: Dict[str, str]):
        self.worker_id = worker_id
        self.argv = argv
        self.env = env
        self.proc: Optional[subprocess.Popen] = None
        self.restarts = 0
        self.outputs: List[dict] = []     # per incarnation

    def spawn(self) -> None:
        # each incarnation knows its respawn ordinal — chaos tests use
        # it to arm fault plans on the FIRST incarnation only
        env = dict(self.env, DL4J_DIST_RESTART=str(self.restarts))
        self.proc = subprocess.Popen(
            self.argv, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True)

    def reap(self) -> Optional[int]:
        """Non-blocking: the exit code when this incarnation finished
        (output captured), else None."""
        if self.proc is None or self.proc.poll() is None:
            return None
        out, err = self.proc.communicate()
        rc = self.proc.returncode
        self.outputs.append({"rc": rc, "stdout": out, "stderr": err})
        self.proc = None
        return rc


class LaunchResult:
    def __init__(self, workers: List[WorkerProc], status: dict):
        self.workers = workers
        self.coordinator_status = status

    @property
    def ok(self) -> bool:
        return all(w.outputs and w.outputs[-1]["rc"] == 0
                   for w in self.workers)

    def stdout(self, worker_id: str) -> str:
        for w in self.workers:
            if w.worker_id == worker_id:
                return "".join(o["stdout"] for o in w.outputs)
        return ""

    def all_stdout(self) -> str:
        return "".join(o["stdout"] for w in self.workers
                       for o in w.outputs)

    def describe_failures(self) -> str:
        msgs = []
        for w in self.workers:
            for i, o in enumerate(w.outputs):
                if o["rc"] != 0:
                    msgs.append(f"--- {w.worker_id} incarnation {i} "
                                f"(rc={o['rc']}):\n{o['stdout'][-2000:]}\n"
                                f"{o['stderr'][-3000:]}")
        return "\n".join(msgs) or "(all workers exited 0)"


def launch_cluster(argv: List[str], processes: int,
                   respawn: bool = True, max_restarts: int = 2,
                   lease_ms: float = 1500.0,
                   env_extra: Optional[Dict[str, str]] = None,
                   per_worker_env: Optional[
                       Callable[[int], Dict[str, str]]] = None,
                   timeout_s: float = 600.0,
                   cwd: Optional[str] = None) -> LaunchResult:
    """Run ``argv`` as an elastic N-worker cluster and supervise it to
    completion.  ``per_worker_env(i)`` layers rank-specific env on top
    of ``env_extra`` (how chaos tests arm a ``DL4J_FAULT_PLAN`` on one
    rank only).  Returns once every rank's final incarnation exited
    (workers that exhaust ``max_restarts`` stay failed)."""
    co = Coordinator(expected=processes, lease_ms=lease_ms)
    server = CoordinatorServer(co).start()
    workers: List[WorkerProc] = []
    try:
        for i in range(processes):
            env = dict(os.environ)
            env.update(env_extra or {})
            env.update((per_worker_env or (lambda _i: {}))(i))
            env[ENV_COORDINATOR] = server.address
            env[ENV_WORKER_ID] = f"w{i}"
            env[ENV_EXPECTED] = str(processes)
            w = WorkerProc(f"w{i}", list(argv), env)
            if cwd is not None:
                w.argv = list(argv)
            w.spawn()
            workers.append(w)
        deadline = time.monotonic() + timeout_s
        pending = set(range(processes))
        while pending:
            if time.monotonic() > deadline:
                for i in pending:
                    p = workers[i].proc
                    if p is not None:
                        p.kill()
                        workers[i].reap()
                break
            for i in list(pending):
                w = workers[i]
                rc = w.reap()
                if rc is None:
                    continue
                if rc != 0 and respawn and w.restarts < max_restarts:
                    w.restarts += 1
                    w.spawn()     # same id: re-admission via breaker
                else:
                    pending.discard(i)
            time.sleep(0.05)
        status = co.status()
    finally:
        server.stop()
    return LaunchResult(workers, status)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m deeplearning4j_tpu.distributed.launch",
        description="Launch an elastic coordinator + N-worker cluster")
    ap.add_argument("--processes", "-n", type=int, default=2)
    ap.add_argument("--no-respawn", action="store_true",
                    help="do not respawn dead workers (no elasticity)")
    ap.add_argument("--max-restarts", type=int, default=2)
    ap.add_argument("--lease-ms", type=float, default=1500.0)
    ap.add_argument("--timeout-s", type=float, default=3600.0)
    ap.add_argument("script", help="worker training script")
    ap.add_argument("script_args", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    result = launch_cluster(
        [sys.executable, args.script] + args.script_args,
        processes=args.processes, respawn=not args.no_respawn,
        max_restarts=args.max_restarts, lease_ms=args.lease_ms,
        timeout_s=args.timeout_s)
    sys.stdout.write(result.all_stdout())
    if not result.ok:
        sys.stderr.write(result.describe_failures() + "\n")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
