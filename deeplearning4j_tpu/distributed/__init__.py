"""Elastic multi-host distributed runtime (docs/DISTRIBUTED.md).

The cluster tier the reference ran on Spark (``TrainingMaster``,
ref: spark/impl/paramavg/ParameterAveragingTrainingMaster.java),
rebuilt preemption-tolerant:

* :mod:`~deeplearning4j_tpu.distributed.coordinator` — membership
  registry, heartbeat leases, generation-numbered cluster epochs, the
  per-step barrier + weighted gradient all-reduce, and the in-memory
  state-snapshot relay that absorbs returning workers;
* :mod:`~deeplearning4j_tpu.distributed.worker` — the per-process
  :class:`DistSession` and the distributed step the engines' fit loops
  route through under ``conf.distributed(processes=N)``;
* :mod:`~deeplearning4j_tpu.distributed.launch` — coordinator + N
  supervised worker processes with automatic respawn;
* :mod:`~deeplearning4j_tpu.distributed.rpc` — the HTTP wire (gateway
  JSON-RPC shape, base64-npy vectors).
"""

from deeplearning4j_tpu.distributed.coordinator import (  # noqa: F401
    Coordinator)
from deeplearning4j_tpu.distributed.launch import (  # noqa: F401
    launch_cluster)
from deeplearning4j_tpu.distributed.rpc import (  # noqa: F401
    CoordinatorClient, CoordinatorServer)
from deeplearning4j_tpu.distributed.worker import (  # noqa: F401
    ClusterFormationError, DistSession, GenerationRolled,
    WorkerEvictedError, active_session, install_session, maybe_session,
    shard_bounds, shutdown_session)
